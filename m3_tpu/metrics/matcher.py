"""Rule matcher: KV-watched rule sets compiled per namespace with a result
cache (reference: src/metrics/matcher/{match.go,ruleset.go,namespaces.go,
cache/cache.go}).

The collector/coordinator matches every incoming metric ID against the
namespace's active rule set; match results carry an expiry (the next rule
cutover) so the cache invalidates itself exactly when rules change."""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, Optional

from ..cluster import kv as cluster_kv
from .filters import TagsFilter
from .pipeline import Op, Pipeline
from .policy import StoragePolicy
from .rules import (
    MappingRuleSnapshot,
    MatchResult,
    RollupRuleSnapshot,
    RollupTarget,
    Rule,
    RuleSet,
)


def pipeline_to_json(p: Pipeline) -> list:
    """Generic op-list serialization: aggregation, transformation, and
    rollup ops all round-trip (pipeline/type.go Pipeline proto shape)."""
    out = []
    for op in p.ops:
        if op.rollup is not None:
            out.append({"t": "rollup", "new_name": op.rollup.new_name.decode(),
                        "tags": [t.decode() for t in op.rollup.tags],
                        "agg_id": op.rollup.aggregation_id})
        elif op.transformation is not None:
            out.append({"t": "transform", "op": int(op.transformation)})
        elif op.aggregation is not None:
            out.append({"t": "agg", "op": int(op.aggregation)})
        else:
            raise ValueError(f"unserializable pipeline op {op}")
    return out


def pipeline_from_json(ops: list) -> Pipeline:
    from .aggregation import AggType
    from .transformation import TransformType

    built = []
    for d in ops:
        if d["t"] == "rollup":
            built.append(Op.roll(d["new_name"].encode(),
                                 tuple(t.encode() for t in d["tags"]),
                                 d["agg_id"]))
        elif d["t"] == "transform":
            built.append(Op.transform(TransformType(d["op"])))
        else:
            built.append(Op.aggregate(AggType(d["op"])))
    return Pipeline(tuple(built))


def ruleset_to_json(rs: RuleSet) -> dict:
    """Serialize a rule set for KV storage (the reference stores protobuf
    rule sets under one key per namespace, matcher/ruleset.go kv watch)."""

    def snap(s):
        if isinstance(s, MappingRuleSnapshot):
            return {
                "kind": "mapping", "name": s.name, "cutover": s.cutover_nanos,
                "filter": s.filter.to_json(),
                "agg_id": s.aggregation_id,
                "policies": [str(p) for p in s.storage_policies],
                "drop": s.drop_policy, "tomb": s.tombstoned,
            }
        return {
            "kind": "rollup", "name": s.name, "cutover": s.cutover_nanos,
            "filter": s.filter.to_json(), "tomb": s.tombstoned,
            "targets": [
                {
                    "pipeline": pipeline_to_json(t.pipeline),
                    "policies": [str(p) for p in t.storage_policies],
                }
                for t in s.targets
            ],
        }

    return {
        "namespace": rs.namespace.decode(),
        "version": rs.version,
        "tombstoned": rs.tombstoned,
        "mapping": [[snap(s) for s in r.snapshots] for r in rs.mapping_rules],
        "rollup": [[snap(s) for s in r.snapshots] for r in rs.rollup_rules],
    }


def ruleset_from_json(obj: dict) -> RuleSet:
    def unsnap(d):
        filt = TagsFilter.from_json(d["filter"])
        if d["kind"] == "mapping":
            return MappingRuleSnapshot(
                d["name"], d["cutover"], filt, d["agg_id"],
                tuple(StoragePolicy.parse(p) for p in d["policies"]),
                d["drop"], d["tomb"],
            )
        return RollupRuleSnapshot(
            d["name"], d["cutover"], filt,
            tuple(
                RollupTarget(
                    pipeline_from_json(t["pipeline"]),
                    tuple(StoragePolicy.parse(p) for p in t["policies"]),
                )
                for t in d["targets"]
            ),
            d["tomb"],
        )

    return RuleSet(
        obj["namespace"].encode(), obj["version"],
        [Rule([unsnap(s) for s in snaps]) for snaps in obj["mapping"]],
        [Rule([unsnap(s) for s in snaps]) for snaps in obj["rollup"]],
        obj["tombstoned"],
    )


class RuleSetStore:
    """Publish/read rule sets in KV, one key per namespace
    (matcher/namespaces.go namespaces key + per-ns ruleset keys)."""

    def __init__(self, store: cluster_kv.MemStore, prefix: str = "_rules"):
        self._store = store
        self._prefix = prefix

    def _key(self, namespace: bytes) -> str:
        return f"{self._prefix}/{namespace.decode()}"

    def publish(self, rs: RuleSet) -> int:
        return self._store.set(
            self._key(rs.namespace), json.dumps(ruleset_to_json(rs)).encode())

    def get(self, namespace: bytes) -> Optional[RuleSet]:
        val = self._store.get(self._key(namespace))
        if val is None:
            return None
        return ruleset_from_json(json.loads(val.data.decode()))

    def on_change(self, namespace: bytes, fn: Callable[[RuleSet], None]):
        self._store.on_change(
            self._key(namespace),
            lambda _k, v: fn(ruleset_from_json(json.loads(v.data.decode()))))


class Matcher:
    """Per-namespace matcher with KV watch + expiring result cache
    (matcher/match.go, cache/cache.go)."""

    def __init__(self, store: RuleSetStore, namespace: bytes,
                 clock: Optional[Callable[[], int]] = None,
                 cache_capacity: int = 65536):
        import time as _time

        self._store = store
        self._namespace = namespace
        self._clock = clock or _time.time_ns
        self._lock = threading.Lock()
        self._cache: Dict[bytes, MatchResult] = {}
        self._capacity = cache_capacity
        self._generation = 0
        rs = store.get(namespace)
        self._active = rs.active_set() if rs is not None else None
        store.on_change(namespace, self._on_ruleset_change)
        self.hits = 0
        self.misses = 0

    def _on_ruleset_change(self, rs: RuleSet):
        with self._lock:
            self._active = rs.active_set()
            self._cache.clear()  # new version invalidates everything
            self._generation += 1

    def match(self, metric_id: bytes,
              from_nanos: Optional[int] = None,
              to_nanos: Optional[int] = None) -> Optional[MatchResult]:
        now = self._clock()
        from_nanos = now if from_nanos is None else from_nanos
        to_nanos = now + 1 if to_nanos is None else to_nanos
        with self._lock:
            active = self._active
            generation = self._generation
            cached = self._cache.get(metric_id)
            if cached is not None and not cached.has_expired(now):
                self.hits += 1
                return cached
        if active is None:
            return None
        self.misses += 1
        result = active.forward_match(metric_id, from_nanos, to_nanos)
        with self._lock:
            # Only cache if no rule-set swap raced this computation — a
            # stale insert after the invalidating clear would otherwise be
            # served until its (possibly infinite) expiry.
            if self._generation == generation:
                if len(self._cache) >= self._capacity:
                    self._cache.clear()  # simple full-flush eviction
                self._cache[metric_id] = result
        return result
