"""Aggregation type system (reference: src/metrics/aggregation/type.go).

Types name the statistics an aggregation window exposes; they map 1:1 onto
the mergeable moments / quantile kernels in m3_tpu.ops.aggregation, so a
Types list is also the device-side output selector for elem consumption.
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence

from .metric import MetricType


class AggType(enum.IntEnum):
    """Supported aggregation types (type.go:34-57). IDs are stable wire IDs."""

    UNKNOWN = 0
    LAST = 1
    MIN = 2
    MAX = 3
    MEAN = 4
    MEDIAN = 5
    COUNT = 6
    SUM = 7
    SUMSQ = 8
    STDEV = 9
    P10 = 10
    P20 = 11
    P30 = 12
    P40 = 13
    P50 = 14
    P60 = 15
    P70 = 16
    P80 = 17
    P90 = 18
    P95 = 19
    P99 = 20
    P999 = 21
    P9999 = 22

    def quantile(self) -> Optional[float]:
        """Quantile value when this is a percentile type (type.go:161)."""
        return _QUANTILES.get(self)

    def is_valid_for(self, mt: MetricType) -> bool:
        """Validity per metric type (type.go:133-158)."""
        if mt == MetricType.COUNTER:
            return self in _COUNTER_VALID
        if mt == MetricType.TIMER:
            return self != AggType.UNKNOWN and self != AggType.LAST
        if mt == MetricType.GAUGE:
            return self in _GAUGE_VALID
        return False

    @property
    def type_string(self) -> str:
        """Output-name suffix (types_options.go defaultTypeStringsMap:
        Min -> 'lower', Max -> 'upper', quantiles -> 'p50'...)."""
        if self in _TYPE_STRINGS:
            return _TYPE_STRINGS[self]
        q = self.quantile()
        if q is not None:
            return "p" + format(q * 100, "g").replace(".", "")
        return self.name.lower()


_QUANTILES = {
    AggType.P10: 0.1, AggType.P20: 0.2, AggType.P30: 0.3, AggType.P40: 0.4,
    AggType.P50: 0.5, AggType.MEDIAN: 0.5, AggType.P60: 0.6, AggType.P70: 0.7,
    AggType.P80: 0.8, AggType.P90: 0.9, AggType.P95: 0.95, AggType.P99: 0.99,
    AggType.P999: 0.999, AggType.P9999: 0.9999,
}

_COUNTER_VALID = {AggType.MIN, AggType.MAX, AggType.MEAN, AggType.COUNT,
                  AggType.SUM, AggType.SUMSQ, AggType.STDEV}
_GAUGE_VALID = _COUNTER_VALID | {AggType.LAST}

_TYPE_STRINGS = {
    AggType.LAST: "last", AggType.SUM: "sum", AggType.SUMSQ: "sum_sq",
    AggType.MEAN: "mean", AggType.MIN: "lower", AggType.MAX: "upper",
    AggType.COUNT: "count", AggType.STDEV: "stdev", AggType.MEDIAN: "median",
}

# Defaults per metric type (types_options.go:125-145).
DEFAULT_COUNTER_AGGREGATION_TYPES = (AggType.SUM,)
DEFAULT_TIMER_AGGREGATION_TYPES = (
    AggType.SUM, AggType.SUMSQ, AggType.MEAN, AggType.MIN, AggType.MAX,
    AggType.COUNT, AggType.STDEV, AggType.MEDIAN, AggType.P50, AggType.P95,
    AggType.P99,
)
DEFAULT_GAUGE_AGGREGATION_TYPES = (AggType.LAST,)


def default_types_for(mt: MetricType) -> tuple:
    return {
        MetricType.COUNTER: DEFAULT_COUNTER_AGGREGATION_TYPES,
        MetricType.TIMER: DEFAULT_TIMER_AGGREGATION_TYPES,
        MetricType.GAUGE: DEFAULT_GAUGE_AGGREGATION_TYPES,
    }[mt]


def is_expensive(types: Sequence[AggType]) -> bool:
    """Whether sumSq tracking is required (common.go:37 isExpensive)."""
    return AggType.SUMSQ in types or AggType.STDEV in types


class AggID:
    """Compressed aggregation-types bitmask (aggregation/id.go AggregationID).

    A Types list packs into one int bitmask for cheap wire transfer and
    equality; DEFAULT (0) means "use the metric type's defaults".
    """

    DEFAULT = 0

    @staticmethod
    def compress(types: Sequence[AggType]) -> int:
        mask = 0
        for t in types:
            if t == AggType.UNKNOWN:
                raise ValueError("cannot compress UNKNOWN aggregation type")
            mask |= 1 << int(t)
        return mask

    @staticmethod
    def decompress(mask: int) -> tuple:
        return tuple(t for t in AggType if t != AggType.UNKNOWN and mask & (1 << int(t)))


def parse_types(s: str) -> tuple:
    """Parse 'Sum,Max,P99' (type.go ParseTypes)."""
    out = []
    for part in s.split(","):
        part = part.strip()
        try:
            out.append(AggType[part.upper()])
        except KeyError:
            raise ValueError(f"invalid aggregation type {part!r}") from None
    return tuple(out)
