"""Storage policies (reference: src/metrics/policy/{storage_policy,
resolution,retention,staged_policy,drop_policy}.go)."""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

from ..utils import xtime


@dataclasses.dataclass(frozen=True, order=True)
class Resolution:
    """Sampling resolution: window size + stored precision (resolution.go:43)."""

    window_ns: int
    precision: xtime.Unit = xtime.Unit.NONE

    def __post_init__(self):
        if self.precision == xtime.Unit.NONE:
            object.__setattr__(self, "precision", xtime.Unit.from_duration_ns(self.window_ns))

    def __str__(self) -> str:
        w = xtime.format_duration(self.window_ns)
        if xtime.Unit.from_duration_ns(self.window_ns) == self.precision:
            return w
        return f"{w}@1{_UNIT_SUFFIX[self.precision]}"


_UNIT_SUFFIX = {
    xtime.Unit.SECOND: "s", xtime.Unit.MILLISECOND: "ms",
    xtime.Unit.MICROSECOND: "us", xtime.Unit.NANOSECOND: "ns",
    xtime.Unit.MINUTE: "m", xtime.Unit.HOUR: "h", xtime.Unit.DAY: "d",
}
_SUFFIX_UNIT = {v: k for k, v in _UNIT_SUFFIX.items()}


@dataclasses.dataclass(frozen=True, order=True)
class StoragePolicy:
    """resolution:retention pair, e.g. '10s:2d' or '1m@1s:40d'
    (storage_policy.go:25, String :54)."""

    resolution: Resolution
    retention_ns: int

    @staticmethod
    def of(window: str, retention: str, precision: Optional[str] = None) -> "StoragePolicy":
        res = Resolution(
            xtime.parse_duration(window),
            _SUFFIX_UNIT[precision] if precision else xtime.Unit.NONE,
        )
        return StoragePolicy(res, xtime.parse_duration(retention))

    @staticmethod
    def parse(s: str) -> "StoragePolicy":
        """Parse 'window[@1precision]:retention' (storage_policy.go
        ParseStoragePolicy). Memoized: policies are drawn from a handful
        of configured strings but arrive once per datapoint on the
        aggregator's timed-metric wire, where re-parsing was 37% of the
        per-entry dispatch cost; instances are frozen so sharing is safe."""
        return _parse_storage_policy(s)

    def __str__(self) -> str:
        return f"{self.resolution}:{xtime.format_duration(self.retention_ns)}"


@functools.lru_cache(maxsize=1024)
def _parse_storage_policy(s: str) -> StoragePolicy:
    res_s, _, ret_s = s.partition(":")
    if not ret_s:
        raise ValueError(f"invalid storage policy {s!r}")
    win_s, _, prec_s = res_s.partition("@")
    precision = xtime.Unit.NONE
    if prec_s:
        if not prec_s.startswith("1") or prec_s[1:] not in _SUFFIX_UNIT:
            raise ValueError(f"invalid precision in storage policy {s!r}")
        precision = _SUFFIX_UNIT[prec_s[1:]]
    return StoragePolicy(Resolution(xtime.parse_duration(win_s), precision),
                         xtime.parse_duration(ret_s))


@dataclasses.dataclass(frozen=True)
class Policy:
    """StoragePolicy + aggregation-types override bitmask (policy.go Policy)."""

    storage_policy: StoragePolicy
    aggregation_id: int = 0  # AggID.DEFAULT means metric-type defaults


@dataclasses.dataclass(frozen=True)
class StagedPolicies:
    """Policies active from a cutover time (staged_policy.go)."""

    cutover_nanos: int
    tombstoned: bool
    policies: Tuple[Policy, ...] = ()


class DropPolicy:
    """Whether a mapping rule drops the metric entirely (drop_policy.go)."""

    NONE = 0
    DROP_MUST = 1
    DROP_IF_ONLY_MATCH = 2


DEFAULT_STAGED_POLICIES = StagedPolicies(0, False, ())
