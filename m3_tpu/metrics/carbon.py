"""Carbon plaintext protocol parser (reference: src/metrics/carbon/parser.go
— 'dotted.metric.path value unix_timestamp\\n' lines).

Graphite paths map onto the tag model the way the reference coordinator
ingests carbon: path component i becomes tag __g{i}__ (m3 coordinator
graphite ingestion convention), so the same inverted index serves both
prom-style and graphite queries."""

from __future__ import annotations

import math
from typing import Dict, Iterator, Optional, Tuple

GRAPHITE_TAG_FMT = b"__g%d__"


def parse_line(line: bytes) -> Optional[Tuple[bytes, float, int]]:
    """One carbon line -> (path, value, unix_seconds); None if malformed
    (parser.go Parse: silently skips bad lines, counting errors)."""
    parts = line.strip().split()
    if len(parts) != 3:
        return None
    path, val_s, ts_s = parts
    if not path or path.startswith(b".") or path.endswith(b"."):
        return None
    try:
        value = float(val_s)
        ts = int(float(ts_s))
    except ValueError:
        return None
    if math.isnan(value):
        return None
    return path, value, ts


def parse_lines(data: bytes) -> Iterator[Tuple[bytes, float, int]]:
    for line in data.splitlines():
        if not line.strip():
            continue
        parsed = parse_line(line)
        if parsed is not None:
            yield parsed


def path_to_tags(path: bytes) -> Dict[bytes, bytes]:
    """'servers.web01.cpu' -> {__g0__: servers, __g1__: web01, __g2__: cpu}."""
    tags = {}
    for i, part in enumerate(path.split(b".")):
        tags[GRAPHITE_TAG_FMT % i] = part
    return tags


def tags_to_path(tags: Dict[bytes, bytes]) -> bytes:
    """Inverse of path_to_tags over however many __gN__ tags exist."""
    parts = []
    i = 0
    while True:
        part = tags.get(GRAPHITE_TAG_FMT % i)
        if part is None:
            break
        parts.append(part)
        i += 1
    return b".".join(parts)
