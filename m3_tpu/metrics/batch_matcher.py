"""Compiled batch rule matching: one KV rule-set version compiled into
index queries, evaluated over a per-batch inverted segment.

The per-metric path (rules.ActiveRuleSet.forward_match) evaluates every
rule's TagsFilter against every metric id — each check re-decodes the id
and runs per-tag regexes, so a 100k-id batch against a 1k-rule set pays
~10^8 Python-level filter evaluations. This module inverts the loop into
the PR 3 index machinery:

  * compile: every ACTIVE rule snapshot's TagsFilter translates ONCE per
    (rule-set version, snapshot epoch) into an index Query — literal
    glob patterns become TermQuery, glob patterns become RegexpQuery
    (same compiled-regex semantics as filters.Filter), '!'-negated
    patterns become NegationQuery (tag absence satisfies negation via
    postings complement, exactly the TagsFilter absence rule). The
    compiled set is valid until the next rule cutover.
  * match: the batch's distinct ids become Documents in ONE
    MutableSegment -> ImmutableSegment (TermDict + postings inversion);
    each snapshot query runs once over the whole segment (vectorized
    binary search + bitmap algebra, literal-prefix prune for globs), and
    per-row results assemble from the per-snapshot row sets.

Row assembly replicates ActiveRuleSet._match_at / forward_match
structurally (rule-order pipeline merging, dict.fromkeys dedup, rollup
new-id generation, last-wins duplicate-rollup-id merge, cutover = max of
matched snapshot cutovers including tombstoned ones), so results are
EQUAL (dataclass equality) to the per-metric oracle — the property suite
(tests/test_batch_matcher.py) and the downsample_rules bench hold the
two paths identical."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from ..index.postings_cache import PostingsListCache
from ..index.query import (
    AllQuery,
    NegationQuery,
    Query,
    RegexpQuery,
    TermQuery,
    new_conjunction,
)
from ..index.segment import Document, ImmutableSegment, MutableSegment, execute
from . import id as metric_id
from .filters import TagsFilter, _glob_to_regex
from .metadata import IDWithMetadatas, Metadata, PipelineMetadata, StagedMetadata
from .rules import ActiveRuleSet, MatchResult

_NAME_FIELD = b"__name__"
_GLOB_META = set("*?[{")


def filter_to_query(tf: TagsFilter) -> Query:
    """TagsFilter -> index Query with identical match semantics.

    Positive pattern: docs holding the tag with a matching value (tag
    absence fails — absent tags simply have no postings). Negated
    pattern: complement of the inner query (tag absence satisfies it).
    Empty filter: AllQuery (filters.MATCH_ALL)."""
    parts: List[Query] = []
    for key, pattern in tf.patterns.items():
        field = _NAME_FIELD if key == TagsFilter.NAME_KEY else key.encode()
        negate = pattern.startswith("!")
        body = pattern[1:] if negate else pattern
        if _GLOB_META.isdisjoint(body):
            inner: Query = TermQuery(field, body.encode())
        else:
            # Same anchored-regex compilation as filters.Filter (the
            # segment matches terms with pattern.fullmatch, so the
            # trailing '$' is redundant but keeps the bytes identical to
            # the per-metric compiled form).
            inner = RegexpQuery(field, _glob_to_regex(body).encode() + b"$")
        parts.append(NegationQuery(inner) if negate else inner)
    if not parts:
        return AllQuery()
    return new_conjunction(*parts)


@dataclasses.dataclass(frozen=True)
class _MappingEntry:
    query: Query
    cutover_nanos: int
    tombstoned: bool
    pipeline: Optional[PipelineMetadata]  # None when tombstoned


@dataclasses.dataclass(frozen=True)
class _RollupEntry:
    query: Query
    cutover_nanos: int
    tombstoned: bool
    # Targets whose pipeline STARTS with the rollup generate new ids:
    # (rollup op, shared sub-pipeline metadata). Others aggregate under
    # the existing id.
    new_id_targets: Tuple[tuple, ...]
    existing_targets: Tuple[PipelineMetadata, ...]


class CompiledRuleSet:
    """One ActiveRuleSet compiled at a snapshot epoch.

    Valid for match times in [compiled-at, expire_at): the active
    snapshot per rule cannot change inside that window (expire_at is the
    rule set's next cutover), so the per-snapshot queries and shared
    PipelineMetadata objects are reusable for every batch until then."""

    __slots__ = ("version", "expire_at_nanos", "mapping", "rollup")

    def __init__(self, active: ActiveRuleSet, t_nanos: int):
        self.version = active.version
        self.expire_at_nanos = active._next_cutover(t_nanos)
        self.mapping: List[_MappingEntry] = []
        for rule in active.mapping_rules:
            snap = rule.active_snapshot(t_nanos)
            if snap is None:
                continue
            pm = None
            if not snap.tombstoned:
                pm = PipelineMetadata(snap.aggregation_id,
                                      snap.storage_policies,
                                      drop_policy=snap.drop_policy)
            self.mapping.append(_MappingEntry(
                filter_to_query(snap.filter), snap.cutover_nanos,
                snap.tombstoned, pm))
        self.rollup: List[_RollupEntry] = []
        for rule in active.rollup_rules:
            snap = rule.active_snapshot(t_nanos)
            if snap is None:
                continue
            new_id_targets: List[tuple] = []
            existing: List[PipelineMetadata] = []
            if not snap.tombstoned:
                for target in snap.targets:
                    ops = target.pipeline.ops
                    if ops and ops[0].rollup is not None:
                        rop = ops[0].rollup
                        new_id_targets.append((rop, PipelineMetadata(
                            rop.aggregation_id, target.storage_policies,
                            target.pipeline.sub(1))))
                    else:
                        existing.append(PipelineMetadata(
                            0, target.storage_policies, target.pipeline))
            self.rollup.append(_RollupEntry(
                filter_to_query(snap.filter), snap.cutover_nanos,
                snap.tombstoned, tuple(new_id_targets), tuple(existing)))

    def has_expired(self, t_nanos: int) -> bool:
        return t_nanos >= self.expire_at_nanos


def build_segment(mids: Sequence[bytes],
                  decoded: Optional[Sequence[tuple]] = None
                  ) -> Tuple[ImmutableSegment, List[tuple]]:
    """Invert a batch of encoded metric ids into an immutable segment.

    Returns (segment, decoded) where decoded[i] = (name, tags dict) —
    the rollup-id generator needs the tags again, so decode is paid once
    per id for the whole match (the per-metric path re-decodes per
    RULE)."""
    if decoded is None:
        decoded = [metric_id.decode(mid) for mid in mids]
    seg = MutableSegment()
    docs = [
        Document(mid, ((_NAME_FIELD, name), *tags.items()))
        for mid, (name, tags) in zip(mids, decoded)
    ]
    seg.insert_batch(docs)
    return ImmutableSegment.from_mutable(seg), list(decoded)


def match_batch(compiled: CompiledRuleSet, mids: Sequence[bytes],
                t_nanos: int,
                decoded: Optional[Sequence[tuple]] = None
                ) -> List[MatchResult]:
    """Match every id in the batch in one pass per rule snapshot.

    Equivalent to [active.forward_match(mid, t, t + 1) for mid in mids]
    with t inside the compiled set's validity window (a streaming match
    at `now`: the [t, t+1) range never crosses a cutover, since the next
    cutover is strictly greater than t)."""
    assert not compiled.has_expired(t_nanos), "stale compiled rule set"
    seg, decoded = build_segment(mids, decoded)
    # Everything below is indexed by segment POSITION: duplicate mids
    # share one document, so positions are NOT input order — route the
    # decoded (name, tags) through the id -> position table before the
    # rollup-id generator reads tags.
    n = len(seg)
    pos = {seg.doc(i).id: i for i in range(n)}
    dec_by_pos: List[tuple] = [None] * n
    for mid, dec in zip(mids, decoded):
        dec_by_pos[pos[mid]] = dec
    # Per-batch leaf cache: distinct snapshots frequently share terms
    # (the same tag filter across many rules resolves one postings list).
    cache = PostingsListCache()
    cutovers = [0] * n
    map_pipes: List[List[PipelineMetadata]] = [[] for _ in range(n)]
    roll_pipes: List[List[PipelineMetadata]] = [[] for _ in range(n)]
    roll_new: List[List[tuple]] = [[] for _ in range(n)]
    for entry in compiled.mapping:
        rows = execute(seg, entry.query, cache).tolist()
        c = entry.cutover_nanos
        for r in rows:
            if c > cutovers[r]:
                cutovers[r] = c
        if entry.tombstoned:
            continue
        pm = entry.pipeline
        for r in rows:
            map_pipes[r].append(pm)
    for entry in compiled.rollup:
        rows = execute(seg, entry.query, cache).tolist()
        c = entry.cutover_nanos
        for r in rows:
            if c > cutovers[r]:
                cutovers[r] = c
        if entry.tombstoned:
            continue
        for rop, pm in entry.new_id_targets:
            for r in rows:
                rid = metric_id.rollup_id(rop.new_name, dec_by_pos[r][1],
                                          rop.tags)
                roll_new[r].append((rid, pm))
        for pm in entry.existing_targets:
            for r in rows:
                roll_pipes[r].append(pm)
    expire = compiled.expire_at_nanos
    version = compiled.version
    out: List[MatchResult] = []
    memo: Dict[int, MatchResult] = {}
    for mid in mids:
        r = pos[mid]
        hit = memo.get(r)
        if hit is not None:
            out.append(hit)
            continue
        cutover = cutovers[r]
        pipelines = tuple(dict.fromkeys(map_pipes[r] + roll_pipes[r]))
        staged = StagedMetadata(cutover, False, Metadata(pipelines))
        # Mirror _match_at + forward_match exactly: sort by rollup id,
        # then the dict rebuild keeps the LAST entry per duplicate id.
        for_new = {
            rid: (StagedMetadata(cutover, False, Metadata((pm,))),)
            for rid, pm in sorted(roll_new[r], key=lambda x: x[0])
        }
        result = MatchResult(
            version, expire, (staged,),
            tuple(IDWithMetadatas(k, v) for k, v in sorted(for_new.items())))
        memo[r] = result
        out.append(result)
    return out
