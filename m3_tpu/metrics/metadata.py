"""Metric metadata binding matched metrics to pipelines + storage policies
(reference: src/metrics/metadata/metadata.go)."""

from __future__ import annotations

import dataclasses
from typing import Tuple

from .pipeline import EMPTY_PIPELINE, Pipeline
from .policy import StoragePolicy


@dataclasses.dataclass(frozen=True)
class PipelineMetadata:
    """One pipeline a metric must flow through: aggregation-types bitmask,
    storage policies for its output, and remaining pipeline ops."""

    aggregation_id: int = 0
    storage_policies: Tuple[StoragePolicy, ...] = ()
    pipeline: Pipeline = EMPTY_PIPELINE
    drop_policy: int = 0

    def is_default(self) -> bool:
        return (
            self.aggregation_id == 0
            and not self.storage_policies
            and self.pipeline.is_empty()
            and self.drop_policy == 0
        )


@dataclasses.dataclass(frozen=True)
class Metadata:
    pipelines: Tuple[PipelineMetadata, ...] = ()


@dataclasses.dataclass(frozen=True)
class StagedMetadata:
    """Metadata active from a cutover time (metadata.go StagedMetadata)."""

    cutover_nanos: int = 0
    tombstoned: bool = False
    metadata: Metadata = Metadata()

    def is_default(self) -> bool:
        return self.cutover_nanos == 0 and not self.tombstoned and not self.metadata.pipelines


@dataclasses.dataclass(frozen=True)
class ForwardMetadata:
    """Metadata for a forwarded (multi-stage pipeline) metric
    (metadata.go ForwardMetadata)."""

    aggregation_id: int
    storage_policy: StoragePolicy
    pipeline: Pipeline
    source_id: bytes
    num_forwarded_times: int


DEFAULT_STAGED_METADATA = StagedMetadata()


@dataclasses.dataclass(frozen=True)
class IDWithMetadatas:
    id: bytes
    metadatas: Tuple[StagedMetadata, ...]
