"""Tag glob filters (reference: src/metrics/filters/filter.go).

Pattern language: '*' wildcards, '?' single char, '[a-z]' ranges, '{a,b}'
alternatives, leading '!' negation (filter.go:53-61). Patterns compile to
anchored regexes once; a TagsFilter is the conjunction of per-tag patterns
plus an optional metric-name pattern (filters/tags_filter.go)."""

from __future__ import annotations

import re
from typing import Dict, Mapping, Optional

from . import id as metric_id

_SPECIAL = set(".^$+()|\\")


def _glob_to_regex(pattern: str) -> str:
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            out.append(".*")
        elif c == "?":
            out.append(".")
        elif c == "[":
            j = pattern.find("]", i + 1)
            if j < 0:
                raise ValueError(f"unterminated range in filter {pattern!r}")
            out.append(pattern[i : j + 1])
            i = j
        elif c == "{":
            j = pattern.find("}", i + 1)
            if j < 0:
                raise ValueError(f"unterminated alternation in filter {pattern!r}")
            inner = pattern[i + 1 : j]
            if any(ch in inner for ch in "?[{"):
                raise ValueError(f"invalid nested pattern in filter {pattern!r}")
            alts = [re.escape(a) for a in inner.split(",")]
            out.append("(?:" + "|".join(alts) + ")")
            i = j
        elif c in _SPECIAL:
            out.append("\\" + c)
        else:
            out.append(c)
        i += 1
    return "".join(out)


class Filter:
    """Single-value glob filter with optional '!' negation (filter.go:88)."""

    def __init__(self, pattern: str):
        self.pattern = pattern
        negate = pattern.startswith("!")
        if negate and len(pattern) == 1:
            raise ValueError("invalid filter pattern: bare negation")
        body = pattern[1:] if negate else pattern
        self._negate = negate
        self._re = re.compile(_glob_to_regex(body).encode() + b"$")

    def matches(self, value: bytes) -> bool:
        ok = self._re.fullmatch(value) is not None
        return ok != self._negate

    def __repr__(self):
        return f"Filter({self.pattern!r})"


class TagsFilter:
    """Conjunction of tag-name -> pattern filters; tag absence fails a
    positive pattern and satisfies a negated one (tags_filter.go)."""

    NAME_KEY = "__name__"

    def __init__(self, filters: Mapping[str, str]):
        self.patterns = dict(filters)
        self._name: Optional[Filter] = None
        self._tags: Dict[bytes, Filter] = {}
        for key, pattern in filters.items():
            f = Filter(pattern)
            if key == self.NAME_KEY:
                self._name = f
            else:
                self._tags[key.encode()] = f

    def matches(self, mid: bytes) -> bool:
        name, tags = metric_id.decode(mid)
        if self._name is not None and not self._name.matches(name):
            return False
        for key, f in self._tags.items():
            value = tags.get(key)
            if value is None:
                if not f._negate:
                    return False
            elif not f.matches(value):
                return False
        return True

    def __repr__(self):
        return f"TagsFilter({self.patterns!r})"

    def to_json(self) -> Dict[str, str]:
        return dict(self.patterns)

    @staticmethod
    def from_json(obj: Mapping[str, str]) -> "TagsFilter":
        return TagsFilter(obj)


MATCH_ALL = TagsFilter({})
