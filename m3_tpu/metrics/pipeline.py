"""Operation pipelines (reference: src/metrics/pipeline/type.go and
pipeline/applied): ordered aggregate -> transform -> rollup stages that a
matched metric flows through, possibly hopping aggregator tiers."""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Tuple

from .aggregation import AggType
from .transformation import TransformType


class OpType(enum.IntEnum):
    """Pipeline operation kinds (pipeline/type.go OpType)."""

    UNKNOWN = 0
    AGGREGATION = 1
    TRANSFORMATION = 2
    ROLLUP = 3


@dataclasses.dataclass(frozen=True)
class RollupOp:
    """Roll up into a new metric keeping `tags` dimensions, aggregated with
    `aggregation_id` (pipeline/type.go RollupOp)."""

    new_name: bytes
    tags: Tuple[bytes, ...]
    aggregation_id: int = 0


@dataclasses.dataclass(frozen=True)
class Op:
    type: OpType
    aggregation: Optional[AggType] = None
    transformation: Optional[TransformType] = None
    rollup: Optional[RollupOp] = None

    @staticmethod
    def aggregate(t: AggType) -> "Op":
        return Op(OpType.AGGREGATION, aggregation=t)

    @staticmethod
    def transform(t: TransformType) -> "Op":
        return Op(OpType.TRANSFORMATION, transformation=t)

    @staticmethod
    def roll(new_name: bytes, tags, aggregation_id: int = 0) -> "Op":
        return Op(OpType.ROLLUP, rollup=RollupOp(new_name, tuple(tags), aggregation_id))


@dataclasses.dataclass(frozen=True)
class Pipeline:
    """Ordered list of ops (pipeline/type.go Pipeline)."""

    ops: Tuple[Op, ...] = ()

    def at(self, i: int) -> Op:
        return self.ops[i]

    def __len__(self):
        return len(self.ops)

    def is_empty(self) -> bool:
        return not self.ops

    def sub(self, start: int, end: Optional[int] = None) -> "Pipeline":
        return Pipeline(self.ops[start:end])


EMPTY_PIPELINE = Pipeline()
