"""Mapping + rollup rules with time-versioned snapshots and forward matching
(reference: src/metrics/rules/{mapping,rollup,ruleset,active_ruleset}.go).

A rule is a list of snapshots, each active from its cutover time until the
next snapshot's cutover (or tombstoned). An ActiveRuleSet matches a metric ID
over a [from, to) time range by evaluating at `from` and at every rule
cutover inside the range, merging results into staged metadatas — so a rule
change mid-range produces a metadata stage at exactly its cutover
(active_ruleset.go:102-144 ForwardMatch)."""

from __future__ import annotations

import bisect
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from . import id as metric_id
from .filters import TagsFilter
from .metadata import (
    IDWithMetadatas,
    Metadata,
    PipelineMetadata,
    StagedMetadata,
)
from .pipeline import Pipeline, RollupOp
from .policy import StoragePolicy


@dataclasses.dataclass(frozen=True)
class MappingRuleSnapshot:
    """One state of a mapping rule (rules/mapping.go mappingRuleSnapshot)."""

    name: str
    cutover_nanos: int
    filter: TagsFilter
    aggregation_id: int = 0
    storage_policies: Tuple[StoragePolicy, ...] = ()
    drop_policy: int = 0
    tombstoned: bool = False


@dataclasses.dataclass(frozen=True)
class RollupTarget:
    """A rollup pipeline + its output storage policies
    (rules/rollup_target.go)."""

    pipeline: Pipeline
    storage_policies: Tuple[StoragePolicy, ...]


@dataclasses.dataclass(frozen=True)
class RollupRuleSnapshot:
    name: str
    cutover_nanos: int
    filter: TagsFilter
    targets: Tuple[RollupTarget, ...] = ()
    tombstoned: bool = False


class Rule:
    """Snapshots sorted by cutover; activeSnapshot(t) = last with cutover <= t
    (mapping.go activeSnapshot)."""

    def __init__(self, snapshots: Sequence):
        self.snapshots = sorted(snapshots, key=lambda s: s.cutover_nanos)
        self._cutovers = [s.cutover_nanos for s in self.snapshots]

    def active_snapshot(self, t_nanos: int):
        i = bisect.bisect_right(self._cutovers, t_nanos) - 1
        return self.snapshots[i] if i >= 0 else None


@dataclasses.dataclass(frozen=True)
class MatchResult:
    """active_ruleset.go MatchResult: staged metadatas for the existing ID,
    metadatas for new rollup IDs, and when this result expires."""

    version: int
    expire_at_nanos: int
    for_existing_id: Tuple[StagedMetadata, ...]
    for_new_rollup_ids: Tuple[IDWithMetadatas, ...]

    def has_expired(self, t_nanos: int) -> bool:
        return t_nanos >= self.expire_at_nanos


class ActiveRuleSet:
    """Matches IDs against active mapping + rollup rule snapshots."""

    def __init__(self, version: int, mapping_rules: Sequence[Rule], rollup_rules: Sequence[Rule]):
        self.version = version
        self.mapping_rules = list(mapping_rules)
        self.rollup_rules = list(rollup_rules)
        cutovers = set()
        for rule in [*self.mapping_rules, *self.rollup_rules]:
            cutovers.update(rule._cutovers)
        self.cutover_times_asc = sorted(cutovers)

    def _next_cutover(self, t_nanos: int) -> int:
        i = bisect.bisect_right(self.cutover_times_asc, t_nanos)
        if i < len(self.cutover_times_asc):
            return self.cutover_times_asc[i]
        return 2**63 - 1

    def _mappings_at(self, mid: bytes, t_nanos: int) -> Tuple[int, List[PipelineMetadata]]:
        cutover, pipelines = 0, []
        for rule in self.mapping_rules:
            snap = rule.active_snapshot(t_nanos)
            if snap is None or not snap.filter.matches(mid):
                continue
            cutover = max(cutover, snap.cutover_nanos)
            if snap.tombstoned:
                continue
            pipelines.append(
                PipelineMetadata(snap.aggregation_id, snap.storage_policies, drop_policy=snap.drop_policy)
            )
        return cutover, pipelines

    def _rollups_at(self, mid: bytes, t_nanos: int):
        """Returns (cutover, pipelines for existing id, list of (rollup_id,
        pipeline metadata)) — a rollup whose first op is the rollup itself
        generates a new ID immediately (active_ruleset.go rollupResultsFor)."""
        cutover, for_existing, for_new = 0, [], []
        name, tags = metric_id.decode(mid)
        for rule in self.rollup_rules:
            snap = rule.active_snapshot(t_nanos)
            if snap is None or not snap.filter.matches(mid):
                continue
            cutover = max(cutover, snap.cutover_nanos)
            if snap.tombstoned:
                continue
            for target in snap.targets:
                ops = target.pipeline.ops
                if ops and ops[0].rollup is not None:
                    rop: RollupOp = ops[0].rollup
                    rid = metric_id.rollup_id(rop.new_name, tags, rop.tags)
                    for_new.append(
                        (rid, PipelineMetadata(rop.aggregation_id, target.storage_policies, target.pipeline.sub(1)))
                    )
                else:
                    for_existing.append(PipelineMetadata(0, target.storage_policies, target.pipeline))
        return cutover, for_existing, for_new

    def _match_at(self, mid: bytes, t_nanos: int):
        mc, mapping_pipes = self._mappings_at(mid, t_nanos)
        rc, rollup_existing, rollup_new = self._rollups_at(mid, t_nanos)
        cutover = max(mc, rc)
        pipelines = tuple(dict.fromkeys(mapping_pipes + rollup_existing))
        staged = StagedMetadata(cutover, False, Metadata(pipelines))
        new_ids = tuple(
            IDWithMetadatas(rid, (StagedMetadata(cutover, False, Metadata((pm,))),))
            for rid, pm in sorted(rollup_new, key=lambda x: x[0])
        )
        return staged, new_ids

    def forward_match(self, mid: bytes, from_nanos: int, to_nanos: int) -> MatchResult:
        staged, new_ids = self._match_at(mid, from_nanos)
        for_existing = [staged]
        for_new: Dict[bytes, List[StagedMetadata]] = {i.id: list(i.metadatas) for i in new_ids}
        next_cutover = self._next_cutover(from_nanos)
        while next_cutover < to_nanos:
            staged_n, new_ids_n = self._match_at(mid, next_cutover)
            if staged_n.metadata != for_existing[-1].metadata:
                for_existing.append(dataclasses.replace(staged_n, cutover_nanos=next_cutover))
            for idm in new_ids_n:
                stages = for_new.setdefault(idm.id, [])
                for sm in idm.metadatas:
                    if not stages or stages[-1].metadata != sm.metadata:
                        stages.append(dataclasses.replace(sm, cutover_nanos=next_cutover))
            next_cutover = self._next_cutover(next_cutover)
        return MatchResult(
            self.version,
            next_cutover,
            tuple(for_existing),
            tuple(IDWithMetadatas(k, tuple(v)) for k, v in sorted(for_new.items())),
        )


class RuleSet:
    """A namespace's versioned rule set (rules/ruleset.go): immutable list of
    rules per version; activates into an ActiveRuleSet."""

    def __init__(self, namespace: bytes, version: int = 1,
                 mapping_rules: Sequence[Rule] = (), rollup_rules: Sequence[Rule] = (),
                 tombstoned: bool = False):
        self.namespace = namespace
        self.version = version
        self.mapping_rules = list(mapping_rules)
        self.rollup_rules = list(rollup_rules)
        self.tombstoned = tombstoned

    def active_set(self) -> ActiveRuleSet:
        return ActiveRuleSet(self.version, self.mapping_rules, self.rollup_rules)
