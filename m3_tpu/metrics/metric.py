"""Core metric value types (reference: src/metrics/metric/types.go and
metric/unaggregated/types.go MetricUnion)."""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Sequence, Tuple


class MetricType(enum.IntEnum):
    """Unaggregated metric types (metric/types.go)."""

    UNKNOWN = 0
    COUNTER = 1
    TIMER = 2
    GAUGE = 3


@dataclasses.dataclass(frozen=True)
class MetricUnion:
    """One unaggregated sample as ingested by the aggregator
    (metric/unaggregated/types.go MetricUnion): a counter int value, a gauge
    float value, or a batch of timer values."""

    type: MetricType
    id: bytes
    counter_val: int = 0
    batch_timer_val: Tuple[float, ...] = ()
    gauge_val: float = 0.0
    annotation: bytes = b""

    @staticmethod
    def counter(id: bytes, value: int) -> "MetricUnion":
        return MetricUnion(MetricType.COUNTER, id, counter_val=value)

    @staticmethod
    def batch_timer(id: bytes, values: Sequence[float]) -> "MetricUnion":
        return MetricUnion(MetricType.TIMER, id, batch_timer_val=tuple(values))

    @staticmethod
    def gauge(id: bytes, value: float) -> "MetricUnion":
        return MetricUnion(MetricType.GAUGE, id, gauge_val=value)


@dataclasses.dataclass(frozen=True)
class Metric:
    """An aggregated metric sample (metric/aggregated/types.go Metric)."""

    id: bytes
    time_nanos: int
    value: float


@dataclasses.dataclass(frozen=True)
class TimedMetric:
    """A timed metric with an explicit client timestamp."""

    type: MetricType
    id: bytes
    time_nanos: int
    value: float
