"""Metric lists: per-resolution collections of elems with batched device
consumption (reference: src/aggregator/aggregator/list.go:296 Flush).

The reference walks a linked list of elems and calls Consume on each, which
re-reduces one locked struct per bucket. Here Flush gathers every closed
bucket across all elems of the resolution, pads them into one
(buckets x max_values) float64 tile, and reduces the whole tile in a single
jitted call (window moments + exact sort quantiles from m3_tpu.ops.aggregation)
— one device launch per flush per resolution, vmapped across metrics, instead
of a Python loop of scalar folds.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .elem import STAT_DEPS, Elem, ElemKey, stat_column

_LANE = 128  # pad the value axis to lane multiples to limit recompiles


@functools.lru_cache(maxsize=64)
def _quantile_rank_fn(width: int, qs: Tuple[float, ...]):
    """Jitted batched rank selector: [B, width] f32 values + [B] counts ->
    [B, len(qs)] i32 indices of each quantile element within its row.

    The sort runs on device in f32 (what the VPU executes natively); only
    *indices* come back, and the host gathers the exact float64 values by
    index — so quantile outputs keep full f64 precision without the global
    x64 flag (ordering ties at f32 granularity pick either of two values
    that agree to 2^-24, far inside the reference CM sketch's eps-rank
    tolerance, quantile/cm/stream.go).
    """

    def fn(values, counts):
        mask = jnp.arange(width)[None, :] < counts[:, None]
        filled = jnp.where(mask, values, jnp.inf)
        order = jnp.argsort(filled, axis=-1).astype(jnp.int32)
        outs = []
        for q in qs:
            # Target rank ceil(q*n), q=0 -> rank 1 (cm/stream.go:160).
            rank = jnp.ceil(q * counts).astype(jnp.int32)
            idx = jnp.clip(jnp.maximum(rank, 1) - 1, 0, width - 1)
            outs.append(jnp.take_along_axis(order, idx[:, None], axis=-1)[:, 0])
        return jnp.stack(outs, axis=-1)

    return jax.jit(fn)


def _columnar_moments(buckets: List[np.ndarray], needed=None) -> dict:
    """Mergeable moments over a ragged bucket list as COLUMNAR f64 arrays
    (np.reduceat — exact f64, matching the reference's float64
    accumulators): sum/sumsq/count/min/max/first/last/m2, each [B].

    `needed` limits which columns are computed ("count" always is): a
    pure counter/gauge flush only pays for the sums/lasts it emits, not
    the m2 chain's extra full-length passes."""
    need = set(_STAT_KEYS if needed is None else needed)
    counts = np.array([b.size for b in buckets], dtype=np.int64)
    nonempty = counts > 0
    safe = [b if b.size else np.zeros(1) for b in buckets]
    sizes = np.maximum(counts, 1)
    starts = np.zeros(len(safe), dtype=np.int64)
    starts[1:] = np.cumsum(sizes)[:-1]
    cat = np.concatenate(safe)
    m = {"count": counts.astype(np.float64)}
    if need & {"sum", "m2"}:
        m["sum"] = sums = np.where(nonempty, np.add.reduceat(cat, starts), 0.0)
    if "sumsq" in need:
        m["sumsq"] = np.where(nonempty, np.add.reduceat(cat * cat, starts), 0.0)
    if "min" in need:
        m["min"] = np.where(nonempty, np.minimum.reduceat(cat, starts), np.inf)
    if "max" in need:
        m["max"] = np.where(nonempty, np.maximum.reduceat(cat, starts), -np.inf)
    if "first" in need:
        m["first"] = np.where(nonempty, cat[starts], 0.0)
    if "last" in need:
        m["last"] = np.where(nonempty, cat[starts + sizes - 1], 0.0)
    if "m2" in need:
        mu = np.where(nonempty, sums / sizes, 0.0)
        dev = cat - np.repeat(mu, sizes)
        m["m2"] = np.where(nonempty, np.add.reduceat(dev * dev, starts), 0.0)
    return m


def _quantile_rows_for(buckets: List[np.ndarray], qs: Tuple[float, ...]):
    """Batched device quantile ordering over a bucket list -> per-bucket
    {q: value} dicts (host gathers exact f64 values by device index)."""
    counts = np.array([b.size for b in buckets], dtype=np.int64)
    max_n = max(1, int(counts.max()))
    width = ((max_n + _LANE - 1) // _LANE) * _LANE
    tile = np.zeros((len(buckets), width), dtype=np.float32)
    for i, b in enumerate(buckets):
        tile[i, : b.size] = b
    idx = np.asarray(
        _quantile_rank_fn(width, qs)(tile, counts.astype(np.int32))
    )
    return [
        {
            q: float(buckets[i][min(idx[i, j], counts[i] - 1)]) if counts[i] else 0.0
            for j, q in enumerate(qs)
        }
        for i in range(len(buckets))
    ]


def batched_reduce(buckets: List[np.ndarray], qs: Tuple[float, ...]):
    """Reduce a ragged list of value arrays: mergeable moments + quantiles.

    Moments are one vectorized host pass (_columnar_moments); the heavy
    O(W log W) work, batched quantile ordering, runs on device. Returns
    (stats_rows, quantile_rows): per-bucket dicts of python floats.
    """
    if not buckets:
        return [], []
    m = _columnar_moments(buckets)
    stats_rows = _stats_rows(m, range(len(buckets)))
    if not qs:
        return stats_rows, [{} for _ in buckets]
    return stats_rows, _quantile_rows_for(buckets, qs)


def _stats_rows(m: dict, idxs) -> list:
    cols = [m[k] for k in _STAT_KEYS]
    return [dict(zip(_STAT_KEYS, (float(c[i]) for c in cols))) for i in idxs]


_STAT_KEYS = ("sum", "sumsq", "count", "min", "max", "first", "last", "m2")


def reduce_and_emit(jobs) -> int:
    """Reduce a batch of (elem, window_start, values, flush_fn, forward_fn)
    jobs — possibly gathered across many lists and shards — in one device
    call, then emit each window through its own sink.

    Emission is two-speed: elems with ONE non-quantile agg type and no
    pipeline (counters/gauges — the bulk of a metrics workload) emit
    straight from the columnar moment arrays with precomputed output ids;
    everything else (timers, pipelines, custom agg sets) goes through the
    general per-elem emit with its per-bucket stat/quantile dicts. The
    device quantile ordering only ever sees the buckets that need it."""
    if not jobs:
        return 0
    slow_idx = [i for i, j in enumerate(jobs) if j[0]._simple_type is None]
    if slow_idx:
        needed = None  # slow emit reads the full stats row
    else:
        needed = {k for j in jobs for k in STAT_DEPS[j[0]._simple_type]}
    m = _columnar_moments([j[2] for j in jobs], needed)
    # quantile ordering only over the slow jobs that want quantiles
    q_idx = [i for i in slow_idx if jobs[i][0]._quantiles]
    qrows = {}
    if q_idx:
        qs = tuple(sorted({q for i in q_idx for q in jobs[i][0]._quantiles}))
        for i, row in zip(q_idx, _quantile_rows_for(
                [jobs[i][2] for i in q_idx], qs)):
            qrows[i] = row
    if slow_idx:
        for i, srow in zip(slow_idx, _stats_rows(m, slow_idx)):
            elem, start, _, flush_fn, forward_fn = jobs[i]
            elem.emit(start, srow, qrows.get(i, {}), flush_fn, forward_fn)
    if len(slow_idx) < len(jobs):
        slow = set(slow_idx)
        cols = {}
        for i, (elem, start, _, flush_fn, _fw) in enumerate(jobs):
            if i in slow:
                continue
            at = elem._simple_type
            col = cols.get(at)
            if col is None:
                col = cols[at] = stat_column(at, m)
            flush_fn(elem._out_ids[at], start + elem.resolution_ns,
                     float(col[i]), elem.key.storage_policy)
    return len(jobs)


class MetricList:
    """All elems sharing one resolution (list.go metricList); flushes are
    aligned to resolution boundaries by the flush manager."""

    def __init__(self, resolution_ns: int):
        self.resolution_ns = resolution_ns
        self._elems: Dict[ElemKey, Elem] = {}

    def get_or_create(self, key: ElemKey, factory: Callable[[], Elem]) -> Elem:
        e = self._elems.get(key)
        if e is None:
            e = self._elems[key] = factory()
        elif e.tombstoned:
            # A metadata change removed this key and a later change re-added
            # it before GC drained the elem: revive it, otherwise collect()
            # drops it from the list and cached Entry references write into
            # an orphan that never flushes.
            e.tombstoned = False
        return e

    def __len__(self):
        return len(self._elems)

    def elems(self) -> List[Elem]:
        return list(self._elems.values())

    def collect(self, target_nanos: int) -> List[Tuple[Elem, int, np.ndarray]]:
        """Pop every window closed before target_nanos as (elem, start, values)
        jobs, and GC drained tombstoned elems (list.go removes closed elems)."""
        jobs = []
        for elem in self._elems.values():
            for start, vals in elem.closed_buckets(target_nanos):
                jobs.append((elem, start, vals))
        self._elems = {
            k: e for k, e in self._elems.items()
            if not (e.tombstoned and e.is_empty())
        }
        return jobs

    def flush(self, target_nanos: int, flush_fn: Callable,
              forward_fn: Optional[Callable] = None) -> int:
        """Consume every window closed before target_nanos across all elems in
        one batched device reduction. Returns number of windows consumed."""
        jobs = self.collect(target_nanos)
        reduce_and_emit([(e, s, v, flush_fn, forward_fn) for e, s, v in jobs])
        return len(jobs)


class MetricLists:
    """Resolution -> MetricList registry (list.go metricLists)."""

    def __init__(self):
        self._lists: Dict[int, MetricList] = {}

    def for_resolution(self, resolution_ns: int) -> MetricList:
        lst = self._lists.get(resolution_ns)
        if lst is None:
            lst = self._lists[resolution_ns] = MetricList(resolution_ns)
        return lst

    def resolutions(self) -> List[int]:
        return sorted(self._lists)

    def lists(self) -> List[MetricList]:
        return [self._lists[r] for r in sorted(self._lists)]
