"""Metric lists: per-resolution collections of elems with batched device
consumption (reference: src/aggregator/aggregator/list.go:296 Flush).

The reference walks a linked list of elems and calls Consume on each, which
re-reduces one locked struct per bucket. Here the flush is columnar end to
end: collect_into pops every closed bucket across all elems straight into a
FlushBatch (parallel row columns grouped by interned EmitClass — no
per-window job tuples), emit_batch reduces each class with host-exact f64
moments (np.reduceat, the reference's float64-accumulator contract) plus ONE
mesh-sharded device program for the exact sort-based timer quantile ordering
(parallel/agg_flush.py, rows partitioned over every attached device), and
emission lands as array slices — one columnar handler call or one tight
per-class loop, never a Python callback chain per datapoint. Rollup-pipeline
forwards coalesce into a per-round sink that ships as per-destination
batches (ForwardedWriter.forward_batch).

The pre-mesh host flush is retained VERBATIM as `reduce_and_emit_ref`, the
bit-exactness oracle (the PR 3/9 pattern): tests/test_agg_mesh.py and the
agg benches assert the columnar/mesh path bit-identical to it across
counter/gauge/timer mixes, empty/NaN windows, and pipeline forwarding.
"""

from __future__ import annotations

import functools
from bisect import bisect_right
from collections import deque
from itertools import repeat
from operator import attrgetter
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..ops import aggregation as aggops
from ..parallel import agg_flush
from .elem import STAT_DEPS, Elem, ElemKey, EmitClass, _concat, stat_column

_LANE = agg_flush.LANE  # pad the value axis to lane multiples (shared rule)


@functools.lru_cache(maxsize=64)
def _quantile_rank_fn(width: int, qs: Tuple[float, ...]):
    """Jitted batched rank selector: [B, width] f32 values + [B] counts ->
    [B, len(qs)] i32 indices of each quantile element within its row.

    The sort runs on device in f32 (what the VPU executes natively); only
    *indices* come back, and the host gathers the exact float64 values by
    index — so quantile outputs keep full f64 precision without the global
    x64 flag (ordering ties at f32 granularity pick either of two values
    that agree to 2^-24, far inside the reference CM sketch's eps-rank
    tolerance, quantile/cm/stream.go). The kernel body is shared with the
    mesh-sharded route (ops/aggregation.quantile_rank_select), so the two
    dispatches are bit-identical by construction.
    """

    def fn(values, counts):
        return aggops.quantile_rank_select(values, counts, qs)

    return jax.jit(fn)


def _columnar_moments(buckets: List[np.ndarray], needed=None) -> dict:
    """Mergeable moments over a ragged bucket list as COLUMNAR f64 arrays
    (np.reduceat — exact f64, matching the reference's float64
    accumulators): sum/sumsq/count/min/max/first/last/m2, each [B].

    `needed` limits which columns are computed ("count" always is): a
    pure counter/gauge flush only pays for the sums/lasts it emits, not
    the m2 chain's extra full-length passes."""
    need = set(_STAT_KEYS if needed is None else needed)
    counts = np.fromiter(map(attrgetter("size"), buckets), np.int64,
                         len(buckets))
    nonempty = counts > 0
    if nonempty.all():
        cat = np.concatenate(buckets)
        sizes = counts
    else:
        safe = [b if b.size else np.zeros(1) for b in buckets]
        sizes = np.maximum(counts, 1)
        cat = np.concatenate(safe)
    starts = np.zeros(len(buckets), dtype=np.int64)
    starts[1:] = np.cumsum(sizes)[:-1]
    m = {"count": counts.astype(np.float64)}
    if need & {"sum", "m2"}:
        m["sum"] = sums = np.where(nonempty, np.add.reduceat(cat, starts), 0.0)
    if "sumsq" in need:
        m["sumsq"] = np.where(nonempty, np.add.reduceat(cat * cat, starts), 0.0)
    if "min" in need:
        m["min"] = np.where(nonempty, np.minimum.reduceat(cat, starts), np.inf)
    if "max" in need:
        m["max"] = np.where(nonempty, np.maximum.reduceat(cat, starts), -np.inf)
    if "first" in need:
        m["first"] = np.where(nonempty, cat[starts], 0.0)
    if "last" in need:
        m["last"] = np.where(nonempty, cat[starts + sizes - 1], 0.0)
    if "m2" in need:
        mu = np.where(nonempty, sums / sizes, 0.0)
        dev = cat - np.repeat(mu, sizes)
        m["m2"] = np.where(nonempty, np.add.reduceat(dev * dev, starts), 0.0)
    return m


def _quantile_rows_for(buckets: List[np.ndarray], qs: Tuple[float, ...]):
    """Batched device quantile ordering over a bucket list -> per-bucket
    {q: value} dicts (host gathers exact f64 values by device index).
    Serves the retained oracle path and batched_reduce; the production
    flush orders through parallel/agg_flush.exact_quantile_values."""
    counts = np.array([b.size for b in buckets], dtype=np.int64)
    max_n = max(1, int(counts.max()))
    width = ((max_n + _LANE - 1) // _LANE) * _LANE
    tile = np.zeros((len(buckets), width), dtype=np.float32)
    for i, b in enumerate(buckets):
        tile[i, : b.size] = b
    idx = np.asarray(
        _quantile_rank_fn(width, qs)(tile, counts.astype(np.int32))
    )
    return [
        {
            q: float(buckets[i][min(idx[i, j], counts[i] - 1)]) if counts[i] else 0.0
            for j, q in enumerate(qs)
        }
        for i in range(len(buckets))
    ]


def batched_reduce(buckets: List[np.ndarray], qs: Tuple[float, ...]):
    """Reduce a ragged list of value arrays: mergeable moments + quantiles.

    Moments are one vectorized host pass (_columnar_moments); the heavy
    O(W log W) work, batched quantile ordering, runs on device. Returns
    (stats_rows, quantile_rows): per-bucket dicts of python floats.
    """
    if not buckets:
        return [], []
    m = _columnar_moments(buckets)
    stats_rows = _stats_rows(m, range(len(buckets)))
    if not qs:
        return stats_rows, [{} for _ in buckets]
    return stats_rows, _quantile_rows_for(buckets, qs)


def _stats_rows(m: dict, idxs) -> list:
    cols = [m[k] for k in _STAT_KEYS]
    return [dict(zip(_STAT_KEYS, (float(c[i]) for c in cols))) for i in idxs]


_STAT_KEYS = ("sum", "sumsq", "count", "min", "max", "first", "last", "m2")

def _reconcile_degraded(elem, b, vals):
    """Degraded-elem drain epilogue (rare; gated on the sticky
    `_degraded` flag a merging `_stage` sets BEFORE its merge becomes
    visible, so every drain that popped a merged slot lands here).

    Under the elem lock — serialized against further merges — this
    (1) normalizes popped chunk lists via `_concat`, and (2) sweeps the
    surviving buckets for the one lock-free hazard left: a merge that
    re-created a just-popped slot as [popped_chunk, late_value]. Chunks
    IDENTICAL (by id) to anything this drain popped are dropped from
    surviving slots, so an emitted window can never be re-emitted;
    identities are stable because `vals` keeps every popped object
    alive for the duration. Returns the normalized vals."""
    with elem._lock:
        emitted = set(map(id, vals))
        for v in vals:
            if type(v) is list:
                emitted.update(map(id, v))
        for s in list(b):
            slot = b[s]
            if type(slot) is list:
                keep = [c for c in slot if id(c) not in emitted]
                if len(keep) != len(slot):
                    if keep:
                        b[s] = keep
                    else:
                        del b[s]
        if not b:
            # nothing survives, so no chunk merge can be outstanding: a
            # stager racing this reset re-sets the flag under this same
            # lock before its merge becomes visible
            elem._degraded = False
        return [_concat(v) for v in vals]


# --------------------------------------------------------------- columnar flush


class _ClassRows:
    """Parallel row columns for one EmitClass: one starts/buckets entry
    per closed window; elems stored run-length ((elem, n_windows) runs —
    windows of one elem are contiguous and ascending), so the collect
    loop appends one run instead of repeating the elem per window and
    the id-column build expands runs with C-level list repeats."""

    __slots__ = ("runs", "starts", "buckets")

    def __init__(self):
        self.runs: List[tuple] = []
        self.starts: List[int] = []
        self.buckets: List[np.ndarray] = []


class FlushBatch:
    """Columnar staged flush: every closed window of one flush round —
    gathered across resolutions, lists and aggregation shards — grouped
    by interned EmitClass. This is the input of ONE emit_batch reduce,
    so all aggregation shards flush in one device program."""

    __slots__ = ("classes",)

    def __init__(self):
        self.classes: Dict[EmitClass, _ClassRows] = {}

    def rows_for(self, cls: EmitClass) -> _ClassRows:
        rows = self.classes.get(cls)
        if rows is None:
            rows = self.classes[cls] = _ClassRows()
        return rows

    def add(self, elem: Elem, start: int, values: np.ndarray):
        rows = self.rows_for(elem._eclass)
        rows.runs.append((elem, 1))
        rows.starts.append(start)
        rows.buckets.append(values)

    def __len__(self):
        return sum(len(r.starts) for r in self.classes.values())


def emit_batch(batch: FlushBatch, flush_fn: Callable,
               forward_fn: Optional[Callable] = None) -> int:
    """Reduce + emit one columnar flush batch.

    Per class: host-exact f64 moments over the class's buckets; quantile
    classes additionally feed ONE mesh-sharded ordering program covering
    every quantile row of the round (agg_flush.exact_quantile_values —
    timer quantile ordering fully on device, exact f64 values landed by
    one columnar gather). Emission consumes the result as array slices:
    a flush_fn exposing `handle_columnar` receives the round's columnar
    groups in ONE call; plain callables get a tight per-class loop.
    Rollup forwards collect into one sink, shipped per-destination via
    forward_fn.forward_batch when available."""
    classes = batch.classes
    if not classes:
        return 0
    # ---- one device ordering pass over every quantile row of the round
    q_slices: Dict[EmitClass, tuple] = {}
    q_classes = [(cls, rows) for cls, rows in classes.items() if cls.quantiles]
    if q_classes:
        qs = tuple(sorted({q for cls, _ in q_classes for q in cls.quantiles}))
        q_buckets: List[np.ndarray] = []
        spans = []
        for cls, rows in q_classes:
            spans.append((cls, len(q_buckets), len(q_buckets) + len(rows.buckets)))
            q_buckets.extend(rows.buckets)
        counts = np.fromiter((b.size for b in q_buckets), np.int64,
                             len(q_buckets))
        vals = agg_flush.exact_quantile_values(q_buckets, counts, qs)
        # Column indices resolved per CLASS (a handful per round), then
        # consumed positionally per row — the tuple-index keying that
        # replaces the old per-row float-equality quantile lookup.
        pos = {q: j for j, q in enumerate(qs)}
        for cls, a, b in spans:
            q_slices[cls] = vals[a:b][:, [pos[q] for q in cls.quantiles]]

    n = 0
    fsink: Optional[list] = [] if forward_fn is not None else None
    columnar = getattr(flush_fn, "handle_columnar", None)
    col_groups: Optional[list] = [] if columnar is not None else None
    # C-speed consumer for the map-driven callback shim: maxlen=0 KEEPS
    # NOTHING by design (it exists to drive the map, not to buffer).
    drain = deque(maxlen=0).extend  # m3lint: disable=unbounded-queue
    for cls, rows in classes.items():
        m = _columnar_moments(rows.buckets, cls.needed)
        nrows = len(rows.starts)
        n += nrows
        ends_arr = np.asarray(rows.starts, dtype=np.int64) + cls.res_ns
        qv = q_slices.get(cls)
        ends_l = None
        if cls.piped:
            ends_l = ends_arr.tolist()
            for at in cls.agg_types:
                qi = cls.q_idx.get(at)
                col = qv[:, qi] if qi is not None else stat_column(at, m)
                vl = np.asarray(col, dtype=np.float64).tolist()
                # Transforms are stateful per elem (prev-window datapoint),
                # so pipelines stay per-row — but rollup forwards append to
                # the shared sink and ship batched after the loop.
                i = 0
                for e, k in rows.runs:
                    pp = e._process_pipeline
                    for r in range(i, i + k):
                        pp(at, ends_l[r], vl[r], flush_fn, forward_fn,
                           fsink)
                    i += k
        else:
            for j, at in enumerate(cls.agg_types):
                qi = cls.q_idx.get(at)
                col = qv[:, qi] if qi is not None else stat_column(at, m)
                col = np.asarray(col, dtype=np.float64)
                if len(rows.runs) == nrows:  # all single-window runs
                    ids = [e._out_tuple[j] for e, _ in rows.runs]
                else:
                    ids = []
                    id_append, id_extend = ids.append, ids.extend
                    for e, k in rows.runs:
                        if k == 1:
                            id_append(e._out_tuple[j])
                        else:
                            id_extend([e._out_tuple[j]] * k)
                if col_groups is not None:
                    col_groups.append((ids, ends_arr, col, cls.policy))
                    continue
                if ends_l is None:
                    ends_l = ends_arr.tolist()
                # Compat shim for plain-callable sinks (tests, capture
                # lambdas): per-datapoint callbacks, but driven by the C
                # map loop; batch-capable handlers take the single
                # handle_columnar call below instead.
                drain(map(flush_fn, ids, ends_l, col.tolist(),
                          repeat(cls.policy)))
    if col_groups:
        columnar(col_groups)
    if fsink:
        forward_batch = getattr(forward_fn, "forward_batch", None)
        if forward_batch is not None:
            forward_batch(fsink)
        else:
            # Compat shim for plain-callable forward sinks (tests, the
            # embedded downsampler); routed writers batch per
            # destination through forward_batch above.
            # m3lint: disable=per-datapoint-callback-in-flush
            for item in fsink:
                forward_fn(*item)
    return n


def reduce_and_emit(jobs) -> int:
    """Reduce a batch of (elem, window_start, values, flush_fn, forward_fn)
    jobs — possibly gathered across many lists and shards — in one columnar
    pass, then emit each window through its sink.

    Compat shim over FlushBatch/emit_batch for tuple-job callers; the hot
    flush paths (MetricList.flush, Aggregator.flush) collect straight into
    a FlushBatch and never build per-window tuples."""
    if not jobs:
        return 0
    groups: Dict[tuple, tuple] = {}
    for j in jobs:
        key = (id(j[3]), id(j[4]))
        g = groups.get(key)
        if g is None:
            g = groups[key] = (FlushBatch(), j[3], j[4])
        g[0].add(j[0], j[1], j[2])
    for grp_batch, f, fw in groups.values():
        emit_batch(grp_batch, f, fw)
    return len(jobs)


def reduce_and_emit_ref(jobs) -> int:
    """The pre-mesh host flush, retained verbatim as the bit-exactness
    oracle for the columnar/mesh path (the PR 3/9 oracle pattern):
    reduces each job with the same host f64 moments, orders quantiles
    through the single-device _quantile_rows_for, and emits per window
    through Python callbacks. tests/test_agg_mesh.py and the agg benches
    assert emit_batch's output bit-identical to this."""
    if not jobs:
        return 0
    slow_idx = [i for i, j in enumerate(jobs) if j[0]._simple_type is None]
    if slow_idx:
        needed = None  # slow emit reads the full stats row
    else:
        needed = {k for j in jobs for k in STAT_DEPS[j[0]._simple_type]}
    m = _columnar_moments([j[2] for j in jobs], needed)
    # quantile ordering only over the slow jobs that want quantiles
    q_idx = [i for i in slow_idx if jobs[i][0]._quantiles]
    qrows = {}
    if q_idx:
        qs = tuple(sorted({q for i in q_idx for q in jobs[i][0]._quantiles}))
        for i, row in zip(q_idx, _quantile_rows_for(
                [jobs[i][2] for i in q_idx], qs)):
            qrows[i] = row
    if slow_idx:
        for i, srow in zip(slow_idx, _stats_rows(m, slow_idx)):
            elem, start, _, flush_fn, forward_fn = jobs[i]
            row = qrows.get(i)
            qvals = [row[q] for q in elem._quantiles] if row else ()
            elem.emit(start, srow, qvals, flush_fn, forward_fn)
    if len(slow_idx) < len(jobs):
        slow = set(slow_idx)
        cols = {}
        for i, (elem, start, _, flush_fn, _fw) in enumerate(jobs):
            if i in slow:
                continue
            at = elem._simple_type
            col = cols.get(at)
            if col is None:
                col = cols[at] = stat_column(at, m)
            flush_fn(elem._out_ids[at], start + elem.resolution_ns,
                     float(col[i]), elem.key.storage_policy)
    return len(jobs)


class MetricList:
    """All elems sharing one resolution (list.go metricList); flushes are
    aligned to resolution boundaries by the flush manager."""

    def __init__(self, resolution_ns: int):
        self.resolution_ns = resolution_ns
        self._elems: Dict[ElemKey, Elem] = {}

    def get_or_create(self, key: ElemKey, factory: Callable[[], Elem]) -> Elem:
        e = self._elems.get(key)
        if e is None:
            e = self._elems[key] = factory()
        elif e.tombstoned:
            # A metadata change removed this key and a later change re-added
            # it before GC drained the elem: revive it, otherwise collect()
            # drops it from the list and cached Entry references write into
            # an orphan that never flushes.
            e.tombstoned = False
        return e

    def __len__(self):
        return len(self._elems)

    def elems(self) -> List[Elem]:
        return list(self._elems.values())

    def collect(self, target_nanos: int) -> List[Tuple[Elem, int, np.ndarray]]:
        """Pop every window closed before target_nanos as (elem, start, values)
        jobs, and GC drained tombstoned elems (list.go removes closed elems).
        Tuple-job compat path (follower discard, tests); the flush hot loop
        uses collect_into."""
        jobs = []
        for elem in self._elems.values():
            for start, vals in elem.closed_buckets(target_nanos):
                jobs.append((elem, start, vals))
        self._elems = {
            k: e for k, e in self._elems.items()
            if not (e.tombstoned and e.is_empty())
        }
        return jobs

    def collect_into(self, target_nanos: int, batch: FlushBatch,
                     already: int = 0) -> Tuple[int, int]:
        """Pop every window closed before target_nanos straight into
        `batch`'s columnar class rows — no per-window tuples, no
        ElemKey re-hashing (GC deletes only the keys that died). With
        `already` (a previous leader's persisted flushed-up-to time),
        covered windows are dropped, not re-emitted. Returns
        (collected, dropped)."""
        res = self.resolution_ns
        classes = batch.classes
        rows_cache: Dict[EmitClass, _ClassRows] = {}
        dead = None
        n = 0
        dropped = 0
        for elem in self._elems.values():
            b = elem._buckets
            if b:
                # Lock-free drain: only this drain ever REMOVES keys
                # (stagers merge get-then-set under elem._lock, never
                # pop), so the plain C pops below cannot miss. Closure
                # is decided off the sorted snapshot itself — a current
                # open window staged just before the snapshot routes to
                # the filtered branch, never the full drain — and a
                # fresh window staged after sorted() survives untouched
                # for the next round.
                if len(b) == 1:
                    # single staged window (half a typical mixed-policy
                    # population): peek, and only pop once the window is
                    # known closed — an open window is never removed, so
                    # a concurrent stage of it can't be clobbered by a
                    # put-back
                    start = next(iter(b))
                    if start + res > target_nanos:
                        continue
                    v = b.pop(start)
                    starts = (start,)
                    if elem._degraded:
                        vals = _reconcile_degraded(elem, b, [v])
                    else:
                        vals = (v,)
                elif (starts := sorted(b))[-1] + res <= target_nanos:
                    # every SNAPSHOTTED bucket is closed (the aligned-
                    # flush common case)
                    vals = list(map(b.pop, starts))
                    if elem._degraded:
                        vals = _reconcile_degraded(elem, b, vals)
                else:
                    starts = [s for s in starts
                              if s + res <= target_nanos]
                    if not starts:
                        continue
                    vals = list(map(b.pop, starts))
                    if elem._degraded:
                        vals = _reconcile_degraded(elem, b, vals)
                if already:
                    lo = bisect_right(starts, already - res)
                    if lo:
                        dropped += lo
                        starts = starts[lo:]
                        vals = vals[lo:]
                k = len(starts)
                if k:
                    cls = elem._eclass
                    rows = rows_cache.get(cls)
                    if rows is None:
                        rows = classes.get(cls)
                        if rows is None:
                            rows = classes[cls] = _ClassRows()
                        rows_cache[cls] = rows
                    rows.runs.append((elem, k))
                    if k == 1:
                        rows.starts.append(starts[0])
                        rows.buckets.append(vals[0])
                    else:
                        rows.starts.extend(starts)
                        rows.buckets.extend(vals)
                    n += k
            if not b and elem.tombstoned:
                if dead is None:
                    dead = []
                dead.append(elem.key)
        if dead:
            for key in dead:
                e = self._elems.get(key)
                if e is not None and e.tombstoned and not e._buckets:
                    del self._elems[key]
        return n, dropped

    def flush(self, target_nanos: int, flush_fn: Callable,
              forward_fn: Optional[Callable] = None) -> int:
        """Consume every window closed before target_nanos across all elems
        in one columnar batched reduction. Returns windows consumed."""
        batch = FlushBatch()
        n, _ = self.collect_into(target_nanos, batch)
        emit_batch(batch, flush_fn, forward_fn)
        return n


class MetricLists:
    """Resolution -> MetricList registry (list.go metricLists)."""

    def __init__(self):
        self._lists: Dict[int, MetricList] = {}

    def for_resolution(self, resolution_ns: int) -> MetricList:
        lst = self._lists.get(resolution_ns)
        if lst is None:
            lst = self._lists[resolution_ns] = MetricList(resolution_ns)
        return lst

    def resolutions(self) -> List[int]:
        return sorted(self._lists)

    def lists(self) -> List[MetricList]:
        return [self._lists[r] for r in sorted(self._lists)]
