"""Aggregation elements: per-(id, storage policy, agg types, pipeline) windowed
state (reference: src/aggregator/aggregator/generic_elem.go:116 and the genny
instantiations counter_elem_gen.go / gauge_elem_gen.go / timer_elem_gen.go).

TPU-first redesign: the reference's elem holds one locked aggregation struct
per time bucket and folds values in scalar-at-a-time (generic_elem.go:199
AddUnion -> lockedAgg.Add). Here an elem only *stages* raw values columnar
per bucket (cheap numpy appends on the ingest path); all reduction work is
deferred to consume time, where the owning metric list pads every closed
bucket of every elem into one (buckets x values) tile and reduces them in a
single jitted device call (see list.py). That turns the per-datapoint hot
loop into an MXU/VPU-friendly batch reduce and keeps the ingest path free of
device transfers.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..metrics import aggregation as magg
from ..metrics.metadata import ForwardMetadata
from ..metrics.metric import MetricType, MetricUnion
from ..metrics.pipeline import OpType, Pipeline
from ..metrics.policy import StoragePolicy
from ..metrics.transformation import TransformType, apply as apply_transform, Datapoint


@dataclasses.dataclass(frozen=True)
class ElemKey:
    """Identity of one aggregation element (aggregator/elem_base.go elemBase:
    id x storage policy x aggregation types x remaining pipeline)."""

    metric_id: bytes
    storage_policy: StoragePolicy
    aggregation_id: int = 0
    pipeline: Pipeline = Pipeline()
    num_forwarded_times: int = 0


def _concat(staged) -> np.ndarray:
    """One window's staged value(s) -> one array. A bucket holds the
    ndarray itself after a single columnar add (the ingest fast path —
    zero copies, zero wrappers) and degrades to a chunk list only when a
    window receives multiple adds."""
    if type(staged) is not list:
        return staged
    if len(staged) == 1:
        return staged[0]
    if not staged:
        return np.empty(0, dtype=np.float64)
    return np.concatenate(staged)


class EmitClass:
    """Shared emission shape of every elem with the same (agg types,
    quantiles, policy, piped) signature — the unit the columnar flush
    (list.py emit_batch) groups rows by. Interned process-wide so the
    per-window classification in the collect hot loop is one identity-
    hashed dict lookup, never a tuple hash of enums and policies."""

    __slots__ = ("agg_types", "quantiles", "policy", "res_ns", "piped",
                 "needed", "q_idx")

    def __init__(self, agg_types, quantiles, policy, piped: bool):
        self.agg_types = agg_types
        self.quantiles = quantiles
        self.policy = policy
        self.res_ns = policy.resolution.window_ns
        self.piped = piped
        # Moment columns this class's emissions read; the flush only
        # ever computes these for the class's buckets ("count" is always
        # available — it gates the empty-window defaults).
        self.needed = frozenset(
            k for at in agg_types if at.quantile() is None
            for k in STAT_DEPS[at])
        # Quantile agg type -> POSITION in `quantiles` (tuple-index
        # keying: emission never looks a quantile up by float equality).
        self.q_idx: Dict["magg.AggType", int] = {
            at: quantiles.index(q) for at in agg_types
            if (q := at.quantile()) is not None}


_EMIT_CLASSES: Dict[tuple, EmitClass] = {}
_EMIT_CLASSES_LOCK = threading.Lock()


def _emit_class_for(agg_types, quantiles, policy, piped: bool) -> EmitClass:
    key = (agg_types, quantiles, policy, piped)
    cls = _EMIT_CLASSES.get(key)
    if cls is None:
        # Check-then-create under the lock: elems are constructed from
        # concurrent connection-handler threads.
        with _EMIT_CLASSES_LOCK:
            cls = _EMIT_CLASSES.get(key)
            if cls is None:
                cls = _EMIT_CLASSES[key] = EmitClass(
                    agg_types, quantiles, policy, piped)
    return cls


class Elem:
    """One metric's windowed aggregation state for one storage policy.

    add_union/add_value stage values into the bucket for the aligned window;
    closed_buckets hands (window_start, values) pairs to the list's batched
    consumer and drops them (generic_elem.go:264 Consume).
    """

    __slots__ = ("key", "metric_type", "agg_types", "resolution_ns",
                 "_quantiles", "_q_idx", "_out_ids", "_out_tuple",
                 "_simple_type", "_eclass", "_buckets",
                 "_degraded", "_lock", "_prev", "tombstoned")

    def __init__(self, key: ElemKey, metric_type: MetricType,
                 agg_types: Optional[Sequence[magg.AggType]] = None):
        self.key = key
        self.metric_type = metric_type
        if agg_types is None:
            if key.aggregation_id == magg.AggID.DEFAULT:
                agg_types = magg.default_types_for(metric_type)
            else:
                agg_types = magg.AggID.decompress(key.aggregation_id)
        self.agg_types: Tuple[magg.AggType, ...] = tuple(agg_types)
        self.resolution_ns = key.storage_policy.resolution.window_ns
        # Static per-elem facts, precomputed: the flush hot loop touches
        # every elem every window, and recomputing these 250k times per
        # flush dominated the aggregation tier's cost.
        self._quantiles: Tuple[float, ...] = tuple(
            sorted({q for t in self.agg_types
                    if (q := t.quantile()) is not None}))
        # Quantile agg type -> POSITION in self._quantiles: emission
        # looks quantile values up by tuple index, so a recomputed float
        # can never miss on bit inequality (reduce paths hand emit a
        # value row aligned to this tuple).
        self._q_idx: Dict[magg.AggType, int] = {
            at: self._quantiles.index(q) for at in self.agg_types
            if (q := at.quantile()) is not None}
        self._out_ids: Dict[magg.AggType, bytes] = {
            at: self._output_id(at) for at in self.agg_types}
        # Positionally aligned with agg_types: the columnar emit indexes
        # output ids by agg-type position (int index beats an enum-keyed
        # dict hash in the 2M-output flush loop).
        self._out_tuple: Tuple[bytes, ...] = tuple(
            self._out_ids[at] for at in self.agg_types)
        # The vectorized-emission shape (list.py reduce_and_emit_ref): ONE
        # non-quantile agg type, no pipeline — counters (Sum) and gauges
        # (Last), i.e. the overwhelming majority of a metrics workload.
        self._simple_type: Optional[magg.AggType] = (
            self.agg_types[0]
            if (key.pipeline.is_empty() and len(self.agg_types) == 1
                and self.agg_types[0].quantile() is None)
            else None)
        # Columnar-flush grouping handle (list.py emit_batch), interned
        # so collect classifies each window by one identity hash.
        self._eclass: EmitClass = _emit_class_for(
            self.agg_types, self._quantiles, key.storage_policy,
            not key.pipeline.is_empty())
        # start -> list of staged value chunks (plain list: the ingest
        # path appends, the collect path concatenates; no per-bucket
        # object or method dispatch on either hot loop).
        self._buckets: Dict[int, List[np.ndarray]] = {}
        # True while this elem's staging MAY hold chunk-list merges (a
        # window received a second add). Collect skips the per-window
        # _concat/reconcile pass until then; reset under the lock once a
        # drain leaves no buckets behind.
        self._degraded = False
        # Serializes slot MUTATION against the flush drain (the
        # reference's per-elem lockedAggregation, generic_elem.go): a
        # first add of a window inserts lock-free (a fresh key can never
        # resurrect flushed data), but degrading a slot to a chunk list
        # and the collect-time pops hold this lock, so a racing flush
        # can never emit a window and then see its data re-staged.
        self._lock = threading.Lock()
        # Per-pipeline-transform previous datapoint, for binary transforms
        # (PerSecond needs the prior window's value: generic_elem.go:300
        # processValueWithAggregationLock keeps lastConsumedValues).
        self._prev: Dict[int, Datapoint] = {}
        self.tombstoned = False

    # -- ingest path -------------------------------------------------------

    def _stage(self, t_nanos: int, values: np.ndarray):
        """Stage one value array into its aligned window. The first add
        stores the array itself (the columnar ingest path stages each
        window exactly once — no wrapper, no chunk list); later adds to
        the same window degrade the slot to a chunk list, concatenated
        lazily at collect time (_concat)."""
        start = t_nanos - t_nanos % self.resolution_ns
        b = self._buckets
        cur = b.get(start)
        if cur is None:
            # lock-free fast path: the common staging shape is exactly
            # one columnar add per window, and inserting a FRESH key can
            # neither disturb a concurrent drain's snapshot (a key
            # inserted after sorted() simply survives for the next
            # round) nor resurrect popped data
            b[start] = values
            return
        with self._lock:
            # Degraded staging (multi-add to one window). The flag is
            # STICKY and set BEFORE the merge becomes visible: a drain
            # that pops a merged slot — or whose popped window gets
            # merged back by this path — is guaranteed to observe
            # _degraded on its post-pop read (GIL total order) and run
            # the locked reconciliation sweep. Keys are NEVER removed
            # here (get-then-merge only), so the drain's plain C pops
            # can never miss.
            self._degraded = True
            cur = b.get(start)
            if cur is None:
                # a racing drain popped (and will emit) the window: the
                # late value starts a FRESH slot, emitted next round
                b[start] = values
            elif type(cur) is list:
                # in place: a drain that already popped this list sees
                # the chunk or not (torn adds stage-or-drop exactly
                # once, the pre-rebuild _Bucket semantics)
                cur.append(values)
            else:
                # slot re-creation is the one hazard (cur may be popped
                # and emitted between our get and this set) — the
                # drain's reconciliation sweep drops just-emitted chunks
                # from merged-back slots by identity, under this lock
                b[start] = [cur, values]

    def add_union(self, t_nanos: int, mu: MetricUnion):
        if mu.type == MetricType.COUNTER:
            self._stage(t_nanos, np.asarray([mu.counter_val], dtype=np.float64))
        elif mu.type == MetricType.GAUGE:
            self._stage(t_nanos, np.asarray([mu.gauge_val], dtype=np.float64))
        elif mu.type == MetricType.TIMER:
            self._stage(t_nanos, np.asarray(mu.batch_timer_val, dtype=np.float64))
        else:
            raise ValueError(f"invalid metric type {mu.type}")

    def add_value(self, t_nanos: int, value: float):
        self._stage(t_nanos, np.asarray([value], dtype=np.float64))

    def add_values(self, t_nanos: int, values: np.ndarray):
        self._stage(t_nanos, np.asarray(values, dtype=np.float64))

    # -- consume path ------------------------------------------------------

    def closed_buckets(self, target_nanos: int) -> List[Tuple[int, np.ndarray]]:
        """Pop buckets whose window has fully closed before target_nanos."""
        out = []
        with self._lock:  # same drain-vs-degrade discipline as collect_into
            for start in sorted(self._buckets):
                if start + self.resolution_ns <= target_nanos:
                    out.append((start, _concat(self._buckets.pop(start))))
            if not self._buckets:
                self._degraded = False  # no surviving chunk merges
        return out

    def is_empty(self) -> bool:
        return not self._buckets

    # -- post-reduction emission ------------------------------------------

    def quantiles_needed(self) -> Tuple[float, ...]:
        return self._quantiles

    def emit(self, window_start: int, stats_row: Dict[str, float],
             quantile_vals: Sequence[float],
             flush_fn: Callable, forward_fn: Optional[Callable] = None):
        """Turn one reduced window into flushed datapoints (the per-window
        scalar path, used by the retained host oracle reduce_and_emit_ref;
        the production columnar path is list.py emit_batch).

        flush_fn(metric_id, time_nanos, value, storage_policy) per agg type;
        an elem with remaining pipeline ops instead applies transforms and
        forwards through forward_fn (aggregator/forwarded_writer.go).
        `quantile_vals` is positionally aligned with self._quantiles and
        indexed through _q_idx — a tuple-index lookup, so a recomputed
        quantile float can never miss on bit inequality. Timestamp is the
        window end, matching the reference's convention
        (generic_elem.go:283 timestamp = timeNanos + resolution).
        """
        end_nanos = window_start + self.resolution_ns
        # The per-window scalar emit exists to serve the retained
        # bit-exactness oracle (reduce_and_emit_ref); production flushes
        # batch through list.py emit_batch and never take this loop.
        # m3lint: disable=per-datapoint-callback-in-flush
        for at in self.agg_types:
            if at in self._q_idx:
                value = quantile_vals[self._q_idx[at]]
            else:
                value = _stat_value(at, stats_row)
            if self.key.pipeline.is_empty():
                flush_fn(self._out_ids[at], end_nanos, value, self.key.storage_policy)
            else:
                self._process_pipeline(at, end_nanos, value, flush_fn, forward_fn)

    def _process_pipeline(self, at, t_nanos: int, value: float,
                          flush_fn, forward_fn, forward_sink=None):
        """Apply the remaining pipeline ops to one reduced value.

        With `forward_sink` (a list), rollup outputs are APPENDED as
        (new_id, t_nanos, value, meta, source_id) instead of calling
        forward_fn per datapoint — the columnar flush coalesces the
        round's forwards into per-destination batches (list.py
        emit_batch -> ForwardedWriter.forward_batch)."""
        ops = self.key.pipeline.ops
        dp = Datapoint(t_nanos, value)
        for i, op in enumerate(ops):
            if op.type == OpType.TRANSFORMATION:
                tt: TransformType = op.transformation
                prev = self._prev.get(int(at))
                if tt.is_binary() and prev is None:
                    self._prev[int(at)] = dp
                    return
                out = apply_transform(tt, prev, dp)
                self._prev[int(at)] = dp
                if out.time_nanos == 0 and math.isnan(out.value):
                    # Empty transform output (transformation/binary.go
                    # emptyDatapoint: NaN input, non-increasing time, or
                    # negative diff): never emitted or forwarded — the
                    # reference's default DiscardNaNAggregatedValues. A
                    # forwarded (t=0, NaN) would stage a bogus epoch-0
                    # window in the next aggregation stage.
                    return
                dp = out
            elif op.type == OpType.ROLLUP:
                if forward_sink is None and forward_fn is None:
                    return
                rop = op.rollup
                meta = ForwardMetadata(
                    aggregation_id=rop.aggregation_id,
                    storage_policy=self.key.storage_policy,
                    pipeline=self.key.pipeline.sub(i + 1),
                    source_id=self.key.metric_id,
                    num_forwarded_times=self.key.num_forwarded_times + 1,
                )
                if forward_sink is not None:
                    forward_sink.append((rop.new_name, dp.time_nanos,
                                         dp.value, meta, self.key.metric_id))
                else:
                    forward_fn(rop.new_name, dp.time_nanos, dp.value, meta,
                               self.key.metric_id)
                return
            else:
                raise ValueError(f"unsupported pipeline op {op.type} in elem")
        flush_fn(self._out_ids[at], dp.time_nanos, dp.value, self.key.storage_policy)

    def _output_id(self, at: magg.AggType) -> bytes:
        """Aggregated output ID: metric name + '.' + type suffix, suppressed
        when the type is the metric type's single default (types_options.go
        default type strings; counters default to bare 'id' for Sum, gauges
        for Last). The suffix lands on the NAME component of a canonical
        'name;tag=v' ID (metrics/id.py) so tag values stay intact."""
        defaults = magg.default_types_for(self.metric_type)
        if len(defaults) == 1 and self.agg_types == tuple(defaults):
            return self.key.metric_id
        name, sep, rest = self.key.metric_id.partition(b";")
        suffixed = name + b"." + at.type_string.encode()
        return suffixed + sep + rest if rest else suffixed


# Moment columns each non-quantile agg type reads ("count" is always
# available — it gates the empty-window defaults).
STAT_DEPS: Dict[magg.AggType, Tuple[str, ...]] = {
    magg.AggType.SUM: ("sum",), magg.AggType.SUMSQ: ("sumsq",),
    magg.AggType.COUNT: (), magg.AggType.MIN: ("min",),
    magg.AggType.MAX: ("max",), magg.AggType.LAST: ("last",),
    magg.AggType.MEAN: ("sum",), magg.AggType.STDEV: ("m2",),
}


def stat_column(at: magg.AggType, m: Dict[str, np.ndarray]):
    """Output value(s) for one non-quantile agg type over moment COLUMNS
    (list.py's vectorized flush emission). _stat_value below is its
    plain-float twin for the per-window scalar emit path — same mapping,
    same empty-window defaults; change both together (tests assert their
    parity)."""
    cnt = m["count"]
    if at == magg.AggType.SUM:
        return m["sum"]
    if at == magg.AggType.SUMSQ:
        return m["sumsq"]
    if at == magg.AggType.COUNT:
        return cnt
    if at == magg.AggType.MIN:
        return np.where(cnt > 0, m["min"], 0.0)
    if at == magg.AggType.MAX:
        return np.where(cnt > 0, m["max"], 0.0)
    if at == magg.AggType.LAST:
        return m["last"]
    if at == magg.AggType.MEAN:
        return np.where(cnt > 0, m["sum"] / np.maximum(cnt, 1), 0.0)
    if at == magg.AggType.STDEV:
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(cnt > 1,
                            np.sqrt(m["m2"] / np.maximum(cnt - 1, 1)), 0.0)
    raise ValueError(f"no stat mapping for {at}")


def _stat_value(at: magg.AggType, stats: Dict[str, float]) -> float:
    """Plain-float twin of stat_column for the per-window scalar emit path:
    one call per agg type per window is a hot loop for timers/pipelines,
    and routing scalars through numpy's where/errstate boxing is ~7x
    slower than float branches (same arithmetic, same empty-window
    defaults)."""
    cnt = stats["count"]
    if at == magg.AggType.SUM:
        return float(stats["sum"])
    if at == magg.AggType.SUMSQ:
        return float(stats["sumsq"])
    if at == magg.AggType.COUNT:
        return float(cnt)
    if at == magg.AggType.MIN:
        return float(stats["min"]) if cnt > 0 else 0.0
    if at == magg.AggType.MAX:
        return float(stats["max"]) if cnt > 0 else 0.0
    if at == magg.AggType.LAST:
        return float(stats["last"])
    if at == magg.AggType.MEAN:
        return float(stats["sum"]) / cnt if cnt > 0 else 0.0
    if at == magg.AggType.STDEV:
        return math.sqrt(stats["m2"] / (cnt - 1)) if cnt > 1 else 0.0
    raise ValueError(f"no stat mapping for {at}")


# Runtime race witness registration (utils/racewatch.py): _buckets is the
# ledger-declared lock-free fresh-key fast path (verified dynamically);
# _degraded is fully lock-protected and rides along as a witnessed
# locked-pair — the witness should SEE its cross-thread accesses share
# Elem._lock.
from ..utils import racewatch as _racewatch  # noqa: E402

_racewatch.register(Elem, "_buckets", "_degraded")
