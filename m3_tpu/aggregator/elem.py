"""Aggregation elements: per-(id, storage policy, agg types, pipeline) windowed
state (reference: src/aggregator/aggregator/generic_elem.go:116 and the genny
instantiations counter_elem_gen.go / gauge_elem_gen.go / timer_elem_gen.go).

TPU-first redesign: the reference's elem holds one locked aggregation struct
per time bucket and folds values in scalar-at-a-time (generic_elem.go:199
AddUnion -> lockedAgg.Add). Here an elem only *stages* raw values columnar
per bucket (cheap numpy appends on the ingest path); all reduction work is
deferred to consume time, where the owning metric list pads every closed
bucket of every elem into one (buckets x values) tile and reduces them in a
single jitted device call (see list.py). That turns the per-datapoint hot
loop into an MXU/VPU-friendly batch reduce and keeps the ingest path free of
device transfers.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..metrics import aggregation as magg
from ..metrics.metadata import ForwardMetadata
from ..metrics.metric import MetricType, MetricUnion
from ..metrics.pipeline import OpType, Pipeline
from ..metrics.policy import StoragePolicy
from ..metrics.transformation import TransformType, apply as apply_transform, Datapoint


@dataclasses.dataclass(frozen=True)
class ElemKey:
    """Identity of one aggregation element (aggregator/elem_base.go elemBase:
    id x storage policy x aggregation types x remaining pipeline)."""

    metric_id: bytes
    storage_policy: StoragePolicy
    aggregation_id: int = 0
    pipeline: Pipeline = Pipeline()
    num_forwarded_times: int = 0


class _Bucket:
    """Staged raw values for one aligned window (generic_elem.go timedAggregation,
    minus the eager reduction)."""

    __slots__ = ("chunks", "n")

    def __init__(self):
        self.chunks: List[np.ndarray] = []
        self.n = 0

    def add(self, values: np.ndarray):
        self.chunks.append(values)
        self.n += values.size

    def concat(self) -> np.ndarray:
        if not self.chunks:
            return np.empty(0, dtype=np.float64)
        if len(self.chunks) == 1:
            return self.chunks[0]
        return np.concatenate(self.chunks)


class Elem:
    """One metric's windowed aggregation state for one storage policy.

    add_union/add_value stage values into the bucket for the aligned window;
    closed_buckets hands (window_start, values) pairs to the list's batched
    consumer and drops them (generic_elem.go:264 Consume).
    """

    def __init__(self, key: ElemKey, metric_type: MetricType,
                 agg_types: Optional[Sequence[magg.AggType]] = None):
        self.key = key
        self.metric_type = metric_type
        if agg_types is None:
            if key.aggregation_id == magg.AggID.DEFAULT:
                agg_types = magg.default_types_for(metric_type)
            else:
                agg_types = magg.AggID.decompress(key.aggregation_id)
        self.agg_types: Tuple[magg.AggType, ...] = tuple(agg_types)
        self.resolution_ns = key.storage_policy.resolution.window_ns
        # Static per-elem facts, precomputed: the flush hot loop touches
        # every elem every window, and recomputing these 250k times per
        # flush dominated the aggregation tier's cost.
        self._quantiles: Tuple[float, ...] = tuple(
            sorted({q for t in self.agg_types
                    if (q := t.quantile()) is not None}))
        self._out_ids: Dict[magg.AggType, bytes] = {
            at: self._output_id(at) for at in self.agg_types}
        # The vectorized-emission shape (list.py reduce_and_emit): ONE
        # non-quantile agg type, no pipeline — counters (Sum) and gauges
        # (Last), i.e. the overwhelming majority of a metrics workload.
        self._simple_type: Optional[magg.AggType] = (
            self.agg_types[0]
            if (key.pipeline.is_empty() and len(self.agg_types) == 1
                and self.agg_types[0].quantile() is None)
            else None)
        self._buckets: Dict[int, _Bucket] = {}
        # Per-pipeline-transform previous datapoint, for binary transforms
        # (PerSecond needs the prior window's value: generic_elem.go:300
        # processValueWithAggregationLock keeps lastConsumedValues).
        self._prev: Dict[int, Datapoint] = {}
        self.tombstoned = False

    # -- ingest path -------------------------------------------------------

    def _bucket_for(self, t_nanos: int) -> _Bucket:
        start = t_nanos - t_nanos % self.resolution_ns
        b = self._buckets.get(start)
        if b is None:
            b = self._buckets[start] = _Bucket()
        return b

    def add_union(self, t_nanos: int, mu: MetricUnion):
        if mu.type == MetricType.COUNTER:
            self._bucket_for(t_nanos).add(np.asarray([mu.counter_val], dtype=np.float64))
        elif mu.type == MetricType.GAUGE:
            self._bucket_for(t_nanos).add(np.asarray([mu.gauge_val], dtype=np.float64))
        elif mu.type == MetricType.TIMER:
            self._bucket_for(t_nanos).add(np.asarray(mu.batch_timer_val, dtype=np.float64))
        else:
            raise ValueError(f"invalid metric type {mu.type}")

    def add_value(self, t_nanos: int, value: float):
        self._bucket_for(t_nanos).add(np.asarray([value], dtype=np.float64))

    def add_values(self, t_nanos: int, values: np.ndarray):
        self._bucket_for(t_nanos).add(np.asarray(values, dtype=np.float64))

    # -- consume path ------------------------------------------------------

    def closed_buckets(self, target_nanos: int) -> List[Tuple[int, np.ndarray]]:
        """Pop buckets whose window has fully closed before target_nanos."""
        out = []
        for start in sorted(self._buckets):
            if start + self.resolution_ns <= target_nanos:
                out.append((start, self._buckets.pop(start).concat()))
        return out

    def is_empty(self) -> bool:
        return not self._buckets

    # -- post-reduction emission ------------------------------------------

    def quantiles_needed(self) -> Tuple[float, ...]:
        return self._quantiles

    def emit(self, window_start: int, stats_row: Dict[str, float],
             quantile_row: Dict[float, float],
             flush_fn: Callable, forward_fn: Optional[Callable] = None):
        """Turn one reduced window into flushed datapoints.

        flush_fn(metric_id, time_nanos, value, storage_policy) per agg type;
        an elem with remaining pipeline ops instead applies transforms and
        forwards through forward_fn (aggregator/forwarded_writer.go).
        Timestamp is the window end, matching the reference's convention
        (generic_elem.go:283 timestamp = timeNanos + resolution).
        """
        end_nanos = window_start + self.resolution_ns
        for at in self.agg_types:
            q = at.quantile()
            value = quantile_row[q] if q is not None else _stat_value(at, stats_row)
            if self.key.pipeline.is_empty():
                flush_fn(self._out_ids[at], end_nanos, value, self.key.storage_policy)
            else:
                self._process_pipeline(at, end_nanos, value, flush_fn, forward_fn)

    def _process_pipeline(self, at, t_nanos: int, value: float,
                          flush_fn, forward_fn):
        ops = self.key.pipeline.ops
        dp = Datapoint(t_nanos, value)
        for i, op in enumerate(ops):
            if op.type == OpType.TRANSFORMATION:
                tt: TransformType = op.transformation
                prev = self._prev.get(int(at))
                if tt.is_binary() and prev is None:
                    self._prev[int(at)] = dp
                    return
                out = apply_transform(tt, prev, dp)
                self._prev[int(at)] = dp
                dp = out
            elif op.type == OpType.ROLLUP:
                if forward_fn is None:
                    return
                rop = op.rollup
                meta = ForwardMetadata(
                    aggregation_id=rop.aggregation_id,
                    storage_policy=self.key.storage_policy,
                    pipeline=self.key.pipeline.sub(i + 1),
                    source_id=self.key.metric_id,
                    num_forwarded_times=self.key.num_forwarded_times + 1,
                )
                forward_fn(rop.new_name, dp.time_nanos, dp.value, meta,
                           self.key.metric_id)
                return
            else:
                raise ValueError(f"unsupported pipeline op {op.type} in elem")
        flush_fn(self._out_ids[at], dp.time_nanos, dp.value, self.key.storage_policy)

    def _output_id(self, at: magg.AggType) -> bytes:
        """Aggregated output ID: metric name + '.' + type suffix, suppressed
        when the type is the metric type's single default (types_options.go
        default type strings; counters default to bare 'id' for Sum, gauges
        for Last). The suffix lands on the NAME component of a canonical
        'name;tag=v' ID (metrics/id.py) so tag values stay intact."""
        defaults = magg.default_types_for(self.metric_type)
        if len(defaults) == 1 and self.agg_types == tuple(defaults):
            return self.key.metric_id
        name, sep, rest = self.key.metric_id.partition(b";")
        suffixed = name + b"." + at.type_string.encode()
        return suffixed + sep + rest if rest else suffixed


# Moment columns each non-quantile agg type reads ("count" is always
# available — it gates the empty-window defaults).
STAT_DEPS: Dict[magg.AggType, Tuple[str, ...]] = {
    magg.AggType.SUM: ("sum",), magg.AggType.SUMSQ: ("sumsq",),
    magg.AggType.COUNT: (), magg.AggType.MIN: ("min",),
    magg.AggType.MAX: ("max",), magg.AggType.LAST: ("last",),
    magg.AggType.MEAN: ("sum",), magg.AggType.STDEV: ("m2",),
}


def stat_column(at: magg.AggType, m: Dict[str, np.ndarray]):
    """Output value(s) for one non-quantile agg type over moment COLUMNS
    (list.py's vectorized flush emission). _stat_value below is its
    plain-float twin for the per-window scalar emit path — same mapping,
    same empty-window defaults; change both together (tests assert their
    parity)."""
    cnt = m["count"]
    if at == magg.AggType.SUM:
        return m["sum"]
    if at == magg.AggType.SUMSQ:
        return m["sumsq"]
    if at == magg.AggType.COUNT:
        return cnt
    if at == magg.AggType.MIN:
        return np.where(cnt > 0, m["min"], 0.0)
    if at == magg.AggType.MAX:
        return np.where(cnt > 0, m["max"], 0.0)
    if at == magg.AggType.LAST:
        return m["last"]
    if at == magg.AggType.MEAN:
        return np.where(cnt > 0, m["sum"] / np.maximum(cnt, 1), 0.0)
    if at == magg.AggType.STDEV:
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(cnt > 1,
                            np.sqrt(m["m2"] / np.maximum(cnt - 1, 1)), 0.0)
    raise ValueError(f"no stat mapping for {at}")


def _stat_value(at: magg.AggType, stats: Dict[str, float]) -> float:
    """Plain-float twin of stat_column for the per-window scalar emit path:
    one call per agg type per window is a hot loop for timers/pipelines,
    and routing scalars through numpy's where/errstate boxing is ~7x
    slower than float branches (same arithmetic, same empty-window
    defaults)."""
    cnt = stats["count"]
    if at == magg.AggType.SUM:
        return float(stats["sum"])
    if at == magg.AggType.SUMSQ:
        return float(stats["sumsq"])
    if at == magg.AggType.COUNT:
        return float(cnt)
    if at == magg.AggType.MIN:
        return float(stats["min"]) if cnt > 0 else 0.0
    if at == magg.AggType.MAX:
        return float(stats["max"]) if cnt > 0 else 0.0
    if at == magg.AggType.LAST:
        return float(stats["last"])
    if at == magg.AggType.MEAN:
        return float(stats["sum"]) / cnt if cnt > 0 else 0.0
    if at == magg.AggType.STDEV:
        return math.sqrt(stats["m2"] / (cnt - 1)) if cnt > 1 else 0.0
    raise ValueError(f"no stat mapping for {at}")
