"""The aggregator: shard-aware ingestion with placement-watched ownership
(reference: src/aggregator/aggregator/aggregator.go:88 — AddUntimed :167,
AddTimed :189, AddForwarded :208, shardFor :268, placement watch :307;
shard.go aggregatorShard).

Each instance owns the shards the placement assigns it; metric IDs hash to
shards with murmur3 % num_shards (aggregator/sharding/hash.go:89). Each
shard owns its own metric map + lists so flushes and ticks parallelize per
shard; a forwarded-writer loops multi-stage pipeline outputs back into the
aggregation ring (forwarded_writer.go)."""

from __future__ import annotations

import threading
import time as _time
from typing import Callable, Dict, List, Optional, Sequence

from ..metrics.metadata import ForwardMetadata, StagedMetadata
from ..metrics.metric import MetricType, MetricUnion
from ..metrics.policy import StoragePolicy
from ..utils.hashing import murmur3_32_cached
from .election import ElectionManager
from .entry import MetricMap
from .flush import FlushManager, FlushTimesManager
from .handler import Handler
from .list import MetricLists


class AggregatorShard:
    """One shard's aggregation state (aggregator/shard.go): a metric map over
    its own lists, with cutover/cutoff write gating for placement changes."""

    def __init__(self, shard_id: int, clock: Callable[[], int],
                 rate_limit_per_second: int = 0,
                 default_policies: Sequence[StoragePolicy] = ()):
        self.shard_id = shard_id
        self.lists = MetricLists()
        self.map = MetricMap(self.lists, clock, rate_limit_per_second,
                             default_policies)
        # Writes accepted only within [cutover, cutoff) — shards being handed
        # off stop accepting before they're removed (shard.go SetWriteableRange).
        self.cutover_nanos = 0
        self.cutoff_nanos = 2**63 - 1
        self._clock = clock

    def is_writeable(self) -> bool:
        now = self._clock()
        return self.cutover_nanos <= now < self.cutoff_nanos


class ForwardedWriter:
    """Routes rollup-pipeline outputs to the next aggregation stage
    (forwarded_writer.go): the forwarded ID hashes to a shard, and the
    partial aggregate is delivered to every instance owning that shard in
    the aggregator placement — over the wire when the owner is another
    instance, directly when it is this one. Without routing configuration
    (the embedded single-instance downsampler) everything loops back into
    the local aggregator, which owns all shards."""

    def __init__(self, target: "Aggregator"):
        self._target = target
        self._placement = None      # Callable[[], Placement] | None
        self._transports = {}       # instance_id -> send_forwarded fn
        self._local_id = None
        self.dropped = 0

    def set_routing(self, placement_getter, transports, local_instance_id):
        """transports: instance_id -> either a transport OBJECT exposing
        send_forwarded / send_forwarded_batch (TCPTransport — enables the
        one-frame-per-destination batched forwarding) or a bare
        fn(metric_type, id, t, value, meta) (legacy per-item form)."""
        self._placement = placement_getter
        self._transports = dict(transports)
        self._local_id = local_instance_id

    @staticmethod
    def _send_fn(transport):
        send = getattr(transport, "send_forwarded", None)
        return send if send is not None else transport

    def __call__(self, new_id: bytes, t_nanos: int, value: float,
                 meta: ForwardMetadata, source_id: bytes):
        if self._placement is None:
            self._target.add_forwarded(
                MetricType.GAUGE, new_id, t_nanos, value, meta)
            return
        from ..cluster.placement import ShardState

        shard = self._target.shard_for(new_id)
        delivered = False
        for inst in self._placement().replicas_for(
                shard, states=(ShardState.INITIALIZING, ShardState.AVAILABLE)):
            if inst.id == self._local_id:
                delivered |= self._target.add_forwarded(
                    MetricType.GAUGE, new_id, t_nanos, value, meta)
                continue
            tr = self._transports.get(inst.id)
            if tr is not None and self._send_fn(tr)(
                    MetricType.GAUGE, new_id, t_nanos, value, meta):
                delivered = True
        if not delivered:
            self.dropped += 1

    def forward_batch(self, items):
        """Ship one flush round's rollup forwards batched (the sink
        list.py emit_batch collects instead of per-datapoint forward_fn
        calls). Local deliveries apply directly; remote deliveries
        coalesce into ONE columnar `fbatch` frame per (destination
        instance, forward-meta group) per flush round — the PR 7
        tile-RPC shape — via TCPTransport.send_forwarded_batch. Items
        are (new_id, t_nanos, value, meta, source_id)."""
        if self._placement is None:
            add = self._target.add_forwarded
            for new_id, t_nanos, value, meta, _src in items:
                add(MetricType.GAUGE, new_id, t_nanos, value, meta)
            return
        from ..cluster.placement import ShardState

        states = (ShardState.INITIALIZING, ShardState.AVAILABLE)
        placement = self._placement()
        delivered = [False] * len(items)
        pending: Dict[str, List[int]] = {}
        for i, (new_id, t_nanos, value, meta, _src) in enumerate(items):
            shard = self._target.shard_for(new_id)
            for inst in placement.replicas_for(shard, states=states):
                if inst.id == self._local_id:
                    if self._target.add_forwarded(
                            MetricType.GAUGE, new_id, t_nanos, value, meta):
                        delivered[i] = True
                    continue
                if inst.id in self._transports:
                    pending.setdefault(inst.id, []).append(i)
        for inst_id, idxs in pending.items():
            tr = self._transports[inst_id]
            batch_send = getattr(tr, "send_forwarded_batch", None)
            if batch_send is None:
                send = self._send_fn(tr)
                for i in idxs:
                    new_id, t_nanos, value, meta, _src = items[i]
                    if send(MetricType.GAUGE, new_id, t_nanos, value, meta):
                        delivered[i] = True
                continue
            # one frame per meta group (metas differ only across
            # pipelines/policies, so a flush round is typically one
            # frame per destination)
            groups: Dict[tuple, List[int]] = {}
            for i in idxs:
                meta = items[i][3]
                gk = (meta.aggregation_id, meta.storage_policy,
                      meta.pipeline, meta.num_forwarded_times)
                groups.setdefault(gk, []).append(i)
            for gidx in groups.values():
                if batch_send(MetricType.GAUGE, [items[i] for i in gidx]):
                    for i in gidx:
                        delivered[i] = True
        undelivered = delivered.count(False)
        if undelivered:
            self.dropped += undelivered


class Aggregator:
    def __init__(self, num_shards: int = 64,
                 clock: Optional[Callable[[], int]] = None,
                 flush_handler: Optional[Handler] = None,
                 election: Optional[ElectionManager] = None,
                 flush_times: Optional[FlushTimesManager] = None,
                 rate_limit_per_second: int = 0,
                 default_policies: Sequence[StoragePolicy] = (),
                 buffer_past_ns: int = 0):
        self.num_shards = num_shards
        self._clock = clock or (lambda: _time.time_ns())
        self._rate_limit = rate_limit_per_second
        self._default_policies = tuple(default_policies)
        self._shards: Dict[int, AggregatorShard] = {}
        self._owned = set(range(num_shards))
        self._flush_handler = flush_handler
        self._forward = ForwardedWriter(self)
        self._flush_mgrs: Dict[int, FlushManager] = {}
        self._election = election
        self._flush_times = flush_times
        self._buffer_past_ns = buffer_past_ns
        self.writes_for_unowned_shard = 0
        # Accepted forwarded partials (tally counter analog; lets tests and
        # operators await "all N stage-1 partials arrived" instead of racing
        # on first-entry creation). Incremented from concurrent
        # per-connection handler threads — guard the non-atomic += the same
        # way RawTCPServer guards frames/errors.
        self.forwarded_received = 0
        self._stats_lock = threading.Lock()
        self._shards_lock = threading.Lock()

    # -- placement ---------------------------------------------------------

    def assign_shards(self, shard_ids: Sequence[int]):
        """React to a placement change (aggregator.go:307 updateShardsWithLock):
        new shards open, removed shards get a cutoff and stop accepting."""
        new = set(shard_ids)
        now = self._clock()
        for sid in new - self._owned:
            if sid in self._shards:
                self._shards[sid].cutoff_nanos = 2**63 - 1
        for sid in self._owned - new:
            if sid in self._shards:
                self._shards[sid].cutoff_nanos = now
        self._owned = new

    def set_forward_routing(self, placement_getter, transports,
                            local_instance_id):
        """Enable cross-instance forwarded pipelines: rollup outputs are
        routed to the instances owning the forwarded ID's shard
        (forwarded_writer.go; proven end-to-end by the reference's
        multi_server_forwarding_pipeline_test.go)."""
        self._forward.set_routing(placement_getter, transports,
                                  local_instance_id)

    def owned_shards(self) -> List[int]:
        return sorted(self._owned)

    def shard_for(self, metric_id: bytes) -> int:
        """aggregator/sharding/hash.go:89 — murmur3 % num_shards."""
        return murmur3_32_cached(metric_id) % self.num_shards

    def _shard(self, metric_id: bytes) -> Optional[AggregatorShard]:
        sid = self.shard_for(metric_id)
        if sid not in self._owned:
            with self._stats_lock:
                self.writes_for_unowned_shard += 1
            return None
        shard = self._shards.get(sid)
        if shard is None:
            # Check-then-create under the lock: concurrent connection
            # handler threads must not each construct the shard — the loser's
            # writes would land in an orphaned object and never flush.
            with self._shards_lock:
                shard = self._shards.get(sid)
                if shard is None:
                    shard = self._shards[sid] = AggregatorShard(
                        sid, self._clock, self._rate_limit,
                        self._default_policies)
        return shard if shard.is_writeable() else None

    # -- ingest ------------------------------------------------------------

    def add_untimed(self, mu: MetricUnion,
                    metadatas: Sequence[StagedMetadata] = ()) -> bool:
        shard = self._shard(mu.id)
        return shard is not None and shard.map.add_untimed(mu, metadatas)

    def add_untimed_batch(self, mus: Sequence[MetricUnion],
                          metadatas: Sequence[StagedMetadata] = ()
                          ) -> List[bool]:
        """Grouped columnar add: every sample in the batch shares ONE
        staged-metadata list (a (pipeline, policy) class from the batch
        matcher), so the clock read and active-stage resolution are paid
        once for the group instead of per metric (entry.go:446
        activeStagedMetadataWith hoisted out of the hot loop). Returns
        per-sample acceptance, order-aligned with mus."""
        from .entry import _active_stage

        now = self._clock()
        active = _active_stage(metadatas, now)
        out = []
        for mu in mus:
            shard = self._shard(mu.id)
            out.append(shard is not None and shard.map.add_untimed_staged(
                mu, active, now))
        return out

    def ensure_entries(self, pairs) -> None:
        """Pre-create entries for (metric_id, metric_type) pairs in
        order — entry type resolution is first-write-wins, so a batched
        writer passes global sample order here before grouped adds."""
        for mid, mtype in pairs:
            shard = self._shard(mid)
            if shard is not None:
                shard.map.ensure_entry(mid, mtype)

    def add_timed(self, metric_type: MetricType, metric_id: bytes,
                  t_nanos: int, value: float, policy: StoragePolicy,
                  aggregation_id: int = 0) -> bool:
        shard = self._shard(metric_id)
        return shard is not None and shard.map.add_timed(
            metric_type, metric_id, t_nanos, value, policy, aggregation_id)

    def add_forwarded(self, metric_type: MetricType, metric_id: bytes,
                      t_nanos: int, value: float, meta: ForwardMetadata) -> bool:
        shard = self._shard(metric_id)
        ok = shard is not None and shard.map.add_forwarded(
            metric_type, metric_id, t_nanos, value, meta)
        if ok:
            with self._stats_lock:
                self.forwarded_received += 1
        return ok

    # -- flush/tick --------------------------------------------------------

    def _flush_mgr(self, shard: AggregatorShard) -> FlushManager:
        mgr = self._flush_mgrs.get(shard.shard_id)
        if mgr is None:
            if self._election is None or self._flush_times is None:
                raise RuntimeError("aggregator not configured for managed flush")
            mgr = self._flush_mgrs[shard.shard_id] = FlushManager(
                shard.lists, self._election, self._flush_times,
                self._flush_handler, self._forward,
                buffer_past_ns=self._buffer_past_ns, shard_id=shard.shard_id)
        return mgr

    def flush(self, now_nanos: Optional[int] = None) -> int:
        """One flush pass over all owned shards, batched into a single
        columnar reduction: every shard collects into ONE FlushBatch, so
        all aggregation shards reduce in one emit_batch (one mesh-sharded
        device program for the round's quantile ordering). With an
        election manager the leader/follower protocol gates emission, and
        the round's per-shard flush times commit as ONE kv transaction
        (FlushTimesManager.store_many); without one, flush directly (the
        embedded coordinator downsampler runs leaderless,
        downsample/leader_local.go)."""
        from .list import FlushBatch, emit_batch

        now = self._clock() if now_nanos is None else now_nanos
        batch = FlushBatch()
        commits = []
        with self._shards_lock:  # snapshot: handler threads insert shards
            shards = {sid: self._shards[sid] for sid in sorted(self._shards)}
        for sid, shard in shards.items():
            if self._election is not None:
                _, commit = self._flush_mgr(shard).plan_into(now, batch)
                commits.append(commit)
            else:
                for lst in shard.lists.lists():
                    res = lst.resolution_ns
                    target = (now - self._buffer_past_ns) // res * res
                    lst.collect_into(target, batch)
        total = emit_batch(batch, self._flush_handler, self._forward)
        if commits:
            pending: Dict[int, Dict[int, int]] = {}
            for commit in commits:
                commit(pending)
            if pending:
                self._flush_times.store_many(pending)
        return total

    def tick(self) -> int:
        """Expire idle entries across shards (aggregator.go tickInternal)."""
        with self._shards_lock:
            shards = list(self._shards.values())
        return sum(s.map.tick() for s in shards)

    def num_entries(self) -> int:
        with self._shards_lock:
            shards = list(self._shards.values())
        return sum(len(s.map) for s in shards)
