"""Aggregator client: shard-aware routing of unaggregated metrics to the
aggregator instances owning each metric's shard (reference:
src/aggregator/client/client.go:191-259 WriteUntimedCounter/BatchTimer/Gauge
and the placement-watched shard routing in writer_mgr/queue.go).

Transport is pluggable: the in-process transport calls a local Aggregator
directly (how the coordinator embeds its downsampler); the network transport
sends over the framed-RPC wire (m3_tpu.rpc.wire) like the reference's raw
TCP msgpack/protobuf connections."""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..cluster.placement import Placement, ShardState
from ..metrics.metadata import StagedMetadata
from ..metrics.metric import MetricUnion
from ..utils.hashing import murmur3_32_cached


class AggregatorClient:
    def __init__(self, num_shards: int,
                 placement_getter: Callable[[], Placement],
                 transports: Dict[str, Callable[[MetricUnion, Sequence[StagedMetadata]], bool]]):
        """transports: instance_id -> delivery fn (add_untimed of a local
        Aggregator, or a connection's send)."""
        self.num_shards = num_shards
        self._placement = placement_getter
        self._transports = transports
        self.dropped = 0

    def shard_for(self, metric_id: bytes) -> int:
        return murmur3_32_cached(metric_id) % self.num_shards

    def _instances_for(self, shard: int) -> List[str]:
        p = self._placement()
        return [
            inst.id for inst in p.replicas_for(
                shard, states=(ShardState.INITIALIZING, ShardState.AVAILABLE))
        ]

    def write_untimed(self, mu: MetricUnion,
                      metadatas: Sequence[StagedMetadata] = ()) -> bool:
        """Deliver to every replica of the metric's shard (client.go write:
        one writer per instance owning the shard)."""
        shard = self.shard_for(mu.id)
        delivered = False
        for instance_id in self._instances_for(shard):
            send = self._transports.get(instance_id)
            if send is not None and send(mu, metadatas):
                delivered = True
        if not delivered:
            self.dropped += 1
        return delivered

    def write_untimed_counter(self, metric_id: bytes, value: int,
                              metadatas: Sequence[StagedMetadata] = ()) -> bool:
        return self.write_untimed(MetricUnion.counter(metric_id, value), metadatas)

    def write_untimed_batch_timer(self, metric_id: bytes, values: Sequence[float],
                                  metadatas: Sequence[StagedMetadata] = ()) -> bool:
        return self.write_untimed(MetricUnion.batch_timer(metric_id, values), metadatas)

    def write_untimed_gauge(self, metric_id: bytes, value: float,
                            metadatas: Sequence[StagedMetadata] = ()) -> bool:
        return self.write_untimed(MetricUnion.gauge(metric_id, value), metadatas)
