"""Aggregator election manager (reference:
src/aggregator/aggregator/election_mgr.go — Leader/Follower/PendingFollower
states :99-126, campaigning via etcd election).

Wraps the cluster leader service: each aggregator instance campaigns for its
shard-set's election; the winner flushes, everyone else shadows. Losing
leadership moves Leader -> PendingFollower until the follower flush manager
has caught up to the new leader's persisted flush times, then Follower —
which prevents double-flushing the same window during a hand-off."""

from __future__ import annotations

import enum
from typing import Callable, Optional

from ..cluster.services import LeaderService


class ElectionState(enum.IntEnum):
    FOLLOWER = 0
    PENDING_FOLLOWER = 1
    LEADER = 2


class ElectionManager:
    def __init__(self, leader_service: LeaderService,
                 on_change: Optional[Callable[[ElectionState], None]] = None):
        self._leader = leader_service
        self._state = ElectionState.FOLLOWER
        self._on_change = on_change

    @property
    def state(self) -> ElectionState:
        return self._state

    def campaign(self) -> ElectionState:
        """One campaign step: attempt/renew leadership and update state."""
        outcome = self._leader.campaign()
        if outcome == "leader":
            self._set(ElectionState.LEADER)
        elif self._state == ElectionState.LEADER:
            # Lost the election while leading: drain before following.
            self._set(ElectionState.PENDING_FOLLOWER)
        elif self._state != ElectionState.PENDING_FOLLOWER:
            # PENDING_FOLLOWER only resolves via confirm_follower() once the
            # follower flush manager reports caught-up; campaigning again must
            # not short-circuit the hand-off drain.
            self._set(ElectionState.FOLLOWER)
        return self._state

    def confirm_follower(self):
        """Called by the follower flush manager once caught up
        (election_mgr.go:126 pendingFollowerToFollower)."""
        if self._state == ElectionState.PENDING_FOLLOWER:
            self._set(ElectionState.FOLLOWER)

    def resign(self):
        self._leader.resign()
        self._set(ElectionState.FOLLOWER)

    def is_leader(self) -> bool:
        return self._state == ElectionState.LEADER

    def _set(self, s: ElectionState):
        if s != self._state:
            self._state = s
            if self._on_change:
                self._on_change(s)
