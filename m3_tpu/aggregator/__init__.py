"""Streaming windowed aggregation tier (reference: src/aggregator).

Host side keeps the reference's control shape — shard-aware routing, per-ID
entries, leader/follower flush with KV-persisted flush times — while all
window reduction work is batched onto the device: every flush pads the
closed windows of a whole resolution into one tile and reduces it in a
single jitted call (list.py batched_reduce over m3_tpu.ops.aggregation
kernels)."""

from .aggregator import Aggregator, AggregatorShard, ForwardedWriter
from .client import AggregatorClient
from .election import ElectionManager, ElectionState
from .elem import Elem, ElemKey
from .entry import Entry, MetricMap, RateLimiter
from .flush import FlushManager, FlushTimesManager
from .handler import (
    AggregatedMetric,
    BlackholeHandler,
    BroadcastHandler,
    CallbackHandler,
    CaptureHandler,
    Handler,
    LoggingHandler,
    ProducerHandler,
    decode_aggregated,
    decode_aggregated_batch,
)
from .list import (FlushBatch, MetricList, MetricLists, batched_reduce,
                   emit_batch, reduce_and_emit, reduce_and_emit_ref)

__all__ = [
    "AggregatedMetric", "Aggregator", "AggregatorClient", "AggregatorShard",
    "BlackholeHandler", "BroadcastHandler", "CallbackHandler", "CaptureHandler",
    "Elem", "ElemKey", "ElectionManager", "ElectionState", "Entry",
    "FlushManager", "FlushTimesManager", "ForwardedWriter", "Handler",
    "LoggingHandler", "ProducerHandler", "decode_aggregated", "MetricList", "MetricLists", "MetricMap", "RateLimiter",
    "batched_reduce", "FlushBatch", "emit_batch", "reduce_and_emit",
    "reduce_and_emit_ref", "decode_aggregated_batch",
]
