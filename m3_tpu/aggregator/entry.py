"""Per-metric-ID entry: metadata resolution, rate limiting, elem fan-out
(reference: src/aggregator/aggregator/entry.go:221 AddUntimed).

An Entry is created per unique unaggregated metric ID; it resolves the
metric's staged metadatas (sent by the client alongside each sample) into
aggregation elements — one per (storage policy x aggregation types x
pipeline) — and routes every incoming sample into those elems' staging
buckets."""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Sequence, Tuple

from ..metrics import aggregation as magg
from ..metrics.metadata import ForwardMetadata, StagedMetadata
from ..metrics.metric import MetricType, MetricUnion
from ..metrics.policy import DropPolicy, StoragePolicy
from .elem import Elem, ElemKey
from .list import MetricLists


class RateLimiter:
    """Simple per-second token limiter (reference: src/aggregator/rate/limiter.go
    — limits values/sec admitted per entry)."""

    def __init__(self, limit_per_second: int, clock: Callable[[], int]):
        self.limit = limit_per_second
        self._clock = clock
        self._window_start = 0
        self._seen = 0

    def is_allowed(self, n: int) -> bool:
        if self.limit <= 0:
            return True
        now = self._clock()
        sec = now // 1_000_000_000
        if sec != self._window_start:
            self._window_start = sec
            self._seen = 0
        self._seen += n
        return self._seen <= self.limit


class Entry:
    def __init__(self, metric_id: bytes, metric_type: MetricType,
                 lists: MetricLists, clock: Callable[[], int],
                 rate_limit_per_second: int = 0,
                 default_policies: Sequence[StoragePolicy] = ()):
        self.metric_id = metric_id
        self.metric_type = metric_type
        self._lists = lists
        self._clock = clock
        self._limiter = RateLimiter(rate_limit_per_second, clock)
        self._default_policies = tuple(default_policies)
        self._elems: Dict[ElemKey, Elem] = {}
        self._active_metadata = None  # (cutover, Metadata) of last rebuild
        self.last_access_nanos = clock()
        self.dropped = 0

    # -- untimed (client-timestamped at arrival) ---------------------------

    def add_untimed(self, mu: MetricUnion,
                    metadatas: Sequence[StagedMetadata] = ()) -> bool:
        """Route one sample into the elems of the currently-active metadata
        stage (entry.go:221; stage selection :446 activeStagedMetadataWith).
        Returns False if rate-limited or dropped by policy."""
        now = self._clock()
        return self.add_untimed_staged(mu, _active_stage(metadatas, now), now)

    def add_untimed_staged(self, mu: MetricUnion,
                           active: Optional[StagedMetadata],
                           now: int) -> bool:
        """add_untimed with the metadata stage already resolved — the
        batched aggregator feed resolves (clock, active stage) ONCE per
        (pipeline, policy) class and fans the group's samples in here."""
        self.last_access_nanos = now
        n = max(1, len(mu.batch_timer_val))
        if not self._limiter.is_allowed(n):
            self.dropped += n
            return False
        if active is not None and active.tombstoned:
            return False
        self._maybe_update_elems(active)
        if not self._elems:
            return False
        for elem in self._elems.values():
            elem.add_union(now, mu)
        return True

    def add_timed(self, t_nanos: int, value: float,
                  policy: StoragePolicy, aggregation_id: int = 0) -> bool:
        """Timed metric with explicit client timestamp (entry.go AddTimed)."""
        self.last_access_nanos = self._clock()
        if not self._limiter.is_allowed(1):
            self.dropped += 1
            return False
        key = ElemKey(self.metric_id, policy, aggregation_id)
        elem = self._get_elem(key)
        elem.add_value(t_nanos, value)
        return True

    def add_forwarded(self, t_nanos: int, value: float,
                      meta: ForwardMetadata) -> bool:
        """Partial aggregate forwarded from an earlier pipeline stage
        (entry.go AddForwarded)."""
        self.last_access_nanos = self._clock()
        key = ElemKey(self.metric_id, meta.storage_policy, meta.aggregation_id,
                      meta.pipeline, meta.num_forwarded_times)
        elem = self._get_elem(key)
        elem.add_value(t_nanos, value)
        return True

    # -- internals ---------------------------------------------------------

    def _get_elem(self, key: ElemKey) -> Elem:
        elem = self._elems.get(key)
        if elem is None:
            lst = self._lists.for_resolution(key.storage_policy.resolution.window_ns)
            elem = lst.get_or_create(key, lambda: Elem(key, self.metric_type))
            self._elems[key] = elem
        return elem

    def _maybe_update_elems(self, active: Optional[StagedMetadata]):
        """(Re)build the elem set when the active metadata stage changes
        (entry.go:509 updateStagedMetadatasWithLock; staleness is judged on
        the metadata contents, not just the cutover — entry.go compares the
        staged metadatas themselves, so a rules update that keeps the same
        cutover still takes effect)."""
        current = (
            (active.cutover_nanos, active.metadata) if active is not None else None
        )
        if self._active_metadata == current and self._elems:
            return
        wanted: Dict[ElemKey, Tuple[int, object]] = {}
        if active is None or not active.metadata.pipelines:
            for sp in self._default_policies:
                wanted[ElemKey(self.metric_id, sp)] = None
        else:
            for pm in active.metadata.pipelines:
                if pm.drop_policy == DropPolicy.DROP_MUST:
                    continue
                policies = pm.storage_policies or self._default_policies
                for sp in policies:
                    wanted[ElemKey(self.metric_id, sp, pm.aggregation_id, pm.pipeline)] = None
        for key, old in list(self._elems.items()):
            if key not in wanted:
                old.tombstoned = True
                del self._elems[key]
        for key in wanted:
            self._get_elem(key)
        self._active_metadata = current


def _active_stage(metadatas: Sequence[StagedMetadata], t_nanos: int):
    """Last stage with cutover <= t (metadata.go StagedMetadatas semantics)."""
    active = None
    for sm in metadatas:
        if sm.cutover_nanos <= t_nanos and (
            active is None or sm.cutover_nanos >= active.cutover_nanos
        ):
            active = sm
    return active


class MetricMap:
    """Sharded id -> Entry map (reference: src/aggregator/aggregator/map.go:145
    AddUntimed; entry expiry :258 tick)."""

    def __init__(self, lists: MetricLists, clock: Callable[[], int],
                 rate_limit_per_second: int = 0,
                 default_policies: Sequence[StoragePolicy] = (),
                 entry_ttl_ns: int = 24 * 3600 * 1_000_000_000):
        self._entries: Dict[bytes, Entry] = {}
        self._lists = lists
        self._clock = clock
        self._rate_limit = rate_limit_per_second
        self._default_policies = tuple(default_policies)
        self._entry_ttl_ns = entry_ttl_ns

    def __len__(self):
        return len(self._entries)

    def _entry_for(self, metric_id: bytes, metric_type: MetricType) -> Entry:
        e = self._entries.get(metric_id)
        if e is None:
            e = self._entries[metric_id] = Entry(
                metric_id, metric_type, self._lists, self._clock,
                self._rate_limit, self._default_policies,
            )
        return e

    def ensure_entry(self, metric_id: bytes, metric_type: MetricType):
        """Pre-create the entry for an id (first-write-wins on type):
        batched writers resolve mixed-type output-id contention in
        sample order before their grouped adds."""
        self._entry_for(metric_id, metric_type)

    def add_untimed(self, mu: MetricUnion,
                    metadatas: Sequence[StagedMetadata] = ()) -> bool:
        return self._entry_for(mu.id, mu.type).add_untimed(mu, metadatas)

    def add_untimed_staged(self, mu: MetricUnion,
                           active: Optional[StagedMetadata],
                           now: int) -> bool:
        return self._entry_for(mu.id, mu.type).add_untimed_staged(
            mu, active, now)

    def add_timed(self, metric_type: MetricType, metric_id: bytes,
                  t_nanos: int, value: float, policy: StoragePolicy,
                  aggregation_id: int = 0) -> bool:
        return self._entry_for(metric_id, metric_type).add_timed(
            t_nanos, value, policy, aggregation_id)

    def add_forwarded(self, metric_type: MetricType, metric_id: bytes,
                      t_nanos: int, value: float, meta: ForwardMetadata) -> bool:
        return self._entry_for(metric_id, metric_type).add_forwarded(
            t_nanos, value, meta)

    def tick(self) -> int:
        """Expire idle entries (map.go tick + entry.go ShouldExpire)."""
        now = self._clock()
        expired = [
            mid for mid, e in self._entries.items()
            if now - e.last_access_nanos > self._entry_ttl_ns
        ]
        for mid in expired:
            for elem in self._entries[mid]._elems.values():
                elem.tombstoned = True
            del self._entries[mid]
        return len(expired)
