"""Staged safe-deploy orchestration for aggregator fleets (reference:
src/aggregator/tools/deploy — deploy in batches, always followers first,
force leader resignation before touching a leader, validate health between
stages so a bad build never takes out both replicas of a shard set)."""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence


@dataclasses.dataclass
class InstanceInfo:
    instance_id: str
    shard_set_id: str
    is_leader: bool
    healthy: bool = True


class DeployError(RuntimeError):
    pass


class Deployer:
    """tools/deploy/planner.go + helper.go: plan stages (followers of each
    shard set first, then leaders after resignation), execute with health
    validation."""

    def __init__(self,
                 inspect: Callable[[str], InstanceInfo],
                 deploy_one: Callable[[str], None],
                 resign: Callable[[str], None],
                 max_stage_fraction: float = 0.5,
                 health_timeout_s: float = 30.0,
                 poll_interval_s: float = 0.2):
        """inspect(id) -> InstanceInfo; deploy_one(id) updates+restarts;
        resign(id) forces leadership hand-off."""
        self._inspect = inspect
        self._deploy_one = deploy_one
        self._resign = resign
        self._max_fraction = max_stage_fraction
        self._health_timeout_s = health_timeout_s
        self._poll_interval_s = poll_interval_s
        self.stages_executed: List[List[str]] = []

    def plan(self, instance_ids: Sequence[str]) -> List[List[str]]:
        """Followers first (batched by shard set so at most one replica of a
        shard set per stage), leaders last (planner.go GeneratePlan)."""
        infos = [self._inspect(i) for i in instance_ids]
        followers = [i for i in infos if not i.is_leader]
        leaders = [i for i in infos if i.is_leader]
        stages: List[List[str]] = []
        for group in (followers, leaders):
            pending = list(group)
            while pending:
                stage, used_sets = [], set()
                limit = max(1, int(len(infos) * self._max_fraction))
                rest = []
                for info in pending:
                    if (info.shard_set_id not in used_sets
                            and len(stage) < limit):
                        stage.append(info.instance_id)
                        used_sets.add(info.shard_set_id)
                    else:
                        rest.append(info)
                stages.append(stage)
                pending = rest
        return stages

    def execute(self, instance_ids: Sequence[str]) -> List[List[str]]:
        stages = self.plan(instance_ids)
        for stage in stages:
            for iid in stage:
                info = self._inspect(iid)
                if info.is_leader:
                    # Never deploy a live leader (helper.go resign-first).
                    self._resign(iid)
                    self._wait(lambda: not self._inspect(iid).is_leader,
                               f"{iid} did not resign leadership")
                self._deploy_one(iid)
            for iid in stage:
                self._wait(lambda: self._inspect(iid).healthy,
                           f"{iid} unhealthy after deploy")
            self.stages_executed.append(stage)
        return stages

    def _wait(self, cond: Callable[[], bool], msg: str):
        deadline = time.monotonic() + self._health_timeout_s
        while time.monotonic() < deadline:
            if cond():
                return
            time.sleep(self._poll_interval_s)
        raise DeployError(msg)
