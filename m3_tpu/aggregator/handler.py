"""Flush output handlers (reference: src/aggregator/aggregator/handler/ —
blackhole, logging, broadcast, protobuf->m3msg producer handler.go:38).

A handler receives fully-aggregated datapoints (id, timestamp, value,
storage policy). The production path publishes them onto the m3msg-style
sharded pub/sub (m3_tpu.msg) for the coordinator's ingester to consume;
tests use the capture/blackhole handlers."""

from __future__ import annotations

import logging
from typing import Callable, List, NamedTuple, Sequence

from ..metrics.policy import StoragePolicy
from ..utils.limits import Backpressure


class AggregatedMetric(NamedTuple):
    id: bytes
    time_nanos: int
    value: float
    storage_policy: StoragePolicy


def _tolist(col):
    return col.tolist() if hasattr(col, "tolist") else list(col)


class Handler:
    def handle(self, metric: AggregatedMetric):  # pragma: no cover - iface
        raise NotImplementedError

    # Adapter so handlers can be passed directly as MetricList flush_fn.
    def __call__(self, metric_id: bytes, time_nanos: int, value: float,
                 storage_policy: StoragePolicy):
        self.handle(AggregatedMetric(metric_id, time_nanos, value, storage_policy))

    def handle_columnar(self, groups):
        """One flush round's emissions as columnar groups of
        (ids, times int64 array, values f64 array, storage_policy) —
        the columnar flush (aggregator/list.py emit_batch) hands the
        WHOLE round in one call so handlers can batch per destination
        (ProducerHandler overrides: one publish per topic shard per
        round). Default: unbatched per-datapoint handle()."""
        for ids, times, values, policy in groups:
            for mid, t, v in zip(ids, _tolist(times), _tolist(values)):
                self.handle(AggregatedMetric(mid, t, v, policy))


class BlackholeHandler(Handler):
    """Drops everything (handler/blackhole.go)."""

    def handle(self, metric: AggregatedMetric):
        pass


class CaptureHandler(Handler):
    """Accumulates flushed metrics in memory — the test sink."""

    def __init__(self):
        self.metrics: List[AggregatedMetric] = []

    def handle(self, metric: AggregatedMetric):
        self.metrics.append(metric)

    def by_id(self, metric_id: bytes) -> List[AggregatedMetric]:
        return [m for m in self.metrics if m.id == metric_id]


class FileHandler(Handler):
    """Appends one durable line per aggregated datapoint:
    `id<TAB>time_nanos<TAB>value<TAB>policy`. Each line is flushed+fsynced
    before handle() returns, so datapoints a leader emitted survive a
    SIGKILL — what lets the failover smoke assert exactly-once flushing
    across a leader crash (the durable analog of handler/logging.go for
    multi-process tests)."""

    def __init__(self, path: str):
        self._f = open(path, "ab", buffering=0)

    def handle(self, metric: AggregatedMetric):
        import os as _os

        self._f.write(b"%s\t%d\t%r\t%s\n" % (
            metric.id, metric.time_nanos, metric.value,
            str(metric.storage_policy).encode()))
        _os.fsync(self._f.fileno())

    def close(self):
        self._f.close()


class LoggingHandler(Handler):
    """handler/logging.go"""

    def __init__(self, logger=None):
        self._log = logger or logging.getLogger("m3_tpu.aggregator.flush")

    def handle(self, metric: AggregatedMetric):
        self._log.info("flush %s@%d=%g (%s)", metric.id, metric.time_nanos,
                       metric.value, metric.storage_policy)


class BroadcastHandler(Handler):
    """Fan out to several handlers (handler/broadcast.go)."""

    def __init__(self, handlers: Sequence[Handler]):
        self._handlers = list(handlers)

    def handle(self, metric: AggregatedMetric):
        for h in self._handlers:
            h.handle(metric)

    def handle_columnar(self, groups):
        for h in self._handlers:
            h.handle_columnar(groups)


class CallbackHandler(Handler):
    """Bridges to an arbitrary callable (used by the coordinator downsampler's
    flush handler, src/cmd/services/m3coordinator/downsample/flush_handler.go)."""

    def __init__(self, fn: Callable[[AggregatedMetric], None]):
        self._fn = fn

    def handle(self, metric: AggregatedMetric):
        self._fn(metric)


class ProducerHandler(Handler):
    """Publishes flushed metrics onto an m3msg producer (handler/protobuf.go:38
    NewProtobufHandler), sharded by metric id the same way the data plane
    shards series. The coordinator's m3msg ingester decodes and writes to
    storage (src/cmd/services/m3coordinator/ingest/m3msg)."""

    def __init__(self, producer, num_shards: int):
        from ..rpc import wire
        from ..utils.hashing import murmur3_32_cached

        self._producer = producer
        self._num_shards = num_shards
        self._encode = wire.encode
        self._hash = murmur3_32_cached
        self.dropped_backpressure = 0
        self.publishes = 0

    def handle(self, metric: AggregatedMetric):
        payload = self._encode({
            "id": metric.id,
            "t": metric.time_nanos,
            "v": metric.value,
            "sp": str(metric.storage_policy),
        })
        try:
            self._producer.publish(
                self._hash(metric.id) % self._num_shards, payload)
            self.publishes += 1
        except Backpressure:
            # The producer buffer is past its watermark: the flush must
            # finish (a wedged flush loses EVERY window, not one metric),
            # so this datapoint is counted as dropped — the same outcome
            # drop-oldest would have forced, surfaced explicitly and
            # earlier, while the buffer still holds undropped history.
            self.dropped_backpressure += 1

    def handle_columnar(self, groups):
        """One flush round batched: rows bucket by topic shard and ship
        as ONE columnar publish per shard per round (ids + raw int64/f64
        columns + per-row policy strings) instead of one encode+publish
        per datapoint. The coordinator ingester decodes either payload
        form via decode_aggregated_batch. Publishes counted in
        `publishes` so tests/smokes can assert the one-publish-per-
        destination contract."""
        import numpy as np

        shards: dict = {}
        nsh = self._num_shards
        h = self._hash
        for ids, times, values, policy in groups:
            sp = str(policy)
            for mid, t, v in zip(ids, _tolist(times), _tolist(values)):
                shards.setdefault(h(mid) % nsh, []).append((mid, t, v, sp))
        for shard, rows in shards.items():
            payload = self._encode({
                "b": 1,
                "ids": [r[0] for r in rows],
                "ts": np.asarray([r[1] for r in rows], np.int64),
                "vs": np.asarray([r[2] for r in rows], np.float64),
                "sps": [r[3] for r in rows],
            })
            try:
                self._producer.publish(shard, payload)
                self.publishes += 1
            except Backpressure:
                # same contract as handle(): the flush must finish; the
                # whole shard batch is counted dropped
                self.dropped_backpressure += len(rows)


def decode_aggregated(payload: bytes) -> AggregatedMetric:
    """Inverse of ProducerHandler's encoding, for the coordinator ingester."""
    from ..metrics.policy import StoragePolicy
    from ..rpc import wire

    obj = wire.decode(payload)
    return AggregatedMetric(
        obj["id"], obj["t"], obj["v"], StoragePolicy.parse(obj["sp"]))


def decode_aggregated_batch(payload: bytes) -> List[AggregatedMetric]:
    """Decode either ProducerHandler payload form — one single-metric
    dict (handle) or one columnar shard batch (handle_columnar) — into
    a list of AggregatedMetric."""
    from ..metrics.policy import StoragePolicy
    from ..rpc import wire

    obj = wire.decode(payload)
    if not obj.get("b"):
        return [AggregatedMetric(
            obj["id"], obj["t"], obj["v"], StoragePolicy.parse(obj["sp"]))]
    pols: dict = {}
    out = []
    for mid, t, v, sp in zip(obj["ids"], _tolist(obj["ts"]),
                             _tolist(obj["vs"]), obj["sps"]):
        pol = pols.get(sp)
        if pol is None:
            pol = pols[sp] = StoragePolicy.parse(sp)
        out.append(AggregatedMetric(mid, t, v, pol))
    return out
