"""Dual-format ingestion migration (reference:
src/metrics/encoding/migration/unaggregated_iterator.go sniffing
msgpack-vs-protobuf per message, convert.go lifting legacy
metric+policies into the staged-metadata model).

One aggregator port accepts BOTH wire generations simultaneously, per
message, so fleets migrate client-by-client with no flag day:

* current: the framed binary codec (m3_tpu.rpc.wire) — 4-byte big-endian
  length prefix + tagged binary body;
* legacy v1: newline-delimited JSON records, the pre-binary text schema
  that carried plain storage policies instead of staged metadatas:
      {"type": "counter"|"gauge"|"timer", "id": <str>,
       "value": <num or list>, "policies": ["10s:2d", ...]}

Format detection mirrors the reference's version-byte sniff, adapted to
this wire's little-endian length prefix: a message is legacy iff byte 0
is '{' (0x7b) AND byte 3 is non-zero — a binary frame under
MIGRATION_MAX_FRAME (16 MiB) always has 0x00 in byte 3 (the length's
most-significant byte), while byte 3 of a JSON record is printable
ASCII. Frames above that cap are rejected on migration-mode connections
so the two byte spaces can never collide."""

from __future__ import annotations

import json
import struct
from typing import List

from ..metrics.metric import MetricType
from ..rpc import wire

MIGRATION_MAX_FRAME = 1 << 24  # keeps length byte 3 at 0x00, unlike ASCII

_U32 = struct.Struct("<I")  # must match m3_tpu.rpc.wire framing

_LEGACY_TYPES = {
    "counter": MetricType.COUNTER,
    "gauge": MetricType.GAUGE,
    "timer": MetricType.TIMER,
}


class RecoverableRecordError(ValueError):
    """A single bad record whose bytes were fully consumed — the stream is
    still frame-aligned, so the connection can keep reading (the reference
    iterator likewise reports per-message decode errors without tearing the
    reader down)."""


def legacy_to_entry(rec: dict) -> dict:
    """convert.go toUnaggregatedMessageUnion: legacy metric + policies ->
    a current-schema untimed entry. Legacy policies carry no aggregation
    types or pipelines, so they become one default staged metadata (agg_id
    0 = metric-type defaults, empty pipeline, cutover 0)."""
    try:
        mtype = _LEGACY_TYPES[rec["type"]]
    except KeyError:
        raise ValueError(f"legacy record: unknown type {rec.get('type')!r}")
    value = rec["value"]
    if mtype == MetricType.TIMER:
        value = [float(v) for v in value]
    elif mtype == MetricType.COUNTER:
        value = int(value)
    else:
        value = float(value)
    policies = [str(p) for p in rec.get("policies", [])]
    return {
        "t": "untimed",
        "mtype": int(mtype),
        "id": rec["id"].encode(),
        "value": value,
        "metadatas": [{
            "cutover": 0,
            "tombstoned": False,
            "pipelines": [{
                "agg_id": 0,
                "policies": policies,
                "pipeline": [],
                "drop": False,
            }],
        }],
    }


def write_legacy(sock, metric_type: str, metric_id: str, value,
                 policies: List[str] = ()) -> None:
    """Emit one legacy v1 record — what a not-yet-migrated client sends."""
    rec = {"type": metric_type, "id": metric_id, "value": value,
           "policies": list(policies)}
    sock.sendall(json.dumps(rec).encode() + b"\n")


class MigrationReader:
    """Per-connection reader yielding current-schema entries regardless of
    which generation each message was written in (the analog of
    migration.unaggregatedIterator holding both sub-iterators over one
    shared stream)."""

    def __init__(self, sock):
        self._sock = sock
        self._buf = bytearray()

    def _fill(self, n: int) -> None:
        while len(self._buf) < n:
            chunk = self._sock.recv(1 << 20)
            if not chunk:
                raise ConnectionError("migration: peer closed")
            self._buf += chunk

    def _take(self, n: int) -> bytes:
        self._fill(n)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def read_entries(self) -> List[dict]:
        """Read ONE message (either generation); return its entries in the
        current schema (a binary batch frame may carry several)."""
        self._fill(4)
        if self._buf[0] == 0x7B and self._buf[3] != 0:  # legacy JSON line
            while b"\n" not in self._buf:
                self._fill(len(self._buf) + 1)
            line, _, rest = bytes(self._buf).partition(b"\n")
            self._buf = bytearray(rest)
            # A line that isn't JSON at all means the sniff mis-fired — most
            # likely a corrupt/oversize binary frame whose length LSB
            # happened to be '{' — and the bytes consumed up to this
            # arbitrary newline desynchronized the stream. That is NOT
            # recoverable: re-raise as a plain error so the server tears the
            # connection down instead of ingesting garbage. Only a
            # well-formed JSON object with a bad schema keeps the
            # frame-aligned recoverable contract.
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(f"migration: stream desync (not JSON): {e}")
            try:
                return [legacy_to_entry(rec)]
            except (ValueError, KeyError, TypeError) as e:
                raise RecoverableRecordError(f"bad legacy record: {e}")
        (n,) = _U32.unpack(self._take(4))
        if n > MIGRATION_MAX_FRAME:
            raise ValueError(
                f"migration: frame too large ({n} > {MIGRATION_MAX_FRAME})")
        frame = wire.decode(self._take(n))
        if isinstance(frame, dict) and frame.get("t") == "batch":
            return list(frame["entries"])
        return [frame]
