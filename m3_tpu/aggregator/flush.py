"""Flush management: leader flushes, followers shadow via KV-persisted flush
times (reference: src/aggregator/aggregator/{flush_mgr.go:188,
leader_flush_mgr.go, follower_flush_mgr.go, flush_times_mgr.go}).

The leader consumes closed windows and emits them to handlers, then persists
per-resolution flushed-up-to times to the KV store. Followers run the same
windowed state but, instead of emitting, discard windows the leader has
already flushed — so on failover the new leader resumes exactly one window
after the old leader's last persisted flush, never double-emitting."""

from __future__ import annotations

import json
from typing import Callable, Dict, Optional

from ..cluster import kv as cluster_kv
from .election import ElectionManager, ElectionState
from .list import MetricLists


class FlushTimesManager:
    """Persist/read per-(shard, resolution) flush times in KV
    (flush_times_mgr.go; the proto ShardSetFlushTimes is likewise keyed by
    shard within the shard set, so concurrent shard flushes never clobber
    each other's entries)."""

    def __init__(self, store: cluster_kv.MemStore, shard_set_id: str):
        self._store = store
        self._prefix = f"_agg/flush_times/{shard_set_id}"

    def _key(self, shard_id: int) -> str:
        return f"{self._prefix}/{shard_id}"

    def get(self, shard_id: int) -> Dict[int, int]:
        val = self._store.get(self._key(shard_id))
        if val is None:
            return {}
        raw = json.loads(val.data.decode())
        return {int(k): int(v) for k, v in raw.items()}

    def store(self, shard_id: int, flush_times: Dict[int, int]):
        self._store.set(self._key(shard_id), json.dumps(
            {str(k): v for k, v in flush_times.items()}).encode())

    def store_many(self, updates: Dict[int, Dict[int, int]]):
        """Persist one flush round's times for MANY shards as one kv
        transaction (MemStore.set_many): leader flush no longer
        serializes on a kv round trip per shard. Stores without a batch
        API (e.g. the remote kv client) fall back to per-shard sets."""
        if not updates:
            return
        items = {self._key(sid): json.dumps(
            {str(k): v for k, v in ft.items()}).encode()
            for sid, ft in updates.items()}
        set_many = getattr(self._store, "set_many", None)
        if set_many is not None:
            set_many(items)
        else:
            for key, data in items.items():
                self._store.set(key, data)


class FlushManager:
    """Drives per-resolution flushes against election state (flush_mgr.go:188).

    flush(now) aligns each resolution's flush target to its window boundary:
    target = now - now % resolution, consuming every fully-closed window.
    """

    def __init__(self, lists: MetricLists, election: ElectionManager,
                 flush_times: FlushTimesManager,
                 flush_fn: Callable, forward_fn: Optional[Callable] = None,
                 buffer_past_ns: int = 0, shard_id: int = 0):
        self._lists = lists
        self._election = election
        self._flush_times = flush_times
        self._flush_fn = flush_fn
        self._forward_fn = forward_fn
        self._shard_id = shard_id
        # Extra delay before a window is considered closed, allowing late
        # arrivals (list.go flushBeforeFn maxLatenessAllowed analog).
        self._buffer_past_ns = buffer_past_ns
        self.windows_flushed = 0
        self.windows_discarded = 0

    def flush(self, now_nanos: int) -> int:
        """One standalone flush pass; returns number of windows consumed."""
        from .list import FlushBatch, emit_batch

        batch = FlushBatch()
        n, commit = self.plan_into(now_nanos, batch)
        emit_batch(batch, self._flush_fn, self._forward_fn)
        commit()
        return n if self._election.state == ElectionState.LEADER else 0

    def plan_into(self, now_nanos: int, batch):
        """Collect this manager's closed windows into `batch` (a columnar
        list.FlushBatch, so a caller can batch many managers' shards into
        ONE device reduction — Aggregator.flush does this across shards)
        plus a commit callback. commit(pending=None): with a dict, the
        shard's updated flush times are RECORDED into it for one batched
        FlushTimesManager.store_many; without, they store immediately.
        Returns (windows_collected, commit)."""
        self._election.campaign()
        if self._election.state == ElectionState.LEADER:
            return self._plan_as_leader(now_nanos, batch)
        return self._plan_as_follower(now_nanos)

    def _plan_as_leader(self, now_nanos: int, batch):
        flushed = self._flush_times.get(self._shard_id)
        n = 0
        stale = 0
        for lst in self._lists.lists():
            res = lst.resolution_ns
            target = (now_nanos - self._buffer_past_ns) // res * res
            # Windows the previous leader already flushed (per KV flush
            # times) are discarded, not re-emitted: a promoted follower
            # may still hold closed windows it had not yet discarded, and
            # re-emitting them would double-count in forwarded rollup
            # pipelines.
            c, d = lst.collect_into(target, batch,
                                    already=flushed.get(res, 0))
            n += c
            stale += d
            # Resume after the last persisted flush (leader_flush_mgr.go:
            # flush times seed the flush schedule on promotion).
            flushed[res] = max(flushed.get(res, 0), target)
        self.windows_discarded += stale
        self.windows_flushed += n

        def commit(pending: Optional[Dict[int, Dict[int, int]]] = None):
            if pending is None:
                self._flush_times.store(self._shard_id, flushed)
            else:
                pending[self._shard_id] = flushed

        return n, commit

    def _plan_as_follower(self, now_nanos: int):
        """Discard windows the leader already flushed (follower_flush_mgr.go
        flushersFromKVUpdateFn): keeps follower memory bounded and marks the
        follower caught-up so PendingFollower can complete."""
        flushed = self._flush_times.get(self._shard_id)
        caught_up = True
        discarded = 0
        for lst in self._lists.lists():
            leader_target = flushed.get(lst.resolution_ns)
            if leader_target is None:
                caught_up = False
                continue
            discarded += len(lst.collect(leader_target))
        self.windows_discarded += discarded

        def commit(pending=None):
            if caught_up:
                self._election.confirm_follower()

        return 0, commit


def plan_jobs(lists: MetricLists, now_nanos: int, buffer_past_ns: int,
              flush_fn: Callable, forward_fn: Optional[Callable],
              flushed: Optional[Dict[int, int]] = None):
    """Collect closed-window reduce jobs for every list, with the flush
    target aligned down to each resolution boundary (list.go flush-before
    alignment). Shared by the managed (leader) and leaderless paths.

    With `flushed` (per-resolution flushed-up-to times from KV), windows
    already covered by a previous leader's persisted flush are dropped.
    Returns (jobs, n_dropped).
    """
    jobs = []
    dropped = 0
    for lst in lists.lists():
        res = lst.resolution_ns
        target = (now_nanos - buffer_past_ns) // res * res
        already = flushed.get(res, 0) if flushed else 0
        for elem, start, vals in lst.collect(target):
            if start + res <= already:
                dropped += 1
                continue
            jobs.append((elem, start, vals, flush_fn, forward_fn))
    return jobs, dropped
