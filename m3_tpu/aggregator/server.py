"""Aggregator network ingestion server (reference:
src/aggregator/server/rawtcp/server.go:122 — raw TCP connections carrying
unaggregated metrics with their staged metadatas). Each connection reads
through the dual-format migration reader (m3_tpu.aggregator.migration):
the framed binary codec below is the current generation, and legacy
JSON-line clients keep working during migration.

Wire frames:
  {"t": "untimed", "mtype": i64, "id": bytes, "value": f64|i64|list,
   "metadatas": [...]}
  {"t": "timed", "mtype": i64, "id": bytes, "time": i64, "value": f64,
   "policy": str, "agg_id": i64}
  {"t": "forwarded", "mtype": i64, "id": bytes, "time": i64, "value": f64,
   "agg_id": i64, "policy": str, "pipeline": [...], "source_id": bytes,
   "num_times": i64}   (partial aggregates between pipeline stages,
   reference: src/aggregator/server/rawtcp handling of forwarded metric
   unions + forwarded_writer.go)
A batch frame {"t": "batch", "entries": [...]} carries many at once.

A COLUMNAR timed batch amortizes the per-entry codec and parse cost —
the dominant share of the per-connection ingest ceiling once dispatch
itself is memoized (policy parse + shard hash). One frame carries one
(mtype, policy, agg_id) group:
  {"t": "tbatch", "mtype": i64, "policy": str, "agg_id": i64,
   "ids": [bytes, ...], "times": ndarray i64, "values": ndarray f64}
The codec writes the two numeric columns as raw ndarray buffers (no
per-element marshalling) and the six key strings once per frame instead
of once per datapoint; the server parses policy/type once and loops
add_timed. This is the wire shape of the reference's protobuf
WriteTimedBatch (src/aggregator/client/client.go WriteTimed batching).
"""

from __future__ import annotations

import socketserver
import threading
from typing import List, Optional, Sequence

import numpy as np

from ..metrics.metadata import (ForwardMetadata, Metadata, PipelineMetadata,
                                StagedMetadata)
from ..metrics.matcher import pipeline_from_json, pipeline_to_json
from ..metrics.metric import MetricType, MetricUnion
from ..metrics.policy import StoragePolicy
from ..rpc import wire
from ..utils.health import AdmissionGate, Priority
from ..utils.limits import Backpressure, tenant_of
from .aggregator import Aggregator


def _frame_tenant(e: dict) -> Optional[bytes]:
    """Tenant for admission fair-share: the explicit frame hint `tn`
    when present, else the metric id prefix of the frame's (first) id
    (utils/limits.tenant_of). Forwarded frames are CRITICAL and bypass
    tenant shedding anyway; extraction still tags their depth."""
    tn = e.get("tn")
    if tn is not None:
        return tn if isinstance(tn, bytes) else str(tn).encode()
    mid = e.get("id")
    if mid is None:
        ids = e.get("ids")
        mid = ids[0] if isinstance(ids, (list, tuple)) and ids else None
    if isinstance(mid, (bytes, bytearray, memoryview)):
        return tenant_of(bytes(mid))
    return None


def metadatas_to_wire(metadatas: Sequence[StagedMetadata]) -> list:
    return [
        {
            "cutover": sm.cutover_nanos,
            "tombstoned": sm.tombstoned,
            "pipelines": [
                {
                    "agg_id": pm.aggregation_id,
                    "policies": [str(p) for p in pm.storage_policies],
                    "pipeline": pipeline_to_json(pm.pipeline),
                    "drop": pm.drop_policy,
                }
                for pm in sm.metadata.pipelines
            ],
        }
        for sm in metadatas
    ]


def metadatas_from_wire(obj: list) -> tuple:
    return tuple(
        StagedMetadata(
            d["cutover"], d["tombstoned"],
            Metadata(tuple(
                PipelineMetadata(
                    p["agg_id"],
                    tuple(StoragePolicy.parse(s) for s in p["policies"]),
                    pipeline_from_json(p["pipeline"]),
                    p["drop"],
                )
                for p in d["pipelines"]
            )),
        )
        for d in obj
    )


def union_to_wire(mu: MetricUnion, metadatas: Sequence[StagedMetadata]) -> dict:
    if mu.type == MetricType.TIMER:
        value = list(mu.batch_timer_val)
    elif mu.type == MetricType.COUNTER:
        value = mu.counter_val
    else:
        value = mu.gauge_val
    return {"t": "untimed", "mtype": int(mu.type), "id": mu.id,
            "value": value, "metadatas": metadatas_to_wire(metadatas)}


def forwarded_to_wire(metric_type: MetricType, metric_id: bytes,
                      t_nanos: int, value: float, meta: ForwardMetadata) -> dict:
    return {
        "t": "forwarded", "mtype": int(metric_type), "id": metric_id,
        "time": t_nanos, "value": float(value),
        "agg_id": meta.aggregation_id, "policy": str(meta.storage_policy),
        "pipeline": pipeline_to_json(meta.pipeline),
        "source_id": meta.source_id, "num_times": meta.num_forwarded_times,
    }


def forwarded_batch_to_wire(metric_type: MetricType, rows) -> dict:
    """One flush round's rollup forwards for one (destination, meta
    group) as a COLUMNAR `fbatch` frame (the tbatch shape for the
    forwarded plane): numeric columns ride as raw ndarray buffers, the
    shared meta fields once per frame instead of once per datapoint.
    Rows are (new_id, t_nanos, value, meta, source_id) with identical
    meta group fields (ForwardedWriter.forward_batch groups them)."""
    meta = rows[0][3]
    return {
        "t": "fbatch", "mtype": int(metric_type),
        "agg_id": meta.aggregation_id,
        "policy": str(meta.storage_policy),
        "pipeline": pipeline_to_json(meta.pipeline),
        "num_times": meta.num_forwarded_times,
        "ids": [r[0] for r in rows],
        "source_ids": [r[4] for r in rows],
        "times": np.asarray([r[1] for r in rows], np.int64),
        "values": np.asarray([r[2] for r in rows], np.float64),
    }


def dispatch_forwarded_batch(agg: Aggregator, e: dict):
    """Columnar forwarded batch: meta parsed once, numeric columns
    converted in one C pass, then the tight add_forwarded loop. Validates
    everything that could raise BEFORE the first add (the tbatch
    all-or-nothing contract: a rejected frame never leaves a partially
    aggregated prefix for the sender's retry to double-count)."""
    ids = e["ids"]
    srcs = e["source_ids"]
    times = e["times"]
    values = e["values"]
    if not (len(ids) == len(srcs) == len(times) == len(values)):
        raise ValueError(
            f"fbatch column length mismatch: {len(ids)} ids, "
            f"{len(srcs)} source_ids, {len(times)} times, "
            f"{len(values)} values")
    if not all(isinstance(m, (bytes, bytearray, memoryview))
               for m in ids) or not all(
                   isinstance(m, (bytes, bytearray, memoryview))
                   for m in srcs):
        raise ValueError("fbatch ids/source_ids must all be bytes")
    ids = [m if type(m) is bytes else bytes(m) for m in ids]
    srcs = [m if type(m) is bytes else bytes(m) for m in srcs]
    mt = MetricType(e["mtype"])
    agg_id = e["agg_id"]
    pol = StoragePolicy.parse(e["policy"])
    pipe = pipeline_from_json(e["pipeline"])
    num_times = e["num_times"]
    times = np.asarray(times)
    values = np.asarray(values)
    if times.dtype.kind not in "iuf" or values.dtype.kind not in "iuf":
        raise ValueError("fbatch times/values must be numeric columns")
    if times.ndim != 1 or values.ndim != 1:
        raise ValueError("fbatch times/values must be one-dimensional")
    add = agg.add_forwarded
    for mid, src, t, v in zip(ids, srcs, times.tolist(), values.tolist()):
        add(mt, mid, t, v, ForwardMetadata(
            aggregation_id=agg_id, storage_policy=pol, pipeline=pipe,
            source_id=src, num_forwarded_times=num_times))


def forwarded_from_wire(frame: dict):
    meta = ForwardMetadata(
        aggregation_id=frame["agg_id"],
        storage_policy=StoragePolicy.parse(frame["policy"]),
        pipeline=pipeline_from_json(frame["pipeline"]),
        source_id=frame["source_id"],
        num_forwarded_times=frame["num_times"],
    )
    return (MetricType(frame["mtype"]), frame["id"], frame["time"],
            frame["value"], meta)


def union_from_wire(frame: dict):
    mt = MetricType(frame["mtype"])
    mid = frame["id"]
    value = frame["value"]
    if mt == MetricType.TIMER:
        mu = MetricUnion.batch_timer(mid, [float(v) for v in value])
    elif mt == MetricType.COUNTER:
        mu = MetricUnion.counter(mid, int(value))
    else:
        mu = MetricUnion.gauge(mid, float(value))
    return mu, metadatas_from_wire(frame["metadatas"])


class RawTCPServer:
    """Accepts connections from aggregator clients; every frame feeds the
    local Aggregator (rawtcp/server.go handleConnection).

    Ingest admission: in-flight records are bounded by an AdmissionGate.
    The raw-TCP protocol is fire-and-forget (no per-record ack channel),
    so shed records are DROPPED and counted (`shed`) — collectors see
    loss in the counters, while producers speaking the acked msg path
    get real backpressure at the consumer. `forwarded` frames (partial
    aggregates between pipeline stages — already-accepted work whose
    loss corrupts downstream rollups) are CRITICAL and never shed; a
    frame may self-mark `"pri": "bulk"` (backfill replay) to shed
    first at the high watermark."""

    def __init__(self, aggregator: Aggregator, host: str = "127.0.0.1",
                 port: int = 0, gate: Optional[AdmissionGate] = None):
        self.aggregator = aggregator
        self.gate = gate if gate is not None else AdmissionGate(
            capacity=8192, name="aggregator.rawtcp")
        self.frames = 0
        self.errors = 0
        self.shed = 0
        # Counters are bumped from per-connection handler threads; a plain
        # += is a non-atomic load/add/store that loses increments.
        self._stats_lock = threading.Lock()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # Per-message dual-format reader: current framed codec and
                # the legacy JSON-line protocol share one port during client
                # migration (encoding/migration/unaggregated_iterator.go).
                from .migration import MigrationReader, RecoverableRecordError

                reader = MigrationReader(self.request)
                try:
                    while True:
                        try:
                            entries = reader.read_entries()
                        except RecoverableRecordError:
                            # one bad legacy record, stream still aligned
                            with outer._stats_lock:
                                outer.errors += 1
                            continue
                        except ValueError:
                            # binary framing is unrecoverable mid-stream
                            with outer._stats_lock:
                                outer.errors += 1
                            break
                        # frames counts successfully ingested RECORDS (a
                        # columnar tbatch carries one per id); a failed
                        # dispatch contributes errors, not phantom frames.
                        n_rec = sum(outer._handle(e) for e in entries)
                        with outer._stats_lock:
                            outer.frames += n_rec
                except (ConnectionError, OSError):
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)

    def _handle(self, e: dict) -> int:
        """Dispatch one entry; returns the record count it ingested
        (len(ids) for a columnar tbatch, else 1), 0 on failure. Both
        counters are in RECORDS: a failed tbatch charges its id count to
        `errors` (tbatch dispatch validates before the first add, so a
        failure means the whole frame was rejected — nothing partial)."""
        def _records() -> int:
            if e.get("t") not in ("tbatch", "fbatch"):
                return 1
            ids = e.get("ids")
            return len(ids) if isinstance(ids, (list, tuple)) else 1

        n = _records()
        pri = (Priority.CRITICAL if e.get("t") in ("forwarded", "fbatch")
               else Priority.BULK if e.get("pri") == "bulk"
               else Priority.NORMAL)
        try:
            with self.gate.held(n, priority=pri, tenant=_frame_tenant(e)):
                dispatch_entry(self.aggregator, e)
        except Backpressure:
            # fire-and-forget transport: shed = counted drop (the msg
            # path's consumer converts the same condition into a skipped
            # ack, i.e. real producer backpressure)
            with self._stats_lock:
                self.shed += n
            return 0
        except Exception:  # noqa: BLE001 - bad frame must not kill the conn
            with self._stats_lock:
                self.errors += n
            return 0
        return n

    @property
    def endpoint(self) -> str:
        h, p = self._server.server_address
        return f"{h}:{p}"

    def start(self) -> "RawTCPServer":
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    def close(self):
        self._server.shutdown()
        self._server.server_close()


def dispatch_entry(agg: Aggregator, e: dict):
    """Route one current-schema entry into the aggregator — the shared
    sink behind both transports (rawtcp frames and HTTP ingest)."""
    if e["t"] == "untimed":
        mu, metadatas = union_from_wire(e)
        agg.add_untimed(mu, metadatas)
    elif e["t"] == "timed":
        agg.add_timed(
            MetricType(e["mtype"]), e["id"], e["time"], e["value"],
            StoragePolicy.parse(e["policy"]), e.get("agg_id", 0))
    elif e["t"] == "tbatch":
        dispatch_timed_batch(agg, e)
    elif e["t"] == "fbatch":
        dispatch_forwarded_batch(agg, e)
    elif e["t"] == "forwarded":
        mt, mid, t_nanos, value, meta = forwarded_from_wire(e)
        agg.add_forwarded(mt, mid, t_nanos, value, meta)
    else:
        raise ValueError(f"unknown entry type {e.get('t')!r}")


def dispatch_timed_batch(agg: Aggregator, e: dict):
    """Columnar timed batch: type/policy parsed once, numeric columns
    converted in one C pass (tolist), then the tight add_timed loop. A
    length mismatch between the columns is a malformed frame (ValueError
    -> the caller's per-entry error accounting)."""
    ids = e["ids"]
    times = e["times"]
    values = e["values"]
    if not (len(ids) == len(times) == len(values)):
        raise ValueError(
            f"tbatch column length mismatch: {len(ids)} ids, "
            f"{len(times)} times, {len(values)} values")
    # Validate EVERYTHING that could raise before the first add: the
    # frame must ingest all-or-nothing, or a mid-loop failure would leave
    # a prefix aggregated while the stats report the whole frame failed
    # (and a sender retry would double-count that prefix).
    if not all(isinstance(m, (bytes, bytearray, memoryview)) for m in ids):
        raise ValueError("tbatch ids must all be bytes")
    # Normalize ids to bytes AFTER the isinstance gate: add_timed ->
    # shard_for memoizes on the id, and a bytearray/memoryview that
    # passed validation would raise (unhashable) on the Nth add.
    ids = [m if type(m) is bytes else bytes(m) for m in ids]
    mt = MetricType(e["mtype"])
    pol = StoragePolicy.parse(e["policy"])
    agg_id = e.get("agg_id", 0)
    # One C-pass conversion doubling as element validation: a list with a
    # non-numeric mid-array element coerces to a non-numeric dtype and is
    # rejected HERE, never mid-loop (np.asarray also raises ValueError on
    # ragged input).
    times = np.asarray(times)
    values = np.asarray(values)
    if times.dtype.kind not in "iuf" or values.dtype.kind not in "iuf":
        raise ValueError("tbatch times/values must be numeric columns")
    if times.ndim != 1 or values.ndim != 1:
        raise ValueError("tbatch times/values must be one-dimensional")
    times = times.tolist()
    values = values.tolist()
    add = agg.add_timed
    for mid, t, v in zip(ids, times, values):
        add(mt, mid, t, v, pol, agg_id)


class HTTPAdminServer:
    """Aggregator HTTP sidecar (src/aggregator/server/http/handlers.go):
    GET /health, GET /status (runtime flush/election status), and
    POST /resign to step down from flush leadership before maintenance —
    plus an HTTP INGEST variant: POST /ingest accepts newline-delimited
    legacy-schema JSON records (the migration reader's entry model,
    migration.legacy_to_entry), so collectors behind an HTTP-only network
    path can write without speaking the framed binary codec."""

    def __init__(self, aggregator: Aggregator, host: str = "127.0.0.1",
                 port: int = 0):
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        agg = aggregator

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, obj: dict):
                body = _json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._reply(200, {"state": "OK"})
                elif self.path == "/status":
                    election = getattr(agg, "_election", None)
                    flush = {
                        "electionState": (election.state.name.lower()
                                          if election else "leader"),
                        "canLead": (election.is_leader()
                                    if election else True),
                    }
                    self._reply(200, {"status": {
                        "flushStatus": flush,
                        "numEntries": agg.num_entries(),
                        "forwardedReceived": agg.forwarded_received,
                    }})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                if self.path == "/resign":
                    election = getattr(agg, "_election", None)
                    if election is None:
                        self._reply(400, {"error": "not running an election"})
                        return
                    try:
                        election.resign()
                        self._reply(200, {"state": "OK"})
                    except Exception as e:  # noqa: BLE001
                        self._reply(500, {"error": str(e)})
                elif self.path == "/ingest":
                    from .migration import legacy_to_entry

                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length)
                    accepted, errors = 0, []
                    for i, line in enumerate(body.splitlines()):
                        if not line.strip():
                            continue
                        try:
                            dispatch_entry(
                                agg, legacy_to_entry(_json.loads(line)))
                            accepted += 1
                        except Exception as e:  # noqa: BLE001
                            errors.append(f"record {i}: {e}")
                    code = 200 if not errors else 400
                    self._reply(code, {"accepted": accepted,
                                       "errors": errors[:16]})
                else:
                    self._reply(404, {"error": "not found"})

        self._server = ThreadingHTTPServer((host, port), _Handler)

    @property
    def endpoint(self) -> str:
        h, p = self._server.server_address
        return f"http://{h}:{p}"

    def start(self) -> "HTTPAdminServer":
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    def close(self):
        self._server.shutdown()
        self._server.server_close()


class _BatchingTransport:
    """Shared client-side batching scaffolding: __call__ encodes one metric
    and appends; a full batch (or flush()) sends via the subclass's
    _send_batch. Encoding failures return False like delivery failures —
    the AggregatorClient transport contract is bool, never an exception."""

    def __init__(self, batch_size: int = 64):
        self._lock = threading.Lock()
        self._batch: List = []
        self._batch_size = batch_size

    def _encode(self, mu: MetricUnion, metadatas: Sequence[StagedMetadata]):
        raise NotImplementedError

    def _send_batch(self, batch: List) -> bool:
        raise NotImplementedError

    def __call__(self, mu: MetricUnion, metadatas: Sequence[StagedMetadata]) -> bool:
        try:
            entry = self._encode(mu, metadatas)
        except Exception:  # noqa: BLE001 - count as a dropped write
            return False
        with self._lock:
            self._batch.append(entry)
            if len(self._batch) < self._batch_size:
                return True
            batch, self._batch = self._batch, []
        return self._send_batch(batch)

    def flush(self) -> bool:
        with self._lock:
            batch, self._batch = self._batch, []
        return self._send_batch(batch) if batch else True


class HTTPTransport(_BatchingTransport):
    """Client-side HTTP ingest to one aggregator admin endpoint, usable as
    an AggregatorClient transport anywhere only HTTP traverses the network
    path. Serializes each metric as a legacy-schema record (the migration
    entry model) and POSTs newline-delimited batches to /ingest; staged
    metadatas flatten to their storage policies, which is exactly the
    information the legacy schema carries. Ids must be UTF-8 (the legacy
    JSON schema is text); non-decodable ids count as dropped writes."""

    def __init__(self, endpoint: str, batch_size: int = 64, timeout_s: float = 5.0):
        super().__init__(batch_size)
        self._url = endpoint.rstrip("/") + "/ingest"
        self._timeout_s = timeout_s

    def _encode(self, mu: MetricUnion, metadatas: Sequence[StagedMetadata]) -> bytes:
        import json as _json

        from .migration import _LEGACY_TYPES

        # inverse of the migration reader's type table, so /ingest always
        # accepts this transport's output
        type_names = {v: k for k, v in _LEGACY_TYPES.items()}
        policies = [str(p) for sm in metadatas
                    for pm in sm.metadata.pipelines
                    for p in pm.storage_policies]
        value = (list(mu.batch_timer_val) if mu.type == MetricType.TIMER
                 else mu.counter_val if mu.type == MetricType.COUNTER
                 else mu.gauge_val)
        return _json.dumps({"type": type_names[mu.type],
                            "id": mu.id.decode(),
                            "value": value, "policies": policies}).encode()

    def _send_batch(self, batch: List[bytes]) -> bool:
        import json as _json
        import urllib.request

        req = urllib.request.Request(
            self._url, data=b"\n".join(batch) + b"\n", method="POST",
            headers={"Content-Type": "application/x-ndjson"})
        try:
            with urllib.request.urlopen(req, timeout=self._timeout_s) as r:
                return _json.loads(r.read()).get("accepted", 0) == len(batch)
        except OSError:
            return False


class TCPTransport(_BatchingTransport):
    """Client-side connection to one aggregator instance, usable as an
    AggregatorClient transport (aggregator/client queue.go: buffered
    connection with reconnect)."""

    def __init__(self, endpoint: str, batch_size: int = 64):
        super().__init__(batch_size)
        self._endpoint = endpoint
        self._sock = None

    def _encode(self, mu: MetricUnion, metadatas: Sequence[StagedMetadata]) -> dict:
        return union_to_wire(mu, metadatas)

    def send_timed_batch(self, metric_type: MetricType, policy,
                         ids: Sequence[bytes], times, values,
                         agg_id: int = 0) -> bool:
        """Ship one (type, policy) group of timed datapoints as a single
        columnar tbatch frame — the codec writes the numeric columns as
        raw buffers and the keys once, so the per-datapoint wire cost is
        ~the raw bytes. This is the client half of the reference's timed
        batching (client.go WriteTimed + queue buffering)."""
        import numpy as _np

        return self._send_frame({
            "t": "tbatch", "mtype": int(metric_type), "policy": str(policy),
            "agg_id": agg_id, "ids": list(ids),
            "times": _np.asarray(times, _np.int64),
            "values": _np.asarray(values, _np.float64),
        })

    def send_forwarded_batch(self, metric_type: MetricType, rows) -> bool:
        """Deliver one flush round's rollup partials for one meta group
        as ONE columnar fbatch frame (forwarded_batch_to_wire) — the
        batched twin of send_forwarded, one frame per destination per
        round instead of one per datapoint. Rows are
        (new_id, t_nanos, value, meta, source_id)."""
        if not rows:
            return True
        with self._lock:
            batch, self._batch = self._batch, []
        if batch and not self._send_batch(batch):
            # The piggybacked client-buffer flush failed: re-buffer those
            # entries for the next send instead of folding their fate
            # into THIS frame's result — ForwardedWriter counts forward
            # drops from our return value, and a delivered fbatch must
            # not be reported dropped because unrelated buffered metrics
            # hit a dead connection.
            with self._lock:
                self._batch = batch + self._batch
        return self._send_frame(forwarded_batch_to_wire(metric_type, rows))

    def send_forwarded(self, metric_type: MetricType, metric_id: bytes,
                       t_nanos: int, value: float,
                       meta: ForwardMetadata) -> bool:
        """Deliver a partial aggregate to the next pipeline stage's owner.

        Sent immediately (not batched): forwards happen at flush boundaries,
        and the downstream stage's flush deadline is already ticking
        (forwarded_writer.go Flush)."""
        with self._lock:
            batch, self._batch = self._batch, []
        batch.append(forwarded_to_wire(metric_type, metric_id, t_nanos,
                                       value, meta))
        return self._send_batch(batch)

    def _send_batch(self, batch: List[dict]) -> bool:
        return self._send_frame({"t": "batch", "entries": batch})

    def _send_frame(self, frame: dict) -> bool:
        """Write one frame with one reconnect attempt — the shared send
        loop behind batch and tbatch shipping."""
        for _ in range(2):
            try:
                sock = self._ensure_conn()
                wire.write_frame(sock, frame)
                return True
            except OSError:
                self._drop_conn()
        return False

    def _ensure_conn(self):
        if self._sock is None:
            import socket as _socket

            host, _, port = self._endpoint.rpartition(":")
            self._sock = _socket.create_connection((host, int(port)), timeout=5.0)
            self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        return self._sock

    def _drop_conn(self):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        """Drop the connection and discard buffered entries — callers are
        placement updates retiring a stale peer, where flushing would send
        metrics to an instance that no longer owns them. Flush explicitly
        first for a graceful shutdown."""
        with self._lock:
            self._batch = []
        self._drop_conn()
