"""M3QL parser: the reference's third, pipe-based query language
(reference: src/query/parser/m3ql/grammar.peg — a PEG grammar feeding a
scriptBuilder; kept as a parser-level placeholder there, mirrored here at
the same level of integration).

Grammar (grammar.peg):

    Grammar      <- Spacing (MacroDef ';')* Pipeline EOF
    MacroDef     <- Identifier '=' Pipeline
    Pipeline     <- Expression ('|' Expression)*
    Expression   <- FunctionCall / '(' Pipeline ')'
    FunctionCall <- (Identifier / Operator) Argument*
    Argument     <- (Identifier ':')? (Boolean / Number / Pattern
                                       / StringLiteral / '(' Pipeline ')')

Example: ``fetch name:cpu.util host:web* | transform perSecond | > 0.5``.

The parser resolves macro references inside pipelines (a bare identifier
expression whose name matches an earlier macro splices that macro's
pipeline, matching the builder's macro table), and validates structure
only — execution is promql/graphite's job; m3ql scripts translate onto
the same batched Block dataflow when wired to an evaluator."""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple, Union

_OPERATORS = ("<=", "==", "!=", ">=", "<", ">")
_NUMBER = re.compile(r"[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?")

Arg = Union[bool, float, str, "Pipeline"]


class M3QLError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class Call:
    """One pipeline stage: function name + positional/keyword arguments."""

    name: str
    args: Tuple[Arg, ...] = ()
    kwargs: Tuple[Tuple[str, Arg], ...] = ()


@dataclasses.dataclass(frozen=True)
class Pipeline:
    stages: Tuple[Call, ...]


@dataclasses.dataclass(frozen=True)
class Script:
    macros: Tuple[Tuple[str, Pipeline], ...]
    pipeline: Pipeline


_TOKEN = re.compile(
    r"""
      (?P<space>[ \t\r\n]+|\#[^\r\n]*)
    | (?P<op><=|==|!=|>=|<|>)
    | (?P<punct>[|;:=()])
    | (?P<string>"(?:[^"\\]|\\.)*")
    | (?P<word>[A-Za-z_][A-Za-z0-9_./\\*?\[\]{},-]*|[^ \t\r\n|;:=()"#]+)
    """,
    re.VERBOSE,
)


def _tokenize(src: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if m is None:
            raise M3QLError(f"m3ql: cannot tokenize at offset {pos}: "
                            f"{src[pos:pos + 12]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "space":
            continue
        out.append((kind, m.group()))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.toks = tokens
        self.i = 0
        self.macros: Dict[str, Pipeline] = {}

    def peek(self) -> Tuple[str, str]:
        return self.toks[self.i]

    def take(self) -> Tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str):
        kind, val = self.take()
        if val != text:
            raise M3QLError(f"m3ql: expected {text!r}, got {val!r}")

    # Grammar <- (MacroDef ';')* Pipeline EOF — a macro def is only
    # distinguishable by the '=' after its identifier, so look ahead.
    def script(self) -> Script:
        macros: List[Tuple[str, Pipeline]] = []
        while (self.peek()[0] == "word"
               and self.toks[self.i + 1][1] == "="):
            name = self.take()[1]
            self.expect("=")
            pipe = self.pipeline()
            self.expect(";")
            self.macros[name] = pipe
            macros.append((name, pipe))
        pipe = self.pipeline()
        if self.peek()[0] != "eof":
            raise M3QLError(f"m3ql: trailing input at {self.peek()[1]!r}")
        return Script(tuple(macros), pipe)

    def pipeline(self) -> Pipeline:
        stages: List[Call] = [*self.expression()]
        while self.peek()[1] == "|":
            self.take()
            stages.extend(self.expression())
        return Pipeline(tuple(stages))

    def expression(self) -> Tuple[Call, ...]:
        kind, val = self.peek()
        if val == "(":
            self.take()
            pipe = self.pipeline()
            self.expect(")")
            return pipe.stages
        if kind not in ("word", "op"):
            raise M3QLError(f"m3ql: expected function, got {val!r}")
        self.take()
        # A bare identifier naming an earlier macro splices its pipeline
        # (scriptBuilder's macro resolution).
        if kind == "word" and val in self.macros and not self._at_argument():
            return self.macros[val].stages
        args: List[Arg] = []
        kwargs: List[Tuple[str, Arg]] = []
        while self._at_argument():
            kw: Optional[str] = None
            if (self.peek()[0] == "word"
                    and self.toks[self.i + 1][1] == ":"):
                kw = self.take()[1]
                self.take()  # ':'
            val_tok = self._argument()
            if kw is None:
                args.append(val_tok)
            else:
                kwargs.append((kw, val_tok))
        return (Call(val, tuple(args), tuple(kwargs)),)

    def _at_argument(self) -> bool:
        kind, val = self.peek()
        return (kind in ("word", "string") or val == "(")

    def _argument(self) -> Arg:
        kind, val = self.take()
        if val == "(":
            pipe = self.pipeline()
            self.expect(")")
            return pipe
        if kind == "string":
            return val[1:-1].replace('\\"', '"').replace("\\\\", "\\")
        if val in ("true", "false"):
            return val == "true"
        # Digit-based number rule like the reference PEG — NOT bare
        # float(), which also accepts "inf"/"nan"/"1_000" and would turn
        # identifier/pattern arguments into numbers.
        if _NUMBER.fullmatch(val):
            return float(val)
        return val  # pattern / identifier argument


def parse(src: str) -> Script:
    """Parse an m3ql script into (macros, pipeline)."""
    return _Parser(_tokenize(src)).script()
