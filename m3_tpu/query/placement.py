"""Transfer-aware query placement: device vs host, per evaluation.

The TPU-first design principle (SURVEY.md §2.13): put the math where the
data motion is cheapest. A range-function evaluation produces a
[series x steps] f32 result plane that must reach the host to serve HTTP;
on a locally-attached accelerator that D2H costs microseconds and the
device wins outright, but over a slow tunnel (~10-80MB/s observed) a
full-matrix result can cost more to SHIP than the host needs to COMPUTE.
The engine therefore contains the host path as a subset — the same jitted
XLA kernels compiled for the CPU backend — and routes each evaluation by
a measured cost model:

    host_cost  = cells / host_rate
    accel_cost = rtt + result_bytes / d2h_bw + cells / accel_rate

All four parameters are measured, not configured: d2h_bw and rtt from a
periodic 1MB probe of the real link (refreshed every PROBE_REFRESH_S),
host_rate / accel_rate as EWMAs of observed evaluations. Aggregated
shapes (sum(rate(..)) over the mesh) never come through here — their
result plane is tiny and the in-mesh scatter-gather path keeps them on
device (m3_tpu/parallel/query.py).

Reference analog: the coordinator's fanout storage picks local vs remote
per query (/root/reference/src/query/storage/fanout/storage.go:1); here
the "fanout" is across XLA backends with a measured link model.

Env: M3_TPU_QUERY_PLACEMENT = auto (default) | device | host.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

import numpy as np

PROBE_REFRESH_S = float(os.environ.get("M3_TPU_PLACEMENT_PROBE_S", "60"))
_PROBE_BYTES = 1 << 20

# Conservative priors, replaced by measurements after the first eval/probe:
# host ~150M grid cells/s (measured: rate+sum_over_time pair over 2x4.47M
# cells in ~60ms of XLA:CPU kernels), accel ~5G cells/s.
_HOST_RATE_PRIOR = 150e6
_ACCEL_RATE_PRIOR = 5e9


def _ewma(old: Optional[float], new: float, alpha: float = 0.3) -> float:
    return new if old is None else (1 - alpha) * old + alpha * new


class QueryPlacement:
    """Per-engine placement chooser + online cost model."""

    def __init__(self):
        self._lock = threading.Lock()
        self._mode = os.environ.get("M3_TPU_QUERY_PLACEMENT", "auto")
        self._host_rate: Optional[float] = None
        self._accel_rate: Optional[float] = None
        self._d2h_bw: Optional[float] = None   # bytes/s
        self._rtt: Optional[float] = None      # seconds
        self._probed_at: Optional[float] = None
        self._probe_fn = None
        self._cpu_device = None
        self._cpu_checked = False

    # -- devices -----------------------------------------------------------

    def _host_device(self):
        """The CPU backend device, or None when unavailable / already the
        default (JAX_PLATFORMS=cpu runs have nothing to place)."""
        if not self._cpu_checked:
            self._cpu_checked = True
            import jax

            try:
                if jax.default_backend() != "cpu":
                    self._cpu_device = jax.local_devices(backend="cpu")[0]
            except Exception:  # no cpu platform registered
                self._cpu_device = None
        return self._cpu_device

    # -- link probe --------------------------------------------------------

    def _claim_probe(self, now: float) -> bool:
        """Freshness guard, check-and-set under the lock: concurrent first
        queries must not each fire a 1MB probe and split the link N ways
        (each would measure ~bw/N and seed the EWMA low). None (never
        probed) always probes — a 0.0 sentinel would compare against raw
        monotonic time and skip every probe for the first PROBE_REFRESH_S
        after boot (CLOCK_MONOTONIC is uptime on Linux)."""
        with self._lock:
            if (self._probed_at is not None
                    and now - self._probed_at < PROBE_REFRESH_S):
                return False
            self._probed_at = now
            return True

    def _probe_link(self) -> None:
        """Measure D2H bandwidth + dispatch RTT of the default accelerator
        with a 1MB round trip. Serialized; refreshed every PROBE_REFRESH_S.
        Runs on the accelerator the engine would use anyway, so a hung
        tunnel costs no more here than the query itself would."""
        import jax
        import jax.numpy as jnp

        now = time.monotonic()
        if not self._claim_probe(now):
            return
        try:
            if self._probe_fn is None:
                # Jitted once per instance: a fresh lambda each probe
                # would re-pay the XLA compile every refresh (jit caches
                # by function identity).
                self._probe_fn = jax.jit(lambda x: x + 1)
            f = self._probe_fn
            tiny = jnp.arange(8)
            # Warm dispatch first: the initial call pays XLA compile +
            # backend warmup (observed 0.5-54s on a cold axon tunnel) and
            # would poison the RTT EWMA for the whole refresh horizon —
            # time the SECOND round trip, which is pure dispatch + D2H.
            np.asarray(f(tiny))
            t0 = time.perf_counter()
            np.asarray(f(tiny))
            rtt = time.perf_counter() - t0
            # DELIBERATE raw put: a fixed 1MB link-bandwidth probe,
            # serialized and immediately fetched back — not block traffic.
            buf = jax.device_put(  # m3lint: disable=unbudgeted-device-put
                np.zeros(_PROBE_BYTES // 4, dtype=np.float32))
            jax.block_until_ready(buf)
            t0 = time.perf_counter()
            np.asarray(buf)
            dt = max(time.perf_counter() - t0, 1e-6)
            with self._lock:
                self._rtt = _ewma(self._rtt, rtt)
                self._d2h_bw = _ewma(self._d2h_bw, _PROBE_BYTES / dt)
        except Exception:
            pass  # a failed probe leaves the prior model in place

    # -- decision ----------------------------------------------------------

    def choose(self, cells: int, result_bytes: int):
        """Device to place this evaluation on: None = default accelerator,
        or the CPU backend device for host evaluation."""
        if self._mode == "device":
            return None
        host_dev = self._host_device()
        if host_dev is None:
            return None
        if self._mode == "host":
            return host_dev
        self._probe_link()
        with self._lock:
            host_rate = self._host_rate or _HOST_RATE_PRIOR
            accel_rate = self._accel_rate or _ACCEL_RATE_PRIOR
            bw = self._d2h_bw
            rtt = self._rtt or 0.003
        if bw is None:
            # No successful probe yet: assume the accelerator is healthy
            # and locally attached until measured otherwise.
            return None
        host_cost = cells / host_rate
        accel_cost = rtt + result_bytes / bw + cells / accel_rate
        return host_dev if host_cost < accel_cost else None

    # -- model updates -----------------------------------------------------

    def observe(self, device, cells: int, result_bytes: int,
                seconds: float) -> None:
        """Fold an observed evaluation (dispatch -> result on host) back
        into the rate model for the path that served it."""
        if seconds <= 0 or cells <= 0:
            return
        with self._lock:
            if device is not None:  # host-placed
                self._host_rate = _ewma(self._host_rate, cells / seconds)
            else:
                bw = self._d2h_bw
                transfer = (result_bytes / bw) if bw else 0.0
                if transfer >= 0.8 * seconds:
                    # Modeled transfer swallows (or exceeds) the whole
                    # observation — the decomposition is unreliable (stale
                    # bw after a link change would clamp compute to ~0 and
                    # inject an absurd rate sample). Wait for the probe to
                    # catch up instead.
                    return
                compute = max(seconds - transfer - (self._rtt or 0.0), 1e-5)
                self._accel_rate = _ewma(self._accel_rate, cells / compute)

    def snapshot(self) -> dict:
        """Observability: /debug/vars + bench extra."""
        with self._lock:
            return {
                "mode": self._mode,
                "host_rate_cells_s": self._host_rate,
                "accel_rate_cells_s": self._accel_rate,
                "d2h_bw_mb_s": (self._d2h_bw / 2**20
                                if self._d2h_bw else None),
                "rtt_ms": (self._rtt * 1e3 if self._rtt else None),
            }
