"""PromQL parser: lexer + Pratt parser producing the query AST (reference:
src/query/parser/promql/parse.go wraps the vendored prometheus promql
parser; this build implements the grammar natively — selectors with label
matchers and range/offset, function calls, aggregations with by/without,
binary operators with precedence, bool modifier and vector matching).

Covers the PromQL surface of the 2018-era engine the reference embeds,
plus the features that postdate it and exist in the upstream engine modern
M3 tracks: subqueries (`expr[range:resolution]`) and @-modifiers
(`expr @ <ts>`, `@ start()`, `@ end()`; end() resolves to the last output
step on the query grid)."""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Sequence, Tuple

from .model import Matcher, MatchType, METRIC_NAME

# ---------------------------------------------------------------- tokens

# ONE duration grammar, shared by the lexer's DURATION token, the
# duration-value parser (_DUR_PART) and the subquery-resolution validator
# (Parser._RESOLUTION_RE) — one edit changes all three.
_DUR_ATOM = r"[0-9]+(?:\.[0-9]+)?(?:ms|[smhdwy])"

_TOKEN_RE = re.compile(r"""
    (?P<WS>\s+)
  | (?P<DURATION>@DUR@(?:@DUR@)*)
  | (?P<NUMBER>(?:0x[0-9a-fA-F]+)|(?:[0-9]*\.[0-9]+(?:[eE][+-]?[0-9]+)?)|(?:[0-9]+(?:[eE][+-]?[0-9]+)?)|[iI][nN][fF]|[nN][aA][nN])
  | (?P<IDENT>[a-zA-Z_:][a-zA-Z0-9_:.]*)
  | (?P<STRING>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<OP>=~|!~|==|!=|<=|>=|<|>|\+|-|\*|/|%|\^|=|@)
  | (?P<LPAREN>\()|(?P<RPAREN>\))
  | (?P<LBRACE>\{)|(?P<RBRACE>\})
  | (?P<LBRACKET>\[)|(?P<RBRACKET>\])
  | (?P<COMMA>,)
""".replace("@DUR@", _DUR_ATOM), re.VERBOSE)

_UNITS_NS = {"ms": 10**6, "s": 10**9, "m": 60 * 10**9, "h": 3600 * 10**9,
             "d": 86400 * 10**9, "w": 7 * 86400 * 10**9, "y": 365 * 86400 * 10**9}
_DUR_PART = re.compile(r"([0-9]+(?:\.[0-9]+)?)(ms|[smhdwy])")  # groups of _DUR_ATOM


def parse_duration_ns(s: str) -> int:
    total = 0
    for num, unit in _DUR_PART.findall(s):
        total += int(float(num) * _UNITS_NS[unit])
    return total


@dataclasses.dataclass
class Token:
    kind: str
    text: str
    pos: int


def lex(s: str) -> List[Token]:
    out, i = [], 0
    while i < len(s):
        m = _TOKEN_RE.match(s, i)
        if not m:
            raise ParseError(f"unexpected character {s[i]!r} at {i}")
        kind = m.lastgroup
        if kind != "WS":
            out.append(Token(kind, m.group(), i))
        i = m.end()
    out.append(Token("EOF", "", len(s)))
    return out


class ParseError(ValueError):
    pass


# ---------------------------------------------------------------- AST

@dataclasses.dataclass(frozen=True)
class Node:
    pass


@dataclasses.dataclass(frozen=True)
class NumberLiteral(Node):
    value: float


@dataclasses.dataclass(frozen=True)
class StringLiteral(Node):
    value: str


@dataclasses.dataclass(frozen=True)
class VectorSelector(Node):
    name: bytes
    matchers: Tuple[Matcher, ...] = ()
    range_ns: int = 0          # 0 = instant vector; >0 = matrix selector
    offset_ns: int = 0
    # @-modifier: None, absolute ns timestamp, or "start"/"end" (resolved
    # against the query range at eval time).
    at_ns: object = None


@dataclasses.dataclass(frozen=True)
class Subquery(Node):
    """`expr[range:resolution]` — evaluate expr as an instant query at
    each resolution-aligned timestamp in the trailing range window,
    producing a range vector for an outer *_over_time/rate-family call.
    step_ns == 0 means "default resolution" (the engine substitutes the
    query step floored at 15s — executor.DEFAULT_SUBQUERY_RES_NS — its
    stand-in for prometheus' eval interval)."""
    expr: Node
    range_ns: int
    step_ns: int = 0
    offset_ns: int = 0
    at_ns: object = None       # see VectorSelector.at_ns


@dataclasses.dataclass(frozen=True)
class Call(Node):
    func: str
    args: Tuple[Node, ...]


@dataclasses.dataclass(frozen=True)
class Aggregation(Node):
    op: str
    expr: Node
    param: Optional[Node] = None
    grouping: Tuple[bytes, ...] = ()
    without: bool = False


@dataclasses.dataclass(frozen=True)
class VectorMatching(Node):
    on: bool = False                     # on(...) vs ignoring(...)
    labels: Tuple[bytes, ...] = ()
    group_left: bool = False
    group_right: bool = False
    include: Tuple[bytes, ...] = ()


@dataclasses.dataclass(frozen=True)
class BinaryOp(Node):
    op: str
    lhs: Node
    rhs: Node
    bool_mode: bool = False
    matching: Optional[VectorMatching] = None


@dataclasses.dataclass(frozen=True)
class Unary(Node):
    op: str
    expr: Node


AGG_OPS = {"sum", "min", "max", "avg", "count", "stddev", "stdvar",
           "topk", "bottomk", "quantile", "count_values", "group"}
_PARAM_AGGS = {"topk", "bottomk", "quantile", "count_values"}

# precedence (prom): or < and/unless < comparisons < +- < */% < ^
_PRECEDENCE = {
    "or": 1,
    "and": 2, "unless": 2,
    "==": 3, "!=": 3, "<=": 3, "<": 3, ">=": 3, ">": 3,
    "+": 4, "-": 4,
    "*": 5, "/": 5, "%": 5,
    "^": 6,
}
_RIGHT_ASSOC = {"^"}
SET_OPS = {"and", "or", "unless"}
COMPARISON_OPS = {"==", "!=", "<=", "<", ">=", ">"}


class Parser:
    def __init__(self, s: str):
        self.toks = lex(s)
        self.i = 0

    # -- token helpers ----------------------------------------------------

    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, kind: str, text: Optional[str] = None) -> Token:
        t = self.next()
        if t.kind != kind or (text is not None and t.text != text):
            raise ParseError(f"expected {text or kind}, got {t.text!r} at {t.pos}")
        return t

    def accept(self, kind: str, text: Optional[str] = None) -> Optional[Token]:
        t = self.peek()
        if t.kind == kind and (text is None or t.text == text):
            return self.next()
        return None

    # -- grammar ----------------------------------------------------------

    def parse(self) -> Node:
        node = self.parse_expr(0)
        if self.peek().kind != "EOF":
            t = self.peek()
            raise ParseError(f"unexpected {t.text!r} at {t.pos}")
        return node

    def parse_expr(self, min_prec: int) -> Node:
        lhs = self.parse_unary()
        while True:
            t = self.peek()
            op = t.text if t.kind in ("OP", "IDENT") else None
            if op not in _PRECEDENCE or _PRECEDENCE[op] < min_prec:
                return lhs
            self.next()
            bool_mode = bool(self.accept("IDENT", "bool"))
            matching = self._parse_matching()
            next_min = _PRECEDENCE[op] + (0 if op in _RIGHT_ASSOC else 1)
            rhs = self.parse_expr(next_min)
            lhs = BinaryOp(op, lhs, rhs, bool_mode, matching)

    def _parse_matching(self) -> Optional[VectorMatching]:
        t = self.peek()
        if t.kind != "IDENT" or t.text not in ("on", "ignoring"):
            return None
        on = self.next().text == "on"
        labels = self._parse_label_list()
        group_left = group_right = False
        include: Tuple[bytes, ...] = ()
        t = self.peek()
        if t.kind == "IDENT" and t.text in ("group_left", "group_right"):
            side = self.next().text
            group_left = side == "group_left"
            group_right = side == "group_right"
            if self.peek().kind == "LPAREN":
                include = self._parse_label_list()
        return VectorMatching(on, labels, group_left, group_right, include)

    def _parse_label_list(self) -> Tuple[bytes, ...]:
        self.expect("LPAREN")
        labels = []
        while not self.accept("RPAREN"):
            labels.append(self.expect("IDENT").text.encode())
            if self.peek().kind == "COMMA":
                self.next()
        return tuple(labels)

    def parse_unary(self) -> Node:
        t = self.peek()
        if t.kind == "OP" and t.text in ("+", "-"):
            self.next()
            # Unary operators bind between '^' and '*' (Go/prom spec):
            # -2^2 == -(2^2), -2*3 == (-2)*3.
            expr = self.parse_expr(_PRECEDENCE["^"])
            return expr if t.text == "+" else Unary("-", expr)
        return self.parse_postfix(self.parse_atom())

    def parse_postfix(self, node: Node) -> Node:
        # range selector [5m], subquery [30m:1m] / [30m:], offset modifier;
        # loops so `min_over_time(rate(x[5m])[30m:])[...]`-style chains and
        # an offset AFTER a subquery both parse.
        offset_seen = False
        while True:
            if self.accept("LBRACKET"):
                tok = self.expect("DURATION")
                rng = parse_duration_ns(tok.text)
                if rng == 0:
                    raise ParseError(f"zero range at {tok.pos}")
                res = self._accept_subquery_resolution()
                self.expect("RBRACKET")
                if res is not None:
                    node = Subquery(node, rng, res)
                    offset_seen = False  # the subquery is a new modifier target
                elif (isinstance(node, VectorSelector) and not node.range_ns
                        and not offset_seen):
                    # offset_seen guard: prom requires the range BEFORE any
                    # offset (`c offset 5m [5m]` is a parse error upstream;
                    # silently reordering would mask the user's mistake).
                    node = dataclasses.replace(node, range_ns=rng)
                else:
                    raise ParseError("range selector on non-selector expression")
                continue
            if self.accept("IDENT", "offset"):
                dur = parse_duration_ns(self.expect("DURATION").text)
                if not isinstance(node, (VectorSelector, Subquery)):
                    raise ParseError("offset on non-selector expression")
                if offset_seen:
                    # prom rejects repeated offset modifiers; silently
                    # letting the last win would query the wrong window
                    # (a flag, not a field truthiness check: `offset 0s`
                    # must arm the rejection too).
                    raise ParseError("duplicate offset modifier")
                offset_seen = True
                node = dataclasses.replace(node, offset_ns=dur)
                continue
            if self.accept("OP", "@"):
                if not isinstance(node, (VectorSelector, Subquery)):
                    raise ParseError("@ modifier on non-selector expression")
                if node.at_ns is not None:
                    raise ParseError("duplicate @ modifier")
                node = dataclasses.replace(node, at_ns=self._parse_at())
                continue
            return node

    def _parse_at(self):
        """`@ <unix-seconds>` (possibly negative/float) or `@ start()` /
        `@ end()` — pins the selector's evaluation time."""
        neg = bool(self.accept("OP", "-"))
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            sec = _parse_number(t.text)
            return int((-sec if neg else sec) * 1e9)
        if not neg and t.kind == "IDENT" and t.text in ("start", "end"):
            self.next()
            self.expect("LPAREN")
            self.expect("RPAREN")
            return t.text
        raise ParseError(f"expected timestamp, start() or end() after @ "
                         f"at {t.pos}")

    _RESOLUTION_RE = re.compile(rf"(?:{_DUR_ATOM})+\Z")

    def _accept_subquery_resolution(self) -> Optional[int]:
        """After the range duration inside brackets: ':' or ':<dur>' marks a
        subquery. The lexer folds ':1m' into one IDENT (':' is an ident
        char for recording-rule names), so the resolution is split back out
        here; a bare ':' may also be followed by a separate DURATION token
        (`[1h : 5m]`). Returns resolution ns (0 = default), or None when
        the bracket is a plain range selector."""
        t = self.peek()
        if t.kind != "IDENT" or not t.text.startswith(":"):
            return None
        self.next()
        res_txt = t.text[1:]
        if not res_txt:
            d = self.accept("DURATION")
            res_txt = d.text if d else ""
        if not res_txt:
            return 0
        if not self._RESOLUTION_RE.match(res_txt):
            raise ParseError(
                f"bad subquery resolution {res_txt!r} at {t.pos}")
        ns = parse_duration_ns(res_txt)
        if ns == 0:
            # explicit zero ([5m:0s]) must not alias the bare-':' default
            raise ParseError(f"zero resolution in subquery at {t.pos}")
        return ns

    def parse_atom(self) -> Node:
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            return NumberLiteral(_parse_number(t.text))
        if t.kind == "STRING":
            self.next()
            return StringLiteral(_unquote(t.text))
        if t.kind == "LPAREN":
            self.next()
            node = self.parse_expr(0)
            self.expect("RPAREN")
            return node
        if t.kind == "LBRACE":
            return VectorSelector(b"", self._parse_matchers())
        if t.kind == "IDENT":
            if t.text in AGG_OPS:
                return self._parse_aggregation()
            return self._parse_ident()
        raise ParseError(f"unexpected {t.text!r} at {t.pos}")

    def _parse_ident(self) -> Node:
        name = self.next().text
        if self.peek().kind == "LPAREN" and name not in ("on", "ignoring"):
            self.next()
            args: List[Node] = []
            while not self.accept("RPAREN"):
                args.append(self.parse_expr(0))
                if self.peek().kind == "COMMA":
                    self.next()
            return Call(name, tuple(args))
        matchers: Tuple[Matcher, ...] = ()
        if self.peek().kind == "LBRACE":
            matchers = self._parse_matchers()
        return VectorSelector(name.encode(), matchers)

    def _parse_aggregation(self) -> Node:
        op = self.next().text
        grouping: Tuple[bytes, ...] = ()
        without = False
        # modifier may precede or follow the parenthesized body
        t = self.peek()
        if t.kind == "IDENT" and t.text in ("by", "without"):
            without = self.next().text == "without"
            grouping = self._parse_label_list()
        self.expect("LPAREN")
        first = self.parse_expr(0)
        param = None
        if self.accept("COMMA"):
            param, first = first, self.parse_expr(0)
        self.expect("RPAREN")
        t = self.peek()
        if t.kind == "IDENT" and t.text in ("by", "without"):
            without = self.next().text == "without"
            grouping = self._parse_label_list()
        if op in _PARAM_AGGS and param is None:
            raise ParseError(f"{op} requires a parameter")
        return Aggregation(op, first, param, grouping, without)

    def _parse_matchers(self) -> Tuple[Matcher, ...]:
        self.expect("LBRACE")
        out: List[Matcher] = []
        while not self.accept("RBRACE"):
            name = self.expect("IDENT").text
            opt = self.expect("OP")
            mt = {"=": MatchType.EQUAL, "!=": MatchType.NOT_EQUAL,
                  "=~": MatchType.REGEXP, "!~": MatchType.NOT_REGEXP}.get(opt.text)
            if mt is None:
                raise ParseError(f"bad matcher operator {opt.text!r} at {opt.pos}")
            value = _unquote(self.expect("STRING").text)
            out.append(Matcher(mt, name.encode(), value.encode()))
            if self.peek().kind == "COMMA":
                self.next()
        return tuple(out)


def _parse_number(s: str) -> float:
    low = s.lower()
    if low == "inf":
        return float("inf")
    if low == "nan":
        return float("nan")
    if low.startswith("0x"):
        return float(int(s, 16))
    return float(s)


_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", "\\": "\\", '"': '"',
            "'": "'", "a": "\a", "b": "\b", "f": "\f", "v": "\v", "/": "/"}
_ESCAPE_RE = re.compile(
    r"\\(x[0-9a-fA-F]{2}|u[0-9a-fA-F]{4}|U[0-9a-fA-F]{8}|[0-7]{1,3}|.)")


def _unquote(s: str) -> str:
    """Resolve escape sequences without the unicode_escape latin-1 round
    trip (which mojibakes non-ASCII literals)."""

    def sub(m: "re.Match") -> str:
        e = m.group(1)
        if e[0] in "xuU":
            return chr(int(e[1:], 16))
        if e[0] in "01234567":
            return chr(int(e, 8))
        if e in _ESCAPES:
            return _ESCAPES[e]
        raise ParseError(f"unknown escape \\{e}")

    return _ESCAPE_RE.sub(sub, s[1:-1])


def parse(s: str) -> Node:
    """Parse a PromQL expression string into an AST."""
    return Parser(s).parse()


# Functions whose CALL types as scalar (promql/parser functions.go return
# types) — kept next to the AST so the engine and the HTTP layer share one
# definition.
SCALAR_FUNCS = frozenset({"scalar", "time", "pi"})


def is_scalar_node(node: Node) -> bool:
    """Static promql typing of the ROOT expression: scalar literals,
    scalar-returning functions, and arithmetic over scalars type as
    scalar (promql/parser checkAST); anything touching a vector types as
    vector. The prom HTTP API shapes instant results by this."""
    if isinstance(node, NumberLiteral):
        return True
    if isinstance(node, Unary):
        return is_scalar_node(node.expr)
    if isinstance(node, Call):
        return node.func in SCALAR_FUNCS
    if isinstance(node, BinaryOp):
        return (node.op not in SET_OPS
                and is_scalar_node(node.lhs) and is_scalar_node(node.rhs))
    return False


def selector_matchers(sel: VectorSelector) -> Tuple[Matcher, ...]:
    """Full matcher set including the metric name."""
    out = list(sel.matchers)
    if sel.name:
        out.insert(0, Matcher(MatchType.EQUAL, METRIC_NAME, sel.name))
    return tuple(out)
