"""Query executor: evaluates a PromQL AST over blocks (reference:
src/query/executor/{engine,state}.go + functions/* — the push-based
per-step iterator DAG is re-expressed as whole-block batched ops; every
transform consumes and produces a dense [series x steps] Block, with the
sliding-window/temporal math in m3_tpu.ops.temporal and cross-series
aggregation in m3_tpu.ops.series_agg running as jitted device kernels).

Matrix selectors grid at gcd(step, range) so sub-step samples inside a
window survive consolidation (the reference's block consolidation has the
same step-alignment semantics, src/query/ts/values.go)."""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..ops import series_agg, temporal
from . import corpus as qcorpus
from . import explain as qexplain
from . import promql
from ..utils import limits as xlimits
from ..utils.retry import DeadlineExceeded
from ..utils.tracing import SLOW_QUERIES, span
from .block import Block, BlockMeta, consolidate_series
from .model import Matcher, MatchType, METRIC_NAME, Tags
from .promql import (
    Aggregation,
    BinaryOp,
    Call,
    Node,
    NumberLiteral,
    StringLiteral,
    Subquery,
    Unary,
    VectorSelector,
)

DEFAULT_LOOKBACK_NS = 5 * 60 * 1_000_000_000
# Floor for the default subquery resolution (`[1h:]` with no explicit res):
# the stand-in for prometheus' default evaluation interval, so an instant
# query (step 1s) doesn't evaluate the inner expression per second of range.
DEFAULT_SUBQUERY_RES_NS = 15 * 1_000_000_000

Scalar = np.ndarray  # [steps] float
Value = Union[Block, np.ndarray, float]


class QueryError(ValueError):
    pass


@dataclasses.dataclass
class QueryParams:
    start_ns: int
    end_ns: int      # inclusive of the last step <= end
    step_ns: int

    @property
    def steps(self) -> int:
        return (self.end_ns - self.start_ns) // self.step_ns + 1

    def meta(self) -> BlockMeta:
        return BlockMeta(self.start_ns, self.step_ns, self.steps)


def _default_query_mesh():
    """One 1-D "shard" mesh over every attached device, or None single-chip.
    Cached after first use — the serving processes build engines per
    coordinator but share the device topology."""
    global _QUERY_MESH
    if _QUERY_MESH is _UNSET:
        import jax
        from jax.sharding import Mesh

        devs = jax.devices()
        _QUERY_MESH = (Mesh(np.asarray(devs), ("shard",))
                       if len(devs) > 1 else None)
    return _QUERY_MESH


_UNSET = object()
_QUERY_MESH = _UNSET


class _GridCache:
    """Consolidated-grid cache for repeated selector evaluations.

    A dashboard burst evaluates the same selector over the same immutable
    sealed blocks every few seconds; re-consolidating a 10k-series fetch
    onto the grid costs ~50ms per query (measured, consolidate_series on
    a [10k x 447] grid) — pure waste when the data hasn't changed. The
    reference leans on block/iterator caching for the same reason
    (src/dbnode/storage/block/wired_list.go:77 WiredList).

    Validity is OBJECT IDENTITY, not content: an entry stores strong
    references to the fetched per-series entry dicts, and a lookup hits
    only when the storage layer handed back the *same entry objects* (an
    `is` check per series, ~1ms for 10k series). Unchanged-identity
    arrays cannot have changed content anywhere in the query layer (fetch
    results are treated as immutable throughout), so a hit is provably
    equivalent to recomputation. Storages that rebuild entry dicts per
    fetch simply never hit — correct, just slower. The strong refs pin
    the fetched arrays while cached; the byte budget bounds that.
    """

    # A storage that rebuilds entry dicts per fetch can never hit; after
    # this many consecutive identity misses with zero hits ever, puts are
    # sampled 1-in-_PROBE_EVERY instead of pinning every fetch's arrays.
    _MISS_DISABLE = 32
    _PROBE_EVERY = 64

    def __init__(self, max_bytes: int = 256 * 1024 * 1024):
        import collections
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[tuple, tuple]" = (
            collections.OrderedDict())
        self._bytes = 0
        self._max_bytes = max_bytes
        self._hits = 0
        self._misses = 0
        self._puts = 0

    def get(self, key: tuple, series: dict):
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self._misses += 1
                return None
            stored_series, tags_list, values, _cost = hit
            ok = len(stored_series) == len(series) and all(
                stored_series.get(sid) is entry
                for sid, entry in series.items())
            if not ok:
                # The stored entry can never hit again (identity moved on)
                # — evict now so a rebuilding storage doesn't accumulate
                # dead pinned arrays across selectors.
                self._entries.pop(key, None)
                self._bytes -= _cost
                self._misses += 1
                return None
            self._hits += 1
            self._entries.move_to_end(key)
            return tags_list, values

    def put(self, key: tuple, series: dict, tags_list, values) -> None:
        cost = values.nbytes + sum(
            e["t"].nbytes + e["v"].nbytes for e in series.values()
            if hasattr(e.get("t"), "nbytes") and hasattr(e.get("v"), "nbytes"))
        if cost > self._max_bytes:
            return
        with self._lock:
            self._puts += 1
            if (self._hits == 0 and self._misses >= self._MISS_DISABLE
                    and self._puts % self._PROBE_EVERY):
                # Rebuilding-storage regime: keep probing occasionally so a
                # storage that starts returning stable entries is noticed.
                return
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[3]
            self._entries[key] = (dict(series), tags_list, values, cost)
            self._bytes += cost
            while self._bytes > self._max_bytes and len(self._entries) > 1:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted[3]


class Engine:
    """executor/engine.go: compile -> plan -> execute. Storage is anything
    with fetch_raw(matchers, start_ns, end_ns) -> {id: {tags, t, v}}.

    mesh: "auto" (default) shards dashboard-shaped aggregations over every
    attached device (the in-mesh expression of the reference's coordinator
    fanout, src/query/storage/fanout/storage.go:1); None forces
    single-device evaluation; or pass an explicit jax Mesh with a "shard"
    axis."""

    def __init__(self, storage, lookback_ns: int = DEFAULT_LOOKBACK_NS,
                 cost_enforcer=None, per_query_cost_limit=None, mesh="auto",
                 query_limits=None):
        self.storage = storage
        # Overload-protection registry (utils.limits). None = resolve the
        # process-global registry at query time, so a deployment that
        # configures limits after engine construction still gets them.
        # Each query runs inside a QueryScope: per-query child enforcers
        # chained to the global concurrent budgets, installed thread-local
        # so the storage/index charge sites below this query bill it.
        self.query_limits = query_limits
        # "auto" resolves LAZILY on the first sharded-eligible query: the
        # resolution touches jax.devices(), i.e. backend init, and a server
        # must not block its startup on accelerator health (a downed tunnel
        # hangs backend init indefinitely).
        self._mesh = mesh
        self.lookback_ns = lookback_ns
        # Per-process datapoint budget (x/cost/enforcer.go). Each query
        # charges a scoped child enforcer whose total is released when the
        # query finishes, so the global budget tracks only in-flight work.
        self.cost_enforcer = cost_enforcer
        self.per_query_cost_limit = per_query_cost_limit
        # Per-QUERY scoped enforcer: thread-local, because one Engine
        # serves concurrent queries from the ThreadingHTTPServer and a
        # shared slot would charge one query's datapoints to another.
        self._local = threading.local()
        self._grid_cache = _GridCache()
        from .placement import QueryPlacement
        self._placement = QueryPlacement()

    def placement_snapshot(self) -> dict:
        """Live device-vs-host cost model state (mode, measured link
        bandwidth/RTT, per-path rate EWMAs) for /debug/vars and the bench
        extra."""
        return self._placement.snapshot()

    @property
    def mesh(self):
        if isinstance(self._mesh, str):  # "auto"
            self._mesh = _default_query_mesh()
        return self._mesh

    @mesh.setter
    def mesh(self, value):
        self._mesh = value

    def execute_range(self, query: str, start_ns: int, end_ns: int,
                      step_ns: int, ast: Optional[Node] = None,
                      use_plan: bool = True) -> Block:
        from ..utils.instrument import ROOT

        ROOT.counter("query.executed").inc()
        timer = ROOT.timer("query.latency_s")
        sp = span("query.execute_range", query=query)
        # A failure before this query's scope runs must not inherit the
        # previous query's totals on this reused serving thread — same
        # for the plan-route record (slow-ring + corpus attribution).
        xlimits.reset_last_totals()
        self._local.route_info = None
        t0 = time.perf_counter_ns()
        # Slow-query accounting: typed sheds record regardless of
        # duration; completed queries record past the threshold, with
        # cost attribution from the span (QueryScope exit annotates it)
        # or, unsampled, the thread-local last-scope totals. Every entry
        # carries the plan route + typed fallback reason so a slow
        # interpreted query tells the operator WHY it missed the
        # compiled path.
        try:
            with timer, sp:
                result = self._execute_range(query, start_ns, end_ns,
                                             step_ns, ast=ast,
                                             use_plan=use_plan)
        except xlimits.ResourceExhausted:
            SLOW_QUERIES.maybe("query", query, time.perf_counter_ns() - t0,
                               costs=xlimits.last_scope_totals(),
                               reason="limit-shed",
                               route=self.last_route(),
                               trace_id=sp.trace_id or None)
            raise
        except DeadlineExceeded:
            SLOW_QUERIES.maybe("query", query, time.perf_counter_ns() - t0,
                               costs=xlimits.last_scope_totals(),
                               reason="deadline",
                               route=self.last_route(),
                               trace_id=sp.trace_id or None)
            raise
        from ..utils import tracing

        duration_ns = time.perf_counter_ns() - t0
        SLOW_QUERIES.maybe("query", query, duration_ns,
                           # Lazy SUBTREE rollup: cache events accrue on
                           # child/grafted spans, and only entries that
                           # actually record pay the walk.
                           costs=((lambda: tracing.collect_costs(sp))
                                  if sp.sampled
                                  else xlimits.last_scope_totals()),
                           route=self.last_route(),
                           trace_id=sp.trace_id or None)
        # Opt-in corpus sampler (query/corpus.py): one module-global
        # read when no recorder is configured. Sampled queries
        # materialize the lazy result inside the hook so recorded
        # latency includes the d2h transfer (symmetric with the eager
        # interpreter route).
        qcorpus.maybe_record(query, self.last_route(), result, t0, step_ns)
        return result

    def _execute_range(self, query: str, start_ns: int, end_ns: int,
                       step_ns: int, ast: Optional[Node] = None,
                       use_plan: bool = True) -> Block:
        # The HTTP layer parses once for its static type check and hands
        # the node in via `ast`; the query STRING still tags the spans.
        if ast is None:
            with span("query.parse"):
                ast = promql.parse(query)
        params = QueryParams(start_ns, end_ns, step_ns)
        # @ start()/end() resolve against the OUTERMOST query range even
        # inside subqueries (prom promql/parser/ast.go StartOrEnd).
        self._local.outer_params = params
        ql = self.query_limits if self.query_limits is not None \
            else xlimits.get_global()
        with ql.scope("query"):
            if self.cost_enforcer is not None:
                child = self.cost_enforcer.child(self.per_query_cost_limit)
                self._local.enforcer = child
                try:
                    val = self._eval_root(ast, params, use_plan)
                finally:
                    self._local.enforcer = None
                    child.release(child.current())
            else:
                val = self._eval_root(ast, params, use_plan)
            return _to_block(val, params)

    def execute_instant(self, query: str, t_ns: int,
                        ast: Optional[Node] = None) -> Block:
        return self.execute_range(query, t_ns, t_ns, 1_000_000_000, ast=ast)

    def execute_range_ref(self, query: str, start_ns: int, end_ns: int,
                          step_ns: int, ast: Optional[Node] = None) -> Block:
        """The retained per-node interpreter — the oracle the compiled
        whole-plan route (query/plan.py -> parallel/compile.py) is proven
        against, same pattern as PR 3's `execute_ref` and PR 7's
        `apply_peer_tiles_ref`. Identical to execute_range with the plan
        route forced off: every node evaluates through the _eval
        tree-walk below, unchanged."""
        return self.execute_range(query, start_ns, end_ns, step_ns, ast=ast,
                                  use_plan=False)

    # -- evaluation --------------------------------------------------------

    def _eval_root(self, node: Node, params: QueryParams,
                   use_plan: bool) -> Value:
        """Root dispatch: compile the WHOLE physical plan into one jitted
        mesh program when every node lowers (query/plan.py), falling back
        per-node to the interpreter otherwise — so a query outside the
        compiled surface behaves exactly as before. The route (and the
        fallback reason) is tagged onto the query span so the slow-query
        log can attribute cold plan compiles."""
        if use_plan and os.environ.get("M3_TPU_PLAN_DISABLE", "0") != "1":
            # Selector overlay for the plan attempt: bind() fetches every
            # selector through the normal charged paths; if the plan then
            # falls back (below floor, backend gap), the interpreter
            # re-evaluation below reuses those exact blocks instead of
            # re-fetching (and re-charging) the storage layer.
            self._local.sel_overlay = {}
            try:
                out = self._try_plan(node, params)
                if out is not None:
                    return out
                return self._eval_interp(node, params)
            finally:
                self._local.sel_overlay = None
        # Plan route off entirely (env kill switch / execute_range_ref):
        # recorded for the slow-ring/corpus surfaces, no span tag (only
        # real plan ATTEMPTS tag their route, as before).
        from . import plan as qplan

        self._local.route_info = {
            "route": "interpreter",
            "fallback_reason": qplan.FallbackReason.DISABLED.value,
            "fallback_detail": "plan route disabled",
        }
        return self._eval_interp(node, params)

    def _eval_interp(self, node: Node, params: QueryParams) -> Value:
        """Interpreter evaluation, staged under ANALYZE when a context
        is active (one thread-local read otherwise)."""
        actx = qexplain.current()
        if actx is None:
            return self._eval(node, params)
        with actx.stage("interpreter_eval"):
            return self._eval(node, params)

    def _try_plan(self, node: Node, params: QueryParams) -> Optional[Value]:
        from ..parallel import telemetry
        from ..utils.instrument import ROOT
        from . import plan as qplan

        plan, err, slot_values = qplan.lower_and_collect(
            node, params, self.lookback_ns)
        if plan is None:
            telemetry.plan_fallback(err.reason.value,
                                    qplan.fallback_scope(err.reason.value))
            self._set_route("interpreter", err.reason.value, str(err))
            return None
        # bind() fetches + grids every selector through the SAME cached
        # selector paths the interpreter uses and runs the host tag
        # algebra; QueryError (matching violations) carries the
        # interpreter's exact semantics and propagates. Under ANALYZE
        # the bind (fetch + host tag algebra) is its own stage.
        actx = qexplain.current()
        if actx is None:
            bound = qplan.bind(plan, self, params, slot_values)
        else:
            with actx.stage("bind"):
                bound = qplan.bind(plan, self, params, slot_values)
        if bound.total_cells < qplan.PLAN_MIN_CELLS:
            # Tiny queries keep the interpreter's exact-f64 finishes; the
            # grids just fetched stay warm in the grid cache, so the
            # fallback evaluation below re-reads them for free.
            ROOT.counter("query.plan.below_floor").inc()
            telemetry.plan_fallback(qplan.FallbackReason.BELOW_FLOOR.value,
                                    "runtime")
            self._set_route("interpreter",
                            qplan.FallbackReason.BELOW_FLOOR.value,
                            f"{bound.total_cells} cells < "
                            f"{qplan.PLAN_MIN_CELLS} floor")
            return None
        from ..parallel import compile as pcompile

        try:
            values, tags, fetch = pcompile.execute(bound, self.mesh)
        except pcompile.PlanFallback as e:
            ROOT.counter("query.plan.fallback").inc()
            reason = getattr(e, "reason", qplan.FallbackReason.BACKEND_GAP)
            telemetry.plan_fallback(reason.value,
                                    qplan.fallback_scope(reason.value))
            self._set_route("interpreter", reason.value, str(e))
            return None
        ROOT.counter("query.plan.executed").inc()
        self._set_route("compiled", "", "")
        if fetch is None:
            return values          # [steps] scalar; _to_block wraps it
        from .block import LazyBlock

        return LazyBlock(params.meta(), tags, fetch)

    def _set_route(self, route: str, reason: str, detail: str) -> None:
        """Record the route decision: span tags (route "plan" for the
        compiled path, the historical tag vocabulary) + the thread-local
        route record `last_route()` reads (the slow ring, the corpus
        sampler and the ?explain=true HTTP surface)."""
        from ..utils import tracing

        self._local.route_info = {
            "route": route,
            "fallback_reason": reason or None,
            "fallback_detail": detail or None,
        }
        cur = getattr(tracing.TRACER._local, "current", None)
        if cur is not None:
            cur.set_tag("route", "plan" if route == "compiled" else route)
            if reason:
                cur.set_tag("plan_fallback", reason)

    def last_route(self) -> Optional[dict]:
        """The route record of this THREAD's most recent query: route
        ("compiled"/"interpreter"), typed fallback_reason (a
        `plan.FallbackReason` value) and a human detail — None when no
        query ran on this thread yet."""
        return getattr(self._local, "route_info", None)

    def _eval(self, node: Node, params: QueryParams) -> Value:
        if isinstance(node, NumberLiteral):
            return float(node.value)
        if isinstance(node, StringLiteral):
            return node.value
        if isinstance(node, VectorSelector):
            if node.range_ns:
                raise QueryError("matrix selector used outside a function")
            return self._eval_instant_selector(node, params)
        if isinstance(node, Subquery):
            raise QueryError("subquery result used outside a range function")
        if isinstance(node, Unary):
            val = self._eval(node.expr, params)
            return _map_values(val, lambda v: -v)
        if isinstance(node, Call):
            return self._eval_call(node, params)
        if isinstance(node, Aggregation):
            return self._eval_aggregation(node, params)
        if isinstance(node, BinaryOp):
            return self._eval_binary(node, params)
        raise QueryError(f"unsupported node {type(node).__name__}")

    # -- selectors ---------------------------------------------------------

    def _fetch(self, sel: VectorSelector, start_ns: int, end_ns: int):
        with span("query.fetch", metric=sel.name.decode(errors="replace")
                  if sel.name else "") as sp:
            series = self.storage.fetch_raw(
                promql.selector_matchers(sel), start_ns, end_ns)
            sp.set_tag("series", len(series))
        points = sum(len(e["t"]) for e in series.values())
        # Per-query datapoint budget: bills the QueryScope's child
        # enforcer installed by _execute_range (utils.limits), so one
        # runaway selector exhausts its own budget, not the process's.
        # This is the single datapoint charge point on the query path —
        # LocalStorage.fetch_raw reads shards directly, below database's
        # charging wrapper.
        xlimits.charge("datapoints_decoded", points)
        enforcer = getattr(self._local, "enforcer", None)
        if enforcer is not None:
            enforcer.add(points)
        return series

    def _resolve_at(self, at) -> int:
        """Absolute eval timestamp for an @-modifier. start()/end() come
        from the outermost query range, not any inner subquery grid."""
        if isinstance(at, str):
            outer: QueryParams = self._local.outer_params
            if at == "start":
                return outer.start_ns
            return outer.start_ns + (outer.steps - 1) * outer.step_ns
        return int(at)

    def _pin_at(self, node, sel, params: QueryParams) -> Block:
        """Evaluate `node` (with range/instant selector `sel` carrying an
        @-modifier) at the pinned timestamp, then tile the single-step
        result across the query's steps — an @-pinned expression is
        constant over the output grid (prom promql/engine.go)."""
        t = self._resolve_at(sel.at_ns)
        pinned = QueryParams(t, t, params.step_ns)
        sel2 = dataclasses.replace(sel, at_ns=None)
        if node is sel:
            out = self._eval(sel2, pinned)
        else:
            node2 = dataclasses.replace(node, args=tuple(
                sel2 if a is sel else a for a in node.args))
            out = self._eval_range_func(node2, pinned)
        blk = _to_block(out, pinned)
        return Block(params.meta(), blk.series_tags,
                     np.repeat(np.asarray(blk.values), params.steps, axis=1))

    def _sel_overlay_get(self, role: str, sel: VectorSelector,
                         params: QueryParams):
        """One-query selector memo (plan bind -> interpreter fallback):
        returns (key, hit). Populated only while a plan attempt is live;
        interpreter-only queries (execute_range_ref) never see it."""
        overlay = getattr(self._local, "sel_overlay", None)
        if overlay is None:
            return None, None
        key = (role, sel, params.start_ns, params.end_ns, params.step_ns)
        return key, overlay.get(key)

    def _eval_instant_selector(self, sel: VectorSelector,
                               params: QueryParams) -> Block:
        if sel.at_ns is not None:
            return self._pin_at(sel, sel, params)
        key, hit = self._sel_overlay_get("instant", sel, params)
        if hit is not None:
            return hit
        off = sel.offset_ns
        meta = params.meta()
        series = self._fetch(sel, params.start_ns - self.lookback_ns - off,
                             params.end_ns - off + 1)
        shifted = BlockMeta(meta.start_ns - off, meta.step_ns, meta.steps)
        tags_list, values = self._consolidate_cached(
            sel, series, shifted, self.lookback_ns)
        out = Block(meta, tags_list, values)
        if key is not None:
            self._local.sel_overlay[key] = out
        return out

    def _eval_range_selector(self, sel: VectorSelector, params: QueryParams
                             ) -> Tuple[Block, int, int]:
        """Fetch + grid a matrix selector: returns (extended block at the
        window grid, W cells per window, stride to subsample back to the
        query step)."""
        key, hit = self._sel_overlay_get("range", sel, params)
        if hit is not None:
            return hit
        off = sel.offset_ns
        wgrid = math.gcd(params.step_ns, sel.range_ns)
        W = sel.range_ns // wgrid
        stride = params.step_ns // wgrid
        meta = params.meta()
        # Extended grid: (W-1) cells of history before the first output step.
        ext_start = meta.start_ns - (W - 1) * wgrid - off
        ext_steps = (W - 1) + (meta.steps - 1) * stride + 1
        ext_meta = BlockMeta(ext_start, wgrid, ext_steps)
        series = self._fetch(sel, ext_start - wgrid, meta.end_ns - off + 1)
        # Range selectors see raw samples (no lookback): a cell holds the
        # latest sample within its grid cell only.
        tags_list, values = self._consolidate_cached(
            sel, series, ext_meta, wgrid)
        out = (Block(ext_meta, tags_list, values), W, stride)
        if key is not None:
            self._local.sel_overlay[key] = out
        return out

    def _consolidate_cached(self, sel: VectorSelector, series: dict,
                            meta: BlockMeta, lookback_ns: int):
        """consolidate_series behind the identity-verified grid cache: a
        repeat evaluation of the same selector over the same (immutable)
        fetched entries reuses the consolidated grid object, which also
        re-arms every id-keyed device cache downstream (temporal's derived
        cache skips its content hash when the same grid object returns)."""
        from ..utils.instrument import ROOT

        from ..utils import tracing

        key = (promql.selector_matchers(sel),
               meta.start_ns, meta.step_ns, meta.steps, lookback_ns)
        actx = qexplain.current()
        hit = self._grid_cache.get(key, series)
        if hit is not None:
            ROOT.counter("query.grid_cache.hit").inc()
            tracing.count_cost("grid_cache_hit")
            if actx is not None:
                actx.event("grid_cache_hit")
            return hit
        ROOT.counter("query.grid_cache.miss").inc()
        tracing.count_cost("grid_cache_miss")
        if actx is not None:
            actx.event("grid_cache_miss")
        tags_list, values = consolidate_series(series, meta, lookback_ns)
        self._grid_cache.put(key, series, tags_list, values)
        return tags_list, values

    def _eval_subquery_grid(self, sub: Subquery, params: QueryParams
                            ) -> Tuple[Block, int, int]:
        """Evaluate `expr[range:res]`: run the inner expression as ONE
        instant-style evaluation over a fine grid of resolution-aligned
        timestamps covering every outer step's trailing window, then hand
        the [series x fine-steps] block to the same W/stride reduce-window
        machinery matrix selectors use (prometheus promql/engine.go
        evalSubquery; each window sees the inner values at the res-aligned
        times in (T-range, T]).

        Default resolution (`[1h:]`) is the query step floored at 15s —
        this engine's stand-in for prometheus' default evaluation interval
        (an unfloored default would make an instant query, step 1s,
        evaluate the inner expression 3601 times per hour of range). Eval
        timestamps are absolute multiples of res (prometheus aligns
        subquery steps independently of the query time). When res divides
        the query step and covers the range at least once, the res grid
        feeds the kernels directly; otherwise the windows are gathered
        into a packed [steps x Wmax] layout (W=stride=Wmax) — sample
        membership per window stays exactly (T-range, T] either way, and
        when res divides the range the packed windows carry no padding
        lanes, so the rate family's position-based extrapolation sees the
        true window span (a non-dividing res leaves one NaN lane whose
        res-sized skew is documented in DIVERGENCES.md)."""
        res = sub.step_ns or max(params.step_ns, DEFAULT_SUBQUERY_RES_NS)
        off = sub.offset_ns
        x0 = params.start_ns - off
        # Window for output T: res-multiples k*res with
        # (T-off-range)//res < k <= (T-off)//res.
        k_min = (x0 - sub.range_ns) // res + 1
        # Last OUTPUT step, not params.end_ns: end is only "last step <=
        # end" and may overshoot the step grid by a fraction of a step.
        k_max = (x0 + (params.steps - 1) * params.step_ns) // res
        # k_max < k_min: no window contains any res-aligned timestamp
        # (single-step query with range < res off-phase). Evaluate one
        # token timestamp so the series set is known; every lane masks
        # invalid below and the result is all-NaN, like prometheus'
        # empty matrix.
        k_max = max(k_max, k_min)
        inner = QueryParams(k_min * res, k_max * res, res)
        val = self._eval(sub.expr, inner)
        block = _to_block(val, inner)
        if params.step_ns % res == 0 and sub.range_ns >= res:
            # Shared grid: every output step's window is a contiguous run
            # ending at a constant offset + i*stride (constant width — the
            # phase x mod res is the same for every step).
            W = x0 // res - (x0 - sub.range_ns) // res
            stride = params.step_ns // res
        else:
            # Packed gather: per-step window ends drift across the res
            # grid (or the range is shorter than one res cell), so windows
            # go side by side. res | range => every window holds exactly
            # range/res samples and no padding lane exists.
            Wmax = max(sub.range_ns // res + (1 if sub.range_ns % res else 0),
                       1)
            steps = params.steps
            x = x0 + np.arange(steps, dtype=np.int64) * params.step_ns
            k_end = x // res
            k_start = (x - sub.range_ns) // res + 1
            cols = (k_end[:, None] - (Wmax - 1) + np.arange(Wmax)[None, :]
                    - k_min)                                # [steps, Wmax]
            valid = cols >= (k_start - k_min)[:, None]
            vals = block.values
            packed = np.where(valid[None, :, :],
                              vals[:, np.clip(cols, 0, vals.shape[1] - 1)],
                              np.nan).reshape(vals.shape[0], steps * Wmax)
            block = Block(BlockMeta(inner.start_ns, res, steps * Wmax),
                          block.series_tags, packed)
            W = stride = Wmax
        assert block.meta.steps == (W - 1) + (params.steps - 1) * stride + 1, (
            block.meta.steps, W, stride, params.steps)
        return block, W, stride

    # -- functions ---------------------------------------------------------

    _RANGE_FUNCS = {
        "rate", "increase", "delta", "irate", "idelta", "deriv",
        "predict_linear", "holt_winters", "changes", "resets",
        "sum_over_time", "avg_over_time", "min_over_time", "max_over_time",
        "count_over_time", "last_over_time", "stddev_over_time",
        "stdvar_over_time", "present_over_time", "quantile_over_time",
        "absent_over_time",
    }

    def _eval_call(self, node: Call, params: QueryParams) -> Value:
        if node.func in self._RANGE_FUNCS:
            return self._eval_range_func(node, params)
        return self._eval_instant_func(node, params)

    def _eval_range_func(self, node: Call, params: QueryParams) -> Block:
        range_args = [a for a in node.args
                      if isinstance(a, (VectorSelector, Subquery))]
        if not range_args or not (isinstance(range_args[-1], Subquery)
                                  or range_args[-1].range_ns):
            raise QueryError(f"{node.func} expects a range vector")
        sel = range_args[-1]
        if sel.at_ns is not None:
            return self._pin_at(node, sel, params)
        if isinstance(sel, Subquery):
            ext, W, stride = self._eval_subquery_grid(sel, params)
        else:
            ext, W, stride = self._eval_range_selector(sel, params)
        grid = ext.values
        step_ns = ext.meta.step_ns
        f = node.func
        # Every kernel consolidates to the query's output step grid ON
        # DEVICE (stride) — the D2H result transfer is the per-query floor
        # on tunneled accelerators, so nothing wider than [series, steps]
        # ever crosses the link. The hot dashboard shapes (rate-family and
        # *_over_time moments) additionally return fetch closures whose
        # async copy overlaps the next query's host prep (LazyBlock).
        # WHERE the kernels run is a measured decision (placement.py):
        # full-matrix results route to the host CPU backend when shipping
        # them off a slow link would cost more than computing them there.
        from ..utils.instrument import ROOT

        cells = int(np.asarray(grid).size)
        result_bytes = ext.n_series * params.meta().steps * 4
        placed = self._placement.choose(cells, result_bytes)
        ROOT.counter("query.placement.host" if placed is not None
                     else "query.placement.device").inc()
        t_dispatch = time.perf_counter()
        with temporal.placed_on(placed):
            return self._dispatch_range_func(
                node, sel, params, ext, grid, W, stride, step_ns,
                placed=placed, cells=cells, result_bytes=result_bytes,
                t_dispatch=t_dispatch)

    def _dispatch_range_func(self, node, sel, params, ext, grid, W, stride,
                             step_ns, *, placed, cells, result_bytes,
                             t_dispatch):
        from .block import LazyBlock

        f = node.func
        fetch = None
        if f == "rate":
            fetch = temporal.rate_async(grid, W, step_ns, sel.range_ns, stride)
        elif f == "increase":
            fetch = temporal.increase_async(
                grid, W, step_ns, sel.range_ns, stride)
        elif f == "delta":
            fetch = temporal.delta_async(
                grid, W, step_ns, sel.range_ns, stride)
        elif f == "irate":
            out = temporal.irate(grid, W, step_ns, stride)
        elif f == "idelta":
            out = temporal.idelta(grid, W, step_ns, stride)
        elif f == "deriv":
            out = temporal.deriv(grid, W, step_ns, stride)
        elif f == "predict_linear":
            out = temporal.predict_linear(
                grid, W, step_ns, _const_param(node.args[1]), stride)
        elif f == "holt_winters":
            out = temporal.holt_winters(
                grid, W, _const_param(node.args[1]), _const_param(node.args[2]),
                stride)
        elif f == "changes":
            out = temporal.changes(grid, W, stride)
        elif f == "resets":
            out = temporal.resets(grid, W, stride)
        elif f == "quantile_over_time":
            out = temporal.quantile_over_time(
                grid, W, _const_param(node.args[0]), stride)
        elif f == "absent_over_time":
            # 1 at steps where NO series has a sample in the window
            # (functions.go funcAbsentOverTime), labelled from the
            # selector's equality matchers like absent().
            if ext.n_series:
                cnt = temporal.over_time(grid, W, "count", stride)
                present = np.nan_to_num(cnt).sum(axis=0) > 0
            else:
                present = np.zeros(params.meta().steps, dtype=bool)
            out = np.where(present, np.nan, 1.0)[None, :]
            return Block(params.meta(), [_absent_tags(sel)], out)
        else:
            kind = f[: -len("_over_time")]
            fetch = temporal.over_time_async(grid, W, kind, stride,
                                             finish="auto")
        drop_name = f not in ("last_over_time",)
        tags = [_strip_name(t) if drop_name else t for t in ext.series_tags]
        if fetch is not None:
            placement, inner = self._placement, fetch
            # Observed cost = dispatch segment + materialization segment.
            # The wall interval between them is EXCLUDED: LazyBlock exists
            # so unrelated work (the next query's prep) interleaves there,
            # and charging it to this eval would deflate the rate model.
            dispatch_s = time.perf_counter() - t_dispatch

            def observed_fetch():
                from ..parallel import telemetry

                t0 = time.perf_counter()
                result = inner()
                placement.observe(placed, cells, result_bytes,
                                  dispatch_s + time.perf_counter() - t0)
                # Result materialization is THE device->host transfer on
                # the query path (kernels consolidate on device first).
                telemetry.count_d2h(result_bytes)
                return result

            return LazyBlock(params.meta(), tags, observed_fetch)
        self._placement.observe(placed, cells, result_bytes,
                                time.perf_counter() - t_dispatch)
        from ..parallel import telemetry

        telemetry.count_d2h(result_bytes)
        return Block(params.meta(), tags, out)

    def _eval_instant_func(self, node: Call, params: QueryParams) -> Value:
        f = node.func
        if f == "time":
            return params.meta().times() / 1e9
        if f == "pi":
            return float(np.pi)
        if f in _DATE_FUNCS:
            # promql date functions: no argument means "now" per step
            # (functions.go dateWrapper); with a vector, per-sample values.
            if node.args:
                block = self._eval(node.args[0], params)
                if not isinstance(block, Block):
                    raise QueryError(f"{f} expects an instant vector")
                vals = _date_part(f, block.values)
                return block.with_values(
                    vals, [_strip_name(t) for t in block.series_tags])
            # dateWrapper emits a one-series vector with empty labels, so
            # `x and on() (hour() < 6)` vector-matches like in Prometheus.
            times = params.meta().times() / 1e9
            return Block(params.meta(), [Tags.of({})],
                         _date_part(f, times)[None, :])
        if f == "scalar":
            block = self._eval(node.args[0], params)
            if not isinstance(block, Block):
                raise QueryError("scalar() expects a vector")
            if block.n_series == 1:
                return block.values[0].astype(np.float64)
            return np.full(params.steps, np.nan)
        if f == "vector":
            val = self._eval(node.args[0], params)
            arr = _broadcast_scalar(val, params)
            return Block(params.meta(), [Tags.of({})], arr[None, :])
        if f == "absent":
            block = self._eval(node.args[0], params)
            present = np.isfinite(block.values).any(axis=0) if block.n_series else (
                np.zeros(params.steps, dtype=bool))
            vals = np.where(present, np.nan, 1.0)[None, :]
            tags = _absent_tags(node.args[0])
            return Block(params.meta(), [tags], vals)
        if f in ("label_replace", "label_join"):
            return self._eval_label_func(node, params)
        if f == "histogram_quantile":
            q = _const_param(node.args[0])
            block = self._eval(node.args[1], params)
            return _histogram_quantile(q, block)
        if f in ("sort", "sort_desc"):
            block = self._eval(node.args[0], params)
            key = np.where(np.isfinite(block.values), block.values, -np.inf).mean(axis=1)
            order = np.argsort(-key if f == "sort_desc" else key, kind="stable")
            return Block(block.meta, [block.series_tags[i] for i in order],
                         block.values[order])
        if f == "timestamp":
            block = self._eval(node.args[0], params)
            times = block.meta.times() / 1e9
            vals = np.where(np.isfinite(block.values), times[None, :], np.nan)
            return block.with_values(vals, [_strip_name(t) for t in block.series_tags])
        fn = _MATH_FUNCS.get(f)
        if fn is None:
            raise QueryError(f"unknown function {f}")
        args = [self._eval(a, params) for a in node.args]
        if not args:
            raise QueryError(f"{f} expects arguments")
        head = args[0]
        extra = [(_broadcast_scalar(a, params) if not isinstance(a, Block) else a)
                 for a in args[1:]]
        if isinstance(head, Block):
            vals = fn(head.values, *[e if isinstance(e, np.ndarray) else e
                                     for e in extra])
            return head.with_values(vals, [_strip_name(t) for t in head.series_tags])
        return fn(_broadcast_scalar(head, params), *extra)

    def _eval_label_func(self, node: Call, params: QueryParams) -> Block:
        import re as _re

        block = self._eval(node.args[0], params)
        if node.func == "label_replace":
            dst, repl, src, regex = (_string_param(a) for a in node.args[1:5])
            pattern = _re.compile(regex)
            tags = []
            for t in block.series_tags:
                val = (t.get(src.encode()) or b"").decode()
                m = pattern.fullmatch(val)
                if m:
                    new = m.expand(_go_template_to_py(repl))
                    t = t.with_tag(dst.encode(), new.encode())
                tags.append(t)
            return block.with_values(block.values, tags)
        # label_join(v, dst, sep, src...)
        dst = _string_param(node.args[1]).encode()
        sep = _string_param(node.args[2]).encode()
        srcs = [_string_param(a).encode() for a in node.args[3:]]
        tags = [
            t.with_tag(dst, sep.join(t.get(s) or b"" for s in srcs))
            for t in block.series_tags
        ]
        return block.with_values(block.values, tags)

    # -- aggregation -------------------------------------------------------

    def _eval_sharded_agg(self, node: Aggregation,
                          params: QueryParams) -> Optional[Block]:
        """Mesh fast path for dashboard-shaped aggregations: a GLOBAL
        op(rate|increase|delta(selector[R])) evaluates as one SPMD program
        — each device runs the fused rate kernel on its series slice and a
        single psum/pmin/pmax over the "shard" axis produces the [steps]
        answer (parallel/query.py; the reference fans the same shape out
        across dbnodes and merges at the coordinator,
        src/query/storage/fanout/storage.go:1). Returns None when the
        query shape doesn't match, falling back to the host path.
        Device sums are f32 (DIVERGENCES.md)."""
        if self.mesh is None or node.grouping or node.without:
            return None
        from ..parallel import query as pq

        if node.op not in pq.AGG_OPS or not isinstance(node.expr, Call):
            return None
        func = node.expr.func
        if func not in pq.RANGE_FUNCS:
            return None
        sel_args = [a for a in node.expr.args
                    if isinstance(a, VectorSelector)]
        if (not sel_args or not sel_args[-1].range_ns
                or sel_args[-1].at_ns is not None):
            return None
        sel = sel_args[-1]
        ext, W, stride = self._eval_range_selector(sel, params)
        if ext.n_series == 0:
            return Block(params.meta(), [], np.zeros((0, params.steps)))
        out = pq.agg_rate(ext.values, self.mesh, op=node.op, func=func, W=W,
                          step_ns=ext.meta.step_ns, range_ns=sel.range_ns,
                          stride=stride)
        from ..utils.instrument import ROOT

        ROOT.counter("query.sharded_agg").inc()
        return Block(params.meta(), [Tags.of({})], out[None, :])

    def _eval_aggregation(self, node: Aggregation, params: QueryParams) -> Block:
        sharded = self._eval_sharded_agg(node, params)
        if sharded is not None:
            return sharded
        block = self._eval(node.expr, params)
        if not isinstance(block, Block):
            raise QueryError(f"{node.op} expects an instant vector")
        group_ids, group_tags = _group_series(
            block.series_tags, node.grouping, node.without)
        G = len(group_tags)
        vals = block.values
        op = node.op
        if op in ("sum", "avg", "min", "max", "count", "stddev", "stdvar",
                  "group"):
            # f64 host reduce keeps counter-sum exactness; the jitted f32
            # segment kernel (series_agg.grouped_reduce) is the fast path
            # for large fan-in where 24-bit mantissas suffice. The large
            # path places by the measured link: its input is a full
            # [S, T] H2D upload, which a slow tunnel turns into the cost
            # (the same economics as the range-func result transfer).
            kind = "count" if op == "group" else op
            if vals.shape[0] < 4096:
                out = series_agg.grouped_reduce_f64(vals, group_ids, G, kind)
            else:
                cells = int(np.asarray(vals).size)
                # Transfer term = H2D upload of the f32 input + D2H of the
                # grouped result; the SAME value feeds observe() so the
                # model nets out what choose() charged (an inconsistent
                # pair would fold the upload into "compute" and bias
                # future choices).
                xfer_bytes = cells * 4 + G * vals.shape[1] * 8
                placed = self._placement.choose(cells, xfer_bytes)
                arr = vals
                if placed is not None:
                    from ..utils import hbm

                    # Budget-charged upload (utils.hbm): the transient
                    # [S, T] f32 plane is real HBM pressure for its
                    # lifetime and must count against the same budget the
                    # resident caches share.
                    arr = hbm.budgeted_put(
                        np.asarray(vals, dtype=np.float32), placed)
                t0 = time.perf_counter()
                out = series_agg.grouped_reduce(arr, group_ids, G, kind)
                self._placement.observe(placed, cells, xfer_bytes,
                                        time.perf_counter() - t0)
            if op == "group":
                # promql group(): 1 per group with any present series.
                out = np.where(out > 0, 1.0, np.nan)
            return Block(block.meta, group_tags, out)
        if op == "quantile":
            q = _const_param(node.param)
            out = series_agg.grouped_quantile(vals, group_ids, G, q)
            return Block(block.meta, group_tags, out)
        if op in ("topk", "bottomk"):
            k = int(_const_param(node.param))
            keep = series_agg.topk_mask(vals, group_ids, G, k, op == "topk")
            out = np.where(keep, vals, np.nan)
            rows = ~np.all(np.isnan(out), axis=1)
            return Block(block.meta,
                         [t for t, r in zip(block.series_tags, rows) if r],
                         out[rows])
        if op == "count_values":
            label = _string_param(node.param).encode()
            counts = series_agg.count_values(vals, group_ids, G)
            tags, rows = [], []
            for (g, v), cnt in sorted(counts.items()):
                tags.append(group_tags[g].with_tag(label, _format_value(v)))
                rows.append(np.where(cnt > 0, cnt, np.nan))
            values = np.stack(rows) if rows else np.zeros((0, block.meta.steps))
            return Block(block.meta, tags, values)
        raise QueryError(f"unsupported aggregation {op}")

    # -- binary ops --------------------------------------------------------

    def _eval_binary(self, node: BinaryOp, params: QueryParams) -> Value:
        lhs = self._eval(node.lhs, params)
        rhs = self._eval(node.rhs, params)
        if node.op in promql.SET_OPS:
            return _set_op(node.op, lhs, rhs, node.matching)
        l_vec, r_vec = isinstance(lhs, Block), isinstance(rhs, Block)
        fn = _BIN_FUNCS[node.op]
        comparison = node.op in promql.COMPARISON_OPS
        if not l_vec and not r_vec:
            lv = _broadcast_scalar(lhs, params)
            rv = _broadcast_scalar(rhs, params)
            out = fn(lv, rv)
            if comparison and not node.bool_mode:
                # scalar comparisons without bool filter to the lhs value
                return np.where(out > 0, lv, np.nan)
            return out.astype(np.float64)
        if l_vec and r_vec:
            return _vector_vector(node, lhs, rhs, fn, comparison)
        # vector <op> scalar (either side)
        block = lhs if l_vec else rhs
        scalar = _broadcast_scalar(rhs if l_vec else lhs, params)
        a = block.values if l_vec else scalar[None, :]
        b = scalar[None, :] if l_vec else block.values
        with np.errstate(divide="ignore", invalid="ignore"):
            out = fn(a, b)
        if comparison:
            if node.bool_mode:
                vals = np.where(np.isfinite(block.values), out.astype(np.float64), np.nan)
                return block.with_values(vals, [_strip_name(t) for t in block.series_tags])
            return block.with_values(np.where(out > 0, block.values, np.nan))
        return block.with_values(out, [_strip_name(t) for t in block.series_tags])


# ---------------------------------------------------------------- helpers

_MATH_FUNCS: Dict[str, Callable] = {
    "abs": np.abs, "ceil": np.ceil, "floor": np.floor, "exp": np.exp,
    "sqrt": lambda v: _guard(np.sqrt, v), "ln": lambda v: _guard(np.log, v),
    "log2": lambda v: _guard(np.log2, v), "log10": lambda v: _guard(np.log10, v),
    "sgn": np.sign,
    "round": lambda v, to=None: (np.round(v) if to is None
                                 else np.round(v / to) * to),
    "clamp": lambda v, lo, hi: np.clip(v, lo, hi),
    "clamp_min": lambda v, lo: np.maximum(v, lo),
    "clamp_max": lambda v, hi: np.minimum(v, hi),
    # trigonometry (promql functions.go funcSin..funcAtanh; domain errors
    # yield NaN like Go's math package)
    "sin": np.sin, "cos": np.cos, "tan": np.tan,
    "asin": lambda v: _guard(np.arcsin, v),
    "acos": lambda v: _guard(np.arccos, v),
    "atan": np.arctan,
    "sinh": np.sinh, "cosh": np.cosh, "tanh": np.tanh,
    "asinh": np.arcsinh,
    "acosh": lambda v: _guard(np.arccosh, v),
    "atanh": lambda v: _guard(np.arctanh, v),
    "deg": np.degrees, "rad": np.radians,
}


def _date_part(kind: str, sec: np.ndarray) -> np.ndarray:
    """One calendar component of unix-seconds values (UTC), NaN-preserving
    — promql functions.go funcDaysInMonth..funcYear. Computes only the
    requested component (a vector query pays one decomposition, not 8)."""
    finite = np.isfinite(sec)
    s = np.where(finite, sec, 0.0).astype(np.int64)
    if kind == "minute":
        v = (s // 60) % 60
    elif kind == "hour":
        v = (s // 3600) % 24
    elif kind == "day_of_week":
        # unix epoch was a Thursday; promql uses 0=Sunday
        v = (s // 86400 + 4) % 7
    else:
        dt = s.astype("datetime64[s]")
        if kind == "year":
            v = dt.astype("datetime64[Y]").astype(np.int64) + 1970
        elif kind == "month":
            v = dt.astype("datetime64[M]").astype(np.int64) % 12 + 1
        elif kind == "day_of_month":
            v = (dt.astype("datetime64[D]")
                 - dt.astype("datetime64[M]").astype("datetime64[D]")
                 ).astype(np.int64) + 1
        elif kind == "day_of_year":
            v = (dt.astype("datetime64[D]")
                 - dt.astype("datetime64[Y]").astype("datetime64[D]")
                 ).astype(np.int64) + 1
        elif kind == "days_in_month":
            months = dt.astype("datetime64[M]")
            v = ((months + np.timedelta64(1, "M")).astype("datetime64[D]")
                 - months.astype("datetime64[D]")).astype(np.int64)
        else:
            raise QueryError(f"unknown date function {kind}")
    return np.where(finite, v.astype(np.float64), np.nan)


_DATE_FUNCS = ("minute", "hour", "day_of_week", "day_of_month",
               "day_of_year", "days_in_month", "month", "year")

_BIN_FUNCS: Dict[str, Callable] = {
    "+": np.add, "-": np.subtract, "*": np.multiply,
    # fmod = Go math.Mod truncated-toward-zero semantics (promql '%'),
    # unlike np.mod's floored modulo.
    "/": np.divide, "%": np.fmod, "^": np.power,
    "==": lambda a, b: (a == b).astype(np.float64),
    "!=": lambda a, b: (a != b).astype(np.float64),
    "<": lambda a, b: (a < b).astype(np.float64),
    ">": lambda a, b: (a > b).astype(np.float64),
    "<=": lambda a, b: (a <= b).astype(np.float64),
    ">=": lambda a, b: (a >= b).astype(np.float64),
}


def _guard(fn, v):
    with np.errstate(invalid="ignore", divide="ignore"):
        return fn(v)


def _map_values(val: Value, fn) -> Value:
    if isinstance(val, Block):
        return val.with_values(fn(val.values))
    if isinstance(val, np.ndarray):
        return fn(val)
    return fn(val)


def _broadcast_scalar(val: Value, params: QueryParams) -> np.ndarray:
    if isinstance(val, Block):
        raise QueryError("expected scalar, got vector")
    if isinstance(val, np.ndarray):
        return val
    return np.full(params.steps, float(val))


def _to_block(val: Value, params: QueryParams) -> Block:
    if isinstance(val, Block):
        return val
    arr = _broadcast_scalar(val, params)
    return Block(params.meta(), [Tags.of({})], arr[None, :])


def _strip_name(t: Tags) -> Tags:
    return t.without([METRIC_NAME])


def _group_series(tags: List[Tags], grouping: Tuple[bytes, ...],
                  without: bool) -> Tuple[np.ndarray, List[Tags]]:
    """Group rows by kept labels (functions/aggregation/function.go
    collectSeries): by(...) keeps listed labels; without(...) drops them
    (and the metric name); no modifier = one global group."""
    ids = np.zeros(len(tags), dtype=np.int64)
    group_tags: List[Tags] = []
    seen: Dict[bytes, int] = {}
    for i, t in enumerate(tags):
        if without:
            gt = t.without(list(grouping) + [METRIC_NAME])
        elif grouping:
            gt = t.keep(grouping)
        else:
            gt = Tags.of({})
        key = gt.id()
        g = seen.get(key)
        if g is None:
            g = seen[key] = len(group_tags)
            group_tags.append(gt)
        ids[i] = g
    return ids, group_tags


def _match_key(t: Tags, matching) -> bytes:
    if matching is not None and matching.on:
        return t.keep(matching.labels).id()
    drop = list(matching.labels) if matching is not None else []
    return t.without(drop + [METRIC_NAME]).id()


def _vector_vector(node: BinaryOp, lhs: Block, rhs: Block, fn,
                   comparison: bool) -> Block:
    matching = node.matching
    many_side_left = matching.group_left if matching else False
    many_side_right = matching.group_right if matching else False
    one_to_one = not (many_side_left or many_side_right)
    # Map the "one" side by matching key.
    if many_side_right:
        many, one, swap = rhs, lhs, True
    else:
        many, one, swap = lhs, rhs, False
    one_map: Dict[bytes, int] = {}
    for j, t in enumerate(one.series_tags):
        key = _match_key(t, matching)
        if key in one_map:
            raise QueryError(
                "many-to-many vector matching: duplicate series on the "
                f"'one' side for key {key!r}")
        one_map[key] = j
    tags_out: List[Tags] = []
    rows: List[np.ndarray] = []
    seen_result: Dict[bytes, int] = {}
    for i, t in enumerate(many.series_tags):
        j = one_map.get(_match_key(t, matching))
        if j is None:
            continue
        a = many.values[i]
        b = one.values[j]
        with np.errstate(divide="ignore", invalid="ignore"):
            out = fn(b, a) if swap else fn(a, b)
        both = np.isfinite(many.values[i]) & np.isfinite(one.values[j])
        result_tags = _result_tags(t, one.series_tags[j], matching, comparison,
                                   node.bool_mode)
        if comparison and not node.bool_mode:
            out = np.where(out > 0, a, np.nan)
        else:
            out = np.where(both, out, np.nan)
        key = result_tags.id()
        if one_to_one and key in seen_result:
            raise QueryError("multiple matches for the same result labels")
        seen_result[key] = i
        tags_out.append(result_tags)
        rows.append(out)
    values = np.stack(rows) if rows else np.zeros((0, lhs.meta.steps))
    return Block(lhs.meta, tags_out, values)


def _result_tags(many_tags: Tags, one_tags: Tags, matching, comparison: bool,
                 bool_mode: bool) -> Tags:
    if comparison and not bool_mode:
        return many_tags
    t = many_tags.without([METRIC_NAME])
    if matching is not None and matching.include:
        for lbl in matching.include:
            v = one_tags.get(lbl)
            if v is not None:
                t = t.with_tag(lbl, v)
            else:
                t = t.without([lbl])
    return t


def _set_op(op: str, lhs: Value, rhs: Value, matching) -> Block:
    if not isinstance(lhs, Block) or not isinstance(rhs, Block):
        raise QueryError(f"{op} requires vector operands")
    rhs_keys = {_match_key(t, matching): j for j, t in enumerate(rhs.series_tags)}
    tags_out, rows = [], []
    if op in ("and", "unless"):
        for i, t in enumerate(lhs.series_tags):
            j = rhs_keys.get(_match_key(t, matching))
            if op == "and":
                if j is None:
                    continue
                keep = np.isfinite(rhs.values[j])
            else:
                keep = (np.zeros(lhs.meta.steps, bool) if j is None else
                        np.isfinite(rhs.values[j]))
                keep = ~keep if j is not None else np.ones(lhs.meta.steps, bool)
            vals = np.where(keep, lhs.values[i], np.nan)
            if np.isfinite(vals).any() or op == "and":
                tags_out.append(t)
                rows.append(vals)
    else:  # or
        lhs_keys = set()
        for i, t in enumerate(lhs.series_tags):
            lhs_keys.add(_match_key(t, matching))
            tags_out.append(t)
            rows.append(lhs.values[i])
        for j, t in enumerate(rhs.series_tags):
            if _match_key(t, matching) not in lhs_keys:
                tags_out.append(t)
                rows.append(rhs.values[j])
    values = np.stack(rows) if rows else np.zeros((0, lhs.meta.steps))
    return Block(lhs.meta, tags_out, values)


def _histogram_quantile(q: float, block: Block) -> Block:
    """promql histogram_quantile over classic le-bucket series
    (functions/linear/histogram_quantile.go)."""
    groups: Dict[bytes, List[Tuple[float, int]]] = {}
    group_tags: Dict[bytes, Tags] = {}
    for i, t in enumerate(block.series_tags):
        le = t.get(b"le")
        if le is None:
            continue
        gt = t.without([b"le", METRIC_NAME])
        key = gt.id()
        group_tags[key] = gt
        groups.setdefault(key, []).append((float(le), i))
    tags_out, rows = [], []
    for key, buckets in sorted(groups.items()):
        buckets.sort()
        ubs = np.array([b[0] for b in buckets])
        idxs = [b[1] for b in buckets]
        if len(buckets) < 2 or not np.isinf(ubs[-1]):
            # upstream requires a +Inf bucket AND at least two buckets:
            # without them the total/interpolation is unknowable and the
            # result is NaN (promql functions.go bucketQuantile), not a
            # guess that treats the largest finite bucket as the total
            # or collapses a lone +Inf bucket to 0.
            tags_out.append(group_tags[key])
            rows.append(np.full(block.meta.steps, np.nan))
            continue
        counts = block.values[idxs]  # cumulative counts [B, T]
        total = counts[-1]
        out = np.full(block.meta.steps, np.nan)
        with np.errstate(invalid="ignore", divide="ignore"):
            rank = q * total
            # First bucket whose cumulative count >= rank.
            ge = counts >= rank[None, :]
            first = np.argmax(ge, axis=0)
            any_ge = ge.any(axis=0)
            b_idx = np.clip(first, 0, len(buckets) - 1)
            ub = ubs[b_idx]
            lb = np.where(b_idx > 0, ubs[np.maximum(b_idx - 1, 0)], 0.0)
            cnt_ub = counts[b_idx, np.arange(counts.shape[1])]
            cnt_lb = np.where(b_idx > 0,
                              counts[np.maximum(b_idx - 1, 0),
                                     np.arange(counts.shape[1])], 0.0)
            frac = np.where(cnt_ub > cnt_lb, (rank - cnt_lb) / (cnt_ub - cnt_lb), 0)
            interp = lb + (ub - lb) * frac
            # +Inf bucket selected -> return the lower bound (prom behavior).
            interp = np.where(np.isinf(ub), lb, interp)
            out = np.where((total > 0) & any_ge, interp, np.nan)
        tags_out.append(group_tags[key])
        rows.append(out)
    values = np.stack(rows) if rows else np.zeros((0, block.meta.steps))
    return Block(block.meta, tags_out, values)


def _const_param(node: Optional[Node]) -> float:
    if isinstance(node, NumberLiteral):
        return float(node.value)
    if isinstance(node, Unary) and isinstance(node.expr, NumberLiteral):
        return -node.expr.value
    raise QueryError("expected a constant parameter")


def _string_param(node: Node) -> str:
    if isinstance(node, StringLiteral):
        return node.value
    raise QueryError("expected a string parameter")


def _absent_tags(node: Node) -> Tags:
    if isinstance(node, Subquery):
        return _absent_tags(node.expr)
    if isinstance(node, VectorSelector):
        d = {}
        if node.name:
            d[METRIC_NAME] = node.name
        for m in node.matchers:
            if m.type == MatchType.EQUAL:
                d[m.name] = m.value
        d.pop(METRIC_NAME, None)
        return Tags.of(d)
    return Tags.of({})


def _format_value(v: float) -> bytes:
    if v == int(v):
        return str(int(v)).encode()
    return repr(v).encode()


def _go_template_to_py(repl: str) -> str:
    """Convert prom's $1/${name} capture refs to python re.expand refs."""
    return re_sub_dollar(repl)


def re_sub_dollar(repl: str) -> str:
    import re as _re

    return _re.sub(r"\$(\d+|\{\w+\})", lambda m: "\\" + m.group(1).strip("{}"), repl)
