"""Query engine: PromQL parse -> plan -> batched block execution
(reference: src/query — the coordinator's engine, storage adapters, and
API surface, re-expressed as whole-block jitted transforms)."""

from .block import Block, BlockMeta, block_from_series, consolidate
from .executor import Engine, QueryError, QueryParams
from .model import Matcher, MatchType, METRIC_NAME, Tags, matchers_to_index_query
from .promql import parse, ParseError
from .storage import FanoutStorage, LocalStorage, SessionStorage

__all__ = [
    "Block", "BlockMeta", "Engine", "FanoutStorage", "LocalStorage",
    "Matcher", "MatchType", "METRIC_NAME", "ParseError", "QueryError",
    "QueryParams", "SessionStorage", "Tags", "block_from_series",
    "consolidate", "matchers_to_index_query", "parse",
]
