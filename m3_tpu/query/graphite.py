"""Graphite query language: expression parser + function evaluator over
blocks (reference: src/query/graphite — lexer/compiler in graphite/native,
~100 builtin functions in native/builtin_functions.go, storage adapter in
graphite/storage).

Path globs compile to per-component matchers on the __gN__ tags written by
carbon ingestion (m3_tpu.metrics.carbon.path_to_tags). Series math runs on
the same dense [series x steps] blocks as PromQL; functions are a curated
core of the reference's builtins, organized for easy widening."""

from __future__ import annotations

import math
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..metrics.carbon import GRAPHITE_TAG_FMT, tags_to_path
from ..ops import temporal
from .block import Block, BlockMeta
from .executor import QueryParams
from .model import Matcher, MatchType, Tags

S = 1_000_000_000


# ---------------------------------------------------------------- parsing

_TOKEN = re.compile(r"""
    (?P<WS>\s+)
  | (?P<NUMBER>-?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)
  | (?P<STRING>"[^"]*"|'[^']*')
  | (?P<NAME>[a-zA-Z_][a-zA-Z0-9_]*(?=\s*\())
  | (?P<PATH>(?:[a-zA-Z0-9_*?.:\[\]\-$%+]|\{[^}]*\})+)
  | (?P<LPAREN>\()|(?P<RPAREN>\))|(?P<COMMA>,)
""", re.VERBOSE)


class GraphiteParseError(ValueError):
    pass


def _lex(s: str):
    out, i = [], 0
    while i < len(s):
        m = _TOKEN.match(s, i)
        if not m:
            raise GraphiteParseError(f"bad character {s[i]!r} at {i}")
        if m.lastgroup != "WS":
            out.append((m.lastgroup, m.group()))
        i = m.end()
    out.append(("EOF", ""))
    return out


class _Expr:
    pass


class PathExpr(_Expr):
    def __init__(self, path: str):
        self.path = path


class CallExpr(_Expr):
    def __init__(self, func: str, args: List):
        self.func = func
        self.args = args


class Literal(_Expr):
    def __init__(self, value):
        self.value = value


def parse_target(s: str) -> _Expr:
    """graphite/native/compiler.go: one render target expression."""
    toks = _lex(s)
    pos = [0]

    def peek():
        return toks[pos[0]]

    def nxt():
        t = toks[pos[0]]
        pos[0] += 1
        return t

    def expr():
        kind, text = peek()
        if kind == "NAME":
            nxt()
            if nxt()[0] != "LPAREN":
                raise GraphiteParseError("expected (")
            args = []
            while peek()[0] != "RPAREN":
                args.append(expr())
                if peek()[0] == "COMMA":
                    nxt()
            nxt()
            return CallExpr(text, args)
        if kind == "NUMBER":
            nxt()
            return Literal(float(text))
        if kind == "STRING":
            nxt()
            return Literal(text[1:-1])
        if kind == "PATH":
            nxt()
            # bare boolean literals (compiler.go: true/false args, e.g.
            # summarize(..., alignToFrom))
            if text in ("true", "True"):
                return Literal(True)
            if text in ("false", "False"):
                return Literal(False)
            return PathExpr(text)
        raise GraphiteParseError(f"unexpected {text!r}")

    node = expr()
    if peek()[0] != "EOF":
        raise GraphiteParseError(f"trailing input {peek()[1]!r}")
    return node


def path_to_matchers(path: str) -> Tuple[Matcher, ...]:
    """Glob path -> per-component __gN__ matchers (graphite/storage/
    converter.go equivalent): literal components match exactly, glob
    components compile to regexes."""
    out = []
    parts = path.split(".")
    for i, part in enumerate(parts):
        name = GRAPHITE_TAG_FMT % i
        if any(c in part for c in "*?{["):
            out.append(Matcher(MatchType.REGEXP, name, _glob_regex(part).encode()))
        else:
            out.append(Matcher(MatchType.EQUAL, name, part.encode()))
    # Exact depth: the next component must not exist.
    out.append(Matcher(MatchType.NOT_REGEXP, GRAPHITE_TAG_FMT % len(parts),
                       b".+"))
    return tuple(out)


def _glob_regex(part: str) -> str:
    out = []
    i = 0
    while i < len(part):
        c = part[i]
        if c == "*":
            out.append("[^.]*")
        elif c == "?":
            out.append("[^.]")
        elif c == "{":
            j = part.find("}", i)
            if j < 0:
                raise GraphiteParseError(f"unterminated {{ in {part!r}")
            alts = part[i + 1:j].split(",")
            out.append("(?:" + "|".join(re.escape(a) for a in alts) + ")")
            i = j
        elif c == "[":
            j = part.find("]", i)
            out.append(part[i:j + 1])
            i = j
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


# ---------------------------------------------------------------- engine

class GraphiteEngine:
    """Evaluate render targets (graphite/native/engine.go)."""

    def __init__(self, storage, step_ns: int = 10 * S):
        self.storage = storage
        self.step_ns = step_ns

    def render(self, target: str, start_ns: int, end_ns: int,
               step_ns: Optional[int] = None) -> Block:
        params = QueryParams(start_ns, end_ns, step_ns or self.step_ns)
        return self._eval(parse_target(target), params)

    # -- evaluation -------------------------------------------------------

    def _eval(self, node: _Expr, params: QueryParams) -> Block:
        if isinstance(node, PathExpr):
            return self._fetch(node.path, params)
        if isinstance(node, CallExpr):
            fn = _FUNCTIONS.get(node.func)
            if fn is None:
                raise GraphiteParseError(f"unknown function {node.func!r}")
            return fn(self, node.args, params)
        raise GraphiteParseError("bare literal is not a series")

    def _eval_arg(self, node, params):
        if isinstance(node, Literal):
            return node.value
        return self._eval(node, params)

    def _fetch(self, path: str, params: QueryParams) -> Block:
        from .block import consolidate

        series = self.storage.fetch_raw(
            path_to_matchers(path), params.start_ns - params.step_ns,
            params.end_ns + 1)
        meta = params.meta()
        tags_list, rows = [], []
        for sid, entry in sorted(series.items()):
            tags_list.append(Tags.of(dict(entry["tags"])))
            rows.append(consolidate(
                np.asarray(entry["t"], np.int64), np.asarray(entry["v"]),
                meta, params.step_ns))
        vals = np.stack(rows) if rows else np.zeros((0, meta.steps))
        return Block(meta, tags_list, vals)


def series_name(tags: Tags) -> bytes:
    """Render name for output: the dotted path (or the alias tag)."""
    alias = tags.get(b"__alias__")
    if alias is not None:
        return alias
    return tags_to_path(tags.as_dict())


# ---------------------------------------------------------------- functions

_FUNCTIONS: Dict[str, Callable] = {}


def _register(*names):
    def deco(fn):
        for n in names:
            _FUNCTIONS[n] = fn
        return fn

    return deco


def _combine(eng, args, params, reducer, name):
    blocks = [eng._eval(a, params) for a in args]
    vals = np.concatenate([b.values for b in blocks]) if blocks else \
        np.zeros((0, params.steps))
    meta = blocks[0].meta if blocks else params.meta()
    with np.errstate(invalid="ignore"):
        row = reducer(vals)
    # Columns where every input is missing stay missing (graphite's safe*
    # combiners return None there; np.nansum/nanprod would fabricate 0/1).
    if vals.shape[0]:
        row = np.where(np.isfinite(vals).any(axis=0), row, np.nan)
    tags = Tags.of({b"__alias__": name.encode()})
    return Block(meta, [tags], row[None, :])


@_register("sumSeries", "sum")
def _sum_series(eng, args, params):
    return _combine(eng, args, params, lambda v: np.nansum(v, axis=0),
                    "sumSeries")


@_register("averageSeries", "avg")
def _avg_series(eng, args, params):
    return _combine(eng, args, params, lambda v: np.nanmean(v, axis=0),
                    "averageSeries")


@_register("maxSeries")
def _max_series(eng, args, params):
    return _combine(eng, args, params, lambda v: np.nanmax(v, axis=0), "maxSeries")


@_register("minSeries")
def _min_series(eng, args, params):
    return _combine(eng, args, params, lambda v: np.nanmin(v, axis=0), "minSeries")


@_register("scale")
def _scale(eng, args, params):
    block = eng._eval(args[0], params)
    factor = args[1].value
    return block.with_values(block.values * factor)


@_register("offset")
def _offset(eng, args, params):
    block = eng._eval(args[0], params)
    return block.with_values(block.values + args[1].value)


@_register("absolute")
def _absolute(eng, args, params):
    block = eng._eval(args[0], params)
    return block.with_values(np.abs(block.values))


@_register("alias")
def _alias(eng, args, params):
    block = eng._eval(args[0], params)
    name = args[1].value.encode()
    return block.with_values(
        block.values, [t.with_tag(b"__alias__", name) for t in block.series_tags])


@_register("aliasByNode")
def _alias_by_node(eng, args, params):
    block = eng._eval(args[0], params)
    nodes = [int(a.value) for a in args[1:]]
    tags = []
    for t in block.series_tags:
        parts = tags_to_path(t.as_dict()).split(b".")
        picked = b".".join(parts[n] for n in nodes if -len(parts) <= n < len(parts))
        tags.append(t.with_tag(b"__alias__", picked))
    return block.with_values(block.values, tags)


@_register("derivative")
def _derivative(eng, args, params):
    block = eng._eval(args[0], params)
    v = block.values
    out = np.full_like(v, np.nan)
    out[:, 1:] = v[:, 1:] - v[:, :-1]
    return block.with_values(out)


@_register("perSecond")
def _per_second(eng, args, params):
    block = eng._eval(args[0], params)
    v = block.values
    d = np.full_like(v, np.nan)
    d[:, 1:] = (v[:, 1:] - v[:, :-1]) / (params.step_ns / S)
    d[d < 0] = np.nan  # counter wrap guard (builtin_functions.go perSecond)
    return block.with_values(d)


@_register("nonNegativeDerivative")
def _non_negative_derivative(eng, args, params):
    block = eng._eval(args[0], params)
    v = block.values
    d = np.full_like(v, np.nan)
    d[:, 1:] = v[:, 1:] - v[:, :-1]
    d[d < 0] = np.nan
    return block.with_values(d)


@_register("movingAverage")
def _moving_average(eng, args, params):
    # Shares _moving's lookback-exclusive window (the reference's
    # movingAverage walks the W points BEFORE each output step,
    # builtin_functions.go:620-666), reduced via the batched temporal
    # kernel.
    return _moving(eng, args, params, "avg")


@_register("keepLastValue")
def _keep_last_value(eng, args, params):
    block = eng._eval(args[0], params)
    v = block.values.copy()
    for row in v:
        finite = np.isfinite(row)
        if not finite.any():
            continue
        idx = np.where(finite, np.arange(row.size), -1)
        run = np.maximum.accumulate(idx)
        valid = run >= 0
        row[valid] = row[run[valid]]
    return block.with_values(v)


@_register("sortByName")
def _sort_by_name(eng, args, params):
    block = eng._eval(args[0], params)
    order = np.argsort([series_name(t) for t in block.series_tags], kind="stable")
    return block.with_values(block.values[order],
                             [block.series_tags[i] for i in order])


@_register("limit")
def _limit(eng, args, params):
    block = eng._eval(args[0], params)
    n = int(args[1].value)
    return block.with_values(block.values[:n], block.series_tags[:n])


@_register("exclude")
def _exclude(eng, args, params):
    block = eng._eval(args[0], params)
    pat = re.compile(args[1].value.encode())
    keep = [i for i, t in enumerate(block.series_tags)
            if not pat.search(series_name(t))]
    return block.with_values(block.values[keep],
                             [block.series_tags[i] for i in keep])


@_register("grep")
def _grep(eng, args, params):
    block = eng._eval(args[0], params)
    pat = re.compile(args[1].value.encode())
    keep = [i for i, t in enumerate(block.series_tags)
            if pat.search(series_name(t))]
    return block.with_values(block.values[keep],
                             [block.series_tags[i] for i in keep])


@_register("highestCurrent")
def _highest_current(eng, args, params):
    return _top_by(eng, args, params, "current", highest=True)


@_register("averageAbove")
def _average_above(eng, args, params):
    return _filter_by(eng, args, params, "average", lambda s, t: s > t)


_GROUP_REDUCERS = {
    "sum": np.nansum, "avg": np.nanmean, "average": np.nanmean,
    "max": np.nanmax, "min": np.nanmin,
    "median": lambda v, axis: np.nanmedian(v, axis=axis),
}


def _grouped_reduce(block: Block, key_fn, agg: str) -> Block:
    """Group series by key_fn(series name parts) and reduce each group;
    shared by groupByNode/groupByNodes/*SeriesWithWildcards."""
    reducer = _GROUP_REDUCERS.get(agg)
    if reducer is None:
        raise GraphiteParseError(f"unknown aggregator {agg!r}")
    groups: Dict[bytes, List[int]] = {}
    for i, t in enumerate(block.series_tags):
        groups.setdefault(key_fn(series_name(t).split(b".")), []).append(i)
    tags_out, rows = [], []
    for key, idxs in sorted(groups.items()):
        sub = block.values[idxs]
        with np.errstate(invalid="ignore"):
            row = reducer(sub, axis=0)
        row = np.where(np.isfinite(sub).any(axis=0), row, np.nan)
        rows.append(row)
        tags_out.append(Tags.of({b"__alias__": key}))
    vals = np.stack(rows) if rows else np.zeros((0, block.meta.steps))
    return Block(block.meta, tags_out, vals)


@_register("groupByNode")
def _group_by_node(eng, args, params):
    block = eng._eval(args[0], params)
    node = int(args[1].value)
    agg = args[2].value if len(args) > 2 else "sum"
    key = lambda parts: (parts[node]
                         if -len(parts) <= node < len(parts) else b"")
    return _grouped_reduce(block, key, agg)


@_register("summarize")
def _summarize(eng, args, params):
    """Reference semantics (native/summarize.go): by default buckets are
    aligned to EPOCH multiples of the interval — the output grid starts
    at floor(start, interval) and runs through newEnd = floor(end,
    interval) + interval, where end is the series' EXCLUSIVE end time
    (summarizeTimeSeries sizes NumSteps from newEnd, so an end already
    on an interval boundary gains one trailing empty bucket) — and each
    point lands in the bucket floor(ts, interval). With alignToFrom=true
    buckets count from the series start and NumSteps is
    ceil((end-start)/interval). Empty buckets emit NaN."""
    from .promql import parse_duration_ns

    # Argument validation FIRST: an invalid interval/func must reject
    # before paying the (potentially wide) series fetch.
    bucket_ns = parse_duration_ns(args[1].value)
    agg = (args[2].value or "sum") if len(args) > 2 else "sum"
    align_to_from = _bool_arg(args[3].value) if len(args) > 3 else False
    if bucket_ns <= 0:
        raise GraphiteParseError(f"invalid summarize interval {args[1].value!r}")
    reducers = {"sum": np.nansum, "avg": np.nanmean, "max": np.nanmax,
                "min": np.nanmin, "last": None}  # last: per-row gather below
    if agg not in reducers:
        raise GraphiteParseError(f"invalid summarize func {agg!r}")
    reduce = reducers[agg]
    block = eng._eval(args[0], params)
    times = block.meta.times()
    start = block.meta.start_ns
    if align_to_from:
        new_start = start
        bucket_of = (times - start) // bucket_ns
    else:
        new_start = start - start % bucket_ns
        bucket_of = (times - new_start) // bucket_ns
    # Grid sizing from the block's EXCLUSIVE end (start + steps*step),
    # matching summarize.go's newEnd/NumSteps — never from the last data
    # timestamp, which silently drops the reference's trailing bucket
    # whenever the query end extends past the last gridded point.
    end = start + block.meta.steps * block.meta.step_ns
    if align_to_from:
        steps = max(1, int(-(-(end - new_start) // bucket_ns)))  # ceil
    else:
        steps = int(((end // bucket_ns) * bucket_ns + bucket_ns
                     - new_start) // bucket_ns)
    # Dashboard-typical fast path: the interval divides the step grid
    # and the epoch-aligned start lands ON the grid, so every bucket has
    # the same width — one reshape + one masked reduce, no Python loop.
    # (bucket_ns > 0 was enforced above, so divisibility implies
    # factor >= 1.) `data_steps` buckets hold data; the epoch-aligned
    # path then carries `steps - data_steps` (0 or 1) trailing NaN
    # buckets from the newEnd sizing above.
    factor = bucket_ns // block.meta.step_ns
    data_steps = times.size // factor if factor else 0
    if (agg != "last" and bucket_ns % block.meta.step_ns == 0
            and (start - new_start) % bucket_ns == 0
            and times.size == data_steps * factor
            and times.size > 0
            and steps in (data_steps, data_steps + 1)):
        v = block.values.reshape(block.n_series, data_steps, factor)
        # NaN is the ONLY missing marker — inf is a real sample and must
        # propagate through every aggregate exactly as in the general
        # path (graphite None vs a value).
        present = ~np.isnan(v)
        have = present.any(axis=2)
        # Identity-filled reduces (never the warning-prone all-NaN
        # nan-reducers): sum/avg from masked sums, min/max from
        # +/-inf fills; `have` masks empty buckets to NaN either way.
        if agg == "sum":
            red = np.where(present, v, 0.0).sum(axis=2)
        elif agg == "avg":
            red = (np.where(present, v, 0.0).sum(axis=2)
                   / np.maximum(present.sum(axis=2), 1))
        elif agg == "max":
            red = np.where(present, v, -np.inf).max(axis=2)
        else:  # min
            red = np.where(present, v, np.inf).min(axis=2)
        out = np.where(have, red, np.nan)
        if steps > data_steps:
            out = np.concatenate(
                [out, np.full((block.n_series, steps - data_steps), np.nan)],
                axis=1)
        return Block(BlockMeta(int(new_start), bucket_ns, steps),
                     block.series_tags, out)
    out = np.full((block.n_series, steps), np.nan)
    # General path: the time grid is regular, so each bucket's columns
    # are one CONTIGUOUS slice: one searchsorted gives every boundary,
    # and each bucket reduces as a whole [n_series, width] tile (no
    # per-series Python loop).
    bounds = np.searchsorted(bucket_of, np.arange(steps + 1))
    with np.errstate(invalid="ignore"):
        for b in range(steps):
            lo, hi = bounds[b], bounds[b + 1]
            if lo == hi:
                continue
            seg = block.values[:, lo:hi]
            present = ~np.isnan(seg)  # inf is a real sample, NaN missing
            have = present.any(axis=1)
            if agg == "last":
                idx = np.where(present, np.arange(hi - lo), -1).max(axis=1)
                vals = seg[np.arange(seg.shape[0]), np.maximum(idx, 0)]
                out[:, b] = np.where(have, vals, np.nan)
            else:
                # reduce only the rows with data: the nan-reducers warn
                # on all-NaN rows, and `have` masks them anyway
                out[have, b] = reduce(seg[have], axis=1)
    meta = BlockMeta(int(new_start), bucket_ns, steps)
    return Block(meta, block.series_tags, out)


# ------------------------------------------------------- function appendix
# Broader builtin coverage (reference:
# src/query/graphite/native/builtin_functions.go). Helpers keep the whole
# block batched: every transform is a vectorized [n_series, steps] op.


def _pick_rows(block: Block, keep) -> Block:
    keep = list(keep)
    vals = block.values[keep] if len(keep) else np.zeros((0, block.meta.steps))
    return block.with_values(vals, [block.series_tags[i] for i in keep])


def _series_stat(block: Block, stat: str) -> np.ndarray:
    """Per-series scalar used by filters/sorts; NaN-aware."""
    v = block.values
    with np.errstate(invalid="ignore", divide="ignore"):
        if stat == "average":
            return np.nanmean(v, axis=1) if v.size else np.zeros(0)
        if stat == "total":
            return np.nansum(v, axis=1) if v.size else np.zeros(0)
        if stat == "max":
            return np.nanmax(v, axis=1) if v.size else np.zeros(0)
        if stat == "min":
            return np.nanmin(v, axis=1) if v.size else np.zeros(0)
        if stat == "current":
            cur = np.full(v.shape[0], np.nan)
            for i in range(v.shape[0]):
                finite = np.flatnonzero(np.isfinite(v[i]))
                if finite.size:
                    cur[i] = v[i][finite[-1]]
            return cur
    raise GraphiteParseError(f"unknown series stat {stat!r}")


def _filter_by(eng, args, params, stat, op, default_thresh=None):
    block = eng._eval(args[0], params)
    thresh = args[1].value if len(args) > 1 else default_thresh
    s = _series_stat(block, stat)
    with np.errstate(invalid="ignore"):
        keep = np.flatnonzero(op(s, thresh))
    return _pick_rows(block, keep)


def _top_by(eng, args, params, stat, highest: bool):
    block = eng._eval(args[0], params)
    n = int(args[1].value) if len(args) > 1 else 1
    s = _series_stat(block, stat)
    s = np.where(np.isfinite(s), s, -np.inf if highest else np.inf)
    order = np.argsort(-s if highest else s, kind="stable")[:n]
    return _pick_rows(block, order)


@_register("aliasSub")
def _alias_sub(eng, args, params):
    block = eng._eval(args[0], params)
    pat = re.compile(args[1].value.encode())
    repl = args[2].value.encode()
    tags = [t.with_tag(b"__alias__", pat.sub(repl, series_name(t)))
            for t in block.series_tags]
    return block.with_values(block.values, tags)


@_register("aliasByMetric")
def _alias_by_metric(eng, args, params):
    block = eng._eval(args[0], params)
    tags = [t.with_tag(b"__alias__",
                       series_name(t).split(b".")[-1].split(b",")[0])
            for t in block.series_tags]
    return block.with_values(block.values, tags)


@_register("substr")
def _substr(eng, args, params):
    block = eng._eval(args[0], params)
    start = int(args[1].value) if len(args) > 1 else 0
    stop = int(args[2].value) if len(args) > 2 else 0
    tags = []
    for t in block.series_tags:
        parts = series_name(t).split(b".")
        picked = parts[start: stop if stop else len(parts)]
        tags.append(t.with_tag(b"__alias__", b".".join(picked)))
    return block.with_values(block.values, tags)


@_register("scaleToSeconds")
def _scale_to_seconds(eng, args, params):
    block = eng._eval(args[0], params)
    seconds = args[1].value
    return block.with_values(block.values * (seconds / (params.step_ns / S)))


@_register("invert")
def _invert(eng, args, params):
    block = eng._eval(args[0], params)
    with np.errstate(divide="ignore", invalid="ignore"):
        v = np.where(block.values != 0, 1.0 / block.values, np.nan)
    return block.with_values(v)


@_register("logarithm", "log")
def _logarithm(eng, args, params):
    block = eng._eval(args[0], params)
    base = args[1].value if len(args) > 1 else 10
    with np.errstate(divide="ignore", invalid="ignore"):
        v = np.where(block.values > 0,
                     np.log(block.values) / np.log(base), np.nan)
    return block.with_values(v)


@_register("pow")
def _pow(eng, args, params):
    block = eng._eval(args[0], params)
    with np.errstate(invalid="ignore"):
        return block.with_values(np.power(block.values, args[1].value))


@_register("squareRoot")
def _square_root(eng, args, params):
    block = eng._eval(args[0], params)
    with np.errstate(invalid="ignore"):
        v = np.where(block.values >= 0, np.sqrt(block.values), np.nan)
    return block.with_values(v)


@_register("timeShift")
def _time_shift(eng, args, params):
    """Render data from `shift` ago at the requested timestamps
    (builtin_functions.go timeShift: positive shifts look back)."""
    from .promql import parse_duration_ns

    spec = str(args[1].value)
    sign = -1
    if spec.startswith("+"):
        sign, spec = 1, spec[1:]
    elif spec.startswith("-"):
        spec = spec[1:]
    delta = sign * parse_duration_ns(spec)
    shifted = QueryParams(params.start_ns + delta, params.end_ns + delta,
                          params.step_ns)
    block = eng._eval(args[0], shifted)
    return Block(params.meta(), block.series_tags, block.values)


@_register("timeSlice")
def _time_slice(eng, args, params):
    block = eng._eval(args[0], params)
    t0 = _parse_graphite_time(args[1].value, params.start_ns)
    t1 = (_parse_graphite_time(args[2].value, params.end_ns)
          if len(args) > 2 else params.end_ns)
    times = block.meta.times()
    # end-INCLUSIVE per graphite-web timeSlice (points outside
    # [start, end] become None; the boundary point survives)
    keep = ((times >= t0) & (times <= t1))[None, :]
    return block.with_values(np.where(keep, block.values, np.nan))


def _parse_graphite_time(spec, default_ns):
    from .promql import parse_duration_ns

    if isinstance(spec, (int, float)):
        return int(spec * S)
    s = str(spec)
    if s in ("now", ""):
        return default_ns
    if s.startswith("-"):
        return default_ns - parse_duration_ns(s[1:])
    return int(float(s) * S)


@_register("transformNull")
def _transform_null(eng, args, params):
    block = eng._eval(args[0], params)
    default = args[1].value if len(args) > 1 else 0.0
    return block.with_values(
        np.where(np.isfinite(block.values), block.values, default))


@_register("isNonNull")
def _is_non_null(eng, args, params):
    block = eng._eval(args[0], params)
    return block.with_values(np.isfinite(block.values).astype(np.float64))


@_register("removeAboveValue")
def _remove_above_value(eng, args, params):
    block = eng._eval(args[0], params)
    with np.errstate(invalid="ignore"):
        v = np.where(block.values > args[1].value, np.nan, block.values)
    return block.with_values(v)


@_register("removeBelowValue")
def _remove_below_value(eng, args, params):
    block = eng._eval(args[0], params)
    with np.errstate(invalid="ignore"):
        v = np.where(block.values < args[1].value, np.nan, block.values)
    return block.with_values(v)


def _get_percentile(finite: np.ndarray, p: float,
                    interpolate: bool = False) -> float:
    """The reference's rank-based percentile, NOT numpy's linear default
    (common/percentiles.go:75 GetPercentile): rank = ceil(p/100 * n),
    value = sorted[rank-1]; with interpolate, blend with sorted[rank-2]
    by the fractional rank. NB: the reference's formula multiplies by
    len(series) — not graphite-web's (len+1) — and interpolates BACKWARD
    (percentiles.go:82-97); M3 is the conformance target, verbatim."""
    s = np.sort(finite)
    n = s.size
    if n == 0:
        return np.nan
    frac = (p / 100.0) * n
    rank = int(np.ceil(frac))
    if rank <= 1:
        return float(s[0])
    rank = min(rank, n)
    out = float(s[rank - 1])
    if interpolate:
        prev = float(s[rank - 2])
        out = prev + (frac - (rank - 1)) * (out - prev)
    return out


def _bool_arg(v) -> bool:
    """Boolean function argument: bare true/false parse as literals, but
    real clients also send the QUOTED strings "true"/"false" — Python
    truthiness would read "false" as True and silently flip the option.
    Anything else ("1", a typo) is a hard error, not a silent False."""
    if isinstance(v, str):
        s = v.strip().lower()
        if s == "true":
            return True
        if s == "false":
            return False
        raise GraphiteParseError(f"invalid boolean argument {v!r}")
    return bool(v)


def _row_percentile(v: np.ndarray, n: float,
                    interpolate: bool = False) -> np.ndarray:
    out = np.full(v.shape[0], np.nan)
    for i in range(v.shape[0]):
        finite = v[i][np.isfinite(v[i])]
        if finite.size:
            out[i] = _get_percentile(finite, n, interpolate)
    return out


@_register("removeAbovePercentile")
def _remove_above_percentile(eng, args, params):
    block = eng._eval(args[0], params)
    p = _row_percentile(block.values, args[1].value)
    with np.errstate(invalid="ignore"):
        v = np.where(block.values > p[:, None], np.nan, block.values)
    return block.with_values(v)


@_register("removeBelowPercentile")
def _remove_below_percentile(eng, args, params):
    block = eng._eval(args[0], params)
    p = _row_percentile(block.values, args[1].value)
    with np.errstate(invalid="ignore"):
        v = np.where(block.values < p[:, None], np.nan, block.values)
    return block.with_values(v)


@_register("integral")
def _integral(eng, args, params):
    block = eng._eval(args[0], params)
    v = np.where(np.isfinite(block.values), block.values, 0.0)
    out = np.cumsum(v, axis=1)
    out[~np.isfinite(block.values)] = np.nan
    return block.with_values(out)


@_register("offsetToZero")
def _offset_to_zero(eng, args, params):
    block = eng._eval(args[0], params)
    with np.errstate(invalid="ignore"):
        mn = np.nanmin(block.values, axis=1, keepdims=True) \
            if block.values.size else np.zeros((0, 1))
    return block.with_values(block.values - mn)


@_register("changed")
def _changed(eng, args, params):
    """1 where the value differs from the previous REAL value; gaps emit
    0 and do not count as changes (graphite-web changed())."""
    block = eng._eval(args[0], params)
    v = block.values
    out = np.zeros_like(v)
    idx = np.arange(v.shape[1])
    for i in range(v.shape[0]):
        finite = np.isfinite(v[i])
        run = np.maximum.accumulate(np.where(finite, idx, -1))
        prev_run = np.concatenate([[-1], run[:-1]])
        cmp_ok = finite & (prev_run >= 0)
        prev_vals = v[i][np.maximum(prev_run, 0)]
        out[i] = np.where(cmp_ok & (v[i] != prev_vals), 1.0, 0.0)
    return block.with_values(out)


@_register("delay")
def _delay(eng, args, params):
    block = eng._eval(args[0], params)
    steps = int(args[1].value)
    v = np.full_like(block.values, np.nan)
    if steps >= 0:
        if steps < v.shape[1]:
            v[:, steps:] = block.values[:, : v.shape[1] - steps]
    else:
        if -steps < v.shape[1]:
            v[:, :steps] = block.values[:, -steps:]
    return block.with_values(v)


@_register("minimumAbove")
def _minimum_above(eng, args, params):
    return _filter_by(eng, args, params, "min", lambda s, t: s > t)


@_register("minimumBelow")
def _minimum_below(eng, args, params):
    return _filter_by(eng, args, params, "min", lambda s, t: s <= t)


@_register("maximumAbove")
def _maximum_above(eng, args, params):
    return _filter_by(eng, args, params, "max", lambda s, t: s > t)


@_register("maximumBelow")
def _maximum_below(eng, args, params):
    return _filter_by(eng, args, params, "max", lambda s, t: s <= t)


@_register("currentAbove")
def _current_above(eng, args, params):
    return _filter_by(eng, args, params, "current", lambda s, t: s > t)


@_register("currentBelow")
def _current_below(eng, args, params):
    return _filter_by(eng, args, params, "current", lambda s, t: s <= t)


@_register("averageBelow")
def _average_below(eng, args, params):
    return _filter_by(eng, args, params, "average", lambda s, t: s <= t)


@_register("highestAverage")
def _highest_average(eng, args, params):
    return _top_by(eng, args, params, "average", highest=True)


@_register("lowestAverage")
def _lowest_average(eng, args, params):
    return _top_by(eng, args, params, "average", highest=False)


@_register("highestMax")
def _highest_max(eng, args, params):
    return _top_by(eng, args, params, "max", highest=True)


@_register("lowestCurrent")
def _lowest_current(eng, args, params):
    return _top_by(eng, args, params, "current", highest=False)


@_register("sortByTotal")
def _sort_by_total(eng, args, params):
    block = eng._eval(args[0], params)
    s = _series_stat(block, "total")
    return _pick_rows(block, np.argsort(-np.nan_to_num(s), kind="stable"))


@_register("sortByMaxima")
def _sort_by_maxima(eng, args, params):
    block = eng._eval(args[0], params)
    s = _series_stat(block, "max")
    return _pick_rows(block, np.argsort(-np.nan_to_num(s, nan=-np.inf),
                                        kind="stable"))


@_register("sortByMinima")
def _sort_by_minima(eng, args, params):
    block = eng._eval(args[0], params)
    s = _series_stat(block, "min")
    return _pick_rows(block, np.argsort(np.nan_to_num(s, nan=np.inf),
                                        kind="stable"))


@_register("nPercentile")
def _n_percentile(eng, args, params):
    """Per-series flat line at that series' n-th percentile."""
    block = eng._eval(args[0], params)
    p = _row_percentile(block.values, args[1].value)
    return block.with_values(np.broadcast_to(
        p[:, None], block.values.shape).copy())


@_register("percentileOfSeries")
def _percentile_of_series(eng, args, params):
    block = eng._eval(args[0], params)
    n = args[1].value
    interpolate = _bool_arg(args[2].value) if len(args) > 2 else False
    out = np.full(block.meta.steps, np.nan)
    v = block.values
    for j in range(v.shape[1]):
        finite = v[:, j][np.isfinite(v[:, j])]
        if finite.size:
            out[j] = _get_percentile(finite, n, interpolate)
    tags = Tags.of({b"__alias__": b"percentileOfSeries"})
    return Block(block.meta, [tags], out[None, :])


def _window_steps(w, params) -> int:
    """Window argument (duration string or point count) -> grid steps;
    shared by the moving* family and stdev."""
    if isinstance(w, str):
        from .promql import parse_duration_ns

        return max(1, parse_duration_ns(w) // params.step_ns)
    return max(1, int(w))


def _moving(eng, args, params, kind):
    """moving* window semantics per the reference: output step i reduces
    the W points STRICTLY BEFORE it (builtin_functions.go:620-666
    movingAverage walks bootstrap[i+offset-W .. i+offset-1], i.e. the
    lookback window EXCLUDES the current point; movingMedian likewise).
    So the selector extends W steps back and the trailing-inclusive
    window reduce drops its last column (the window ending AT the
    current step)."""
    W = _window_steps(args[1].value, params)
    ext = QueryParams(params.start_ns - W * params.step_ns,
                      params.end_ns, params.step_ns)
    block = eng._eval(args[0], ext)
    if kind == "median":
        out = temporal.quantile_over_time(block.values, W, 0.5)
    else:
        out = temporal.over_time(block.values, W, kind)
    return Block(params.meta(), block.series_tags, out[:, :-1])


@_register("movingMax")
def _moving_max(eng, args, params):
    return _moving(eng, args, params, "max")


@_register("movingMin")
def _moving_min(eng, args, params):
    return _moving(eng, args, params, "min")


@_register("movingSum")
def _moving_sum(eng, args, params):
    return _moving(eng, args, params, "sum")


@_register("movingMedian")
def _moving_median(eng, args, params):
    return _moving(eng, args, params, "median")


@_register("stdev", "stddev")
def _stdev(eng, args, params):
    """Unlike the moving* family, the reference's stdev window INCLUDES
    the current point (common/transform.go:222-248 folds ValueAt(index)
    in before emitting index) and gates output on windowTolerance: emit
    when validPoints/points >= tolerance — transform.go:250's exact
    condition, which is a MINIMUM valid fraction (default 0.1), not
    graphite-web's maximum-missing fraction."""
    W = _window_steps(args[1].value, params)
    tolerance = float(args[2].value) if len(args) > 2 else 0.1
    ext = QueryParams(params.start_ns - (W - 1) * params.step_ns,
                      params.end_ns, params.step_ns)
    block = eng._eval(args[0], ext)
    # both window passes dispatch before either result is fetched
    fetch_out = temporal.over_time_async(block.values, W, "stddev")
    fetch_cnt = temporal.over_time_async(block.values, W, "count")
    out, cnt = fetch_out(), fetch_cnt()
    with np.errstate(invalid="ignore"):
        out = np.where(cnt / W >= tolerance, out, np.nan)
    return Block(params.meta(), block.series_tags, out)


@_register("diffSeries")
def _diff_series(eng, args, params):
    blocks = [eng._eval(a, params) for a in args]
    vals = np.concatenate([b.values for b in blocks])
    if not vals.shape[0]:
        return Block(params.meta(), [], np.zeros((0, params.steps)))
    rest = np.where(np.isfinite(vals[1:]), vals[1:], 0.0)
    out = vals[0] - rest.sum(axis=0)
    return Block(blocks[0].meta, [Tags.of({b"__alias__": b"diffSeries"})],
                 out[None, :])


@_register("multiplySeries")
def _multiply_series(eng, args, params):
    return _combine(eng, args, params,
                    lambda v: np.nanprod(v, axis=0), "multiplySeries")


@_register("rangeOfSeries")
def _range_of_series(eng, args, params):
    return _combine(
        eng, args, params,
        lambda v: np.nanmax(v, axis=0) - np.nanmin(v, axis=0),
        "rangeOfSeries")


@_register("stddevSeries")
def _stddev_series(eng, args, params):
    return _combine(eng, args, params,
                    lambda v: np.nanstd(v, axis=0), "stddevSeries")


@_register("countSeries")
def _count_series(eng, args, params):
    """Constant line of the number of series (builtin_functions.go
    countSeries draws len(seriesList), not a per-step finite count)."""
    blocks = [eng._eval(a, params) for a in args]
    n = sum(b.n_series for b in blocks)
    meta = blocks[0].meta if blocks else params.meta()
    return Block(meta, [Tags.of({b"__alias__": b"countSeries"})],
                 np.full((1, meta.steps), float(n)))


@_register("divideSeries")
def _divide_series(eng, args, params):
    dividend = eng._eval(args[0], params)
    divisor = eng._eval(args[1], params)
    if divisor.n_series != 1:
        raise GraphiteParseError(
            f"divideSeries divisor must be one series, got {divisor.n_series}")
    with np.errstate(divide="ignore", invalid="ignore"):
        d = divisor.values[0]
        v = np.where(d != 0, dividend.values / d, np.nan)
    tags = [t.with_tag(b"__alias__",
                       b"divideSeries(%s,%s)" % (series_name(t),
                                                 series_name(divisor.series_tags[0])))
            for t in dividend.series_tags]
    return dividend.with_values(v, tags)


@_register("asPercent")
def _as_percent(eng, args, params):
    block = eng._eval(args[0], params)
    if len(args) > 1 and not isinstance(args[1], Literal):
        total = eng._eval(args[1], params).values
        total = np.nansum(total, axis=0)
    elif len(args) > 1:
        total = np.full(block.meta.steps, float(args[1].value))
    else:
        with np.errstate(invalid="ignore"):
            total = np.nansum(block.values, axis=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        v = np.where(total != 0, block.values / total * 100.0, np.nan)
    return block.with_values(v)


def _with_wildcards(eng, args, params, agg):
    block = eng._eval(args[0], params)
    positions = {int(a.value) for a in args[1:]}
    key = lambda parts: b".".join(
        p for j, p in enumerate(parts) if j not in positions)
    return _grouped_reduce(block, key, agg)


@_register("sumSeriesWithWildcards")
def _sum_series_with_wildcards(eng, args, params):
    return _with_wildcards(eng, args, params, "sum")


@_register("averageSeriesWithWildcards")
def _average_series_with_wildcards(eng, args, params):
    return _with_wildcards(eng, args, params, "average")


@_register("group")
def _group(eng, args, params):
    blocks = [eng._eval(a, params) for a in args]
    vals = np.concatenate([b.values for b in blocks]) if blocks else \
        np.zeros((0, params.steps))
    tags = [t for b in blocks for t in b.series_tags]
    meta = blocks[0].meta if blocks else params.meta()
    return Block(meta, tags, vals)


@_register("groupByNodes")
def _group_by_nodes(eng, args, params):
    block = eng._eval(args[0], params)
    agg = args[1].value
    nodes = [int(a.value) for a in args[2:]]
    key = lambda parts: b".".join(parts[n] for n in nodes
                                  if -len(parts) <= n < len(parts))
    return _grouped_reduce(block, key, agg)


@_register("constantLine")
def _constant_line(eng, args, params):
    value = float(args[0].value)
    meta = params.meta()
    tags = Tags.of({b"__alias__": str(value).encode()})
    return Block(meta, [tags], np.full((1, meta.steps), value))


@_register("threshold")
def _threshold(eng, args, params):
    value = float(args[0].value)
    label = str(args[1].value) if len(args) > 1 else str(value)
    meta = params.meta()
    return Block(meta, [Tags.of({b"__alias__": label.encode()})],
                 np.full((1, meta.steps), value))


@_register("stacked")
def _stacked(eng, args, params):
    """Cumulative stacking: series i becomes sum of series 0..i; a series'
    own gaps stay gaps."""
    block = eng._eval(args[0], params)
    v = np.where(np.isfinite(block.values), block.values, 0.0)
    out = np.cumsum(v, axis=0)
    out[~np.isfinite(block.values)] = np.nan
    tags = [t.with_tag(b"__alias__", b"stacked(" + series_name(t) + b")")
            for t in block.series_tags]
    return block.with_values(out, tags)


@_register("consolidateBy")
def _consolidate_by(eng, args, params):
    """Annotation only: block consolidation already happens at fetch grid
    resolution; the chosen function is recorded in the alias (render-layer
    consolidation concern, builtin_functions.go consolidateBy)."""
    block = eng._eval(args[0], params)
    return block


@_register("averageOutsidePercentile")
def _average_outside_percentile(eng, args, params):
    block = eng._eval(args[0], params)
    n = args[1].value
    n = max(n, 100 - n)
    means = _series_stat(block, "average")
    finite = means[np.isfinite(means)]
    if not finite.size:
        return block
    hi = _get_percentile(finite, n)
    lo = _get_percentile(finite, 100 - n)
    # graphite-web keeps anything NOT strictly inside (lo, hi), so the
    # boundary series (including n=100/n=0) survive.
    with np.errstate(invalid="ignore"):
        keep = np.flatnonzero(~((means > lo) & (means < hi)))
    return _pick_rows(block, keep)


# --------------------------------------------------- presentation + synthesis

def _rename_all(block: Block, fmt) -> Block:
    tags = [t.with_tag(b"__alias__", fmt(series_name(t)))
            for t in block.series_tags]
    return block.with_values(block.values, tags)


@_register("dashed")
def _dashed(eng, args, params):
    """Presentation-only in this renderer: records the dash request in the
    alias (builtin_functions.go:1786 dashed)."""
    block = eng._eval(args[0], params)
    length = float(args[1].value) if len(args) > 1 else 5.0
    if length <= 0:
        raise GraphiteParseError(f"expected a positive dashLength, got {length}")
    return _rename_all(
        block, lambda n: b"dashed(%s, %.3f)" % (n, length))


@_register("identity", "timeFunction", "time")
def _time_function(eng, args, params):
    """Series whose value at each step is that step's unix time in seconds
    (builtin_functions.go:184 identity, :1767 timeFunction). The optional
    step argument is subsumed by the render grid."""
    name = args[0].value if args else "time"
    meta = params.meta()
    vals = (meta.times() / S).astype(np.float64)
    return Block(meta, [Tags.of({b"__alias__": str(name).encode()})],
                 vals[None, :])


@_register("randomWalkFunction", "randomWalk")
def _random_walk(eng, args, params):
    """Uniform noise in [-0.5, 0.5) (builtin_functions.go:1513 — despite the
    name, the reference emits independent draws, not a cumulative walk).
    Seeded from the series name so renders are reproducible."""
    import zlib

    name = str(args[0].value)
    meta = params.meta()
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    vals = rng.random(meta.steps) - 0.5
    return Block(meta, [Tags.of({b"__alias__": name.encode()})], vals[None, :])


@_register("fallbackSeries")
def _fallback_series(eng, args, params):
    """builtin_functions.go:521: the fallback target renders only when the
    primary returns no series."""
    block = eng._eval(args[0], params)
    if block.n_series:
        return block
    return eng._eval(args[1], params)


@_register("removeEmptySeries")
def _remove_empty_series(eng, args, params):
    block = eng._eval(args[0], params)
    keep = np.flatnonzero(np.isfinite(block.values).any(axis=1)) if \
        block.n_series else []
    return _pick_rows(block, keep)


@_register("mostDeviant")
def _most_deviant(eng, args, params):
    """Top-N series by standard deviation (builtin_functions.go:533)."""
    block = eng._eval(args[0], params)
    n = int(args[1].value)
    with np.errstate(invalid="ignore"):
        s = np.nanstd(block.values, axis=1) if block.n_series else np.zeros(0)
    s = np.where(np.isfinite(s), s, -np.inf)
    return _pick_rows(block, np.argsort(-s, kind="stable")[:n])


_LEGEND_STATS = {"avg": "average", "average": "average", "total": "total",
                 "sum": "total", "min": "min", "max": "max",
                 "last": "current", "current": "current"}


@_register("aggregateLine")
def _aggregate_line(eng, args, params):
    """Horizontal line at f(first series) (builtin_functions.go:1532)."""
    block = eng._eval(args[0], params)
    fname = str(args[1].value) if len(args) > 1 else "avg"
    stat = _LEGEND_STATS.get(fname)
    if stat is None:
        raise GraphiteParseError(f"invalid function {fname!r}")
    if not block.n_series:
        raise GraphiteParseError("aggregateLine: empty series list")
    value = float(_series_stat(block, stat)[0])
    name = b"aggregateLine(%s,%.3f)" % (series_name(block.series_tags[0]),
                                        value)
    meta = params.meta()
    return Block(meta, [Tags.of({b"__alias__": name})],
                 np.full((1, meta.steps), value))


@_register("legendValue")
def _legend_value(eng, args, params):
    """Append '(type: value)' per requested stat to each legend name
    (builtin_functions.go:1635)."""
    block = eng._eval(args[0], params)
    kinds = [str(a.value) for a in args[1:]] or ["avg"]
    stats = []
    for k in kinds:
        stat = _LEGEND_STATS.get(k)
        if stat is None:
            raise GraphiteParseError(f"invalid function {k!r}")
        stats.append((k, _series_stat(block, stat)))
    tags = []
    for i, t in enumerate(block.series_tags):
        suffix = b"".join(b" (%s: %.3f)" % (k.encode(), col[i])
                          for k, col in stats)
        tags.append(t.with_tag(b"__alias__", series_name(t) + suffix))
    return block.with_values(block.values, tags)


@_register("cactiStyle")
def _cacti_style(eng, args, params):
    """Column-aligned 'name Current: Max: Min:' legends
    (builtin_functions.go:1683)."""
    block = eng._eval(args[0], params)
    if not block.n_series:
        return block
    cur = _series_stat(block, "current")
    mx = _series_stat(block, "max")
    mn = _series_stat(block, "min")

    def fmt(v):
        return "nan" if not math.isfinite(v) else "%.2f" % v

    names = [series_name(t).decode(errors="replace")
             for t in block.series_tags]
    name_w = max(len(n) for n in names)
    cur_s, max_s, min_s = ([fmt(v) for v in col] for col in (cur, mx, mn))
    cur_w = max(len(s) for s in cur_s)
    max_w = max(len(s) for s in max_s)
    min_w = max(len(s) for s in min_s)
    tags = []
    for i, t in enumerate(block.series_tags):
        legend = (f"{names[i]:<{name_w}} Current:{cur_s[i]:<{cur_w}} "
                  f"Max:{max_s[i]:<{max_w}} Min:{min_s[i]:<{min_w}} ")
        tags.append(t.with_tag(b"__alias__", legend.encode()))
    return block.with_values(block.values, tags)


# ----------------------------------------------------- interval reductions

@_register("hitcount")
def _hitcount(eng, args, params):
    """Integrate each series over interval buckets: value x seconds per
    bucket, buckets anchored at the window end (builtin_functions.go:1039).
    The render grid is regular, so each step contributes value*step_s to
    the bucket containing its start."""
    from .promql import parse_duration_ns

    block = eng._eval(args[0], params)
    interval_ns = parse_duration_ns(str(args[1].value))
    if interval_ns <= 0 or interval_ns < params.step_ns:
        raise GraphiteParseError(
            f"hitcount interval must be >= step, got {args[1].value!r}")
    n_buckets = max(1, math.ceil((params.end_ns - params.start_ns) / interval_ns))
    new_start = params.end_ns - n_buckets * interval_ns
    # The render grid is end-inclusive; a step starting at/after end_ns lies
    # outside every bucket and is dropped, not folded into the last one.
    bucket_of = (block.meta.times() - new_start) // interval_ns
    bucket_of = np.where(bucket_of >= n_buckets, -1, bucket_of.clip(0))
    step_s = params.step_ns / S
    out = np.zeros((block.n_series, n_buckets))
    v = np.where(np.isfinite(block.values), block.values, 0.0) * step_s
    for b in range(n_buckets):
        cols = bucket_of == b
        if cols.any():
            out[:, b] = v[:, cols].sum(axis=1)
    meta = QueryParams(new_start + interval_ns, params.end_ns,
                       interval_ns).meta()
    tags = [t.with_tag(b"__alias__", b"hitcount(%s, '%s')" % (
        series_name(t), str(args[1].value).encode()))
        for t in block.series_tags]
    return Block(meta, tags, out)


def _sustained(eng, args, params, above: bool):
    """Keep values only once the comparison has held for >= interval
    (builtin_functions.go:401 sustainedCompare); earlier points of each run
    flatten to the zero line threshold -/+ |threshold|."""
    from .promql import parse_duration_ns

    block = eng._eval(args[0], params)
    threshold = float(args[1].value)
    min_steps = max(1, parse_duration_ns(str(args[2].value)) // params.step_ns)
    v = block.values
    with np.errstate(invalid="ignore"):
        ok = (v >= threshold) if above else (v <= threshold)
    ok &= np.isfinite(v)
    # Per-row consecutive-True run lengths, vectorized: within each run the
    # count ascends; a False resets the base.
    idx = np.arange(v.shape[1])
    run = np.zeros_like(v, dtype=np.int64)
    for i in range(v.shape[0]):
        base = np.maximum.accumulate(np.where(~ok[i], idx, -1))
        run[i] = np.where(ok[i], idx - base, 0)
    zero = threshold - abs(threshold) if above else threshold + abs(threshold)
    out = np.where(run >= min_steps, v, zero)
    name = b"sustainedAbove" if above else b"sustainedBelow"
    tags = [t.with_tag(b"__alias__", b"%s(%s, %f, '%s')" % (
        name, series_name(t), threshold, str(args[2].value).encode()))
        for t in block.series_tags]
    return block.with_values(out, tags)


@_register("sustainedAbove")
def _sustained_above(eng, args, params):
    return _sustained(eng, args, params, True)


@_register("sustainedBelow")
def _sustained_below(eng, args, params):
    return _sustained(eng, args, params, False)


@_register("weightedAverage")
def _weighted_average(eng, args, params):
    """sum(value*weight)/sum(weight) over series matched by path node
    (aggregation_functions.go:307)."""
    values = eng._eval(args[0], params)
    weights = eng._eval(args[1], params)
    node = int(args[2].value)

    def keyed(block):
        out = {}
        for i, t in enumerate(block.series_tags):
            parts = tags_to_path(t.as_dict()).split(b".")
            if -len(parts) <= node < len(parts):
                out.setdefault(parts[node], i)
        return out

    vk, wk = keyed(values), keyed(weights)
    top = np.zeros(values.meta.steps)
    bottom = np.zeros(values.meta.steps)
    matched = False
    for key, vi in vk.items():
        wi = wk.get(key)
        if wi is None:
            continue  # no associated weight series
        matched = True
        v = values.values[vi]
        w = weights.values[wi]
        prod = v * w
        top += np.where(np.isfinite(prod), prod, 0.0)
        bottom += np.where(np.isfinite(w), w, 0.0)
    if not matched:
        return Block(values.meta, [], np.zeros((0, values.meta.steps)))
    with np.errstate(divide="ignore", invalid="ignore"):
        out = np.where(bottom != 0, top / bottom, np.nan)
    return Block(values.meta, [Tags.of({b"__alias__": b"weightedAverage"})],
                 out[None, :])


# --------------------------------------------------------------- holt-winters

_HW_ALPHA = 0.1    # builtin_functions.go:45-47
_HW_GAMMA = 0.1
_HW_BETA = 0.0035
_HW_BOOTSTRAP_NS = 7 * 24 * 3600 * S   # one week of history seeds the model
_HW_SEASON_NS = 24 * 3600 * S          # daily seasonality


def _holt_winters_analysis(v: np.ndarray, season_steps: int):
    """Triple-exponential analysis, vectorized across series and sequential
    over time (builtin_functions.go:1371 holtWintersAnalysis). Returns
    (predictions, deviations) aligned with the input grid."""
    n, steps = v.shape
    intercepts = np.zeros((n, steps))
    slopes = np.zeros((n, steps))
    seasonals = np.zeros((n, steps))
    predictions = np.zeros((n, steps))
    deviations = np.zeros((n, steps))
    next_pred = np.full(n, np.nan)
    for i in range(steps):
        actual = v[:, i]
        nan = ~np.isfinite(actual)
        last_seasonal = seasonals[:, i - season_steps] if i >= season_steps \
            else np.zeros(n)
        j = i + 1 - season_steps
        next_last_seasonal = seasonals[:, j] if j >= 0 else np.zeros(n)
        last_dev = deviations[:, i - season_steps] if i >= season_steps \
            else np.zeros(n)
        if i == 0:
            last_intercept = actual.copy()
            last_slope = np.zeros(n)
            prediction = actual.copy()
        else:
            last_intercept = intercepts[:, i - 1].copy()
            last_slope = slopes[:, i - 1]
            last_intercept = np.where(np.isfinite(last_intercept),
                                      last_intercept, actual)
            prediction = next_pred
        with np.errstate(invalid="ignore"):
            intercept = (_HW_ALPHA * (actual - last_seasonal)
                         + (1 - _HW_ALPHA) * (last_intercept + last_slope))
            slope = (_HW_BETA * (intercept - last_intercept)
                     + (1 - _HW_BETA) * last_slope)
            seasonal = (_HW_GAMMA * (actual - intercept)
                        + (1 - _HW_GAMMA) * last_seasonal)
            pred_for_dev = np.where(np.isfinite(prediction), prediction, 0.0)
            deviation = (_HW_GAMMA * np.abs(actual - pred_for_dev)
                         + (1 - _HW_GAMMA) * last_dev)
        intercepts[:, i] = np.where(nan, np.nan, intercept)
        slopes[:, i] = np.where(nan, last_slope, slope)
        seasonals[:, i] = np.where(nan, last_seasonal, seasonal)
        predictions[:, i] = prediction
        deviations[:, i] = np.where(nan, 0.0, deviation)
        next_pred = np.where(nan, np.nan,
                             intercept + slope + next_last_seasonal)
    return predictions, deviations


def _hw_forecast_parts(eng, arg, params):
    """Fetch with one week of bootstrap history, run the analysis, trim the
    bootstrap prefix back off (builtin_functions.go:1224 + trimBootstrap)."""
    boot_steps = _HW_BOOTSTRAP_NS // params.step_ns
    season_steps = max(1, int(_HW_SEASON_NS // params.step_ns))
    ext = QueryParams(params.start_ns - boot_steps * params.step_ns,
                      params.end_ns, params.step_ns)
    block = eng._eval(arg, ext)
    pred, dev = _holt_winters_analysis(block.values, season_steps)
    keep = params.meta().steps
    return block, pred[:, -keep:], dev[:, -keep:]


@_register("holtWintersForecast")
def _holt_winters_forecast(eng, args, params):
    block, pred, _ = _hw_forecast_parts(eng, args[0], params)
    tags = [t.with_tag(b"__alias__",
                       b"holtWintersForecast(" + series_name(t) + b")")
            for t in block.series_tags]
    return Block(params.meta(), tags, pred)


@_register("holtWintersConfidenceBands")
def _holt_winters_confidence_bands(eng, args, params):
    delta = float(args[1].value) if len(args) > 1 else 3.0
    block, pred, dev = _hw_forecast_parts(eng, args[0], params)
    scaled = delta * dev
    lower, upper = pred - scaled, pred + scaled
    tags, rows = [], []
    for i, t in enumerate(block.series_tags):
        name = series_name(t)
        tags.append(t.with_tag(b"__alias__",
                               b"holtWintersConfidenceLower(" + name + b")"))
        rows.append(lower[i])
        tags.append(t.with_tag(b"__alias__",
                               b"holtWintersConfidenceUpper(" + name + b")"))
        rows.append(upper[i])
    vals = np.stack(rows) if rows else np.zeros((0, params.meta().steps))
    return Block(params.meta(), tags, vals)


@_register("holtWintersAberration")
def _holt_winters_aberration(eng, args, params):
    """Deviation of the actual outside the confidence bands; 0 inside
    (builtin_functions.go:1298)."""
    delta = float(args[1].value) if len(args) > 1 else 3.0
    block, pred, dev = _hw_forecast_parts(eng, args[0], params)
    keep = params.meta().steps
    actual = block.values[:, -keep:]
    scaled = delta * dev
    lower, upper = pred - scaled, pred + scaled
    with np.errstate(invalid="ignore"):
        out = np.where(np.isfinite(actual) & np.isfinite(upper)
                       & (actual > upper), actual - upper, 0.0)
        out = np.where(np.isfinite(actual) & np.isfinite(lower)
                       & (actual < lower), actual - lower, out)
        out = np.where(np.isfinite(actual), out, 0.0)
    tags = [t.with_tag(b"__alias__",
                       b"holtWintersAberration(" + series_name(t) + b")")
            for t in block.series_tags]
    return Block(params.meta(), tags, out)
