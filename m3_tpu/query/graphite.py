"""Graphite query language: expression parser + function evaluator over
blocks (reference: src/query/graphite — lexer/compiler in graphite/native,
~100 builtin functions in native/builtin_functions.go, storage adapter in
graphite/storage).

Path globs compile to per-component matchers on the __gN__ tags written by
carbon ingestion (m3_tpu.metrics.carbon.path_to_tags). Series math runs on
the same dense [series x steps] blocks as PromQL; functions are a curated
core of the reference's builtins, organized for easy widening."""

from __future__ import annotations

import math
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..metrics.carbon import GRAPHITE_TAG_FMT, tags_to_path
from ..ops import temporal
from .block import Block, BlockMeta
from .executor import QueryParams
from .model import Matcher, MatchType, Tags

S = 1_000_000_000


# ---------------------------------------------------------------- parsing

_TOKEN = re.compile(r"""
    (?P<WS>\s+)
  | (?P<NUMBER>-?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)
  | (?P<STRING>"[^"]*"|'[^']*')
  | (?P<NAME>[a-zA-Z_][a-zA-Z0-9_]*(?=\s*\())
  | (?P<PATH>(?:[a-zA-Z0-9_*?.:\[\]\-$%+]|\{[^}]*\})+)
  | (?P<LPAREN>\()|(?P<RPAREN>\))|(?P<COMMA>,)
""", re.VERBOSE)


class GraphiteParseError(ValueError):
    pass


def _lex(s: str):
    out, i = [], 0
    while i < len(s):
        m = _TOKEN.match(s, i)
        if not m:
            raise GraphiteParseError(f"bad character {s[i]!r} at {i}")
        if m.lastgroup != "WS":
            out.append((m.lastgroup, m.group()))
        i = m.end()
    out.append(("EOF", ""))
    return out


class _Expr:
    pass


class PathExpr(_Expr):
    def __init__(self, path: str):
        self.path = path


class CallExpr(_Expr):
    def __init__(self, func: str, args: List):
        self.func = func
        self.args = args


class Literal(_Expr):
    def __init__(self, value):
        self.value = value


def parse_target(s: str) -> _Expr:
    """graphite/native/compiler.go: one render target expression."""
    toks = _lex(s)
    pos = [0]

    def peek():
        return toks[pos[0]]

    def nxt():
        t = toks[pos[0]]
        pos[0] += 1
        return t

    def expr():
        kind, text = peek()
        if kind == "NAME":
            nxt()
            if nxt()[0] != "LPAREN":
                raise GraphiteParseError("expected (")
            args = []
            while peek()[0] != "RPAREN":
                args.append(expr())
                if peek()[0] == "COMMA":
                    nxt()
            nxt()
            return CallExpr(text, args)
        if kind == "NUMBER":
            nxt()
            return Literal(float(text))
        if kind == "STRING":
            nxt()
            return Literal(text[1:-1])
        if kind == "PATH":
            nxt()
            return PathExpr(text)
        raise GraphiteParseError(f"unexpected {text!r}")

    node = expr()
    if peek()[0] != "EOF":
        raise GraphiteParseError(f"trailing input {peek()[1]!r}")
    return node


def path_to_matchers(path: str) -> Tuple[Matcher, ...]:
    """Glob path -> per-component __gN__ matchers (graphite/storage/
    converter.go equivalent): literal components match exactly, glob
    components compile to regexes."""
    out = []
    parts = path.split(".")
    for i, part in enumerate(parts):
        name = GRAPHITE_TAG_FMT % i
        if any(c in part for c in "*?{["):
            out.append(Matcher(MatchType.REGEXP, name, _glob_regex(part).encode()))
        else:
            out.append(Matcher(MatchType.EQUAL, name, part.encode()))
    # Exact depth: the next component must not exist.
    out.append(Matcher(MatchType.NOT_REGEXP, GRAPHITE_TAG_FMT % len(parts),
                       b".+"))
    return tuple(out)


def _glob_regex(part: str) -> str:
    out = []
    i = 0
    while i < len(part):
        c = part[i]
        if c == "*":
            out.append("[^.]*")
        elif c == "?":
            out.append("[^.]")
        elif c == "{":
            j = part.find("}", i)
            if j < 0:
                raise GraphiteParseError(f"unterminated {{ in {part!r}")
            alts = part[i + 1:j].split(",")
            out.append("(?:" + "|".join(re.escape(a) for a in alts) + ")")
            i = j
        elif c == "[":
            j = part.find("]", i)
            out.append(part[i:j + 1])
            i = j
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


# ---------------------------------------------------------------- engine

class GraphiteEngine:
    """Evaluate render targets (graphite/native/engine.go)."""

    def __init__(self, storage, step_ns: int = 10 * S):
        self.storage = storage
        self.step_ns = step_ns

    def render(self, target: str, start_ns: int, end_ns: int,
               step_ns: Optional[int] = None) -> Block:
        params = QueryParams(start_ns, end_ns, step_ns or self.step_ns)
        return self._eval(parse_target(target), params)

    # -- evaluation -------------------------------------------------------

    def _eval(self, node: _Expr, params: QueryParams) -> Block:
        if isinstance(node, PathExpr):
            return self._fetch(node.path, params)
        if isinstance(node, CallExpr):
            fn = _FUNCTIONS.get(node.func)
            if fn is None:
                raise GraphiteParseError(f"unknown function {node.func!r}")
            return fn(self, node.args, params)
        raise GraphiteParseError("bare literal is not a series")

    def _eval_arg(self, node, params):
        if isinstance(node, Literal):
            return node.value
        return self._eval(node, params)

    def _fetch(self, path: str, params: QueryParams) -> Block:
        from .block import consolidate

        series = self.storage.fetch_raw(
            path_to_matchers(path), params.start_ns - params.step_ns,
            params.end_ns + 1)
        meta = params.meta()
        tags_list, rows = [], []
        for sid, entry in sorted(series.items()):
            tags_list.append(Tags.of(dict(entry["tags"])))
            rows.append(consolidate(
                np.asarray(entry["t"], np.int64), np.asarray(entry["v"]),
                meta, params.step_ns))
        vals = np.stack(rows) if rows else np.zeros((0, meta.steps))
        return Block(meta, tags_list, vals)


def series_name(tags: Tags) -> bytes:
    """Render name for output: the dotted path (or the alias tag)."""
    alias = tags.get(b"__alias__")
    if alias is not None:
        return alias
    return tags_to_path(tags.as_dict())


# ---------------------------------------------------------------- functions

_FUNCTIONS: Dict[str, Callable] = {}


def _register(*names):
    def deco(fn):
        for n in names:
            _FUNCTIONS[n] = fn
        return fn

    return deco


def _combine(eng, args, params, reducer, name):
    blocks = [eng._eval(a, params) for a in args]
    vals = np.concatenate([b.values for b in blocks]) if blocks else \
        np.zeros((0, params.steps))
    meta = blocks[0].meta if blocks else params.meta()
    with np.errstate(invalid="ignore"):
        row = reducer(vals)
    tags = Tags.of({b"__alias__": name.encode()})
    return Block(meta, [tags], row[None, :])


@_register("sumSeries", "sum")
def _sum_series(eng, args, params):
    return _combine(eng, args, params, lambda v: np.nansum(v, axis=0),
                    "sumSeries")


@_register("averageSeries", "avg")
def _avg_series(eng, args, params):
    return _combine(eng, args, params, lambda v: np.nanmean(v, axis=0),
                    "averageSeries")


@_register("maxSeries")
def _max_series(eng, args, params):
    return _combine(eng, args, params, lambda v: np.nanmax(v, axis=0), "maxSeries")


@_register("minSeries")
def _min_series(eng, args, params):
    return _combine(eng, args, params, lambda v: np.nanmin(v, axis=0), "minSeries")


@_register("scale")
def _scale(eng, args, params):
    block = eng._eval(args[0], params)
    factor = args[1].value
    return block.with_values(block.values * factor)


@_register("offset")
def _offset(eng, args, params):
    block = eng._eval(args[0], params)
    return block.with_values(block.values + args[1].value)


@_register("absolute")
def _absolute(eng, args, params):
    block = eng._eval(args[0], params)
    return block.with_values(np.abs(block.values))


@_register("alias")
def _alias(eng, args, params):
    block = eng._eval(args[0], params)
    name = args[1].value.encode()
    return block.with_values(
        block.values, [t.with_tag(b"__alias__", name) for t in block.series_tags])


@_register("aliasByNode")
def _alias_by_node(eng, args, params):
    block = eng._eval(args[0], params)
    nodes = [int(a.value) for a in args[1:]]
    tags = []
    for t in block.series_tags:
        parts = tags_to_path(t.as_dict()).split(b".")
        picked = b".".join(parts[n] for n in nodes if -len(parts) <= n < len(parts))
        tags.append(t.with_tag(b"__alias__", picked))
    return block.with_values(block.values, tags)


@_register("derivative")
def _derivative(eng, args, params):
    block = eng._eval(args[0], params)
    v = block.values
    out = np.full_like(v, np.nan)
    out[:, 1:] = v[:, 1:] - v[:, :-1]
    return block.with_values(out)


@_register("perSecond")
def _per_second(eng, args, params):
    block = eng._eval(args[0], params)
    v = block.values
    d = np.full_like(v, np.nan)
    d[:, 1:] = (v[:, 1:] - v[:, :-1]) / (params.step_ns / S)
    d[d < 0] = np.nan  # counter wrap guard (builtin_functions.go perSecond)
    return block.with_values(d)


@_register("nonNegativeDerivative")
def _non_negative_derivative(eng, args, params):
    block = eng._eval(args[0], params)
    v = block.values
    d = np.full_like(v, np.nan)
    d[:, 1:] = v[:, 1:] - v[:, :-1]
    d[d < 0] = np.nan
    return block.with_values(d)


@_register("movingAverage")
def _moving_average(eng, args, params):
    w = args[1].value
    if isinstance(w, str):
        from .promql import parse_duration_ns

        W = max(1, parse_duration_ns(w) // params.step_ns)
    else:
        W = max(1, int(w))
    # Shift the fetch window back W-1 steps so the first output point has a
    # full window of history (graphite-web movingAverage semantics), then
    # reduce every window via the batched temporal kernel (device path).
    ext = QueryParams(params.start_ns - (W - 1) * params.step_ns,
                      params.end_ns, params.step_ns)
    block = eng._eval(args[0], ext)
    out = temporal.over_time(block.values, W, "avg")
    return Block(params.meta(), block.series_tags, out)


@_register("keepLastValue")
def _keep_last_value(eng, args, params):
    block = eng._eval(args[0], params)
    v = block.values.copy()
    for row in v:
        finite = np.isfinite(row)
        if not finite.any():
            continue
        idx = np.where(finite, np.arange(row.size), -1)
        run = np.maximum.accumulate(idx)
        valid = run >= 0
        row[valid] = row[run[valid]]
    return block.with_values(v)


@_register("sortByName")
def _sort_by_name(eng, args, params):
    block = eng._eval(args[0], params)
    order = np.argsort([series_name(t) for t in block.series_tags], kind="stable")
    return block.with_values(block.values[order],
                             [block.series_tags[i] for i in order])


@_register("limit")
def _limit(eng, args, params):
    block = eng._eval(args[0], params)
    n = int(args[1].value)
    return block.with_values(block.values[:n], block.series_tags[:n])


@_register("exclude")
def _exclude(eng, args, params):
    block = eng._eval(args[0], params)
    pat = re.compile(args[1].value.encode())
    keep = [i for i, t in enumerate(block.series_tags)
            if not pat.search(series_name(t))]
    return block.with_values(block.values[keep],
                             [block.series_tags[i] for i in keep])


@_register("grep")
def _grep(eng, args, params):
    block = eng._eval(args[0], params)
    pat = re.compile(args[1].value.encode())
    keep = [i for i, t in enumerate(block.series_tags)
            if pat.search(series_name(t))]
    return block.with_values(block.values[keep],
                             [block.series_tags[i] for i in keep])


@_register("highestCurrent")
def _highest_current(eng, args, params):
    block = eng._eval(args[0], params)
    n = int(args[1].value) if len(args) > 1 else 1
    last = np.where(np.isfinite(block.values), block.values, -np.inf)
    cur = np.full(block.n_series, -np.inf)
    for i in range(block.n_series):
        finite = np.flatnonzero(np.isfinite(block.values[i]))
        if finite.size:
            cur[i] = block.values[i][finite[-1]]
    order = np.argsort(-cur, kind="stable")[:n]
    return block.with_values(block.values[order],
                             [block.series_tags[i] for i in order])


@_register("averageAbove")
def _average_above(eng, args, params):
    block = eng._eval(args[0], params)
    thresh = args[1].value
    with np.errstate(invalid="ignore"):
        mean = np.nanmean(np.where(np.isfinite(block.values), block.values,
                                   np.nan), axis=1)
    keep = np.flatnonzero(mean > thresh)
    return block.with_values(block.values[keep],
                             [block.series_tags[i] for i in keep])


@_register("groupByNode")
def _group_by_node(eng, args, params):
    block = eng._eval(args[0], params)
    node = int(args[1].value)
    agg = args[2].value if len(args) > 2 else "sum"
    reducers = {"sum": np.nansum, "avg": np.nanmean, "average": np.nanmean,
                "max": np.nanmax, "min": np.nanmin}
    reducer = reducers[agg]
    groups: Dict[bytes, List[int]] = {}
    for i, t in enumerate(block.series_tags):
        parts = tags_to_path(t.as_dict()).split(b".")
        key = parts[node] if -len(parts) <= node < len(parts) else b""
        groups.setdefault(key, []).append(i)
    tags_out, rows = [], []
    for key, idxs in sorted(groups.items()):
        with np.errstate(invalid="ignore"):
            rows.append(reducer(block.values[idxs], axis=0))
        tags_out.append(Tags.of({b"__alias__": key}))
    vals = np.stack(rows) if rows else np.zeros((0, block.meta.steps))
    return Block(block.meta, tags_out, vals)


@_register("summarize")
def _summarize(eng, args, params):
    from .promql import parse_duration_ns

    block = eng._eval(args[0], params)
    bucket_ns = parse_duration_ns(args[1].value)
    agg = args[2].value if len(args) > 2 else "sum"
    factor = max(1, bucket_ns // params.step_ns)
    steps = block.meta.steps // factor
    if steps == 0:
        return block
    v = block.values[:, : steps * factor].reshape(block.n_series, steps, factor)
    reducers = {"sum": np.nansum, "avg": np.nanmean, "max": np.nanmax,
                "min": np.nanmin, "last": lambda a, axis: a[..., -1]}
    with np.errstate(invalid="ignore"):
        out = reducers[agg](v, axis=2)
    meta = BlockMeta(block.meta.start_ns, bucket_ns, steps)
    return Block(meta, block.series_tags, out)
