"""Query-corpus recorder: an opt-in sampler that appends one record per
served query — (normalized query SHAPE, route taken, typed fallback
reason, series count, latency) — to a bounded on-disk JSONL corpus, so
the compiled-path coverage number ROADMAP item 4 gates on ("≥80% of a
recorded dashboard query corpus taking the compiled path") is measured
against real traffic instead of hand-picked test queries.
`scripts/coverage_report.py` replays a corpus through the lowering and
prints the coverage number + per-reason fallback counts.

Normalization (`normalize`): the recorded shape is the query with label
matcher VALUES stripped (matcher names and operators survive — they
don't change routing; values are unbounded user data), numeric literals
canonicalized to 1 and @-timestamps to 0 (routing depends on plan
STRUCTURE, never on the literal value), and string literals emptied.
Durations (ranges, subquery resolutions, offsets) are kept — they are
part of the physical shape (W/stride geometry). A normalized shape
re-parses as valid PromQL and lowers to the same route as the original,
so a corpus replays without the original data or label values.

Versus the reference: m3/Prometheus ship ALWAYS-ON query logging (the
dbnode query log / prom's active query log). Here recording is opt-in
(`M3_TPU_QUERY_CORPUS=<path>`), sampled (`M3_TPU_CORPUS_SAMPLE`,
default 0.01) and bounded (`M3_TPU_CORPUS_MAX` records, drops counted)
— a corpus is a measurement instrument, not an audit trail
(DIVERGENCES.md)."""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Dict, List, Optional

from . import promql
from .model import MatchType

_OP = {MatchType.EQUAL: "=", MatchType.NOT_EQUAL: "!=",
       MatchType.REGEXP: "=~", MatchType.NOT_REGEXP: "!~"}


def _dur(ns: int) -> str:
    """Exact-round-trip duration literal (seconds when whole, else ms;
    sub-ms remainders floor to ms — shape-preserving for any grid the
    engine serves)."""
    if ns < 0:
        return "-" + _dur(-ns)
    if ns % 1_000_000_000 == 0:
        return f"{ns // 1_000_000_000}s"
    return f"{max(ns // 1_000_000, 1)}ms"


def _at(at_ns) -> str:
    if at_ns == "start":
        return " @ start()"
    if at_ns == "end":
        return " @ end()"
    return " @ 0"  # numeric pins normalize: the timestamp is user data


def _selector(node: promql.VectorSelector) -> str:
    name = node.name.decode(errors="replace") if node.name else ""
    if node.matchers:
        body = ",".join(
            f'{m.name.decode(errors="replace")}{_OP[m.type]}""'
            for m in node.matchers)
        name += "{" + body + "}"
    elif not node.name:
        name = "{}"
    if node.range_ns:
        name += f"[{_dur(node.range_ns)}]"
    if node.offset_ns:
        name += f" offset {_dur(node.offset_ns)}"
    if node.at_ns is not None:
        name += _at(node.at_ns)
    return name


def _matching(m: Optional[promql.VectorMatching]) -> str:
    if m is None:
        return ""
    labels = ",".join(l.decode(errors="replace") for l in m.labels)
    out = f" {'on' if m.on else 'ignoring'}({labels})"
    if m.group_left or m.group_right:
        inc = ",".join(l.decode(errors="replace") for l in m.include)
        out += f" {'group_left' if m.group_left else 'group_right'}({inc})"
    return out


def _render(node: promql.Node) -> str:
    if isinstance(node, promql.NumberLiteral):
        return "1"
    if isinstance(node, promql.StringLiteral):
        return '""'
    if isinstance(node, promql.VectorSelector):
        return _selector(node)
    if isinstance(node, promql.Subquery):
        res = _dur(node.step_ns) if node.step_ns else ""
        out = f"({_render(node.expr)})[{_dur(node.range_ns)}:{res}]"
        if node.offset_ns:
            out += f" offset {_dur(node.offset_ns)}"
        if node.at_ns is not None:
            out += _at(node.at_ns)
        return out
    if isinstance(node, promql.Unary):
        return f"{node.op}({_render(node.expr)})"
    if isinstance(node, promql.Call):
        return f"{node.func}({', '.join(_render(a) for a in node.args)})"
    if isinstance(node, promql.Aggregation):
        head = node.op
        if node.grouping or node.without:
            labels = ",".join(g.decode(errors="replace")
                              for g in node.grouping)
            head += f" {'without' if node.without else 'by'} ({labels})"
        args = ([_render(node.param)] if node.param is not None else []) + \
            [_render(node.expr)]
        return f"{head} ({', '.join(args)})"
    if isinstance(node, promql.BinaryOp):
        op = node.op + (" bool" if node.bool_mode else "")
        return (f"({_render(node.lhs)}) {op}{_matching(node.matching)} "
                f"({_render(node.rhs)})")
    raise ValueError(f"unrenderable node {type(node).__name__}")


def normalize(query: str) -> str:
    """Normalized shape of one query string (see module docstring);
    raises promql.ParseError/ValueError on unparseable input — callers
    on the serving path catch and count."""
    return _render(promql.parse(query))


# ----------------------------------------------------------------- recorder


class CorpusRecorder:
    """Appends sampled query records to one JSONL file, bounded by
    `max_records` (existing lines count against the bound, so a restart
    can't grow the corpus past it; drops are counted, never silent)."""

    def __init__(self, path: str, sample: float = 1.0,
                 max_records: int = 50000):
        self.path = path
        self.sample = min(1.0, max(0.0, float(sample)))
        self.max_records = int(max_records)
        self._lock = threading.Lock()
        self._rng = random.Random()
        self.dropped = 0
        self.errors = 0
        self.written = 0
        self._count = 0
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    self._count = sum(1 for _ in f)
            except OSError:
                self.errors += 1

    def want(self) -> bool:
        """Consume one sampling draw: True when the next record should
        be written. Callers that need work BETWEEN the sampling decision
        and the append (the executor materializes the lazy result so
        recorded latency includes the d2h transfer) draw here and pass
        presampled=True to record()."""
        return self.sample >= 1.0 or self._rng.random() < self.sample

    def record(self, query: str, route: Optional[str] = None,
               reason: Optional[str] = None, series: int = 0,
               latency_ns: int = 0, step_ns: int = 0,
               presampled: bool = False) -> bool:
        if not presampled and not self.want():
            return False
        try:
            shape = normalize(query)
        except Exception:  # noqa: BLE001 — a recorder parse failure
            self.errors += 1   # must never surface on the serving path
            return False
        entry = {"shape": shape, "route": route, "reason": reason,
                 "series": int(series),
                 "latency_ms": round(latency_ns / 1e6, 3),
                 "step_ns": int(step_ns)}
        line = json.dumps(entry, sort_keys=True) + "\n"
        with self._lock:
            if self._count >= self.max_records:
                self.dropped += 1
                return False
            try:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line)
            except OSError:
                self.errors += 1
                return False
            self._count += 1
            self.written += 1
        return True


# ------------------------------------------------------- process-level hook

_STATE_LOCK = threading.Lock()
_RECORDER: Optional[CorpusRecorder] = None
_RESOLVED = False


def install(recorder: Optional[CorpusRecorder]):
    """Install (or clear, with None) the process recorder explicitly —
    tests and the smoke drive use this; production opts in via env."""
    global _RECORDER, _RESOLVED
    with _STATE_LOCK:
        _RECORDER = recorder
        _RESOLVED = True


def _resolve() -> Optional[CorpusRecorder]:
    global _RECORDER, _RESOLVED
    with _STATE_LOCK:
        if not _RESOLVED:
            path = os.environ.get("M3_TPU_QUERY_CORPUS", "")
            if path:
                try:
                    sample = float(
                        os.environ.get("M3_TPU_CORPUS_SAMPLE", "0.01"))
                except ValueError:
                    sample = 0.01
                _RECORDER = CorpusRecorder(
                    path, sample=sample,
                    max_records=int(
                        os.environ.get("M3_TPU_CORPUS_MAX", "50000")))
            _RESOLVED = True
        return _RECORDER


def maybe_record(query: str, route_info: Optional[dict], result,
                 t0_ns: int, step_ns: int):
    """The executor's per-query hook: one module-global read when no
    recorder is configured (the default). For a SAMPLED query the lazy
    result materializes first, so recorded latency includes the d2h
    result transfer — without this, compiled queries (lazy fetch) would
    systematically under-report against the eagerly-evaluated
    interpreter and bias the coverage report's cost picture."""
    rec = _RECORDER
    if rec is None:
        if _RESOLVED:
            return
        rec = _resolve()
        if rec is None:
            return
    if not rec.want():
        return
    try:
        result.values  # LazyBlock caches; a plain Block is a no-op read
    except Exception:  # noqa: BLE001 — a failed late materialization
        pass               # must not kill the served response
    latency_ns = time.perf_counter_ns() - t0_ns
    route = reason = None
    if route_info:
        route = route_info.get("route")
        reason = route_info.get("fallback_reason")
    rec.record(query, route=route, reason=reason,
               series=len(result.series_tags), latency_ns=latency_ns,
               step_ns=step_ns, presampled=True)


# ----------------------------------------------------------------- coverage


def read_corpus(path: str) -> List[dict]:
    """Records from one corpus file; corrupt lines are skipped (a torn
    tail from a dying process must not void the rest)."""
    out: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "shape" in rec:
                out.append(rec)
    return out


def coverage(records: List[dict]) -> dict:
    """Compiled-path coverage over a recorded corpus: the RECORDED
    routes (what actually happened, below-floor included) plus a
    STRUCTURAL replay — each unique shape re-lowered through
    query/plan.py — so the report separates "not compilable" from
    "compilable but the data was too small". Recorded per-reason
    fallback counts + the compiled count always sum to the total."""
    from . import plan as qplan
    from .executor import DEFAULT_LOOKBACK_NS, QueryParams

    total = len(records)
    compiled = 0
    fallbacks: Dict[str, int] = {}
    runtime_fallbacks: Dict[str, int] = {}
    structural_compiled = 0
    structural_fallbacks: Dict[str, int] = {}
    shape_route: Dict[tuple, tuple] = {}
    for rec in records:
        if rec.get("route") == "compiled":
            compiled += 1
        else:
            reason = rec.get("reason") or "unknown"
            fallbacks[reason] = fallbacks.get(reason, 0) + 1
            # The recorded-route split telemetry carries as the `scope`
            # tag: a runtime miss (below-floor/disabled/backend-gap) is
            # not a lowering gap, so the structural replay below can
            # legitimately disagree with it on small-series corpora.
            if reason in qplan.RUNTIME_REASONS:
                runtime_fallbacks[reason] = \
                    runtime_fallbacks.get(reason, 0) + 1
        step_ns = int(rec.get("step_ns") or 30_000_000_000)
        key = (rec["shape"], step_ns)
        hit = shape_route.get(key)
        if hit is None:
            try:
                ast = promql.parse(rec["shape"])
                params = QueryParams(0, 119 * step_ns, step_ns)
                plan, err, _ = qplan.lower_and_collect(
                    ast, params, DEFAULT_LOOKBACK_NS)
                hit = ("compiled", None) if plan is not None \
                    else ("interpreter", err.reason.value)
            except Exception:  # noqa: BLE001 — an unreplayable shape
                hit = ("interpreter", "unreplayable")
            shape_route[key] = hit
        if hit[0] == "compiled":
            structural_compiled += 1
        else:
            structural_fallbacks[hit[1]] = \
                structural_fallbacks.get(hit[1], 0) + 1
    runtime_total = sum(runtime_fallbacks.values())
    return {
        "total": total,
        "shapes": len(shape_route),
        "compiled": compiled,
        "coverage": compiled / total if total else 0.0,
        "fallbacks": dict(sorted(fallbacks.items())),
        # Recorded fallbacks split by telemetry scope: runtime reasons
        # (data size / kill switches) vs structural lowering gaps.
        "runtime_fallbacks": dict(sorted(runtime_fallbacks.items())),
        "runtime_fallback_total": runtime_total,
        "structural_compiled": structural_compiled,
        "structural_coverage": structural_compiled / total if total else 0.0,
        "structural_fallbacks": dict(sorted(structural_fallbacks.items())),
    }
