"""Columnar Prometheus result rendering: HTTP response bytes straight
from a Block's value matrix (reference: src/query/api/v1/handler/
prometheus/renderResultsJSON — the reference streams per-series JSON
through a json.NewEncoder; this build renders the whole result from the
COLUMNS, with zero per-series Python dicts on the path).

The pre-change coordinator path built one dict per series and one
[t, "v"] list per sample, each value formatted by
np.format_float_positional (~2µs/call), then json.dumps'd the nested
structure — bench r16 measured 1.07 responses/sec on the 10k-series
dashboard mix, ~1.9s per fat-matrix response, nearly all of it in that
loop. Here the finite mask, per-row sample counts and column indices
come from three vectorized passes over the matrix; time strings render
once for the whole block (every series shares the step grid); values
format through a repr() fast path (CPython's float repr is the same
shortest-round-trip decimal Dragon4 produces — positional-range values
differ from format_float_positional only by the trailing ".0", which is
trimmed; everything else falls back to the exact formatter); and the
response assembles as one bytes join.

Byte identity is a CONTRACT, not a hope: `render_result_ref` is the old
per-series materialization retained verbatim (the established `_ref`
oracle pattern — m3lint's per-series-result-dict rule exempts `_ref`
renderers by name), and tests/test_result_frame.py asserts the columnar
bytes equal `json.dumps(ref_dict).encode()` across the whole
compiled-vs-oracle corpus plus adversarial value grids. The separators
(", ", ": ") reproduce json.dumps defaults."""

from __future__ import annotations

import json
import math
from typing import Dict, List

import numpy as np

from .block import Block

S = 1_000_000_000

# The C-accelerated ASCII string escaper json.dumps itself uses.
_esc = json.encoder.encode_basestring_ascii


def prom_sample_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    # Go strconv.FormatFloat(v, 'f', -1)-style: shortest POSITIONAL
    # round-trip decimal — no trailing .0 on integers and no scientific
    # notation at any magnitude ("100000000000000000000", "0.0000001") —
    # what prometheus emits and strict clients byte-compare against.
    return np.format_float_positional(float(v), unique=True, trim="-")


def _metric_labels(tags) -> Dict[str, str]:
    return {k.decode(): v.decode() for k, v in tags.pairs}


# ------------------------------------------------------------ ref oracle
#
# The pre-change per-series materialization, retained VERBATIM: one dict
# per series, one [t, "v"] list per sample. `render_result_ref` is the
# byte-identity oracle every columnar response is proven against.


def prom_matrix_ref(block: Block) -> dict:
    times = block.meta.times() / S
    result = []
    for tags, row in zip(block.series_tags, block.values):
        finite = np.isfinite(row)
        if not finite.any():
            continue
        values = [[float(t), prom_sample_value(v)]
                  for t, v, ok in zip(times, row, finite) if ok]
        result.append({"metric": _metric_labels(tags), "values": values})
    return {"status": "success",
            "data": {"resultType": "matrix", "result": result}}


def prom_vector_ref(block: Block) -> dict:
    t = block.meta.times()[-1] / S
    result = []
    for tags, row in zip(block.series_tags, block.values):
        v = row[-1]
        if not math.isfinite(v):
            continue
        result.append({"metric": _metric_labels(tags),
                       "value": [float(t), prom_sample_value(v)]})
    return {"status": "success",
            "data": {"resultType": "vector", "result": result}}


def render_result_ref(block: Block, instant: bool = False) -> bytes:
    """The byte-identity oracle: the retained per-series renderer +
    json.dumps, exactly what the pre-change HTTP layer emitted."""
    out = prom_vector_ref(block) if instant else prom_matrix_ref(block)
    return json.dumps(out).encode()


# ------------------------------------------------------- columnar render


def _format_values(flat: np.ndarray) -> List[str]:
    """Shortest-positional-decimal strings for a flat FINITE f64 column.

    Integer-valued cells below 2^53 (the dashboard bulk: counter
    samples, window counts, increase sums) format as one vectorized
    C-level sprintf — below 2^53 a double's integer digits ARE its
    shortest unique positional form, and the bound excludes the even-
    spaced range where a neighboring odd integer could be the shorter
    Dragon4 pick (negative zero stays on the slow path: "-0", not "0").
    The rest go through repr() — the same shortest-round-trip Dragon4
    digits — whose only positional-range difference from
    np.format_float_positional(unique, trim="-") is the ".0" integer
    suffix; scientific-notation cases fall back to the exact
    formatter."""
    n = flat.shape[0]
    ints = ((flat == np.floor(flat)) & (np.abs(flat) < 2.0 ** 53)
            & ((flat != 0) | ~np.signbit(flat)))
    if ints.all():
        return list(map(str, flat.astype(np.int64).tolist()))
    if not ints.any():
        return _format_floats(flat)
    out: List[str] = [""] * n
    int_pos = np.nonzero(ints)[0]
    int_strs = map(str, flat[int_pos].astype(np.int64).tolist())
    for p, s in zip(int_pos.tolist(), int_strs):
        out[p] = s
    rest_pos = np.nonzero(~ints)[0]
    for p, s in zip(rest_pos.tolist(), _format_floats(flat[rest_pos])):
        out[p] = s
    return out


def _format_floats(rest: np.ndarray) -> List[str]:
    """The non-integer tail: one C-level map(repr, ...) pass, then an
    in-place fix-up (strip the ".0" suffix; route the rare scientific-
    notation magnitudes through the exact positional formatter)."""
    strs = list(map(repr, rest.tolist()))
    fallback = prom_sample_value
    vals = None
    for j, s in enumerate(strs):
        if s[-2:] == ".0":
            strs[j] = s[:-2]
        elif "e" in s:
            if vals is None:
                vals = rest.tolist()
            strs[j] = fallback(vals[j])
    return strs


def _metric_json(tags) -> str:
    """The series' label object, rendered exactly as json.dumps renders
    the ref's insertion-ordered dict — directly from the tag pairs, no
    dict on the path."""
    return ("{" + ", ".join(
        f"{_esc(k.decode())}: {_esc(v.decode())}" for k, v in tags.pairs)
        + "}")


def prom_matrix_bytes(block: Block) -> bytes:
    """One columnar pass over the [series, steps] matrix -> the full
    query_range response bytes, byte-identical to render_result_ref."""
    vals = np.asarray(block.values, dtype=np.float64)
    finite = np.isfinite(vals)
    times = block.meta.times() / S
    # One '[<time>, "' prefix per COLUMN — every series shares the step
    # grid, so each cell costs one concat + its share of one join.
    t_open = [f'[{repr(t)}, "' for t in times.tolist()]
    flat_strs = _format_values(vals[finite])
    col_idx = np.nonzero(finite)[1].tolist()
    counts = finite.sum(axis=1).tolist()
    series_chunks: List[str] = []
    pos = 0
    for r, n in enumerate(counts):
        if n == 0:
            continue
        cells = '"], '.join(
            t_open[c] + s
            for c, s in zip(col_idx[pos:pos + n], flat_strs[pos:pos + n]))
        pos += n
        series_chunks.append(
            '{"metric": ' + _metric_json(block.series_tags[r])
            + ', "values": [' + cells + '"]]}')
    body = ('{"status": "success", "data": {"resultType": "matrix", '
            '"result": [' + ", ".join(series_chunks) + "]}}")
    return body.encode()


def prom_vector_bytes(block: Block) -> bytes:
    """Instant-vector twin: the last column only."""
    vals = np.asarray(block.values, dtype=np.float64)
    t_str = repr(float(block.meta.times()[-1] / S))
    last = vals[:, -1] if vals.size else np.zeros(0)
    finite = np.isfinite(last)
    rows = np.nonzero(finite)[0].tolist()
    val_strs = _format_values(last[finite])
    series_chunks = [
        '{"metric": ' + _metric_json(block.series_tags[r])
        + f', "value": [{t_str}, "{s}"]}}'
        for r, s in zip(rows, val_strs)]
    body = ('{"status": "success", "data": {"resultType": "vector", '
            '"result": [' + ", ".join(series_chunks) + "]}}")
    return body.encode()
