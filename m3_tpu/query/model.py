"""Query data model: tags (labels) and matchers (reference:
src/query/models/{tags,matchers}.go — prom-style label sets and the four
matcher kinds =, !=, =~, !~)."""

from __future__ import annotations

import dataclasses
import enum
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

METRIC_NAME = b"__name__"


class MatchType(enum.IntEnum):
    """models/matcher.go MatchType."""

    EQUAL = 0
    NOT_EQUAL = 1
    REGEXP = 2
    NOT_REGEXP = 3


@dataclasses.dataclass(frozen=True)
class Matcher:
    type: MatchType
    name: bytes
    value: bytes

    def matches(self, value: bytes) -> bool:
        if self.type == MatchType.EQUAL:
            return value == self.value
        if self.type == MatchType.NOT_EQUAL:
            return value != self.value
        ok = re.fullmatch(self.value, value) is not None
        return ok if self.type == MatchType.REGEXP else not ok

    def __str__(self):
        op = {MatchType.EQUAL: "=", MatchType.NOT_EQUAL: "!=",
              MatchType.REGEXP: "=~", MatchType.NOT_REGEXP: "!~"}[self.type]
        return f'{self.name.decode()}{op}"{self.value.decode()}"'


@dataclasses.dataclass(frozen=True)
class Tags:
    """Immutable sorted label set (models/tags.go)."""

    pairs: Tuple[Tuple[bytes, bytes], ...]

    @staticmethod
    def of(d: Dict[bytes, bytes]) -> "Tags":
        return Tags(tuple(sorted(d.items())))

    def get(self, name: bytes) -> Optional[bytes]:
        for k, v in self.pairs:
            if k == name:
                return v
        return None

    def name(self) -> bytes:
        return self.get(METRIC_NAME) or b""

    def as_dict(self) -> Dict[bytes, bytes]:
        return dict(self.pairs)

    def without(self, names: Iterable[bytes]) -> "Tags":
        drop = set(names)
        return Tags(tuple((k, v) for k, v in self.pairs if k not in drop))

    def keep(self, names: Iterable[bytes]) -> "Tags":
        want = set(names)
        return Tags(tuple((k, v) for k, v in self.pairs if k in want))

    def with_tag(self, name: bytes, value: bytes) -> "Tags":
        return Tags.of({**self.as_dict(), name: value})

    def id(self) -> bytes:
        """Canonical series ID for grouping/output (models/tags.go ID)."""
        return b",".join(k + b"=" + v for k, v in self.pairs)

    def __str__(self):
        name = self.name().decode()
        rest = ",".join(
            f'{k.decode()}="{v.decode()}"'
            for k, v in self.pairs if k != METRIC_NAME)
        return f"{name}{{{rest}}}"


def matchers_to_index_query(matchers: Sequence[Matcher]):
    """Compile label matchers to an inverted-index query
    (query/storage/m3/storage.go FetchOptionsToM3Options ->
    idx query conversion in storage/index/convert)."""
    from ..index import query as iq

    parts = []
    for m in matchers:
        if m.type == MatchType.EQUAL:
            parts.append(iq.new_term(m.name, m.value))
        elif m.type == MatchType.NOT_EQUAL:
            parts.append(iq.new_negation(iq.new_term(m.name, m.value)))
        elif m.type == MatchType.REGEXP:
            parts.append(iq.new_regexp(m.name, m.value))
        else:
            parts.append(iq.new_negation(iq.new_regexp(m.name, m.value)))
    if not parts:
        return iq.AllQuery()
    if len(parts) == 1:
        return parts[0]
    return iq.new_conjunction(*parts)
