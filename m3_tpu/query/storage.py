"""Query storage interface + implementations (reference:
src/query/storage/types.go Storage, storage/m3/storage.go the dbnode
adapter, storage/fanout/storage.go the multi-store fanout).

fetch_raw(matchers, start_ns, end_ns) -> {series_id: {tags, t, v}} raw
datapoints; the executor grids them per query. Tag index queries compile
from label matchers via model.matchers_to_index_query."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .model import Matcher, matchers_to_index_query


class LocalStorage:
    """Direct adapter over an in-process storage.Database (the coordinator
    embedded in a dbnode, storage/m3/storage.go Fetch -> ReadEncoded)."""

    def __init__(self, db, namespace: bytes):
        self._db = db
        self._namespace = namespace

    def fetch_raw(self, matchers: Sequence[Matcher], start_ns: int,
                  end_ns: int) -> Dict[bytes, dict]:
        q = matchers_to_index_query(matchers)
        ids = self._db.query_ids(self._namespace, q, start_ns, end_ns)
        out: Dict[bytes, dict] = {}
        ns = self._db.namespace(self._namespace)
        for sid in ids:
            shard_id = self._db.shard_set.lookup(sid)
            shard = ns.shards.get(shard_id)
            if shard is None:
                continue
            t, v = shard.read(sid, start_ns, end_ns)
            idx = shard.registry.get(sid)
            tags = shard.registry.tags_of(idx) if idx is not None else {}
            out[sid] = {"tags": tags or {}, "t": t, "v": v}
        return out

    def write(self, series_id: bytes, tags: Dict[bytes, bytes], t_ns: int,
              value: float):
        self._db.write(self._namespace, series_id, t_ns, value, tags=tags)

    def write_batch(self, series_ids: Sequence[bytes], tags: Sequence[dict],
                    ts, vals):
        """Columnar write: one shard-routed db.write_batch append instead
        of a per-sample write loop (the coordinator ingest batch path)."""
        self._db.write_batch(self._namespace, list(series_ids), ts, vals,
                             tags=list(tags))

    def complete_tags(self, matchers: Sequence[Matcher], start_ns: int,
                      end_ns: int, name_only: bool = False,
                      filter_names: Sequence[bytes] = ()) -> Dict[bytes, set]:
        """storage/types.go CompleteTags: tag name -> distinct values for
        series matching the matchers, from the index — no datapoints read.
        name_only leaves the value sets empty (CompleteNameOnly)."""
        return self._db.aggregate_tags(
            self._namespace, matchers_to_index_query(matchers), start_ns,
            end_ns, name_only=name_only, filter_names=filter_names)


class SessionStorage:
    """Adapter over the replicating client session (storage/m3/storage.go
    Fetch -> session.FetchTagged, the coordinator's production path)."""

    def __init__(self, session, namespace: bytes):
        self._session = session
        self._namespace = namespace

    def fetch_raw(self, matchers: Sequence[Matcher], start_ns: int,
                  end_ns: int) -> Dict[bytes, dict]:
        q = matchers_to_index_query(matchers)
        return self._session.fetch_tagged(self._namespace, q, start_ns, end_ns)

    def write(self, series_id: bytes, tags: Dict[bytes, bytes], t_ns: int,
              value: float):
        self._session.write_tagged(self._namespace, series_id, tags, t_ns, value)

    def complete_tags(self, matchers: Sequence[Matcher], start_ns: int,
                      end_ns: int, name_only: bool = False,
                      filter_names: Sequence[bytes] = ()) -> Dict[bytes, set]:
        q = matchers_to_index_query(matchers)
        return self._session.aggregate(
            self._namespace, q, start_ns, end_ns, name_only=name_only,
            field_filter=filter_names)


class FanoutStorage:
    """Fan out fetches across stores and merge by series id
    (storage/fanout/storage.go; replica-level merge already happened in the
    client, so cross-store merge is simple union preferring more points)."""

    def __init__(self, stores: Sequence):
        self._stores = list(stores)

    def fetch_raw(self, matchers: Sequence[Matcher], start_ns: int,
                  end_ns: int) -> Dict[bytes, dict]:
        merged: Dict[bytes, dict] = {}
        for store in self._stores:
            for sid, entry in store.fetch_raw(matchers, start_ns, end_ns).items():
                cur = merged.get(sid)
                if cur is None:
                    merged[sid] = dict(entry)
                else:
                    t = np.concatenate([np.asarray(cur["t"]), np.asarray(entry["t"])])
                    v = np.concatenate([np.asarray(cur["v"]), np.asarray(entry["v"])])
                    order = np.argsort(t, kind="stable")
                    t, v = t[order], v[order]
                    keep = np.ones(t.size, dtype=bool)
                    keep[1:] = t[1:] != t[:-1]
                    cur["t"], cur["v"] = t[keep], v[keep]
                    if not cur["tags"] and entry["tags"]:
                        cur["tags"] = entry["tags"]
        return merged

    def write(self, series_id: bytes, tags, t_ns: int, value: float):
        for store in self._stores:
            store.write(series_id, tags, t_ns, value)

    def complete_tags(self, matchers: Sequence[Matcher], start_ns: int,
                      end_ns: int, name_only: bool = False,
                      filter_names: Sequence[bytes] = ()) -> Dict[bytes, set]:
        merged: Dict[bytes, set] = {}
        for store in self._stores:
            part = _store_complete_tags(store, matchers, start_ns, end_ns,
                                        name_only, filter_names)
            for name, vals in part.items():
                merged.setdefault(name, set()).update(vals)
        return merged


def _store_complete_tags(store, matchers, start_ns, end_ns, name_only,
                         filter_names) -> Dict[bytes, set]:
    """CompleteTags for any store: use the store's index-backed fast path
    when present, else derive from fetched series tags (the reference's
    remote storages similarly degrade to a series fetch)."""
    fn = getattr(store, "complete_tags", None)
    if fn is not None:
        return fn(matchers, start_ns, end_ns, name_only=name_only,
                  filter_names=filter_names)
    from ..storage.database import fold_tags

    ff = set(filter_names) if filter_names else None
    out: Dict[bytes, set] = {}
    for entry in store.fetch_raw(matchers, start_ns, end_ns).values():
        fold_tags(out, dict(entry["tags"]), ff, name_only)
    return out
