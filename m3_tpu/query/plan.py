"""Query plan IR: the logical->physical plan a PromQL AST lowers to
before whole-plan compilation (reference: src/query/parser builds a
logical DAG that executor/engine.go walks per block; here the DAG is
lowered ONCE into a typed physical plan whose operator chain compiles to
ONE jitted program over the shard x time mesh — parallel/compile.py).

A plan is a frozen tree of physical nodes (Fetch / RangeFunc /
InstantFunc / Aggregate / Binary / ScalarConst), each edge annotated
with its value kind ("series" = a [S, T] block, "scalar" = a 0-d value
broadcast over steps) and its mesh sharding ("shard" = rows partitioned
over the mesh's shard axis, "replicated" = identical on every device).
Sharding annotations are how the compiler picks its execution mode: a
plan whose every series edge stays row-partitioned compiles to a
shard_map program with collective fan-in (psum / all_gather over ICI);
a plan needing cross-row gathers (vector-vector matching) compiles
single-device; a plan containing any non-lowerable node doesn't compile
at all and the executor falls back per-node to the retained interpreter
(`Engine.execute_range_ref`, the oracle).

Host/tag algebra stays OUT of the plan: `bind()` runs the label work
(grouping, vector matching, result tags) on the host once per query and
produces index arrays the compiled program consumes as inputs — the
device program touches values only.
"""

from __future__ import annotations

import collections
import dataclasses
import enum
import math
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import promql
from .model import Tags, METRIC_NAME
from .promql import (
    Aggregation,
    BinaryOp,
    Call,
    Node as AstNode,
    NumberLiteral,
    Subquery,
    Unary,
    VectorSelector,
)

# Dispatch floor: a query fetching fewer grid cells than this stays on the
# interpreter — tiny queries gain nothing from a compiled program and the
# interpreter's exact-f64 finishes are the reference semantics for them
# (same pattern as M3_TPU_MESH_FLUSH_MIN_CELLS on the flush path).
PLAN_MIN_CELLS = int(os.environ.get("M3_TPU_PLAN_MIN_CELLS", "4096"))

SERIES = "series"
SCALAR = "scalar"

SHARDED = "shard"
REPLICATED = "replicated"


@dataclasses.dataclass(frozen=True)
class Edge:
    """Type + sharding annotation of a node's output edge."""

    kind: str        # SERIES | SCALAR
    sharding: str    # SHARDED | REPLICATED


@dataclasses.dataclass(frozen=True)
class PlanNode:
    pass


@dataclasses.dataclass(frozen=True)
class Fetch(PlanNode):
    """A gridded selector: the consolidated [S, ext_T] grid at the window
    grid (range role) or the step grid with lookback (instant role).
    `sel` carries the source selector for binding; the compile key strips
    it (the traced program depends only on the physical fields). `ctx`
    distinguishes otherwise-equal selectors gridded in DIFFERENT time
    contexts (each subquery's inner grid gets a fresh ctx id), so
    binding/staging never conflates an outer step-grid fetch with the
    same selector on a subquery's resolution grid."""

    sel: VectorSelector
    role: str                 # "range" | "instant"
    W: int                    # cells per window (1 for instant)
    stride: int               # window-grid cells per output step
    wgrid_ns: int             # grid cell width
    ctx: int = 0              # subquery grid context (0 = outer query)

    @property
    def edge(self) -> Edge:
        return Edge(SERIES, SHARDED)


@dataclasses.dataclass(frozen=True)
class RangeFunc(PlanNode):
    """A temporal kernel over a range-gridded Fetch (ops/temporal math)."""

    func: str
    arg: Fetch
    step_ns: int
    range_ns: int
    params: Tuple[float, ...] = ()

    @property
    def edge(self) -> Edge:
        # absent_over_time collapses every row into one presence row —
        # a cross-shard reduce whose output is whole on every device.
        if self.func == "absent_over_time":
            return Edge(SERIES, REPLICATED)
        return Edge(SERIES, SHARDED)


@dataclasses.dataclass(frozen=True)
class SubqueryFunc(PlanNode):
    """A range function over `expr[range:res]`: the inner plan evaluates
    on its own resolution grid (a nested range grid over the same
    shard x time mesh), then the outer func re-windows that plane with
    the SAME W/stride machinery matrix selectors use. `packed=False`
    (res divides the query step) reads contiguous strided windows
    straight off the inner plane; `packed=True` gathers each output
    step's drifting window through a bind-time column-index map (the
    compiled twin of the interpreter's packed layout). Window extraction
    is a pure per-row COLUMN operation, so the mesh sharding of the
    inner plan is preserved."""

    func: str
    arg: PlanNode
    W: int                    # window cells (packed: == stride)
    stride: int
    packed: bool
    res_ns: int               # inner resolution (the kernels' step)
    range_ns: int
    offset_ns: int = 0        # bind-only (stripped from the compile key)
    inner_steps: int = 0      # inner grid length (geometry; stripped)
    params: Tuple[float, ...] = ()

    @property
    def edge(self) -> Edge:
        return Edge(SERIES, self.arg.edge.sharding)


@dataclasses.dataclass(frozen=True)
class RankAgg(PlanNode):
    """Order-statistic aggregation (topk / bottomk / quantile): bind()
    packs each group's rows contiguously (perm index arrays), the device
    sort-selects along the packed axis (ops/series_agg packed_* math —
    the PR 10 quantile_rank_select shape generalized), and the k / q
    parameter rides as a runtime slot so one executable serves every
    threshold. Needs cross-row gathers, so plans containing one compile
    single-device (same rule as vector-vector matching)."""

    op: str                   # "topk" | "bottomk" | "quantile"
    arg: PlanNode
    param: "ScalarConst"
    grouping: Tuple[bytes, ...] = ()
    without: bool = False

    @property
    def edge(self) -> Edge:
        return Edge(SERIES, REPLICATED)


@dataclasses.dataclass(frozen=True)
class InstantFunc(PlanNode):
    """Elementwise math over a series plane (the _MATH_FUNCS subset with
    jnp equivalents); scalar params ride as slots."""

    func: str
    arg: PlanNode
    params: Tuple["ScalarConst", ...] = ()

    @property
    def edge(self) -> Edge:
        return self.arg.edge


@dataclasses.dataclass(frozen=True)
class Aggregate(PlanNode):
    """Cross-series aggregation; grouping structure is bind-time host
    work, the reduce is a compensated device sum with collective fan-in.
    exact=True marks the counter-sum path (aggregate directly over a raw
    Fetch): residual/baseline decomposition + two-sum compensated
    reduction preserve the interpreter's f64 host-reduce semantics."""

    op: str
    arg: PlanNode
    grouping: Tuple[bytes, ...] = ()
    without: bool = False
    exact: bool = False

    @property
    def edge(self) -> Edge:
        return Edge(SERIES, REPLICATED)


@dataclasses.dataclass(frozen=True)
class Binary(PlanNode):
    op: str
    lhs: PlanNode
    rhs: PlanNode
    bool_mode: bool = False
    # vector-vector only: bind() computes row alignment; the compiled
    # program gathers by the bound index arrays. `swap` (the many side
    # is the RHS, i.e. group_right) is static program structure and
    # survives compile-key stripping; the matching labels are bind-only.
    matching: Optional[promql.VectorMatching] = None
    swap: bool = False

    @property
    def edge(self) -> Edge:
        le, re_ = self.lhs.edge, self.rhs.edge
        if le.kind == SCALAR and re_.kind == SCALAR:
            return Edge(SCALAR, REPLICATED)
        if le.kind == SERIES and re_.kind == SERIES:
            # vv matching needs cross-row gathers -> not mesh-shardable
            return Edge(SERIES, REPLICATED)
        vec = le if le.kind == SERIES else re_
        return vec


@dataclasses.dataclass(frozen=True)
class ScalarConst(PlanNode):
    """A runtime scalar slot: the VALUE is not part of the plan (so the
    plan cache reuses one executable across thresholds); bind() records
    slot values in plan order."""

    slot: int

    @property
    def edge(self) -> Edge:
        return Edge(SCALAR, REPLICATED)


@dataclasses.dataclass(frozen=True)
class Plan:
    root: PlanNode
    steps: int
    n_slots: int
    fetches: Tuple[Fetch, ...]
    # True when every series edge stays row-partitioned (no cross-row
    # gathers), i.e. the plan can run as ONE shard_map program with
    # collective fan-in.
    mesh_ok: bool


class FallbackReason(enum.Enum):
    """The catalogued reasons a query misses the compiled whole-plan
    route. Every `NotCompilable` raise site names one (enforced by
    tests/test_explain.py's raise-site scan — free-form strings cannot
    creep back in), the executor counts each fallback reason-tagged in
    instrument scope `telemetry.plan_fallback` (visible in /debug/vars,
    the self-scrape pipeline and the slow-query ring), and EXPLAIN
    (`query/explain.py`) annotates the failing plan node with it. The
    values are a CLOSED set: they ride as telemetry tag values, where an
    unbounded value (a raw query string) would explode the metric
    registry — m3lint's `unbounded-telemetry-tag` rule gates that."""

    # Retired in round 16 (now lowered): "subquery" (the SubqueryFunc
    # nested range grid) and "group-matching" (one-to-many vv index
    # maps). The members are GONE, not parked: the raise-site scan in
    # tests/test_explain.py proves nothing still names them.
    MATRIX_SELECTOR = "matrix-selector"        # bare m[5m] outside a func
    AT_MODIFIER = "at-modifier"                # @-pinned selector
    SELECTOR_SHAPE = "selector-shape"          # range func w/o matrix arg
    UNSUPPORTED_NODE = "unsupported-node"      # AST node kind not lowered
    UNSUPPORTED_FUNC = "unsupported-func"      # absent/label_replace/...
    UNSUPPORTED_AGG = "unsupported-agg"        # count_values/non-root topk
    AGG_OVER_SCALAR = "agg-over-scalar"        # sum(2) — type error shape
    SET_OP = "set-op"                          # and / or / unless
    F64_ARITH = "f64-arith"                    # % / ^ need f64 granularity
    ABS_COMPARISON = "abs-comparison"          # compare on 1e9+ f32 plane
    NON_CONSTANT_PARAM = "non-constant-param"  # clamp(m, x) etc.
    SCALAR_ONLY = "scalar-only"                # no selector in the plan
    BELOW_FLOOR = "below-floor"                # total cells < PLAN_MIN_CELLS
    BACKEND_GAP = "backend-gap"                # compile-time PlanFallback
    DISABLED = "disabled"                      # plan route off (env/ref)
    DEVICE_FAULT = "device-fault"              # guarded dispatch tripped


# Reasons that are RUNTIME routing decisions (data size, kill switches,
# backend gaps), not plan-structure facts: telemetry tags each fallback
# with this split so a coverage replay's STRUCTURAL re-lowering can never
# disagree with recorded routes on small-series corpora — a below-floor
# miss is not a lowering gap (scripts/coverage_report.py reads both).
RUNTIME_REASONS = frozenset({
    "below-floor", "backend-gap", "disabled", "device-fault",
})


def fallback_scope(reason_value: str) -> str:
    """telemetry.plan_fallback's scope tag for one FallbackReason value:
    "runtime" (data-dependent / operational) vs "structural" (the query
    shape is outside the compiled surface)."""
    return "runtime" if reason_value in RUNTIME_REASONS else "structural"


class NotCompilable(Exception):
    """Raised during lowering when a node falls outside the compiled
    surface; the executor falls back to the per-node interpreter.

    Carries a typed `reason` (FallbackReason — the bounded taxonomy the
    telemetry/EXPLAIN surfaces consume), a free-form `detail` for humans,
    and the AST `node` that raised (EXPLAIN pins the reason onto it)."""

    def __init__(self, reason: FallbackReason, detail: str = "",
                 node=None):
        self.reason = reason
        self.detail = detail
        self.node = node
        super().__init__(f"{reason.value}: {detail}" if detail
                         else reason.value)


# Range functions with fully-traceable device bodies (ops/temporal math).
# Round 16 closed the last gaps: irate/idelta compute their last-two-
# sample differences in residual space on device (temporal.instant_math
# — the staged resid decomposition keeps counter-magnitude diffs exact,
# where the old host path gathered f64 values by device indices, a host
# sync mid-plan), quantile_over_time interpolates in residual space
# (shift-equivariant, temporal.quantile_ot_math), and absent_over_time
# is a window-count + cross-row presence reduce.
RANGE_FUNCS = frozenset({
    "rate", "increase", "delta", "deriv", "changes", "resets",
    "predict_linear", "holt_winters", "irate", "idelta",
    "sum_over_time", "avg_over_time", "min_over_time", "max_over_time",
    "count_over_time", "last_over_time", "stddev_over_time",
    "stdvar_over_time", "present_over_time", "quantile_over_time",
    "absent_over_time",
})

# Elementwise math with exact jnp twins (NaN-propagating like the host
# versions). round/clamp* take scalar params as slots.
MATH_FUNCS = frozenset({
    "abs", "ceil", "floor", "exp", "sqrt", "ln", "log2", "log10", "sgn",
    "round", "clamp", "clamp_min", "clamp_max",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh",
    "asinh", "acosh", "atanh", "deg", "rad",
})

AGG_OPS = frozenset({"sum", "avg", "min", "max", "count", "group",
                     "stddev", "stdvar"})

# Order-statistic aggregations: the RankAgg packed sort-select path.
RANK_AGGS = frozenset({"topk", "bottomk", "quantile"})

# Outer funcs lowerable over a subquery. absent_over_time's cross-row
# presence reduce is Fetch-shaped (selector tags, empty-fetch rows) and
# stays on the interpreter over subqueries.
SUBQUERY_FUNCS = RANGE_FUNCS - {"absent_over_time"}

# Subquery funcs whose math DIFFERENCES or REGRESSES the plane: over a
# composite (non-selector) inner expression the prep runs in-trace at
# f32, which at absolute counter magnitudes (1e9+, ulp 64) turns
# consecutive-sample diffs into rounding noise — those stay on the
# interpreter (same f64-granularity reason %/^ do). Direct selector
# inners stage their preps on the host in exact f64 and lower fully.
_SUBQ_DIFF_FUNCS = frozenset({
    "rate", "increase", "delta", "irate", "idelta", "deriv",
    "predict_linear", "holt_winters", "stddev_over_time",
    "stdvar_over_time",
})

# %/^ stay on the interpreter: fmod/pow need f64 granularity at counter
# magnitudes (2^m % 7 on an f32 plane is pure rounding noise), and the
# compiled value planes are f32 by design.
ARITH_OPS = frozenset({"+", "-", "*", "/"})


# Range functions whose output is in the units of the raw samples
# (reconstructed absolute magnitudes: window stats over values, or a
# regression/forecast with the baseline added back) — as opposed to
# difference/count space (rate, delta, changes, ...), which is small
# regardless of counter magnitude.
_ABS_RANGE_FUNCS = frozenset({
    "sum_over_time", "avg_over_time", "min_over_time", "max_over_time",
    "last_over_time", "predict_linear", "holt_winters",
    "quantile_over_time",
})


def _abs_space(node: PlanNode) -> bool:
    """True when the node's value plane carries raw-sample magnitudes
    (1e9+ for counters), where f32 granularity is coarser than the
    interpreter's f64 — a comparison there can flip sample PRESENCE, a
    discrete divergence no FP tolerance covers."""
    if isinstance(node, Fetch):
        return True
    if isinstance(node, RangeFunc):
        return node.func in _ABS_RANGE_FUNCS
    if isinstance(node, SubqueryFunc):
        return node.func in _ABS_RANGE_FUNCS
    if isinstance(node, RankAgg):
        # topk/bottomk/quantile select VALUES of the argument plane.
        return _abs_space(node.arg)
    if isinstance(node, InstantFunc):
        # timestamp() emits unix seconds (~1.7e9): absolute magnitudes
        # regardless of its argument's space.
        if node.func == "timestamp":
            return True
        return _abs_space(node.arg)
    if isinstance(node, Aggregate):
        # stddev/stdvar spread across series of different baselines can
        # itself reach baseline magnitude — treat as absolute space.
        return node.op in ("sum", "avg", "min", "max", "stddev",
                           "stdvar") and _abs_space(node.arg)
    if isinstance(node, Binary):
        return _abs_space(node.lhs) or _abs_space(node.rhs)
    return False


class _Lowerer:
    def __init__(self, params, lookback_ns: int):
        self.params = params
        self.lookback_ns = lookback_ns
        self.slots: List[AstNode] = []
        self._depth = 0       # AST nesting below the root (1 = root node)
        self._ctx = 0         # current subquery grid context (0 = outer)
        self._next_ctx = 0

    def _slot(self, node: AstNode) -> ScalarConst:
        self.slots.append(node)
        return ScalarConst(len(self.slots) - 1)

    def lower(self, node: AstNode) -> PlanNode:
        self._depth += 1
        try:
            return self._lower(node)
        finally:
            self._depth -= 1

    def _lower(self, node: AstNode) -> PlanNode:
        p = self.params
        if isinstance(node, NumberLiteral):
            return self._slot(node)
        if isinstance(node, Unary):
            inner = self.lower(node.expr)
            return InstantFunc("neg", inner)
        if isinstance(node, VectorSelector):
            if node.at_ns is not None:
                raise NotCompilable(FallbackReason.AT_MODIFIER,
                                    "@-pinned selector", node)
            if node.range_ns:
                raise NotCompilable(FallbackReason.MATRIX_SELECTOR,
                                    "bare matrix selector", node)
            return Fetch(node, "instant", 1, 1, p.step_ns, self._ctx)
        if isinstance(node, Call):
            return self._lower_call(node)
        if isinstance(node, Aggregation):
            return self._lower_aggregation(node)
        if isinstance(node, BinaryOp):
            return self._lower_binary(node)
        raise NotCompilable(FallbackReason.UNSUPPORTED_NODE,
                            type(node).__name__, node)

    def _func_params(self, f: str, node: Call) -> Tuple[float, ...]:
        if f == "predict_linear":
            return (self._const(node.args[1]),)
        if f == "holt_winters":
            return (self._const(node.args[1]), self._const(node.args[2]))
        if f == "quantile_over_time":
            return (self._const(node.args[0]),)
        return ()

    def _lower_call(self, node: Call) -> PlanNode:
        f = node.func
        if f in RANGE_FUNCS:
            sels = [a for a in node.args
                    if isinstance(a, (VectorSelector, Subquery))]
            if sels and isinstance(sels[-1], Subquery):
                return self._lower_subquery(f, node, sels[-1])
            if not sels:
                raise NotCompilable(FallbackReason.SELECTOR_SHAPE,
                                    f"{f} without a matrix selector", node)
            sel = sels[-1]
            if sel.at_ns is not None:
                raise NotCompilable(FallbackReason.AT_MODIFIER,
                                    f"{f} over @-pinned selector", node)
            if not sel.range_ns:
                raise NotCompilable(FallbackReason.SELECTOR_SHAPE,
                                    f"{f} over an instant selector", node)
            p = self.params
            wgrid = math.gcd(p.step_ns, sel.range_ns)
            W = sel.range_ns // wgrid
            stride = p.step_ns // wgrid
            fetch = Fetch(sel, "range", W, stride, wgrid, self._ctx)
            return RangeFunc(f, fetch, wgrid, sel.range_ns,
                             self._func_params(f, node))
        if f == "timestamp":
            if not node.args:
                raise NotCompilable(FallbackReason.SELECTOR_SHAPE,
                                    "timestamp with no args", node)
            arg = self.lower(node.args[0])
            if arg.edge.kind != SERIES:
                raise NotCompilable(FallbackReason.SELECTOR_SHAPE,
                                    "timestamp over a scalar operand", node)
            return InstantFunc("timestamp", arg)
        if f in MATH_FUNCS:
            if not node.args:
                raise NotCompilable(FallbackReason.SELECTOR_SHAPE,
                                    f"{f} with no args", node)
            arg = self.lower(node.args[0])
            for a in node.args[1:]:
                self._const(a)  # only constant params compile
            extra = tuple(self._slot(a) for a in node.args[1:])
            return InstantFunc(f, arg, extra)
        raise NotCompilable(FallbackReason.UNSUPPORTED_FUNC,
                            f"function {f}", node)

    def _lower_subquery(self, f: str, node: Call, sub: Subquery) -> PlanNode:
        """`f(expr[range:res])`: lower the inner expression on its own
        resolution grid (a fresh Fetch ctx), then wrap it in a
        SubqueryFunc carrying the SAME W/stride window geometry the
        interpreter's _eval_subquery_grid derives — shared-grid when res
        divides the query step, packed-gather otherwise."""
        from .executor import DEFAULT_SUBQUERY_RES_NS, QueryParams

        if f not in SUBQUERY_FUNCS:
            raise NotCompilable(FallbackReason.UNSUPPORTED_FUNC,
                                f"{f} over subquery", node)
        if sub.at_ns is not None:
            raise NotCompilable(FallbackReason.AT_MODIFIER,
                                f"{f} over @-pinned subquery", node)
        p = self.params
        res = sub.step_ns or max(p.step_ns, DEFAULT_SUBQUERY_RES_NS)
        k_min, k_max = subquery_grid(sub.range_ns, res, sub.offset_ns, p)
        inner_params = QueryParams(k_min * res, k_max * res, res)
        x0 = p.start_ns - sub.offset_ns
        if p.step_ns % res == 0 and sub.range_ns >= res:
            W = x0 // res - (x0 - sub.range_ns) // res
            stride = p.step_ns // res
            packed = False
        else:
            W = stride = max(sub.range_ns // res
                             + (1 if sub.range_ns % res else 0), 1)
            packed = True
        self._next_ctx += 1
        outer_params, outer_ctx = self.params, self._ctx
        self.params, self._ctx = inner_params, self._next_ctx
        try:
            arg = self.lower(sub.expr)
        finally:
            self.params, self._ctx = outer_params, outer_ctx
        abs_arg = _abs_space(arg)
        if not isinstance(arg, Fetch) and f in _SUBQ_DIFF_FUNCS and abs_arg:
            # Composite inner at counter magnitudes: the in-trace f32
            # prep would turn consecutive-sample diffs into rounding
            # noise (selector inners stage exact-f64 preps instead).
            raise NotCompilable(
                FallbackReason.F64_ARITH,
                f"{f} differences an absolute-magnitude subquery plane "
                "(f64 granularity)", node)
        if packed and f in ("rate", "increase") and abs_arg:
            # The interpreter's packed layout places each window's first
            # lane after a LATER cell of the previous window, so its
            # counter-reset rule fires with the full absolute value
            # (1e9+) as the adjustment — which then cancels only in the
            # oracle's own f32 accumulation noise. That cancellation is
            # not reproducible faithfully from the exact inner-grid
            # preps, so counter rates over packed-grid subqueries of
            # absolute-magnitude planes stay on the interpreter (delta
            # and the window-local funcs are unaffected).
            raise NotCompilable(
                FallbackReason.F64_ARITH,
                f"{f} over a packed-grid subquery of an "
                "absolute-magnitude plane (f64 granularity)", node)
        return SubqueryFunc(f, arg, W, stride, packed, res, sub.range_ns,
                            sub.offset_ns, k_max - k_min + 1,
                            self._func_params(f, node))

    def _lower_aggregation(self, node: Aggregation) -> PlanNode:
        if node.op in RANK_AGGS:
            return self._lower_rank_agg(node)
        if node.op not in AGG_OPS:
            raise NotCompilable(FallbackReason.UNSUPPORTED_AGG,
                                f"aggregation {node.op}", node)
        arg = self.lower(node.expr)
        if arg.edge.kind != SERIES:
            raise NotCompilable(FallbackReason.AGG_OVER_SCALAR,
                                f"{node.op} over a scalar operand", node)
        exact = isinstance(arg, Fetch) and node.op in ("sum", "avg")
        return Aggregate(node.op, arg, node.grouping, node.without, exact)

    def _lower_rank_agg(self, node: Aggregation) -> PlanNode:
        if node.op in ("topk", "bottomk") and self._depth > 1:
            # topk's output SERIES SET is data-dependent (rows in the k
            # best at any step survive, the rest are dropped): only the
            # root can host-filter rows after materialization; an inner
            # topk would feed phantom all-NaN rows to its consumer.
            raise NotCompilable(FallbackReason.UNSUPPORTED_AGG,
                                f"non-root {node.op}", node)
        if node.param is None:
            raise NotCompilable(FallbackReason.NON_CONSTANT_PARAM,
                                f"{node.op} without a parameter", node)
        p_val = self._const(node.param)  # only constant k/q compile
        if node.op == "quantile" and not 0.0 <= p_val <= 1.0:
            # The interpreter (np.nanquantile) RAISES for q outside
            # [0, 1]; the device sort-select would clip and extrapolate
            # — keep the error behavior by staying interpreted.
            raise NotCompilable(FallbackReason.UNSUPPORTED_AGG,
                                f"quantile parameter {p_val} outside "
                                "[0, 1]", node)
        arg = self.lower(node.expr)
        if arg.edge.kind != SERIES:
            raise NotCompilable(FallbackReason.AGG_OVER_SCALAR,
                                f"{node.op} over a scalar operand", node)
        return RankAgg(node.op, arg, self._slot(node.param),
                       node.grouping, node.without)

    def _lower_binary(self, node: BinaryOp) -> PlanNode:
        if node.op in promql.SET_OPS:
            raise NotCompilable(FallbackReason.SET_OP,
                                f"set op {node.op}", node)
        if node.op not in ARITH_OPS and node.op not in promql.COMPARISON_OPS:
            raise NotCompilable(FallbackReason.F64_ARITH,
                                f"f64-sensitive arithmetic {node.op}", node)
        lhs = self.lower(node.lhs)
        rhs = self.lower(node.rhs)
        if node.op in promql.COMPARISON_OPS and (
                _abs_space(lhs) or _abs_space(rhs)):
            # A comparison FILTERS: flipping one side across the
            # threshold changes which samples EXIST, not a value within
            # tolerance. Absolute selector planes carry raw counter
            # magnitudes (1e9+: f32 ulp 64) where the interpreter's f64
            # compare and an f32 device compare disagree discretely —
            # same f64-granularity reason %/^ stay on the interpreter.
            # Difference-space planes (rate/delta) are f32 in BOTH
            # routes, so those comparisons stay compiled.
            raise NotCompilable(
                FallbackReason.ABS_COMPARISON,
                "comparison over an absolute-magnitude plane (f64 "
                "granularity)", node)
        # group_left/group_right lowers like one-to-one matching: bind()
        # emits one-to-many index maps and the compiled gather replays
        # them — the label-copy columns are bind-time tag algebra.
        swap = bool(node.matching and node.matching.group_right)
        return Binary(node.op, lhs, rhs, node.bool_mode, node.matching,
                      swap)

    @staticmethod
    def _const(node: AstNode) -> float:
        if isinstance(node, NumberLiteral):
            return float(node.value)
        if isinstance(node, Unary) and isinstance(node.expr, NumberLiteral):
            return -node.expr.value
        raise NotCompilable(FallbackReason.NON_CONSTANT_PARAM,
                            "non-constant parameter", node)


def _walk_fetches(node: PlanNode, out: List[Fetch]):
    if isinstance(node, Fetch):
        if node not in out:
            out.append(node)
        return
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, PlanNode):
            _walk_fetches(v, out)
        elif isinstance(v, tuple):
            for item in v:
                if isinstance(item, PlanNode):
                    _walk_fetches(item, out)


def _mesh_ok(node: PlanNode) -> bool:
    """True when no node needs cross-row gathers: vector-vector binaries
    re-align rows by bind-time index maps, and rank aggregations sort
    across their whole group — both need rows a row-partitioned device
    doesn't hold, so those plans compile single-device instead.
    (SubqueryFunc's window extraction is a pure COLUMN operation and
    preserves mesh sharding.)"""
    if isinstance(node, RankAgg):
        return False
    if isinstance(node, Binary):
        if (node.lhs.edge.kind == SERIES and node.rhs.edge.kind == SERIES):
            return False
        return _mesh_ok(node.lhs) and _mesh_ok(node.rhs)
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, PlanNode) and not _mesh_ok(v):
            return False
    return True


# ------------------------------------------------------------------ binding


@dataclasses.dataclass
class BoundFetch:
    fetch: Fetch
    grid: np.ndarray          # [S, ext_T] f64 consolidated grid
    tags: List[Tags]
    W: int
    stride: int
    step_ns: int


@dataclasses.dataclass
class Bound:
    """Host-side query binding: grids, tag algebra, index maps and scalar
    slot values — everything the compiled program consumes as inputs plus
    everything the host needs to assemble the result Block."""

    plan: Plan
    params: object
    fetches: Dict[Fetch, BoundFetch]
    slots: np.ndarray                       # [n_slots] f64 slot values
    node_tags: Dict[int, List[Tags]]        # id(plan node) -> output tags
    aux: Dict[int, dict]                    # id(plan node) -> bind aux data
    total_cells: int
    out_tags: List[Tags]
    out_kind: str                            # SERIES | SCALAR


# Bind-time tag-algebra memo: the host label work (name stripping,
# grouping, vector-match alignment) is a pure function of (plan
# structure, the per-fetch tag LISTS) — and the grid cache hands back the
# SAME list object on every repeat evaluation of an unchanged selector.
# A dashboard burst re-running one query shape pays the O(series) tag
# algebra once, not per refresh (measured 35-60ms/query at 10k series —
# larger than the compiled dispatch it was feeding). Entries pin their
# source lists (strong refs), so an id() can never be recycled while its
# entry lives; the `is` checks make a stale hit structurally impossible.
_BIND_MEMO: "collections.OrderedDict[tuple, tuple]" = (
    collections.OrderedDict())
_BIND_MEMO_LOCK = threading.Lock()
_BIND_MEMO_MAX = int(os.environ.get("M3_TPU_BIND_MEMO", "256"))


def _preorder(node: PlanNode, out: List[PlanNode]) -> List[PlanNode]:
    out.append(node)
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, PlanNode):
            _preorder(v, out)
        elif isinstance(v, tuple):
            for item in v:
                if isinstance(item, PlanNode):
                    _preorder(item, out)
    return out


def subquery_grid(range_ns: int, res: int, offset_ns: int, outer_params
                  ) -> Tuple[int, int]:
    """(k_min, k_max) of the res-aligned inner evaluation grid for a
    subquery under `outer_params` — the ONE derivation shared by the
    lowerer (window geometry), bind (inner QueryParams) and the packed
    column maps, mirroring the interpreter's _eval_subquery_grid."""
    x0 = outer_params.start_ns - offset_ns
    k_min = (x0 - range_ns) // res + 1
    k_max = max((x0 + (outer_params.steps - 1) * outer_params.step_ns)
                // res, k_min)
    return k_min, k_max


def subquery_inner_params(node: SubqueryFunc, outer_params):
    """The inner resolution-grid QueryParams for one SubqueryFunc under
    `outer_params` — recomputed from the node's geometry fields so
    binding needs no side-channel from the lowerer."""
    from .executor import QueryParams

    k_min, k_max = subquery_grid(node.range_ns, node.res_ns,
                                 node.offset_ns, outer_params)
    return QueryParams(k_min * node.res_ns, k_max * node.res_ns,
                       node.res_ns)


def node_params_map(root: PlanNode, params) -> Dict[int, object]:
    """id(plan node) -> the QueryParams of its time-grid context: the
    outer query's for everything outside subqueries, the inner
    resolution grid inside each SubqueryFunc (nested subqueries
    compose)."""
    out: Dict[int, object] = {}

    def walk(node: PlanNode, p):
        out[id(node)] = p
        child_p = (subquery_inner_params(node, p)
                   if isinstance(node, SubqueryFunc) else p)
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, PlanNode):
                walk(v, child_p)
            elif isinstance(v, tuple):
                for item in v:
                    if isinstance(item, PlanNode):
                        walk(item, child_p)

    walk(root, params)
    return out


def _packed_cols(node: SubqueryFunc, outer_params) -> np.ndarray:
    """Bind-time column-index map for a packed subquery window: for each
    output step, the W inner-grid columns of its trailing (T-range, T]
    window, -1 where the lane is outside the window (the interpreter's
    packed-gather geometry, flattened to [steps * W])."""
    res, W = node.res_ns, node.W
    x0 = outer_params.start_ns - node.offset_ns
    k_min, _ = subquery_grid(node.range_ns, res, node.offset_ns,
                             outer_params)
    steps = outer_params.steps
    x = x0 + np.arange(steps, dtype=np.int64) * outer_params.step_ns
    k_end = x // res
    k_start = (x - node.range_ns) // res + 1
    cols = (k_end[:, None] - (W - 1) + np.arange(W)[None, :] - k_min)
    valid = cols >= (k_start - k_min)[:, None]
    return np.where(valid, cols, -1).astype(np.int32).reshape(steps * W)


def bind(plan: Plan, engine, params,
         slot_values: Sequence[float] = ()) -> Bound:
    """Fetch + grid every selector through the engine's cached selector
    paths (grid cache, datapoint charging — identical to the interpreter)
    and run the host tag algebra for every node. Raises QueryError with
    the interpreter's exact semantics for matching violations."""
    from . import executor as ex

    # Per-node time-grid context: fetches under a subquery grid at the
    # inner resolution (the fetch's ctx field keeps them distinct from
    # equal selectors on the outer grid).
    params_of = node_params_map(plan.root, params)

    fetches: Dict[Fetch, BoundFetch] = {}
    total = 0
    for f in plan.fetches:
        fp = params_of[id(f)]
        if f.role == "range":
            blk, W, stride = engine._eval_range_selector(f.sel, fp)
            bf = BoundFetch(f, np.asarray(blk.values, dtype=np.float64),
                            blk.series_tags, W, stride,
                            blk.meta.step_ns)
        else:
            blk = engine._eval_instant_selector(f.sel, fp)
            bf = BoundFetch(f, np.asarray(blk.values, dtype=np.float64),
                            blk.series_tags, 1, 1, blk.meta.step_ns)
        fetches[f] = bf
        total += bf.grid.size

    slots = np.zeros(plan.n_slots, dtype=np.float64)
    for i, v in enumerate(slot_values):
        slots[i] = v

    src_lists = tuple(fetches[f].tags for f in plan.fetches)
    memo_key = (plan.root, tuple(map(id, src_lists)))
    with _BIND_MEMO_LOCK:
        ent = _BIND_MEMO.get(memo_key)
        if ent is not None and all(
                a is b for a, b in zip(ent[0], src_lists)):
            _BIND_MEMO.move_to_end(memo_key)
            _, tags_seq, aux_seq, out_kind = ent
        else:
            ent = None
    if ent is not None:
        nodes = _preorder(plan.root, [])
        node_tags = {id(n): t for n, t in zip(nodes, tags_seq)}
        aux = {id(n): a for n, a in zip(nodes, aux_seq) if a is not None}
        _merge_param_aux(plan, params_of, aux)
        return Bound(plan, params, fetches, slots, node_tags, aux, total,
                     node_tags[id(plan.root)], out_kind)

    node_tags: Dict[int, List[Tags]] = {}
    aux: Dict[int, dict] = {}

    def tags_of(node: PlanNode) -> List[Tags]:
        key = id(node)
        if key in node_tags:
            return node_tags[key]
        if isinstance(node, Fetch):
            out = fetches[node].tags
        elif isinstance(node, RangeFunc):
            base = tags_of(node.arg)
            if node.func == "absent_over_time":
                # One presence row labelled from the selector's equality
                # matchers (functions.go funcAbsentOverTime).
                out = [ex._absent_tags(node.arg.sel)]
            elif node.func == "last_over_time":
                out = list(base)
            else:
                out = [ex._strip_name(t) for t in base]
        elif isinstance(node, SubqueryFunc):
            base = tags_of(node.arg)
            if node.func == "last_over_time":
                out = list(base)
            else:
                out = [ex._strip_name(t) for t in base]
        elif isinstance(node, RankAgg):
            base = tags_of(node.arg)
            gids, gtags = ex._group_series(base, node.grouping,
                                           node.without)
            smax = (int(np.bincount(
                gids, minlength=max(len(gtags), 1)).max())
                if len(base) else 0)
            aux[id(node)] = {"group_ids": gids.astype(np.int32),
                             "n_groups": len(gtags), "smax": smax}
            # quantile collapses to group rows; topk/bottomk keep the
            # argument's rows (the data-dependent subset is filtered on
            # the host after materialization — root-only by lowering).
            out = gtags if node.op == "quantile" else list(base)
        elif isinstance(node, InstantFunc):
            base = tags_of(node.arg)
            if node.func == "neg":
                out = list(base)
            else:
                out = [ex._strip_name(t) for t in base]
        elif isinstance(node, Aggregate):
            base = tags_of(node.arg)
            gids, gtags = ex._group_series(base, node.grouping, node.without)
            aux[id(node)] = {"group_ids": gids.astype(np.int32),
                             "n_groups": len(gtags)}
            out = gtags
        elif isinstance(node, Binary):
            out = _bind_binary(node, tags_of, aux)
        elif isinstance(node, ScalarConst):
            out = []
        else:  # pragma: no cover
            raise ex.QueryError(f"unbound plan node {type(node).__name__}")
        node_tags[key] = out
        return out

    def _bind_binary(node: Binary, tags_of, aux) -> List[Tags]:
        le, re_ = node.lhs.edge, node.rhs.edge
        comparison = node.op in promql.COMPARISON_OPS
        if le.kind == SCALAR and re_.kind == SCALAR:
            tags_of(node.lhs), tags_of(node.rhs)
            return []
        if le.kind == SERIES and re_.kind == SERIES:
            ltags, rtags = tags_of(node.lhs), tags_of(node.rhs)
            matching = node.matching
            many_side_right = bool(matching and matching.group_right)
            if many_side_right:
                many_tags, one_tags, swap = rtags, ltags, True
            else:
                many_tags, one_tags, swap = ltags, rtags, False
            one_map: Dict[bytes, int] = {}
            for j, t in enumerate(one_tags):
                k = ex._match_key(t, matching)
                if k in one_map:
                    raise ex.QueryError(
                        "many-to-many vector matching: duplicate series on "
                        f"the 'one' side for key {k!r}")
                one_map[k] = j
            many_idx: List[int] = []
            one_idx: List[int] = []
            out_tags: List[Tags] = []
            seen: Dict[bytes, int] = {}
            # Duplicate result labels only raise for one-to-one matching
            # (the interpreter's _vector_vector rule): group_left/right
            # legitimately map many rows onto one match key.
            one_to_one = not (matching and (matching.group_left
                                            or matching.group_right))
            for i, t in enumerate(many_tags):
                j = one_map.get(ex._match_key(t, matching))
                if j is None:
                    continue
                rt = ex._result_tags(t, one_tags[j], matching, comparison,
                                     node.bool_mode)
                k = rt.id()
                if one_to_one and k in seen:
                    raise ex.QueryError(
                        "multiple matches for the same result labels")
                seen[k] = i
                many_idx.append(i)
                one_idx.append(j)
                out_tags.append(rt)
            aux[id(node)] = {
                "many_idx": np.asarray(many_idx, dtype=np.int32),
                "one_idx": np.asarray(one_idx, dtype=np.int32),
                "swap": swap,
            }
            return out_tags
        # vector <op> scalar (either side)
        vec = node.lhs if le.kind == SERIES else node.rhs
        tags_of(node.lhs), tags_of(node.rhs)
        base = node_tags[id(vec)]
        if comparison and not node.bool_mode:
            return list(base)
        return [ex._strip_name(t) for t in base]

    out_tags = tags_of(plan.root)
    nodes = _preorder(plan.root, [])
    # .get: InstantFunc's ScalarConst params are preorder nodes the tag
    # walk never visits (they carry no series) — store them as empty.
    tags_seq = tuple(node_tags.get(id(n), []) for n in nodes)
    aux_seq = tuple(aux.get(id(n)) for n in nodes)
    with _BIND_MEMO_LOCK:
        _BIND_MEMO[memo_key] = (src_lists, tags_seq, aux_seq,
                                plan.root.edge.kind)
        while len(_BIND_MEMO) > _BIND_MEMO_MAX:
            _BIND_MEMO.popitem(last=False)
    _merge_param_aux(plan, params_of, aux)
    return Bound(plan, params, fetches, slots, node_tags, aux, total,
                 out_tags, plan.root.edge.kind)


def _merge_param_aux(plan: Plan, params_of: Dict[int, object],
                     aux: Dict[int, dict]) -> None:
    """Params-DEPENDENT aux entries, recomputed on every bind (never
    memoized — the bind memo is keyed on plan structure + tag lists, and
    a sliding dashboard window changes these while hitting it): packed
    subquery column maps and timestamp() step-time vectors."""
    for n in _preorder(plan.root, []):
        if isinstance(n, SubqueryFunc) and n.packed:
            aux.setdefault(id(n), {})["cols"] = _packed_cols(
                n, params_of[id(n)])
        elif isinstance(n, InstantFunc) and n.func == "timestamp":
            p = params_of[id(n)]
            aux.setdefault(id(n), {})["times"] = (
                p.meta().times() / 1e9)


def lower_and_collect(ast: AstNode, params, lookback_ns: int
                      ) -> Tuple[Optional[Plan], Optional[NotCompilable],
                                 List[float]]:
    """AST -> physical plan (or (None, NotCompilable, []) when any node
    falls outside the compiled surface — the error carries the typed
    FallbackReason plus the AST node that raised) plus the scalar slot
    VALUES (in slot order) for binding."""
    lw = _Lowerer(params, lookback_ns)
    try:
        root = lw.lower(ast)
    except NotCompilable as e:
        return None, e, []
    fetches: List[Fetch] = []
    _walk_fetches(root, fetches)
    if not fetches:
        return None, NotCompilable(FallbackReason.SCALAR_ONLY,
                                   "scalar-only expression", ast), []
    values = []
    for node in lw.slots:
        if isinstance(node, NumberLiteral):
            values.append(float(node.value))
        elif isinstance(node, Unary) and isinstance(node.expr, NumberLiteral):
            values.append(-node.expr.value)
        else:  # unreachable: _slot only records constants
            return None, NotCompilable(FallbackReason.NON_CONSTANT_PARAM,
                                       "non-constant slot", node), []
    root = _demote_exact(root, is_root=True)
    fetches = []
    _walk_fetches(root, fetches)
    plan = Plan(root, params.steps, len(lw.slots), tuple(fetches),
                _mesh_ok(root))
    return plan, None, values


def _demote_exact(node: PlanNode, is_root: bool) -> PlanNode:
    """The exact counter-sum path finishes on the HOST (f64 baseline
    mass), so only the ROOT aggregate may carry it; inner aggregates
    collapse on device in f32 (documented divergence, same tolerance as
    the pre-existing sharded-agg fast path)."""
    if isinstance(node, Aggregate):
        arg = _demote_exact(node.arg, False)
        return Aggregate(node.op, arg, node.grouping, node.without,
                         node.exact and is_root)
    if isinstance(node, RangeFunc) or isinstance(node, Fetch) \
            or isinstance(node, ScalarConst):
        return node
    if isinstance(node, SubqueryFunc):
        return dataclasses.replace(node,
                                   arg=_demote_exact(node.arg, False))
    if isinstance(node, RankAgg):
        return dataclasses.replace(node,
                                   arg=_demote_exact(node.arg, False))
    if isinstance(node, InstantFunc):
        return InstantFunc(node.func, _demote_exact(node.arg, False),
                           node.params)
    if isinstance(node, Binary):
        return Binary(node.op, _demote_exact(node.lhs, False),
                      _demote_exact(node.rhs, False), node.bool_mode,
                      node.matching, node.swap)
    return node


# -------------------------------------------------------------- compile key


def strip(node: PlanNode, fetch_index: Dict[Fetch, int]) -> PlanNode:
    """The compile-key projection of a plan node: selectors (label
    matchers, offsets) do not change the traced program, so Fetch nodes
    keep only their physical geometry plus a positional identity (so two
    DIFFERENT selectors with the same geometry stay distinct inputs while
    one executable still serves every metric with the plan shape);
    grouping labels and matching labels are bind-only and drop out."""
    if isinstance(node, Fetch):
        idx = fetch_index[node]
        return Fetch(VectorSelector(b"%d" % idx), node.role, node.W,
                     node.stride, node.wgrid_ns)
    if isinstance(node, RangeFunc):
        return RangeFunc(node.func, strip(node.arg, fetch_index),
                         node.step_ns, node.range_ns, node.params)
    if isinstance(node, SubqueryFunc):
        # offset/inner length are bind-time data: the traced program
        # depends only on the window geometry (inner widths ride the
        # Geometry bucket, packed column maps are aux inputs).
        return SubqueryFunc(node.func, strip(node.arg, fetch_index),
                            node.W, node.stride, node.packed, node.res_ns,
                            node.range_ns, 0, 0, node.params)
    if isinstance(node, RankAgg):
        return RankAgg(node.op, strip(node.arg, fetch_index), node.param,
                       (), node.without)
    if isinstance(node, InstantFunc):
        return InstantFunc(node.func, strip(node.arg, fetch_index),
                           node.params)
    if isinstance(node, Aggregate):
        return Aggregate(node.op, strip(node.arg, fetch_index), (),
                         node.without, node.exact)
    if isinstance(node, Binary):
        return Binary(node.op, strip(node.lhs, fetch_index),
                      strip(node.rhs, fetch_index), node.bool_mode, None,
                      node.swap)
    return node


def next_bucket(n: int) -> int:
    """Quarter-octave shape bucket: the smallest of {1, 1.25, 1.5, 1.75}
    * 2^k >= n. Pure pow2 buckets waste up to 2x compute on the padded
    lanes (10000 rows -> 16384); the quarter-octave grid caps the waste
    at 14% for four executables per octave — the right trade for the
    plan cache, whose entries are whole fused programs serving many
    queries each."""
    if n <= 3:
        return max(1, n)
    p = 1 << (int(n - 1).bit_length())      # pow2 >= n
    half = p >> 1
    for frac in (5, 6, 7):                   # 1.25, 1.5, 1.75 * (p/2)
        cand = (half * frac) >> 2
        if cand >= n:
            return cand
    return p
