"""Block model: the unit of query dataflow (reference: src/query/block/
{types,column,series}.go — a Block is a (series x time-step) matrix viewable
by column or by series).

TPU-first redesign: the reference streams per-step column iterators between
transform goroutines; here a Block literally IS the dense [n_series, n_steps]
float32 matrix (NaN = no sample), so every transform is one batched device
op over the whole block instead of a per-step iterator hop. Series metadata
(tags) stays host-side alongside the matrix."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .model import Tags

NAN = np.nan


@dataclasses.dataclass(frozen=True)
class BlockMeta:
    """Time bounds of a block (block/types.go Metadata/Bounds): steps at
    start_ns, start_ns+step_ns, ..., count steps."""

    start_ns: int
    step_ns: int
    steps: int

    def step_time(self, i: int) -> int:
        return self.start_ns + i * self.step_ns

    def times(self) -> np.ndarray:
        return self.start_ns + self.step_ns * np.arange(self.steps, dtype=np.int64)

    @property
    def end_ns(self) -> int:
        """Exclusive end."""
        return self.start_ns + self.step_ns * self.steps


@dataclasses.dataclass
class Block:
    meta: BlockMeta
    series_tags: List[Tags]
    values: np.ndarray  # [n_series, steps] float, NaN = missing

    def __post_init__(self):
        assert self.values.ndim == 2
        assert self.values.shape == (len(self.series_tags), self.meta.steps), (
            self.values.shape, len(self.series_tags), self.meta.steps)

    @property
    def n_series(self) -> int:
        return len(self.series_tags)

    def with_values(self, values: np.ndarray, tags: Optional[List[Tags]] = None,
                    meta: Optional[BlockMeta] = None) -> "Block":
        return Block(meta or self.meta, tags if tags is not None else self.series_tags,
                     np.asarray(values))

    @staticmethod
    def empty(meta: BlockMeta) -> "Block":
        return Block(meta, [], np.zeros((0, meta.steps)))


def consolidate(timestamps: np.ndarray, values: np.ndarray, meta: BlockMeta,
                lookback_ns: int) -> np.ndarray:
    """Consolidate one series' raw datapoints onto the block's step grid:
    value at step time t = the latest sample in (t - lookback, t]
    (reference: src/query/ts/values.go consolidation + the Prometheus
    lookback-delta instant-vector rule its engine follows). Vectorized via
    searchsorted; returns [steps] with NaN where no sample qualifies."""
    out = np.full(meta.steps, NAN)
    if timestamps.size == 0:
        return out
    order = np.argsort(timestamps, kind="stable")
    ts = timestamps[order]
    vs = values[order]
    step_times = meta.times()
    idx = np.searchsorted(ts, step_times, side="right") - 1
    ok = idx >= 0
    safe = np.clip(idx, 0, ts.size - 1)
    age_ok = (step_times - ts[safe]) < lookback_ns
    take = ok & age_ok
    out[take] = vs[safe[take]]
    return out


def block_from_series(series: Dict[bytes, dict], meta: BlockMeta,
                      lookback_ns: int) -> Block:
    """Assemble a Block from a client fetch_tagged result
    ({id: {tags, t, v}}), consolidating every series onto the step grid."""
    tags_list: List[Tags] = []
    rows = np.full((len(series), meta.steps), NAN)
    for i, (sid, entry) in enumerate(sorted(series.items())):
        tags_list.append(Tags.of(dict(entry["tags"])))
        rows[i] = consolidate(
            np.asarray(entry["t"], dtype=np.int64),
            np.asarray(entry["v"], dtype=np.float64),
            meta, lookback_ns)
    return Block(meta, tags_list, rows)
