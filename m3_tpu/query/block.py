"""Block model: the unit of query dataflow (reference: src/query/block/
{types,column,series}.go — a Block is a (series x time-step) matrix viewable
by column or by series).

TPU-first redesign: the reference streams per-step column iterators between
transform goroutines; here a Block literally IS the dense [n_series, n_steps]
float32 matrix (NaN = no sample), so every transform is one batched device
op over the whole block instead of a per-step iterator hop. Series metadata
(tags) stays host-side alongside the matrix."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .model import Tags

NAN = np.nan


@dataclasses.dataclass(frozen=True)
class BlockMeta:
    """Time bounds of a block (block/types.go Metadata/Bounds): steps at
    start_ns, start_ns+step_ns, ..., count steps."""

    start_ns: int
    step_ns: int
    steps: int

    def step_time(self, i: int) -> int:
        return self.start_ns + i * self.step_ns

    def times(self) -> np.ndarray:
        return self.start_ns + self.step_ns * np.arange(self.steps, dtype=np.int64)

    @property
    def end_ns(self) -> int:
        """Exclusive end."""
        return self.start_ns + self.step_ns * self.steps


@dataclasses.dataclass
class Block:
    meta: BlockMeta
    series_tags: List[Tags]
    values: np.ndarray  # [n_series, steps] float, NaN = missing

    def __post_init__(self):
        assert self.values.ndim == 2
        assert self.values.shape == (len(self.series_tags), self.meta.steps), (
            self.values.shape, len(self.series_tags), self.meta.steps)

    @property
    def n_series(self) -> int:
        return len(self.series_tags)

    def with_values(self, values: np.ndarray, tags: Optional[List[Tags]] = None,
                    meta: Optional[BlockMeta] = None) -> "Block":
        return Block(meta or self.meta, tags if tags is not None else self.series_tags,
                     np.asarray(values))

    @staticmethod
    def empty(meta: BlockMeta) -> "Block":
        return Block(meta, [], np.zeros((0, meta.steps)))


def _grid_snap(sorted_ts: np.ndarray, step_times: np.ndarray,
               lookback_ns: int) -> Tuple[np.ndarray, np.ndarray]:
    """Grid-snap rule shared by every consolidation path: for each step time
    t, pick the latest sample in (t - lookback, t]. Returns (take, src):
    step positions that receive a value and the sorted-sample index each
    reads from."""
    idx = np.searchsorted(sorted_ts, step_times, side="right") - 1
    safe = np.clip(idx, 0, sorted_ts.size - 1)
    take = (idx >= 0) & ((step_times - sorted_ts[safe]) < lookback_ns)
    return take, safe


def consolidate(timestamps: np.ndarray, values: np.ndarray, meta: BlockMeta,
                lookback_ns: int) -> np.ndarray:
    """Consolidate one series' raw datapoints onto the block's step grid:
    value at step time t = the latest sample in (t - lookback, t]
    (reference: src/query/ts/values.go consolidation + the Prometheus
    lookback-delta instant-vector rule its engine follows). Vectorized via
    searchsorted; returns [steps] with NaN where no sample qualifies."""
    out = np.full(meta.steps, NAN)
    if timestamps.size == 0:
        return out
    order = np.argsort(timestamps, kind="stable")
    ts = timestamps[order]
    vs = values[order]
    take, safe = _grid_snap(ts, meta.times(), lookback_ns)
    out[take] = vs[safe[take]]
    return out


def consolidate_series(series: Dict[bytes, dict], meta: BlockMeta,
                       lookback_ns: int) -> Tuple[List[Tags], np.ndarray]:
    """Consolidate a fetch result ({id: {tags, t, v}}) onto the step grid.

    Series sharing an identical timestamp grid (the scrape-aligned common
    case) are consolidated as one vectorized batch: argsort/searchsorted run
    once per distinct grid instead of once per series, which is what makes
    10k-series range queries host-cheap.
    """
    items = sorted(series.items())
    tags_list = [Tags.of(dict(entry["tags"])) for _, entry in items]
    rows = np.full((len(items), meta.steps), NAN)
    groups: Dict[tuple, List[int]] = {}
    ts_arrays = []
    for i, (_, entry) in enumerate(items):
        t = np.asarray(entry["t"], dtype=np.int64)
        ts_arrays.append(t)
        key = (t.size, int(t[0]) if t.size else 0, int(t[-1]) if t.size else 0)
        groups.setdefault(key, []).append(i)
    step_times = meta.times()
    for idxs in groups.values():
        rep = ts_arrays[idxs[0]]
        same = [i for i in idxs if ts_arrays[i] is rep
                or np.array_equal(ts_arrays[i], rep)]
        for i in set(idxs) - set(same):  # rare: key collision, per-series path
            rows[i] = consolidate(
                ts_arrays[i], np.asarray(items[i][1]["v"], np.float64),
                meta, lookback_ns)
        if rep.size == 0:
            continue
        order = np.argsort(rep, kind="stable")
        take, safe = _grid_snap(rep[order], step_times, lookback_ns)
        vs = np.stack([np.asarray(items[i][1]["v"], np.float64) for i in same])
        vs = vs[:, order]
        cols = np.nonzero(take)[0]
        rows[np.ix_(same, cols)] = vs[:, safe[cols]]
    return tags_list, rows


def block_from_series(series: Dict[bytes, dict], meta: BlockMeta,
                      lookback_ns: int) -> Block:
    """Assemble a Block from a client fetch_tagged result
    ({id: {tags, t, v}}), consolidating every series onto the step grid."""
    tags_list, rows = consolidate_series(series, meta, lookback_ns)
    return Block(meta, tags_list, rows)
