"""Block model: the unit of query dataflow (reference: src/query/block/
{types,column,series}.go — a Block is a (series x time-step) matrix viewable
by column or by series).

TPU-first redesign: the reference streams per-step column iterators between
transform goroutines; here a Block literally IS the dense [n_series, n_steps]
float32 matrix (NaN = no sample), so every transform is one batched device
op over the whole block instead of a per-step iterator hop. Series metadata
(tags) stays host-side alongside the matrix."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .model import Tags

NAN = np.nan


@dataclasses.dataclass(frozen=True)
class BlockMeta:
    """Time bounds of a block (block/types.go Metadata/Bounds): steps at
    start_ns, start_ns+step_ns, ..., count steps."""

    start_ns: int
    step_ns: int
    steps: int

    def step_time(self, i: int) -> int:
        return self.start_ns + i * self.step_ns

    def times(self) -> np.ndarray:
        return self.start_ns + self.step_ns * np.arange(self.steps, dtype=np.int64)

    @property
    def end_ns(self) -> int:
        """Exclusive end."""
        return self.start_ns + self.step_ns * self.steps


@dataclasses.dataclass
class Block:
    meta: BlockMeta
    series_tags: List[Tags]
    values: np.ndarray  # [n_series, steps] float, NaN = missing

    def __post_init__(self):
        assert self.values.ndim == 2
        assert self.values.shape == (len(self.series_tags), self.meta.steps), (
            self.values.shape, len(self.series_tags), self.meta.steps)

    @property
    def n_series(self) -> int:
        return len(self.series_tags)

    def with_values(self, values: np.ndarray, tags: Optional[List[Tags]] = None,
                    meta: Optional[BlockMeta] = None) -> "Block":
        return Block(meta or self.meta, tags if tags is not None else self.series_tags,
                     np.asarray(values))

    @staticmethod
    def empty(meta: BlockMeta) -> "Block":
        return Block(meta, [], np.zeros((0, meta.steps)))


class LazyBlock(Block):
    """Block whose values materialize on first access.

    The device->host result copy is started asynchronously at construction
    (ops/temporal.py _copy_async), so any host work done before `.values`
    is touched — parsing/fetching/gridding the NEXT query of a dashboard
    burst — overlaps the transfer instead of serializing behind it. On a
    remote-tunnel accelerator the result D2H is the per-query floor, which
    makes this the double-buffering lever for BASELINE config #3."""

    def __init__(self, meta: BlockMeta, series_tags: List[Tags], fetch):
        # No super().__init__: values don't exist yet, so the dataclass
        # shape assert runs at materialization instead.
        self.meta = meta
        self.series_tags = series_tags
        self._fetch = fetch
        self._cache: Optional[np.ndarray] = None

    @property
    def values(self) -> np.ndarray:  # type: ignore[override]
        if self._cache is None:
            vals = np.asarray(self._fetch())
            assert vals.shape == (len(self.series_tags), self.meta.steps), (
                vals.shape, len(self.series_tags), self.meta.steps)
            self._cache = vals
            self._fetch = None
        return self._cache

    @values.setter
    def values(self, vals: np.ndarray):
        self._cache = np.asarray(vals)
        self._fetch = None


def _grid_snap(sorted_ts: np.ndarray, step_times: np.ndarray,
               lookback_ns: int) -> Tuple[np.ndarray, np.ndarray]:
    """Grid-snap rule shared by every consolidation path: for each step time
    t, pick the latest sample in (t - lookback, t]. Returns (take, src):
    step positions that receive a value and the sorted-sample index each
    reads from."""
    idx = np.searchsorted(sorted_ts, step_times, side="right") - 1
    safe = np.clip(idx, 0, sorted_ts.size - 1)
    take = (idx >= 0) & ((step_times - sorted_ts[safe]) < lookback_ns)
    return take, safe


def consolidate(timestamps: np.ndarray, values: np.ndarray, meta: BlockMeta,
                lookback_ns: int) -> np.ndarray:
    """Consolidate one series' raw datapoints onto the block's step grid:
    value at step time t = the latest sample in (t - lookback, t]
    (reference: src/query/ts/values.go consolidation + the Prometheus
    lookback-delta instant-vector rule its engine follows). Vectorized via
    searchsorted; returns [steps] with NaN where no sample qualifies."""
    out = np.full(meta.steps, NAN)
    if timestamps.size == 0:
        return out
    order = np.argsort(timestamps, kind="stable")
    ts = timestamps[order]
    vs = values[order]
    take, safe = _grid_snap(ts, meta.times(), lookback_ns)
    out[take] = vs[safe[take]]
    return out


def _entry_tags(entry: dict) -> Tags:
    """Tags object for a fetch-result entry, memoized INTO the entry —
    storages that serve the same entry dicts across queries (hot-block
    serving, dashboard bursts) pay tag interning once, not per query.
    Keyed on the tags object's identity so a later reassignment of
    entry["tags"] (e.g. FanoutStorage's cross-store merge) invalidates
    the memo instead of serving stale labels."""
    raw = entry["tags"]
    cached = entry.get("_tags")
    if cached is None or cached[0] is not raw:
        cached = (raw, Tags.of(dict(raw)))
        entry["_tags"] = cached
    return cached[1]


def consolidate_series(series: Dict[bytes, dict], meta: BlockMeta,
                       lookback_ns: int) -> Tuple[List[Tags], np.ndarray]:
    """Consolidate a fetch result ({id: {tags, t, v}}) onto the step grid.

    Series sharing an identical timestamp grid (the scrape-aligned common
    case) are consolidated as one vectorized batch: argsort/searchsorted run
    once per distinct grid instead of once per series, which is what makes
    10k-series range queries host-cheap. Grids are grouped by array object
    IDENTITY first (series from one storage batch share one grid object —
    zero per-series work), then by a cheap content key verified with
    array_equal.
    """
    items = sorted(series.items())
    tags_list = [_entry_tags(entry) for _, entry in items]
    rows: Optional[np.ndarray] = None  # lazy: fast path below skips it
    id_groups: Dict[int, List[int]] = {}
    raw_ts = []
    for i, (_, entry) in enumerate(items):
        t = entry["t"]
        raw_ts.append(t)
        id_groups.setdefault(id(t), []).append(i)
    # Singleton identity groups (distinct array objects) coalesce by
    # content key + array_equal check; shared-object groups skip both.
    groups: List[List[int]] = []
    by_key: Dict[tuple, List[List[int]]] = {}
    ts_arrays: List[Optional[np.ndarray]] = [None] * len(items)
    for idxs in id_groups.values():
        t = np.asarray(raw_ts[idxs[0]], dtype=np.int64)
        for i in idxs:
            ts_arrays[i] = t
        if len(idxs) > 1:
            groups.append(idxs)
            continue
        key = (t.size, int(t[0]) if t.size else 0,
               int(t[-1]) if t.size else 0)
        merged = False
        for g in by_key.setdefault(key, []):
            if np.array_equal(ts_arrays[g[0]], t):
                g.extend(idxs)
                merged = True
                break
        if not merged:
            by_key[key].append(idxs)
    for gl in by_key.values():
        groups.extend(gl)
    step_times = meta.times()
    for same in groups:
        rep = ts_arrays[same[0]]
        if rep.size == 0:
            continue
        # Skip the argsort for already-sorted grids (the storage layers
        # emit sorted timestamps) and fuse sort-order + grid-snap into ONE
        # gather — at 10k x 360 each avoided intermediate is a ~30MB copy.
        if rep.size > 1 and not (rep[1:] >= rep[:-1]).all():
            order = np.argsort(rep, kind="stable")
            sorted_rep = rep[order]
        else:
            order = None
            sorted_rep = rep
        take, safe = _grid_snap(sorted_rep, step_times, lookback_ns)
        vs = np.stack([np.asarray(items[i][1]["v"], np.float64) for i in same])
        cols = np.nonzero(take)[0]
        src = safe[cols] if order is None else order[safe[cols]]
        if (rows is None and len(groups) == 1 and cols.size == meta.steps
                and len(same) == len(items)):
            # ONE shared grid covering every step (the hot dashboard
            # shape): the gather IS the result — no NaN canvas, no fancy
            # double-index write (each a full-matrix pass at 10k series).
            return tags_list, vs[:, src]
        if rows is None:
            rows = np.full((len(items), meta.steps), NAN)
        rows[np.ix_(same, cols)] = vs[:, src]
    if rows is None:
        rows = np.full((len(items), meta.steps), NAN)
    return tags_list, rows


def block_from_series(series: Dict[bytes, dict], meta: BlockMeta,
                      lookback_ns: int) -> Block:
    """Assemble a Block from a client fetch_tagged result
    ({id: {tags, t, v}}), consolidating every series onto the step grid."""
    tags_list, rows = consolidate_series(series, meta, lookback_ns)
    return Block(meta, tags_list, rows)
