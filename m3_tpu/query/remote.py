"""Coordinator-to-coordinator federation: remote query storage over the
framed wire (reference: src/query/tsdb/remote/{client,server}.go + the
rpcpb protobuf service — a coordinator exposes its storage so sibling
coordinators can fan out fetches across clusters/regions).

The reference speaks gRPC; this build rides the same framed binary codec
as the node RPC (m3_tpu.rpc.wire) so fetched columns stay numpy end to
end."""

from __future__ import annotations

import socketserver
import threading
from typing import Dict, Optional, Sequence

import numpy as np

from ..rpc import wire
from ..utils.retry import (
    Breaker,
    BreakerOpen,
    Deadline,
    DeadlineExceeded,
    Retrier,
    RetryOptions,
    default_is_retryable,
)
from .model import Matcher, MatchType


def _matchers_to_wire(matchers: Sequence[Matcher]) -> list:
    return [{"t": int(m.type), "n": m.name, "v": m.value} for m in matchers]


def _matchers_from_wire(obj: list):
    return tuple(Matcher(MatchType(d["t"]), d["n"], d["v"]) for d in obj)


class RemoteStorageServer:
    """Serves fetch_raw over TCP (tsdb/remote/server.go)."""

    def __init__(self, storage, host: str = "127.0.0.1", port: int = 0):
        self.storage = storage
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        req = wire.read_dict_frame(self.request)
                        try:
                            # Per-request deadline: a federated fetch whose
                            # caller stopped waiting must not run to
                            # completion against local storage.
                            deadline = wire.deadline_from_frame(req)
                            if deadline is not None:
                                deadline.check(str(req.get("method")))
                            resp = outer._dispatch(req)
                        except DeadlineExceeded as e:
                            resp = {"err": str(e), "kind": "deadline"}
                        except Exception as e:  # noqa: BLE001
                            resp = {"err": str(e)}
                        wire.write_frame(self.request, resp)
                except (ConnectionError, OSError, ValueError):
                    # ValueError = malformed frame: stream desync, drop conn
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)

    def _dispatch(self, req: dict) -> dict:
        if req["method"] == "fetch_raw":
            series = self.storage.fetch_raw(
                _matchers_from_wire(req["matchers"]), req["start"], req["end"])
            # Columnar result frame: ids/tags sidecar + ONE pair of
            # concatenated (t, v) columns with an offsets vector —
            # instead of one dict of arrays per series. The ragged
            # per-series runs survive as offset slices; the client
            # rebuilds zero-copy views.
            ids, tags, ts, vs = [], [], [], []
            for sid, entry in series.items():
                ids.append(sid)
                tags.append(entry["tags"])
                ts.append(np.asarray(entry["t"], np.int64))
                vs.append(np.asarray(entry["v"], np.float64))
            offs = np.zeros(len(ids) + 1, np.int64)
            if ids:
                offs[1:] = np.cumsum([t.size for t in ts])
            return {"ids": ids, "tags": tags, "offs": offs,
                    "t": (np.concatenate(ts) if ids
                          else np.zeros(0, np.int64)),
                    "v": (np.concatenate(vs) if ids
                          else np.zeros(0, np.float64))}
        if req["method"] == "write":
            self.storage.write(req["id"], req["tags"], req["time"], req["value"])
            return {"ok": True}
        raise ValueError(f"unknown method {req['method']!r}")

    @property
    def endpoint(self) -> str:
        h, p = self._server.server_address
        return f"{h}:{p}"

    def start(self) -> "RemoteStorageServer":
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    def close(self):
        self._server.shutdown()
        self._server.server_close()


class RemoteStorage:
    """Client side: a query-storage implementation backed by a remote
    coordinator (tsdb/remote/client.go); drop it into FanoutStorage next
    to local stores for cross-cluster reads."""

    def __init__(self, endpoint: str, timeout_s: float = 10.0,
                 retry_opts: Optional[RetryOptions] = None,
                 breaker: Optional[Breaker] = None):
        self._endpoint = endpoint
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._sock = None
        # Desync (ValueError) IS retryable here — unlike mid-stream
        # protocol users — because _exchange drops the connection first,
        # so the re-attempt runs on a fresh stream; this storage's writes
        # are idempotent, so re-sending a maybe-applied request is safe.
        self._retrier = Retrier(
            retry_opts if retry_opts is not None
            else RetryOptions(max_attempts=2, initial_backoff_s=0.05),
            is_retryable=lambda e: (isinstance(e, ValueError)
                                    or default_is_retryable(e)))
        self._breaker = breaker if breaker is not None else Breaker(
            name=endpoint)

    def _call(self, req: dict, deadline: Optional[Deadline] = None) -> dict:
        resp = self._retrier.attempt(self._exchange, req, deadline,
                                     deadline=deadline)
        if "err" in resp:
            if resp.get("kind") == "deadline":
                raise DeadlineExceeded(resp["err"])
            raise RuntimeError(f"remote storage error: {resp['err']}")
        return resp

    def _exchange(self, req: dict, deadline: Optional[Deadline]) -> dict:
        """One serialized request/response exchange; transport errors are
        surfaced typed so the retrier classifies them (a malformed reply
        stays a ValueError — desync, NOT retryable on this stream, but the
        connection is dropped so the next attempt starts clean)."""
        if not self._breaker.allow():
            raise BreakerOpen(f"remote storage {self._endpoint} shed")
        # From here EVERY exit must settle the allow() grant, or a granted
        # half-open probe slot leaks and the breaker wedges half-open.
        try:
            resp = self._exchange_locked(req, deadline)
        except DeadlineExceeded:
            # Always pre-I/O here (the budget died waiting on the LOCAL
            # serialized-exchange lock — endpoint-side expiry surfaces as
            # a socket timeout/OSError instead): release the grant but
            # don't blame a host we never reached.
            self._breaker.cancel()
            raise
        except BaseException:
            self._breaker.record_failure()
            raise
        self._breaker.record_success()
        return resp

    def _exchange_locked(self, req: dict, deadline: Optional[Deadline]) -> dict:
        with self._lock:
            try:
                if deadline is not None:
                    deadline.check("remote storage")
                # connect phase capped by the remaining budget as well
                sock = self._ensure_conn(
                    None if deadline is None
                    else deadline.min_timeout(self._timeout_s))
                if deadline is not None:
                    req = dict(req)
                    req[wire.DEADLINE_KEY] = deadline.to_wire()
                    sock.settimeout(deadline.min_timeout(self._timeout_s))
                else:
                    sock.settimeout(self._timeout_s)
                wire.write_frame(sock, req)  # m3lint: disable=lock-held-blocking-call
                return wire.read_dict_frame(sock)  # m3lint: disable=lock-held-blocking-call
            except (OSError, ValueError, ConnectionError):
                # OSError covers socket.timeout; either way the stream may
                # carry a late reply — unusable for the next exchange.
                self._drop_conn()
                raise

    def fetch_raw(self, matchers: Sequence[Matcher], start_ns: int,
                  end_ns: int, deadline: Optional[Deadline] = None
                  ) -> Dict[bytes, dict]:
        resp = self._call({"method": "fetch_raw",
                           "matchers": _matchers_to_wire(matchers),
                           "start": start_ns, "end": end_ns}, deadline)
        offs, t, v = resp["offs"], resp["t"], resp["v"]
        # Offset-sliced VIEWS of the two wire columns — no per-series
        # array copies on the federation read path.
        return {
            sid: {"tags": tags, "t": t[offs[i]:offs[i + 1]],
                  "v": v[offs[i]:offs[i + 1]]}
            for i, (sid, tags) in enumerate(zip(resp["ids"], resp["tags"]))
        }

    def write(self, series_id: bytes, tags, t_ns: int, value: float,
              deadline: Optional[Deadline] = None):
        """Datapoint writes are idempotent (replica merge dedups on
        timestamp), so the retrier may safely re-send one that failed
        mid-exchange — unlike the KV store's mutations."""
        self._call({"method": "write", "id": series_id, "tags": dict(tags),
                    "time": t_ns, "value": value}, deadline)

    def _ensure_conn(self, connect_timeout: Optional[float] = None):
        if self._sock is None:
            import socket as _socket

            host, _, port = self._endpoint.rpartition(":")
            self._sock = _socket.create_connection(
                (host, int(port)),
                timeout=self._timeout_s if connect_timeout is None
                else connect_timeout)
            self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        return self._sock

    def _drop_conn(self):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        self._drop_conn()
