"""Coordinator-to-coordinator federation: remote query storage over the
framed wire (reference: src/query/tsdb/remote/{client,server}.go + the
rpcpb protobuf service — a coordinator exposes its storage so sibling
coordinators can fan out fetches across clusters/regions).

The reference speaks gRPC; this build rides the same framed binary codec
as the node RPC (m3_tpu.rpc.wire) so fetched columns stay numpy end to
end."""

from __future__ import annotations

import socketserver
import threading
from typing import Dict, Optional, Sequence

import numpy as np

from ..rpc import wire
from .model import Matcher, MatchType


def _matchers_to_wire(matchers: Sequence[Matcher]) -> list:
    return [{"t": int(m.type), "n": m.name, "v": m.value} for m in matchers]


def _matchers_from_wire(obj: list):
    return tuple(Matcher(MatchType(d["t"]), d["n"], d["v"]) for d in obj)


class RemoteStorageServer:
    """Serves fetch_raw over TCP (tsdb/remote/server.go)."""

    def __init__(self, storage, host: str = "127.0.0.1", port: int = 0):
        self.storage = storage
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        req = wire.read_dict_frame(self.request)
                        try:
                            resp = outer._dispatch(req)
                        except Exception as e:  # noqa: BLE001
                            resp = {"err": str(e)}
                        wire.write_frame(self.request, resp)
                except (ConnectionError, OSError, ValueError):
                    # ValueError = malformed frame: stream desync, drop conn
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)

    def _dispatch(self, req: dict) -> dict:
        if req["method"] == "fetch_raw":
            series = self.storage.fetch_raw(
                _matchers_from_wire(req["matchers"]), req["start"], req["end"])
            return {"series": [
                {"id": sid, "tags": entry["tags"],
                 "times": np.asarray(entry["t"], np.int64),
                 "values": np.asarray(entry["v"], np.float64)}
                for sid, entry in series.items()
            ]}
        if req["method"] == "write":
            self.storage.write(req["id"], req["tags"], req["time"], req["value"])
            return {"ok": True}
        raise ValueError(f"unknown method {req['method']!r}")

    @property
    def endpoint(self) -> str:
        h, p = self._server.server_address
        return f"{h}:{p}"

    def start(self) -> "RemoteStorageServer":
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    def close(self):
        self._server.shutdown()
        self._server.server_close()


class RemoteStorage:
    """Client side: a query-storage implementation backed by a remote
    coordinator (tsdb/remote/client.go); drop it into FanoutStorage next
    to local stores for cross-cluster reads."""

    def __init__(self, endpoint: str, timeout_s: float = 10.0):
        self._endpoint = endpoint
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._sock = None

    def _call(self, req: dict) -> dict:
        with self._lock:
            for _ in range(2):
                try:
                    sock = self._ensure_conn()
                    wire.write_frame(sock, req)
                    resp = wire.read_dict_frame(sock)
                    break
                except (OSError, ValueError):
                    # ValueError = malformed reply (desync): same reset
                    self._drop_conn()
            else:
                raise ConnectionError(f"remote storage {self._endpoint} unreachable")
        if "err" in resp:
            raise RuntimeError(f"remote storage error: {resp['err']}")
        return resp

    def fetch_raw(self, matchers: Sequence[Matcher], start_ns: int,
                  end_ns: int) -> Dict[bytes, dict]:
        resp = self._call({"method": "fetch_raw",
                           "matchers": _matchers_to_wire(matchers),
                           "start": start_ns, "end": end_ns})
        return {
            e["id"]: {"tags": e["tags"], "t": e["times"], "v": e["values"]}
            for e in resp["series"]
        }

    def write(self, series_id: bytes, tags, t_ns: int, value: float):
        self._call({"method": "write", "id": series_id, "tags": dict(tags),
                    "time": t_ns, "value": value})

    def _ensure_conn(self):
        if self._sock is None:
            import socket as _socket

            host, _, port = self._endpoint.rpartition(":")
            self._sock = _socket.create_connection(
                (host, int(port)), timeout=self._timeout_s)
            self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        return self._sock

    def _drop_conn(self):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        self._drop_conn()
