"""Query EXPLAIN/ANALYZE: per-node plan introspection for the compiled
whole-plan route (reference: the Prometheus HTTP API returns per-query
`stats` beside the data, and m3query attributes per-query cost — this
build goes one layer deeper and explains WHY a query did or didn't take
the 5-6.8x compiled path, per plan node).

EXPLAIN (`explain()`) is STATIC — it lowers the query and renders a
structured tree without touching storage:

  * a compilable query renders its physical plan IR (query/plan.py):
    per node the kind, a human detail, the edge type (series/scalar),
    the mesh sharding annotation (shard/replicated) and route
    "compiled";
  * a non-compilable query renders the AST with every node routed
    "interpreter" and the node that raised `NotCompilable` annotated
    with the typed `FallbackReason` + detail — the operator sees
    exactly which subexpression blocks the compiled path.

Because EXPLAIN never fetches, the data-dependent below-floor decision
(`PLAN_MIN_CELLS`) can't be resolved statically; the payload carries the
floor so the caller can compare, and the HTTP surfaces additionally
report the route the execution ACTUALLY took (`Engine.last_route`).

ANALYZE is an instrumented execution mode: `with analyzing() as a:`
installs a thread-local context the query path feeds stage wall times
(host tag-algebra bind, device program dispatch per shape bucket, d2h
result materialization) and cache events (grid-cache hit/miss per
fetch, d2h bytes) into. Zero cost when disabled: every hook is one
`current()` call returning None — enforced by
scripts/obs_overhead_guard.py's ANALYZE section. Exposed over HTTP via
`/debug/explain?query=...&analyze=true` and `?explain=true` on the
PromQL read API (coordinator/http_api.py)."""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Dict, Iterator, Optional

from . import plan as qplan
from . import promql
from .plan import (
    Aggregate, Binary, Fetch, InstantFunc, NotCompilable, PlanNode,
    RangeFunc, RankAgg, ScalarConst, SubqueryFunc,
)

ROUTE_COMPILED = "compiled"
ROUTE_INTERPRETER = "interpreter"


# ----------------------------------------------------------------- EXPLAIN


def explain(ast: promql.Node, params, lookback_ns: int,
            query: Optional[str] = None) -> dict:
    """Static plan introspection for one parsed query: route, typed
    fallback reason, and the per-node tree (see module docstring). Pure
    of (ast, params, lookback) — no storage access, no execution."""
    plan, err, _ = qplan.lower_and_collect(ast, params, lookback_ns)
    out = {
        "steps": params.steps,
        "step_ns": params.step_ns,
        "plan_min_cells": qplan.PLAN_MIN_CELLS,
    }
    if query is not None:
        out["query"] = query
    if plan is not None:
        out["route"] = ROUTE_COMPILED
        out["fallback_reason"] = None
        out["mesh_ok"] = plan.mesh_ok
        out["fetches"] = len(plan.fetches)
        out["root"] = _plan_tree(plan.root)
    else:
        out["route"] = ROUTE_INTERPRETER
        out["fallback_reason"] = err.reason.value
        out["fallback_detail"] = str(err)
        out["root"] = _ast_tree(ast, err)
    return out


def walk(tree: dict) -> Iterator[dict]:
    """Every node dict of an explain tree, preorder (tests/smoke use
    this to assert per-node routes)."""
    yield tree
    for child in tree.get("children", ()):
        yield from walk(child)


def _plan_tree(node: PlanNode) -> dict:
    d = {
        "node": type(node).__name__,
        "detail": _plan_detail(node),
        "kind": node.edge.kind,
        "sharding": node.edge.sharding,
        "route": ROUTE_COMPILED,
    }
    children = []
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, PlanNode):
            children.append(_plan_tree(v))
        elif isinstance(v, tuple):
            children.extend(_plan_tree(x) for x in v
                            if isinstance(x, PlanNode))
    if children:
        d["children"] = children
    return d


def _plan_detail(node: PlanNode) -> str:
    if isinstance(node, Fetch):
        name = node.sel.name.decode(errors="replace") if node.sel.name \
            else "{...}"
        return f"{name} role={node.role} W={node.W} stride={node.stride}"
    if isinstance(node, RangeFunc):
        return node.func
    if isinstance(node, SubqueryFunc):
        mode = "packed" if node.packed else "shared"
        return (f"{node.func} subquery[{node.range_ns / 1e9:g}s"
                f":{node.res_ns / 1e9:g}s] W={node.W} "
                f"stride={node.stride} {mode}")
    if isinstance(node, RankAgg):
        mode = "without" if node.without else "by"
        grp = ",".join(g.decode(errors="replace") for g in node.grouping)
        return f"{node.op} {mode}({grp})" if node.grouping else node.op
    if isinstance(node, InstantFunc):
        return node.func
    if isinstance(node, Aggregate):
        mode = "without" if node.without else "by"
        grp = ",".join(g.decode(errors="replace") for g in node.grouping)
        out = f"{node.op} {mode}({grp})" if node.grouping else node.op
        return out + (" exact" if node.exact else "")
    if isinstance(node, Binary):
        return node.op
    if isinstance(node, ScalarConst):
        return f"slot{node.slot}"
    return type(node).__name__  # pragma: no cover


def _ast_tree(node: promql.Node, err: NotCompilable) -> dict:
    d = {
        "node": type(node).__name__,
        "detail": _ast_detail(node),
        "route": ROUTE_INTERPRETER,
    }
    if err.node is node:
        # The exact node whose lowering raised: the typed reason pins
        # here, everything else just reports the interpreter route.
        d["reason"] = err.reason.value
        d["reason_detail"] = err.detail
    children = []
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, promql.VectorMatching):
            continue  # matching metadata, not an operand
        if isinstance(v, promql.Node):
            children.append(_ast_tree(v, err))
        elif isinstance(v, tuple):
            children.extend(_ast_tree(x, err) for x in v
                            if isinstance(x, promql.Node))
    if children:
        d["children"] = children
    return d


def _ast_detail(node: promql.Node) -> str:
    if isinstance(node, promql.VectorSelector):
        name = node.name.decode(errors="replace") if node.name else "{...}"
        return f"{name}[{node.range_ns / 1e9:g}s]" if node.range_ns else name
    if isinstance(node, promql.Subquery):
        return (f"subquery[{node.range_ns / 1e9:g}s"
                f":{node.step_ns / 1e9:g}s]" if node.step_ns
                else f"subquery[{node.range_ns / 1e9:g}s:]")
    if isinstance(node, promql.Call):
        return node.func
    if isinstance(node, promql.Aggregation):
        mode = "without" if node.without else "by"
        grp = ",".join(g.decode(errors="replace") for g in node.grouping)
        return f"{node.op} {mode}({grp})" if node.grouping else node.op
    if isinstance(node, promql.BinaryOp):
        return node.op
    if isinstance(node, promql.Unary):
        return node.op
    if isinstance(node, promql.NumberLiteral):
        return f"{node.value:g}"
    if isinstance(node, promql.StringLiteral):
        return "<string>"
    return type(node).__name__


# ----------------------------------------------------------------- ANALYZE


class Analyze:
    """One query's (or request's) stage/event accumulator. Stages are
    wall seconds keyed by stage name (device stages carry their shape
    bucket in the name, so one ANALYZE run shows per-bucket program
    wall; a plan-cache miss's first invocation fuses trace+XLA compile
    with execution, so that stage is suffixed `+compile` and a
    `plan_cache_miss` event records — a one-time compile must not read
    as steady-state program wall); events are counts/bytes (grid-cache
    hits/misses, d2h bytes)."""

    __slots__ = ("stages", "events")

    def __init__(self):
        self.stages: Dict[str, float] = {}
        self.events: Dict[str, float] = {}

    def add(self, stage: str, seconds: float):
        self.stages[stage] = self.stages.get(stage, 0.0) + seconds

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def event(self, name: str, n: float = 1):
        self.events[name] = self.events.get(name, 0) + n

    def to_dict(self) -> dict:
        return {
            "stages_ms": {k: round(v * 1000, 3)
                          for k, v in sorted(self.stages.items())},
            "events": {k: v for k, v in sorted(self.events.items())},
        }


_TLS = threading.local()


def current() -> Optional[Analyze]:
    """The thread's active ANALYZE context, or None (the hot-path check:
    one thread-local read, same shape as tracing's NOOP test)."""
    return getattr(_TLS, "analyze", None)


@contextlib.contextmanager
def analyzing():
    """Install a fresh ANALYZE context for this thread; restores the
    previous one on exit (nesting yields the inner context)."""
    prev = getattr(_TLS, "analyze", None)
    ctx = Analyze()
    _TLS.analyze = ctx
    try:
        yield ctx
    finally:
        _TLS.analyze = prev
