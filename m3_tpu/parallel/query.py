"""Sharded scatter-gather query execution over a device mesh.

The reference's distributed query path is coordinator fanout: each dbnode
computes partial results for its shards and the coordinator merges
(src/query/storage/fanout + the session's cross-replica merge). On a TPU
pod the same shape is an in-mesh collective: the gridded series live
sharded over the "shard" mesh axis, each device runs the temporal kernel
on its slice, reduces across its local series, and one psum over ICI
yields the global aggregate — no host in the loop until the final [steps]
vector comes back.

This is the long-context/distributed analog for the query tier; ingest's
mesh counterpart (time-axis collectives) lives in parallel/ingest.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import telemetry
from ..ops import temporal


# (is_counter, is_rate) per supported range function — the rate family all
# reduces to temporal.rate_math.
RANGE_FUNCS = {"rate": (True, True), "increase": (True, False),
               "delta": (False, False)}
AGG_OPS = ("sum", "avg", "count", "min", "max")


@telemetry.jit_builder("sharded_agg_rate")
@functools.lru_cache(maxsize=64)
def make_sharded_agg_rate(mesh: Mesh, *, op: str, func: str, W: int,
                          step_ns: int, range_ns: int, stride: int = 1):
    """jit one dashboard-shaped aggregation over the mesh: inputs [S, T]
    sharded on the "shard" axis; output the dense [T_out] global
    aggregate-by-step plus the contributing-series count (replicated).

    op(rate(m[5m])) for op in sum/avg/count/min/max is the canonical
    dashboard shape; NaN cells (insufficient window samples) are excluded
    per series like the executor's host-side nan-aware reduce. Each device
    runs the fused rate kernel on its series slice and reduces locally;
    ONE psum/pmin/pmax over ICI yields the global answer — no host in the
    loop until the final [T_out] vector. Accumulation is f32 on device
    (TPU has no native f64), so sums carry ~sqrt(S)*2^-24 relative error —
    about 2e-5 at 100k series — where the host path is exact f64
    (DIVERGENCES.md).

    lru-cached on (mesh, shape params): repeated dashboard queries reuse
    the compiled executable instead of retracing (Mesh is hashable)."""
    if op not in AGG_OPS:
        raise ValueError(f"unsupported sharded aggregation {op!r}")
    is_counter, is_rate = RANGE_FUNCS[func]
    math = functools.partial(
        temporal.rate_math, W=W, step_s=step_ns / 1e9,
        range_s=range_ns / 1e9, is_counter=is_counter, is_rate=is_rate,
        stride=stride)

    def local(adj, finite, grid32):
        out = math(adj, finite, grid32)  # [S_local, T_out]
        fin = jnp.isfinite(out)
        n = jax.lax.psum(fin.sum(axis=0), "shard")
        if op in ("sum", "avg"):
            part = jnp.where(fin, out, 0.0).sum(axis=0)
            total = jax.lax.psum(part, "shard")
            if op == "avg":
                total = total / jnp.maximum(n, 1)
        elif op == "count":
            total = n.astype(out.dtype)
        elif op == "min":
            total = jax.lax.pmin(
                jnp.where(fin, out, jnp.inf).min(axis=0), "shard")
        else:  # max
            total = jax.lax.pmax(
                jnp.where(fin, out, -jnp.inf).max(axis=0), "shard")
        return total, n

    spec = P("shard", None)
    from .ingest import shard_map_compat

    fn = shard_map_compat(local, mesh=mesh,
                          in_specs=(spec, spec, spec),
                          out_specs=(P(), P()))
    return jax.jit(fn)


def make_sharded_sum_rate(mesh: Mesh, *, W: int, step_ns: int, range_ns: int):
    """Back-compat alias for the op="sum", func="rate" kernel."""
    return make_sharded_agg_rate(mesh, op="sum", func="rate", W=W,
                                 step_ns=step_ns, range_ns=range_ns)


def shard_grid(grid: np.ndarray, mesh: Mesh, is_counter: bool = True):
    """Host prep + placement: f64 [S, T] grid -> device-sharded
    (adj, finite, grid32) on the mesh's "shard" axis. S is padded with
    all-NaN rows (which contribute nothing) up to a multiple of the shard
    axis size, so any S works."""
    n_shard = mesh.shape["shard"]
    S = grid.shape[0]
    pad = (-S) % n_shard
    if pad:
        grid = np.concatenate(
            [grid, np.full((pad, grid.shape[1]), np.nan)], axis=0)
    adj, finite, grid32 = temporal.rate_inputs(grid, is_counter)
    if grid32 is None:
        grid32 = np.zeros_like(adj)
    sharding = NamedSharding(mesh, P("shard", None))
    # DELIBERATE raw put (sharded-query staging): the placed grid feeds
    # the SPMD aggregation immediately and dies with the query; resident
    # device grids are the upload/derived caches' (budgeted) job.
    return tuple(jax.device_put(a, sharding) for a in (adj, finite, grid32))  # m3lint: disable=unbudgeted-device-put


def agg_rate(grid: np.ndarray, mesh: Mesh, *, op: str, func: str, W: int,
             step_ns: int, range_ns: int, stride: int = 1) -> np.ndarray:
    """op(func(...)) over the mesh, NaN where no series had a full window
    — the serving entry the query executor dispatches dashboard
    aggregations through (query/executor.py _eval_sharded_agg)."""
    is_counter, _ = RANGE_FUNCS[func]
    args = shard_grid(grid, mesh, is_counter)
    fn = make_sharded_agg_rate(mesh, op=op, func=func, W=W, step_ns=step_ns,
                               range_ns=range_ns, stride=stride)
    telemetry.mesh_dispatch("agg_rate", cells=int(np.asarray(grid).size))
    total, n = fn(*args)
    total = np.asarray(total, np.float64)
    n = np.asarray(n)
    return np.where(n > 0, total, np.nan)


def sum_rate(grid: np.ndarray, mesh: Mesh, *, W: int, step_ns: int,
             range_ns: int):
    """Convenience wrapper: sum(rate(...)) over the mesh, NaN where no
    series had a full window."""
    return agg_rate(grid, mesh, op="sum", func="rate", W=W, step_ns=step_ns,
                    range_ns=range_ns)


def sum_rate_host_reference(grid: np.ndarray, *, W: int, step_ns: int,
                            range_ns: int) -> np.ndarray:
    """Single-device reference semantics for sum_rate — the definition the
    sharded path is verified against (per-series rate, NaN-excluding sum,
    NaN where no series had a full window). Used by the multichip dryrun
    and tests so the oracle lives in exactly one place."""
    per_series = temporal.rate(grid, W, step_ns, range_ns)
    finite = np.isfinite(per_series)
    return np.where(finite.any(axis=0),
                    np.nansum(np.where(finite, per_series, 0.0), axis=0),
                    np.nan)
