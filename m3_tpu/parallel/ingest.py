"""Sharded ingest pipeline: the framework's flagship device program.

One "step" is the per-shard hot path of the reference's write+flush loop
(src/dbnode/storage/series/buffer.go:178 Write -> m3tsz encoder, and
src/aggregator/aggregator/generic_elem.go:264 Consume) executed as a single
XLA program over a whole shard of series at once:

  (N series x W points) -> M3TSZ-compressed bitstreams
                         + 1m rollup moments + block-level moments + quantiles

Multi-chip layout (SPMD via shard_map over a Mesh):
  axis "shard": data-parallel over series — the TPU expression of the
      reference's murmur3 virtual-shard partitioning
      (src/dbnode/sharding/shardset.go:76). No cross-series communication.
  axis "time": sequence-parallel over block windows — the TPU expression of
      the reference's time-partitioned blocks (series/buffer.go:51 rotating
      block buckets). Each device encodes its own block (blocks are
      independent bitstreams by design, exactly like the reference's sealed
      blocks), while block-spanning aggregates are merged with ICI
      collectives: psum for moments, pmin/pmax for extremes, ppermute-free
      `last` resolution by taking the final time chunk's value.

This is why the design is TPU-first rather than a port: the reference
serialises per-series encoder state behind mutexes; here the only sequential
state (the Gorilla leading/meaningful window) lives in a lax.scan carry while
series ride vector lanes and shards/blocks ride the mesh.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import guard as pguard
from . import telemetry
from ..ops import aggregation as agg
from ..ops import bits64 as b64
from ..ops import tsz


def shard_map_compat(fn, *, mesh, in_specs, out_specs):
    """jax.shard_map across JAX versions: the top-level API (newer
    releases, `check_vma` kwarg) or jax.experimental.shard_map (0.4.x,
    `check_rep` kwarg). The serving flush path routes through this, so
    mesh encode must not depend on which spelling the installed JAX
    ships."""
    top = getattr(jax, "shard_map", None)
    if top is not None:
        return top(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    from jax.experimental.shard_map import shard_map as exp_shard_map

    return exp_shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


class IngestBatch(NamedTuple):
    """Device inputs for one shard x block-window ingest step.

    Leading dims: [T, N, W] = (time chunks, series, points-per-chunk) for the
    sharded path; [N, W] single-chip. Produced by `make_example_batch` /
    m3_tpu.ops.tsz.prepare_encode_inputs.
    """

    dt: jax.Array        # int32 [..., W] timestamp deltas, first col 0
    t0_hi: jax.Array     # u32 [...] first-timestamp high word
    t0_lo: jax.Array     # u32 [...]
    vhi: jax.Array       # u32 [..., W] value bits (f64 or int64 m)
    vlo: jax.Array       # u32 [..., W]
    int_mode: jax.Array  # bool [...]
    k: jax.Array         # int32 [...] decimal exponent
    npoints: jax.Array   # int32 [...] valid points
    ts_regular: jax.Array  # bool [...] all deltas equal delta0
    delta0: jax.Array    # int32 [...] common scrape interval (ticks)
    values: jax.Array    # f32 [..., W] raw values for aggregation


def ingest_step(batch: IngestBatch, *, rollup_factor: int, max_words: int, quantile_qs=(0.5, 0.99)):
    """Single-chip ingest: encode one block + rollup/aggregate its window.

    Returns (words u32 [N, max_words], nbits i32 [N], rollup stats dict
    [N, W//factor], block stats dict [N], quantiles [N, W//factor, Q]).
    """
    words, nbits = tsz.encode_batch(
        batch.dt,
        (batch.t0_hi, batch.t0_lo),
        batch.vhi,
        batch.vlo,
        batch.int_mode,
        batch.k,
        batch.npoints,
        batch.ts_regular,
        batch.delta0,
        max_words=max_words,
    )
    w = batch.values.shape[-1]
    mask = jnp.arange(w, dtype=jnp.int32) < batch.npoints[..., None]
    roll = agg.rollup_stats(batch.values, mask, rollup_factor)
    blk = agg.window_stats(batch.values, mask)
    qs = agg.rollup_quantiles(batch.values, mask, rollup_factor, quantile_qs)
    return words, nbits, roll, blk, qs


class RawIngestBatch(NamedTuple):
    """Raw device inputs for the fused prep+encode ingest step:
    INTERLEAVED u32-pair views of the int64 timestamps / f64 value bits —
    the exact memory the host already holds. Host cost to build one: two
    zero-copy views (make_raw_batch, ~0ms); the hi/lo split is a strided
    slice fused into the encode program and the f32 aggregation values
    are derived on device by exact RNE bit conversion
    (bits64.f64_bits_to_f32), so no host pass touches the data at all
    (was ~440ms of splits + cast per 100k x 120 block) and the f32 plane
    never crosses H2D."""

    ts_pairs: jax.Array  # u32 [N, W, 2] raw int64 bytes, native order
    v_pairs: jax.Array   # u32 [N, W, 2] raw f64 bytes, native order
    npoints: jax.Array   # int32 [N]


# THE endianness decision lives in bits64 (shared with from_u64_np).
_HI = b64.PAIR_HI


def make_raw_batch(ts: np.ndarray, values: np.ndarray,
                   npoints: np.ndarray) -> RawIngestBatch:
    """Zero-cost host prep for ingest_step_raw: two zero-copy pair views —
    the hi/lo split, the f32 value derivation, and all delta/int-mode/
    mantissa work happens on device."""
    return RawIngestBatch(
        b64.pair_view_np(np.asarray(ts, np.int64)),
        b64.pair_view_np(np.asarray(values, np.float64)),
        np.asarray(npoints, np.int32))


def ingest_step_raw(raw: RawIngestBatch, *, rollup_factor: int,
                    max_words: int, quantile_qs=(0.5, 0.99)):
    """Fused prep+encode+aggregate from raw inputs: ONE XLA program covers
    what prepare_encode_inputs did on the host plus ingest_step's device
    work. Returns ingest_step's outputs plus a range_ok bool scalar (the
    device twin of the host prep's int32 delta/DoD ValueErrors — callers
    must check it once per block)."""
    lo = 1 - _HI
    vhi_raw, vlo_raw = raw.v_pairs[..., _HI], raw.v_pairs[..., lo]
    prep, range_ok = tsz.prepare_on_device_math(
        raw.ts_pairs[..., _HI], raw.ts_pairs[..., lo],
        vhi_raw, vlo_raw, raw.npoints)
    # f32 aggregation values from the ORIGINAL f64 bits (prep rewrites
    # vhi/vlo to extracted mantissas for int-mode series).
    values32 = b64.f64_bits_to_f32(vhi_raw, vlo_raw)
    batch = IngestBatch(
        dt=prep["dt"], t0_hi=prep["t0"][0], t0_lo=prep["t0"][1],
        vhi=prep["vhi"], vlo=prep["vlo"], int_mode=prep["int_mode"],
        k=prep["k"], npoints=prep["npoints"],
        ts_regular=prep["ts_regular"], delta0=prep["delta0"],
        values=values32)
    return (*ingest_step(batch, rollup_factor=rollup_factor,
                         max_words=max_words, quantile_qs=quantile_qs),
            range_ok)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """Build the ("shard", "time") device mesh.

    Time-axis size 2 when the device count allows (>=4 and even), exercising
    sequence parallelism; otherwise all devices go to the shard axis.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = np.asarray(devices[:n_devices])
    t = 2 if n_devices >= 4 and n_devices % 2 == 0 else 1
    return Mesh(devices.reshape(n_devices // t, t), ("shard", "time"))


@functools.lru_cache(maxsize=1)
def flush_mesh() -> Mesh | None:
    """The serving flush's shard x time mesh: make_mesh() over every
    attached device when >1 is present, else None (single-device
    platforms keep the plain jit path). M3_TPU_MESH_FLUSH=0 disables
    mesh routing for A/B comparison (write_smoke uses it to prove
    bit-equality against the single-device encode)."""
    import os

    if os.environ.get("M3_TPU_MESH_FLUSH", "1") == "0":
        return None
    if len(jax.devices()) <= 1:
        return None
    return make_mesh()


@telemetry.jit_builder("flush_encoder")
@functools.lru_cache(maxsize=32)
def make_flush_encoder(mesh: Mesh, max_words: int):
    """The serving-flush encode as a shard_map program over the
    shard x time mesh: sealed-block rows (series) are data-parallel, so
    they shard across BOTH mesh axes — every attached device encodes its
    slice of the block with the same kernel the single-device path runs,
    and the results are bit-identical by construction (encode_batch is
    row-independent; no collectives are needed). This is
    make_sharded_ingest's mesh carrying the REAL flush path
    (storage/block.py encode_block -> Shard._tick_locked /
    mediator.snapshot), not just the dryrun/bench ingest program."""
    rows = P(("shard", "time"))
    rowc = P(("shard", "time"), None)

    def local_encode(dt, t0_hi, t0_lo, vhi, vlo, int_mode, k, npoints,
                     ts_regular, delta0):
        from ..ops import tsz

        return tsz.encode_batch(
            dt, (t0_hi, t0_lo), vhi, vlo, int_mode, k, npoints,
            ts_regular, delta0, max_words=max_words)

    fn = shard_map_compat(
        local_encode, mesh=mesh,
        in_specs=(rowc, rows, rows, rowc, rowc, rows, rows, rows, rows,
                  rows),
        out_specs=(rowc, rows))
    return jax.jit(fn)


def flush_encode_prepared(inp: dict, max_words: int):
    """Route prepared encode inputs (ops.tsz.prepare_encode_inputs)
    through the shard x time mesh. Returns (words, nbits) — bit-identical
    to the single-device encode — or None when no mesh is attached, the
    padded row count does not divide it (caller falls back to the plain
    path; encode_block's power-of-two row padding makes most real blocks
    divisible), or the tile is below the dispatch floor
    (M3_TPU_MESH_FLUSH_MIN_CELLS, default 2048): a tiny seal costs more
    in multi-device dispatch than the parallel encode saves."""
    import os

    mesh = flush_mesh()
    if mesh is None:
        return None
    shape = np.asarray(inp["dt"]).shape
    n = shape[0]
    ndev = mesh.devices.size
    if n < ndev or n % ndev:
        return None
    min_cells = int(os.environ.get("M3_TPU_MESH_FLUSH_MIN_CELLS", "2048"))
    if n * shape[1] < min_cells:
        return None
    def _mesh_encode():
        enc = make_flush_encoder(mesh, max_words)
        telemetry.mesh_dispatch("flush_encode", cells=int(n * shape[1]))
        return enc(inp["dt"], inp["t0"][0], inp["t0"][1], inp["vhi"],
                   inp["vlo"], inp["int_mode"], inp["k"], inp["npoints"],
                   inp["ts_regular"], inp["delta0"])

    # Guarded dispatch: a device fault here degrades to the plain
    # single-device encode by returning None — the caller consumes ONLY
    # this function's return value, so a mid-dispatch fault leaves
    # nothing partially applied (the PR 5 all-or-nothing seal contract
    # holds under injected faults; acked writes still seal via the
    # fallback path).
    return pguard.dispatch("flush_encode", _mesh_encode, lambda _err: None)


def make_sharded_ingest(mesh: Mesh, *, rollup_factor: int, max_words: int, quantile_qs=(0.5, 0.99)):
    """Build the jitted multi-chip ingest step over `mesh`.

    Inputs carry a leading time-chunk axis T == mesh "time" size: dt/vhi/vlo/
    values are [T, N, W_chunk], per-series headers [T, N]. Outputs: compressed
    words stay sharded in place ([T, N, MW], one block per time chunk, exactly
    the reference's per-blockstart fileset layout persist/fs/write.go:53);
    whole-window stats are merged across the time axis with collectives and
    replicated over it.
    """
    chunk = P("time", "shard", None)
    per_series = P("time", "shard")
    merged = P("shard")

    def local_step(dt, t0_hi, t0_lo, vhi, vlo, int_mode, k, npoints,
                   ts_regular, delta0, values):
        # Each device sees [1, N_local, W_chunk]: its own block of its shard.
        squeeze = lambda a: a.reshape(a.shape[1:])
        batch = IngestBatch(*(squeeze(a) for a in (
            dt, t0_hi, t0_lo, vhi, vlo, int_mode, k, npoints, ts_regular,
            delta0, values)))
        words, nbits, roll, blk, qtl = ingest_step(
            batch, rollup_factor=rollup_factor, max_words=max_words, quantile_qs=quantile_qs
        )

        # Cross-block merge over the sequence axis (ICI collectives).
        whole = {
            "sum": jax.lax.psum(blk["sum"], "time"),
            "sumsq": jax.lax.psum(blk["sumsq"], "time"),
            "count": jax.lax.psum(blk["count"], "time"),
            "min": jax.lax.pmin(blk["min"], "time"),
            "max": jax.lax.pmax(blk["max"], "time"),
        }
        # Centered second moment across chunks (generalized Chan merge):
        # m2_tot = sum_i m2_i + sum_i n_i*(mean_i - mean_tot)^2.
        mean_tot = jnp.where(whole["count"] > 0, whole["sum"] / jnp.maximum(whole["count"], 1), 0.0)
        dmu = jnp.where(blk["count"] > 0, agg.mean(blk) - mean_tot, 0.0)
        whole["m2"] = jax.lax.psum(blk["m2"] + blk["count"] * dmu * dmu, "time")
        # `last` comes from the latest chunk holding data; gather per-chunk
        # lasts and counts along the time axis and select the last non-empty.
        lasts = jax.lax.all_gather(blk["last"], "time")          # [T, N_local]
        counts = jax.lax.all_gather(blk["count"], "time")
        t_idx = jnp.arange(lasts.shape[0])[:, None]
        last_t = jnp.where(counts > 0, t_idx, -1).max(axis=0)
        whole["last"] = jnp.take_along_axis(lasts, jnp.maximum(last_t, 0)[None, :], axis=0)[0]
        firsts = jax.lax.all_gather(blk["first"], "time")
        first_t = jnp.where(counts > 0, t_idx, lasts.shape[0]).min(axis=0)
        whole["first"] = jnp.take_along_axis(
            firsts, jnp.minimum(first_t, lasts.shape[0] - 1)[None, :], axis=0
        )[0]

        # Global compressed-bits total (for bytes/datapoint accounting):
        # psum over both mesh axes, replicated scalar out.
        total_bits = jax.lax.psum(jax.lax.psum(nbits.sum(), "time"), "shard")

        expand = lambda a: a.reshape((1,) + a.shape)
        return (
            expand(words),
            expand(nbits),
            jax.tree.map(expand, roll),
            jax.tree.map(expand, qtl),
            whole,
            total_bits,
        )

    fn = shard_map_compat(
        local_step,
        mesh=mesh,
        in_specs=(chunk, per_series, per_series, chunk, chunk, per_series,
                  per_series, per_series, per_series, per_series, chunk),
        out_specs=(chunk, per_series, chunk, chunk, merged, P()),
    )
    return jax.jit(fn)


def make_example_raw(n: int, tw: int, rng: np.random.Generator,
                     start=1_600_000_000):
    """Synthetic raw shard data shaped like production metrics: regular 10s
    timestamps, mixed int-optimizable gauges/counters and true floats.
    Returns (timestamps int64 [n, tw], values f64 [n, tw], npoints [n])."""
    # Timestamps: scrape-style regular 10s interval; ~5% of series see
    # per-point jitter (mirrors the production workload behind the
    # reference's 1.45 bytes/datapoint figure, where delta-of-delta is
    # overwhelmingly zero — docs/m3db/architecture/engine.md:20-24).
    jittered = rng.random((n, 1)) < 0.05
    jitter = np.where(jittered, rng.integers(0, 3, size=(n, tw)), 0)
    ts = np.int64(start) + np.arange(tw, dtype=np.int64)[None, :] * 10 + jitter
    ts = np.maximum.accumulate(ts, axis=1)
    # Values: 40% counters (steady rates, occasional step), 40% integer
    # gauges (slow random walk, frequently flat), 20% float gauges.
    kind = rng.integers(0, 5, size=(n, 1))
    base = rng.integers(0, 1000, size=(n, 1)).astype(np.float64)
    rate = rng.integers(1, 20, size=(n, 1)).astype(np.float64)
    steps = rate + np.where(rng.random((n, tw)) < 0.05, rng.integers(-3, 4, size=(n, tw)), 0)
    counters = base + np.cumsum(steps, axis=1)
    moves = np.where(rng.random((n, tw)) < 0.2, rng.integers(-2, 3, size=(n, tw)), 0)
    gauges = base + np.cumsum(moves, axis=1).astype(np.float64)
    floats = base + np.cumsum(moves, axis=1) * 0.1 + rng.standard_normal((n, tw)) * 1e-3
    values = np.where(kind <= 1, counters, np.where(kind <= 3, gauges, floats))
    return ts, values, np.full(n, tw, np.int32)


def make_batch_from_raw(ts2: np.ndarray, v2: np.ndarray,
                        npoints: np.ndarray) -> IngestBatch:
    """Host prep: raw (timestamps, values) -> device-ready IngestBatch."""
    inp = tsz.prepare_encode_inputs(ts2, v2, npoints)
    return IngestBatch(
        dt=inp["dt"],
        t0_hi=inp["t0"][0],
        t0_lo=inp["t0"][1],
        vhi=inp["vhi"],
        vlo=inp["vlo"],
        int_mode=inp["int_mode"],
        k=inp["k"],
        npoints=inp["npoints"],
        ts_regular=inp["ts_regular"],
        delta0=inp["delta0"],
        values=v2.astype(np.float32),
    )


def make_example_batch(n: int, w: int, rng: np.random.Generator, *, chunks: int | None = None, start=1_600_000_000):
    """Synthetic shard batch: make_example_raw + host prep, optionally split
    into `chunks` leading time chunks for the sharded [T, N, W] layout."""
    t_chunks = chunks or 1
    ts, values, _ = make_example_raw(n, t_chunks * w, rng, start=start)

    def prep(ts2, v2):
        return make_batch_from_raw(
            ts2, v2, np.full(ts2.shape[0], ts2.shape[1], np.int32))

    if chunks is None:
        return prep(ts, values)
    parts = [prep(ts[:, i * w : (i + 1) * w], values[:, i * w : (i + 1) * w]) for i in range(t_chunks)]
    return IngestBatch(*(np.stack(cols) for cols in zip(*parts)))


def shard_batch(batch: IngestBatch, mesh: Mesh) -> IngestBatch:
    """Place an example [T, N, ...] batch onto the mesh with ingest shardings."""
    chunk = NamedSharding(mesh, P("time", "shard", None))
    per_series = NamedSharding(mesh, P("time", "shard"))
    specs = IngestBatch(
        dt=chunk, t0_hi=per_series, t0_lo=per_series, vhi=chunk, vlo=chunk,
        int_mode=per_series, k=per_series, npoints=per_series,
        ts_regular=per_series, delta0=per_series, values=chunk,
    )
    # DELIBERATE raw put (mesh staging for the dryrun/bench ingest step):
    # the placed batch is the program input the caller immediately
    # consumes; per-example staging is not resident-cache memory.
    return IngestBatch(*(jax.device_put(a, s) for a, s in zip(batch, specs)))  # m3lint: disable=unbudgeted-device-put
