"""Shard sets: murmur3 virtual-shard hashing (reference:
src/dbnode/sharding/shardset.go — murmur3.Sum32(id) % numShards over 4096
default virtual shards, docs/m3db/architecture/sharding.md)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..utils.hashing import hash_batch, murmur3_32

DEFAULT_NUM_SHARDS = 4096


class ShardSet:
    """The set of virtual shards this node (or a topology) hashes over."""

    def __init__(self, num_shards: int = DEFAULT_NUM_SHARDS,
                 owned: Optional[Sequence[int]] = None):
        self.num_shards = num_shards
        self.owned = sorted(owned) if owned is not None else list(range(num_shards))

    def lookup(self, series_id: bytes) -> int:
        """shardset.go:76 Lookup."""
        return murmur3_32(series_id) % self.num_shards

    def lookup_batch(self, ids: Sequence[bytes]) -> np.ndarray:
        return (hash_batch(ids) % np.uint32(self.num_shards)).astype(np.int32)

    def all_shard_ids(self) -> List[int]:
        return list(self.owned)

    def owns(self, shard_id: int) -> bool:
        return shard_id in set(self.owned)
