"""JAX/TPU runtime telemetry: the compile/dispatch/transfer visibility
layer (reference: the reference exposes its runtime internals through
tally scopes on every component; the TPU build's equivalent blind spot
was XLA — jit cache behavior, compile stalls, shape-bucket churn, and
host<->device transfer volume were invisible at runtime, so "whole-plan
pjit wins" claims had nothing to measure against).

Everything exports through `utils.instrument` under the `telemetry.*`
scope (visible in /debug/vars and the self-scrape pipeline) and tags the
ACTIVE span via `utils.tracing.count_cost`, so a traced query that paid a
compile shows `jit_compile` in its cost tags.

  jit_builder(name)   decorator stacked ABOVE the repo's
                      `functools.lru_cache` jit-builder idiom (the inner
                      decorator stays visible to m3lint's traced-fn
                      discovery): counts builder cache hits vs misses
                      from cache_info() deltas, and wraps each MISS's
                      returned jitted callable so its FIRST invocation —
                      where tracing + XLA compilation actually happen —
                      is timed into the `telemetry.jit.compile_s`
                      histogram.

  record_bucket(path, key)
                      pow2 shape-bucket tracking for the batched decode
                      paths: first sight of a (path, geometry) bucket is
                      a `bucket_miss` (a fresh compile for that shape),
                      repeats are hits. Bounded by eviction.

  count_h2d / count_d2h
                      host<->device transfer bytes at the choke points
                      (hbm.budgeted_put uploads, the upload cache's
                      inserts, LazyBlock result materialization).

  mesh_dispatch(kernel)
                      per-kernel mesh-program dispatch counter (flush
                      encode, sharded aggregation) — the denominator for
                      "did this query actually run on the mesh".

This module deliberately imports NOTHING from jax/ops/parallel so it is
a leaf every layer (ops kernels included) can import without cycles.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Callable, Optional

from ..utils import tracing
from ..utils.instrument import ROOT

_SCOPE = ROOT.sub_scope("telemetry")
_JIT = _SCOPE.sub_scope("jit")
_XFER = _SCOPE.sub_scope("transfer")
_BUCKETS = _SCOPE.sub_scope("shape_bucket")
_MESH = _SCOPE.sub_scope("mesh")

# Compile wall time in seconds; boundaries skewed high — XLA compiles are
# 10ms..10s, not the default sub-ms request buckets.
_COMPILE_BOUNDS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class _CompileTimed:
    """Wrap a freshly-built jitted callable so its FIRST call (trace +
    XLA compile) is timed; later calls pass through one attribute check.
    Thread-safe in the benign direction: a race times the compile twice,
    never misses it."""

    __slots__ = ("fn", "name", "done")

    def __init__(self, fn: Callable, name: str):
        self.fn = fn
        self.name = name
        self.done = False

    def __call__(self, *args, **kwargs):
        if self.done:
            return self.fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        self.done = True
        _JIT.counter("compiles").inc()
        _JIT.histogram("compile_s", _COMPILE_BOUNDS).record(dt)
        _SCOPE.sub_scope("jit", builder=self.name).counter("compiles").inc()
        tracing.count_cost("jit_compile")
        return out


def jit_builder(name: str):
    """Stack above an lru_cache'd jit-builder:

        @telemetry.jit_builder("rate")
        @functools.lru_cache(maxsize=256)
        def _rate_fn(...): ... return jax.jit(fn)

    Hits/misses come from the wrapped cache's own cache_info() (no
    second cache, no key divergence); a miss's result is wrapped so its
    first invocation records compile wall time. The lru_cache decorator
    stays on the function itself, keeping m3lint's jit-builder discovery
    (jax_rules) and the callers' cache_clear()/cache_info() surface
    intact."""

    def deco(cached: Callable):
        if not hasattr(cached, "cache_info"):  # defensive: wrong stacking
            raise TypeError(
                f"jit_builder({name!r}) must wrap an lru_cache'd builder")
        hits = _SCOPE.sub_scope("jit", builder=name).counter("hits")
        misses = _SCOPE.sub_scope("jit", builder=name).counter("misses")
        total_hits = _JIT.counter("hits")
        total_misses = _JIT.counter("misses")
        lock = threading.Lock()

        @functools.wraps(cached)
        def wrapper(*args, **kwargs):
            # cache_info() delta under a private lock: concurrent callers
            # must not double-count one miss (lru_cache itself is
            # thread-safe; only the delta read needs serializing).
            with lock:
                before = cached.cache_info().misses
                out = cached(*args, **kwargs)
                missed = cached.cache_info().misses != before
            if missed:
                misses.inc()
                total_misses.inc()
                # The BUILDING call gets the timing wrapper; the cache
                # itself keeps serving the raw jitted fn on later hits —
                # by then the first (timed) invocation already happened,
                # so hits lose nothing and never risk a stale wrapper.
                return _CompileTimed(out, name)
            hits.inc()
            total_hits.inc()
            return out

        wrapper.cache_info = cached.cache_info
        wrapper.cache_clear = cached.cache_clear
        wrapper.__wrapped__ = cached
        return wrapper

    return deco


# ------------------------------------------------------------ plan cache

_PLAN_CACHE = _SCOPE.sub_scope("plan_cache")


def plan_cache_hit():
    """One compiled-plan executable served from the plan cache."""
    _PLAN_CACHE.counter("hits").inc()


def plan_cache_miss():
    """One plan-cache miss: a fresh whole-plan trace + XLA compile is
    about to happen (its wall time lands via plan_compile_recorded)."""
    _PLAN_CACHE.counter("misses").inc()


def plan_compile_recorded(seconds: float):
    """Wall time of one whole-plan trace + compile (the first invocation
    of a plan-cache miss), tagged onto the active span so the slow-query
    log can attribute cold compiles."""
    _PLAN_CACHE.counter("compiles").inc()
    _PLAN_CACHE.histogram("compile_s", _COMPILE_BOUNDS).record(seconds)
    tracing.count_cost("plan_compile")


# ---------------------------------------------------------- plan fallbacks

_PLAN_FALLBACK = _SCOPE.sub_scope("plan_fallback")


def plan_fallback(reason: str, scope: str = "structural"):
    """One query that missed the compiled whole-plan route, tagged with
    its typed `query.plan.FallbackReason` VALUE (a closed set — raw
    query strings or other unbounded values must never ride as tag
    values; m3lint's `unbounded-telemetry-tag` rule gates it) and its
    SCOPE: "structural" (the query shape is outside the compiled
    surface) vs "runtime" (a data-dependent or operational routing
    decision — below-floor, kill switch, backend gap; see
    query.plan.fallback_scope). The split keeps coverage_report.py's
    structural re-lowering consistent with recorded routes: a
    below-floor miss on a small-series corpus is not a lowering gap.
    The reason-tagged counters are the fallback taxonomy /debug/vars,
    the self-scrape pipeline and scripts/coverage_report.py read."""
    _SCOPE.sub_scope("plan_fallback", reason=reason,
                     scope=scope).counter("count").inc()
    _PLAN_FALLBACK.counter("total").inc()
    tracing.count_cost("plan_fallback")


# ------------------------------------------------------------ transfers


def count_h2d(nbytes: int):
    """Host->device transfer bytes at an upload choke point."""
    if nbytes > 0:
        _XFER.counter("h2d_bytes").inc(int(nbytes))
        _XFER.counter("h2d_transfers").inc()
        tracing.count_cost("h2d_bytes", int(nbytes))


def count_d2h(nbytes: int):
    """Device->host transfer bytes at a result materialization point."""
    if nbytes > 0:
        _XFER.counter("d2h_bytes").inc(int(nbytes))
        _XFER.counter("d2h_transfers").inc()
        tracing.count_cost("d2h_bytes", int(nbytes))


# ---------------------------------------------------------- shape buckets

_BUCKET_LOCK = threading.Lock()
_SEEN_BUCKETS: set = set()
_BUCKET_CAP = 4096  # safety bound; real bucket sets are tens of entries


def record_bucket(path: str, key: tuple):
    """pow2 shape-bucket accounting for a batched decode/encode path: a
    first-seen (path, geometry) is a bucket MISS — the next dispatch with
    it compiles a fresh kernel — repeats are hits. The per-path miss
    counter is the "is bucketing actually bounding recompiles" signal."""
    k = (path, key)
    with _BUCKET_LOCK:
        if k in _SEEN_BUCKETS:
            hit = True
        else:
            hit = False
            if len(_SEEN_BUCKETS) >= _BUCKET_CAP:
                _SEEN_BUCKETS.clear()  # degenerate workload: restart
            _SEEN_BUCKETS.add(k)
    scope = _SCOPE.sub_scope("shape_bucket", path=path)
    if hit:
        scope.counter("hits").inc()
        _BUCKETS.counter("hits").inc()
    else:
        scope.counter("misses").inc()
        _BUCKETS.counter("misses").inc()
        tracing.count_cost("shape_bucket_miss")


# ------------------------------------------------------------ codec routes

_CODEC = _SCOPE.sub_scope("codec")


def codec_route(kernel: str, pallas: bool):
    """Count one codec dispatch for `kernel` in {"encode", "decode",
    "hash"}: Pallas kernel route (`telemetry.codec.pallas_<kernel>`) vs
    the XLA/numpy path (`telemetry.codec.xla_<kernel>`), tagged onto the
    active span — EXPLAIN/slow-query output shows which codec route a
    query actually took. The smoke tier asserts the pallas_* counters
    move when M3_TPU_PALLAS=1, proving dispatch rather than silently
    falling back."""
    name = ("pallas_" if pallas else "xla_") + kernel
    _CODEC.counter(name).inc()
    _CODEC.counter("pallas" if pallas else "fallback").inc()
    tracing.count_cost(f"codec_{name}")


def codec_compile_recorded(kernel: str, seconds: float):
    """Wall time of one codec kernel build's first invocation (trace +
    Mosaic lowering, or interpret-mode setup on CPU) — the codec twin of
    jit_builder's compile timing, same histogram bounds, span-tagged."""
    _SCOPE.sub_scope("codec", kernel=kernel).counter("compiles").inc()
    _CODEC.counter("compiles").inc()
    _CODEC.histogram("compile_s", _COMPILE_BOUNDS).record(seconds)
    tracing.count_cost("codec_pallas_compile")


# ---------------------------------------------------------- compute plane

_COMPUTE = _SCOPE.sub_scope("compute")


@functools.lru_cache(maxsize=None)
def _compute_route_counters(route: str):
    # The guard dispatches on hot interpreter paths (one per temporal
    # op invocation): resolve the tagged counter objects once per route
    # so the per-dispatch cost is two Counter.inc()s, not a sub_scope
    # build + registry lookup (the obs_overhead_guard guard-seam section
    # holds this under 3%).
    scope = _SCOPE.sub_scope("compute", route=route)
    return (scope.counter("primary"), scope.counter("fallback"),
            _COMPUTE.counter("primary"), _COMPUTE.counter("fallback"))


def compute_route(route: str, primary: bool):
    """Count one guarded dispatch for an accelerated `route` (plan,
    agg_flush, flush_encode, codec.*, block.decode, temporal.*): the
    primary accelerated path vs its proven fallback twin. `route` is a
    closed set — the guard registry's route names — never a query string
    (m3lint `unbounded-telemetry-tag` applies). Span-tagged so EXPLAIN
    and the slow-query log name the degraded route."""
    prim, fb, tot_prim, tot_fb = _compute_route_counters(route)
    if primary:
        prim.inc()
        tot_prim.inc()
    else:
        fb.inc()
        tot_fb.inc()
        tracing.count_cost(f"compute_fallback_{route}")


def compute_fault(route: str, kind: str):
    """One classified device/kernel fault on `route`, tagged with its
    `ComputeError` taxonomy kind (compile / oom / kernel / timeout — a
    closed set)."""
    _SCOPE.sub_scope("compute", route=route, kind=kind).counter(
        "faults").inc()
    _COMPUTE.counter("faults").inc()
    tracing.count_cost(f"compute_fault_{kind}")


def compute_trip(route: str, state: str):
    """One breaker state transition on `route` (state in {"open",
    "half_open", "closed"}). `open` transitions are the degradation
    signal HealthTracker's compute probe and /debug/vars surface."""
    _SCOPE.sub_scope("compute", route=route).counter(
        "trip_" + state).inc()
    if state == "open":
        _COMPUTE.counter("trips").inc()
        tracing.count_cost("compute_breaker_trip")


def compute_quarantine(route: str):
    """One shape-bucket executable quarantined on `route` (a post-compile
    fault dropped the cache entry and keyed the bucket into the TTL'd
    quarantine set — no recompile-crash-loop)."""
    _SCOPE.sub_scope("compute", route=route).counter("quarantined").inc()
    _COMPUTE.counter("quarantined").inc()
    tracing.count_cost("compute_quarantine")


def compute_oom_reclaim(route: str, freed: int):
    """One DeviceOOM-triggered HBMBudget cross-tenant reclaim before the
    single retry; `freed` accumulates bytes reclaimed."""
    _SCOPE.sub_scope("compute", route=route).counter("oom_reclaims").inc()
    _COMPUTE.counter("oom_reclaims").inc()
    if freed > 0:
        _COMPUTE.counter("oom_reclaimed_bytes").inc(int(freed))
    tracing.count_cost("compute_oom_reclaim")


# ------------------------------------------------------------- dispatches


def mesh_dispatch(kernel: str, cells: Optional[int] = None):
    """Count one mesh-program dispatch for `kernel` (flush_encode,
    agg_rate, ...); `cells` accumulates the dispatched volume."""
    scope = _SCOPE.sub_scope("mesh", kernel=kernel)
    scope.counter("dispatches").inc()
    _MESH.counter("dispatches").inc()
    if cells:
        scope.counter("cells").inc(int(cells))
    tracing.count_cost("mesh_dispatch")


def snapshot() -> dict:
    """The telemetry.* slice of the instrument registry (obs smoke and
    tests read this; /debug/vars carries the full registry anyway)."""
    return {k: v for k, v in ROOT.snapshot().items()
            if k.startswith("telemetry.")}
