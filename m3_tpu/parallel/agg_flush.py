"""Mesh-sharded aggregator flush reduce: the aggregation tier's device
program (ROADMAP item 4; the same shard_map pattern PR 5 proved for
seal-time flush encode and PR 9 for plan fan-in).

One flush round batches the staged closed windows of EVERY aggregation
shard (Aggregator.flush gathers across shards and resolutions) into one
padded (rows x width) f32 tile, and the O(W log W) work — exact
sort-based timer quantile ordering (ops/aggregation.quantile_rank_select)
— runs as ONE shard_map'd program with the rows partitioned over every
attached device (both mesh axes, the make_flush_encoder layout). Rows
are independent, so no collectives are needed and the mesh result is
bit-identical to the single-device jit by construction; the host then
lands the exact float64 quantile values with one columnar gather by the
returned indices (aggregator/list.py emit_batch).

Moments stay in the host-exact f64 columnar pass (np.reduceat in
aggregator/list.py): the bit-exactness oracle contract — every emitted
moment equals the reference's float64 accumulator output — cannot be
met by f32 device reductions, and PR 9's residual/baseline
decomposition is exact only for integer-valued counters, not the
arbitrary f64 gauges/timers this tier aggregates. The ordering work the
device IS exact at (ranks, not sums) is what ships here; measured, the
moments pass is a single-digit percentage of flush cost while the sort
dominates the timer path.
"""

from __future__ import annotations

import functools
import os

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from . import guard as pguard
from . import telemetry
from .ingest import flush_mesh, shard_map_compat
from ..ops import aggregation as agg
from ..utils import numwatch

# Pad the value axis to lane multiples to limit recompiles. MUST match
# aggregator/list.py's _LANE: the oracle's single-device tile and the
# mesh tile quantize width identically, so a NaN-bearing row (whose
# in-row inf-padding count is order-visible to the stable argsort)
# selects the same element on both routes.
LANE = 128


@telemetry.jit_builder("agg_flush_reducer")
@functools.lru_cache(maxsize=64)
def make_mesh_rank_selector(mesh, width: int, qs: tuple):
    """Quantile rank selection as a shard_map program over the
    shard x time mesh: tile rows (one staged window each) are
    data-parallel, so they shard across BOTH mesh axes — every attached
    device orders its slice of the flush with the same kernel the
    single-device path runs (ops/aggregation.quantile_rank_select), and
    the indices are bit-identical by construction (row-independent, no
    collectives)."""
    rows = P(("shard", "time"))
    rowc = P(("shard", "time"), None)

    def local_select(values, counts):
        return agg.quantile_rank_select(values, counts, qs)

    fn = shard_map_compat(local_select, mesh=mesh,
                          in_specs=(rowc, rows), out_specs=rowc)
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _single_rank_selector(width: int, qs: tuple):
    return jax.jit(
        lambda values, counts: agg.quantile_rank_select(values, counts, qs))


def quantile_rank_rows(tile: np.ndarray, counts: np.ndarray,
                       qs: tuple) -> np.ndarray:
    """Dispatch the flush's quantile ordering: the shard x time mesh when
    one is attached, the tile divides it (rows pad with count-0 windows)
    and the tile is above the dispatch floor (M3_TPU_MESH_AGG_MIN_CELLS,
    default 2048 — a tiny flush costs more in multi-device dispatch than
    the parallel sort saves); otherwise the single-device jit. Returns
    [B, len(qs)] i32 in-row indices, identical on every route."""
    n, width = tile.shape
    mesh = flush_mesh()
    min_cells = int(os.environ.get("M3_TPU_MESH_AGG_MIN_CELLS", "2048"))
    if mesh is not None and n * width >= min_cells:
        orig_tile, orig_counts = tile, counts
        ndev = mesh.devices.size
        pad = (-n) % ndev
        if pad:
            tile = np.concatenate(
                [tile, np.zeros((pad, width), tile.dtype)])
            counts = np.concatenate([counts, np.zeros(pad, counts.dtype)])

        def _mesh_select():
            telemetry.mesh_dispatch("agg_flush", cells=int(tile.size))
            sel = make_mesh_rank_selector(mesh, width, qs)
            return np.asarray(sel(tile, counts))[:n]

        def _single_select(_err):
            # The single-device jit is bit-identical by construction
            # (row-independent, same kernel) — the proven fallback when
            # the mesh program faults or its breaker is open. Runs on the
            # UNpadded tile; nothing was partially applied (the flush
            # consumes only this function's return value).
            return np.asarray(
                _single_rank_selector(width, qs)(orig_tile, orig_counts))

        return pguard.dispatch("agg_flush", _mesh_select, _single_select)
    return np.asarray(_single_rank_selector(width, qs)(tile, counts))


def build_quantile_tile(buckets, counts: np.ndarray):
    """Pad a ragged bucket list into the [B, width] f32 tile the rank
    selector consumes, width quantized to LANE multiples of the max
    bucket length (the same rule as the oracle's _quantile_rows_for).
    One vectorized scatter fills the tile — no per-row Python assignment
    — from the same concatenation the exact-value gather reuses.
    Returns (tile f32, cat f64, starts i64): cat/starts locate each
    row's exact f64 values for the post-ordering host gather."""
    max_n = max(1, int(counts.max()))
    width = ((max_n + LANE - 1) // LANE) * LANE
    sizes = np.maximum(counts, 1)
    starts = np.zeros(len(buckets), dtype=np.int64)
    starts[1:] = np.cumsum(sizes)[:-1]
    safe = [b if b.size else np.zeros(1) for b in buckets]
    cat = np.concatenate(safe)
    tile = np.zeros((len(buckets), width), dtype=np.float32)
    total = int(sizes.sum())
    within = np.arange(total, dtype=np.int64) - np.repeat(starts, sizes)
    rows = np.repeat(np.arange(len(buckets), dtype=np.int64), sizes)
    flat = rows * width + within
    tile.ravel()[flat] = cat.astype(np.float32)
    # zero-size rows scattered a placeholder 0 into column 0; their
    # count is 0 so the selector never reads it, and the gather below
    # guards count==0 explicitly.
    return tile, cat, starts


def exact_quantile_values(buckets, counts: np.ndarray, qs: tuple):
    """Timer quantile ordering end-to-end: build the tile, order on
    device (mesh-sharded when attached), then ONE columnar host gather
    of the exact f64 values by index. Returns [B, len(qs)] f64, rows
    with count 0 all-zero (stream.go:145-146 empty convention)."""
    tile, cat, starts = build_quantile_tile(buckets, counts)
    idx = quantile_rank_rows(tile, counts.astype(np.int32), qs)
    safe_idx = np.minimum(idx.astype(np.int64),
                          np.maximum(counts - 1, 0)[:, None])
    vals = cat[starts[:, None] + safe_idx]
    vals[counts == 0] = 0.0
    if numwatch.installed():
        # Numerics witness: live rows (count > 0) carry the gathered
        # exact values; count-0 rows must be exactly zero (the
        # stream.go:145-146 empty convention) — a non-zero there means
        # a padding row's ordering index leaked into the gather.
        numwatch.observe_rows("agg_flush", vals, counts > 0)
    return vals
