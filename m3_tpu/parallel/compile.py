"""Whole-plan compilation: one jitted program per PromQL physical plan
over the shard x time mesh (ROADMAP item 1; the Titanax
compile_step_with_plan shape from SNIPPETS.md [3]).

The interpreter (query/executor.py, retained as the oracle
`Engine.execute_range_ref`) dispatches one jitted kernel per temporal op
per block with host round trips between operators and a fully host-side
aggregation fan-in. Here the plan IR (query/plan.py) lowers into ONE
traced function: operator chains fuse, cross-shard aggregation fan-in
becomes XLA collectives (psum/pmin/pmax over ICI via shard_map_compat)
instead of host gather, and the only device->host transfer is the final
result. In/out shardings match the layout the selector staging places
(rows partitioned over the mesh "shard" axis, NamedSharding
P("shard", None)), so a staged grid feeds the program without
repartitioning — SNIPPETS.md [1]'s advice of matching a producer's
out_axis_resources to the consumer's in_axis_resources.

Compiled executables are cached per (plan structure, pow2 shape bucket,
mesh) — `telemetry.plan_cache` counts hits/misses/compile wall — with
row/time padding chosen so one executable serves every query with the
same plan shape: rows pad with NaN (masked everywhere), the time axis
pads past the real output and the host slices it back. Selector label
matchers are stripped from the key (one executable serves every metric
with the same plan shape); scalar literals ride as runtime slots (one
executable serves every threshold).

Counter-sum exactness (the query/executor.py:789 contract): an
aggregate sum/avg DIRECTLY over a raw selector decomposes each series
as baseline + residual (ops/temporal.center). The device accumulates
only the small f32 residuals (per-shard partials combine via psum —
still residual-space, still small), while the baseline mass — where
plain f32 accumulation of 1e9-magnitude counters loses the f64
host-reduce semantics — is accounted on the host in exact f64 (group
baseline totals minus per-missing-cell corrections).
tests/test_plan_compile.py proves this against the interpreter oracle
over seeded counter grids.

The lowering rules (`_lower_*`) run under jax trace: they must never
sync a traced value to the host (np.asarray / jax.device_get / .item()
mid-plan is exactly the per-op dispatch this module replaces) — m3lint's
`host-sync-in-plan` rule gates it.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import guard as pguard
from . import telemetry
from ..ops import series_agg, temporal
from ..utils import numwatch
from ..query import explain as qexplain
from ..query import plan as qplan
from ..query import promql
from ..query.plan import (
    Aggregate, Binary, Fetch, InstantFunc, Plan, PlanNode, RangeFunc,
    RankAgg, ScalarConst, SubqueryFunc, SERIES, SCALAR, _preorder,
)

_F32 = jnp.float32


class PlanFallback(Exception):
    """The bound plan can't execute compiled (shape pathology, missing
    backend feature); the executor falls back to the interpreter.
    Carries a typed `FallbackReason` (default BACKEND_GAP) so the
    telemetry/EXPLAIN taxonomy covers compile-time bail-outs too."""

    def __init__(self, detail: str = "",
                 reason: "qplan.FallbackReason" = None):
        self.reason = reason or qplan.FallbackReason.BACKEND_GAP
        self.detail = detail
        super().__init__(f"{self.reason.value}: {detail}" if detail
                         else self.reason.value)


# --------------------------------------------------------------- geometry


@dataclasses.dataclass(frozen=True)
class Geometry:
    """Static shape signature of one compiled executable: pow2 row/time
    buckets per fetch, group buckets per aggregate, row buckets per
    vector-vector binary, inner-grid widths per subquery, and
    (group, group-size) buckets per rank aggregation — each entry
    aligned to its node kind's plan-preorder occurrence order."""

    t_pad: int                       # padded output steps
    s_pads: Tuple[int, ...]          # per plan.fetches entry
    f_exts: Tuple[int, ...]          # staged grid width per fetches entry
    g_pads: Tuple[int, ...]          # per Aggregate node, preorder
    r_pads: Tuple[int, ...]          # per vv Binary node, preorder
    sub_pads: Tuple[int, ...]        # per SubqueryFunc node, preorder
    rank_pads: Tuple[Tuple[int, int], ...]  # per RankAgg: (g_pad, smax_pad)
    n_shard: int                     # 1 = single-device


# The aux-array ordering contract between bind(), _aux_layout(),
# geometry_for() and execute() hangs on ONE preorder walk: plan.py's.
def _is_vv(node: PlanNode) -> bool:
    return (isinstance(node, Binary) and node.lhs.edge.kind == SERIES
            and node.rhs.edge.kind == SERIES)


def _row_bucket(s: int, n_shard: int) -> int:
    """Rows padded to n_shard * bucket(per-device rows): the shard axis
    divides evenly and one executable serves a half-octave bucket of
    sizes (plan.next_bucket)."""
    per_dev = max(1, -(-s // n_shard))
    return n_shard * qplan.next_bucket(per_dev)


def _widths(root: PlanNode, t_pad: int,
            sub_pads: Optional[Tuple[int, ...]] = None
            ) -> Tuple[Dict[int, int], Tuple[int, ...]]:
    """Per-node padded TIME width: t_pad outside subqueries; inside a
    SubqueryFunc, the inner resolution grid's padded width — long enough
    that contiguous strided windows cover every padded output step
    (shared mode), or the bucketed inner-grid length (packed mode, where
    the bind-time column map does the indexing). With `sub_pads` given
    (trace time, on the stripped plan whose inner_steps is zeroed) the
    recorded Geometry widths are consumed instead of recomputed."""
    width_of: Dict[int, int] = {}
    pads_out: List[int] = []
    it = iter(sub_pads) if sub_pads is not None else None

    def walk(n: PlanNode, w: int):
        width_of[id(n)] = w
        if isinstance(n, SubqueryFunc):
            if it is not None:
                w_in = next(it)
            elif n.packed:
                w_in = qplan.next_bucket(max(n.inner_steps, 1))
            else:
                w_in = (w - 1) * n.stride + n.W
            pads_out.append(w_in)
            walk(n.arg, w_in)
            return
        for fld in dataclasses.fields(n):
            v = getattr(n, fld.name)
            if isinstance(v, PlanNode):
                walk(v, w)
            elif isinstance(v, tuple):
                for item in v:
                    if isinstance(item, PlanNode):
                        walk(item, w)

    walk(root, t_pad)
    return width_of, tuple(pads_out)


def _fetch_exts(root: PlanNode, width_of: Dict[int, int],
                fetches: Tuple[Fetch, ...]) -> Tuple[int, ...]:
    """Staged grid width per fetches entry: the max extended-grid length
    any occurrence of that (equality-keyed) fetch needs in its time
    context — consumers slice down to their own need."""
    need: Dict[Fetch, int] = {}
    for n in _preorder(root, []):
        if isinstance(n, Fetch):
            ext = _ext_len(n, width_of[id(n)])
            need[n] = max(need.get(n, 0), ext)
    return tuple(need[f] for f in fetches)


def geometry_for(bound: "qplan.Bound", n_shard: int) -> Geometry:
    plan = bound.plan
    t_pad = qplan.next_bucket(plan.steps)
    s_pads = tuple(_row_bucket(bound.fetches[f].grid.shape[0], n_shard)
                   for f in plan.fetches)
    width_of, sub_pads = _widths(plan.root, t_pad)
    f_exts = _fetch_exts(plan.root, width_of, plan.fetches)
    nodes: List[PlanNode] = []
    _preorder(plan.root, nodes)
    g_pads = tuple(qplan.next_bucket(max(1, bound.aux[id(n)]["n_groups"]))
                   for n in nodes if isinstance(n, Aggregate))
    r_pads = tuple(qplan.next_bucket(max(1, len(bound.aux[id(n)]["many_idx"])))
                   for n in nodes if _is_vv(n))
    rank_pads = tuple(
        (qplan.next_bucket(max(1, bound.aux[id(n)]["n_groups"])),
         qplan.next_bucket(max(1, bound.aux[id(n)]["smax"])))
        for n in nodes if isinstance(n, RankAgg))
    return Geometry(t_pad, s_pads, f_exts, g_pads, r_pads, sub_pads,
                    rank_pads, n_shard)


# ---------------------------------------------------------- input staging

# Which prepared arrays a fetch contributes, per consumer need, and how
# many arrays each kind flattens to.
#   ratec: (adj, finite, grid32)   rate/increase (ops/temporal.rate_inputs)
#   rated: (adj, finite)           delta
#   resid: (resid, base32)         *_over_time / regression / exact sums
#   value: (value32,)              elementwise / binary / min-max-count
#   value2: (hi, lo)               exact double-f32 split (topk ranking)
_KIND_ARITY = {"ratec": 3, "rated": 2, "resid": 2, "value": 1, "value2": 2}
_RATE_COUNTER = frozenset({"rate", "increase"})


def _consumer_kinds(consumer: Optional[PlanNode]) -> Tuple[str, ...]:
    """Which staged-input kinds one consumer reads off a direct Fetch."""
    if isinstance(consumer, (RangeFunc, SubqueryFunc)):
        f = consumer.func
        if f in ("rate", "increase", "delta"):
            return ("ratec",) if f in _RATE_COUNTER else ("rated",)
        if f in ("irate", "idelta"):
            # residual-space diffs + the absolute plane for the counter
            # reset branch (temporal.instant_math)
            return ("resid", "value")
        return ("resid",)
    if isinstance(consumer, Aggregate) and consumer.exact:
        return ("resid",)
    if isinstance(consumer, RankAgg) and consumer.op != "quantile":
        # topk/bottomk MEMBERSHIP is discrete: rank on the exact
        # double-f32 split so sub-ulp counter differences don't scramble
        # the surviving series set (series_agg.packed_topk_keep_math).
        return ("value2",)
    return ("value",)


def fetch_kinds(root: PlanNode) -> Dict[Fetch, Tuple[str, ...]]:
    """Deterministic (sorted) set of staged-input kinds per fetch,
    keyed by Fetch equality (equal selectors share staged inputs)."""
    kinds: Dict[Fetch, set] = {}

    def walk(node: PlanNode, consumer: Optional[PlanNode]):
        if isinstance(node, Fetch):
            kinds.setdefault(node, set()).update(_consumer_kinds(consumer))
            return
        for fld in dataclasses.fields(node):
            v = getattr(node, fld.name)
            if isinstance(v, PlanNode):
                walk(v, node)
            elif isinstance(v, tuple):
                for item in v:
                    if isinstance(item, PlanNode):
                        walk(item, node)

    walk(root, None)
    return {f: tuple(sorted(ks)) for f, ks in kinds.items()}


def _ext_len(f: Fetch, width: int) -> int:
    """Padded extended-grid length for a fetch in a `width`-wide time
    context: long enough that the strided window output covers every
    padded column. Every output step j < real steps reads window cells
    [j*stride, j*stride + W) — real cells only, so end-padding is
    exact. Staged widths are Geometry.f_exts = the max of this over a
    fetch's occurrences (via _fetch_exts); consumers slice down to
    their own need."""
    if f.role == "instant":
        return width
    return (width - 1) * f.stride + f.W


def _pad_grid(grid: np.ndarray, s_pad: int, ext_pad: int) -> np.ndarray:
    S, T = grid.shape
    if S == s_pad and T == ext_pad:
        return grid
    out = np.full((s_pad, ext_pad), np.nan, dtype=grid.dtype)
    out[:S, :T] = grid
    return out


def stage_value_plane(grid: np.ndarray, s_pad: int, ext_pad: int
                      ) -> np.ndarray:
    """Padded f32 staging for a `value`-kind fetch plane in ONE pass:
    allocate the padded plane at f32 and downcast-copy the grid straight
    into it, replacing the f64 pad + separate astype(float32) two-pass
    (which materialized an [s_pad, ext_pad] f64 intermediate per fetch).
    Identical cells: NaN padding survives the downcast and copyto's
    unsafe cast is exactly astype's round-to-nearest."""
    S, T = grid.shape
    out = np.full((s_pad, ext_pad), np.nan, np.float32)
    np.copyto(out[:S, :T], grid, casting="unsafe")
    return out


def _stage_fetch(bf: "qplan.BoundFetch", kinds: Tuple[str, ...],
                 s_pad: int, ext_pad: int, mesh: Optional[Mesh]):
    """Prepared, padded, placed input arrays for one fetch — content/id
    cached via ops/temporal's derived cache, so a repeat query (the grid
    cache returning the same consolidated grid object, e.g. served off
    the block cache's resident decoded planes) reuses the staged device
    arrays without re-upload or repartitioning."""
    mesh_tag = "1" if mesh is None else f"{mesh.shape['shard']}@{id(mesh)}"
    kind_tag = f"plan:{','.join(kinds)}:{s_pad}x{ext_pad}:{mesh_tag}"

    def build(g):
        # The padded f64 intermediate is only needed by the non-"value"
        # kinds; a plain value fetch stages through the one-pass f32 path.
        gp = (_pad_grid(g, s_pad, ext_pad)
              if any(kind != "value" for kind in kinds) else None)
        arrs: List[np.ndarray] = []
        for kind in kinds:
            if kind in ("ratec", "rated"):
                adj, finite, grid32 = temporal.rate_inputs(
                    gp, kind == "ratec")
                arrs += [adj, finite]
                if kind == "ratec":
                    arrs.append(grid32)
            elif kind == "resid":
                resid, base = temporal.center(gp)
                # DELIBERATE downcast: base32 feeds only the device
                # plane (predict_linear/holt_winters adds); the exact
                # f64 baseline mass is re-derived on the host by
                # _exact_base_contrib from the same grid, so nothing
                # the f32 copy drops ever reaches a counter sum.
                arrs += [resid, base.astype(np.float32)]  # m3lint: disable=f64-downcast-on-exact-path
            elif kind == "value2":
                # Exact double-f32 split of the f64 grid: hi + lo
                # round-trips the value to ~2e-4 absolute, and the lo
                # plane is what makes compiled topk ranking faithful to
                # the interpreter's f64 sort at counter magnitudes.
                hi = gp.astype(np.float32)
                lo = (gp - hi.astype(np.float64)).astype(np.float32)
                arrs += [hi, lo]
            else:  # "value"
                arrs.append(stage_value_plane(g, s_pad, ext_pad))
        if mesh is not None:
            sh2 = NamedSharding(mesh, P("shard", None))
            sh1 = NamedSharding(mesh, P("shard"))
            placed = tuple(
                jax.device_put(a, sh1 if a.ndim == 1 else sh2)  # m3lint: disable=unbudgeted-device-put
                for a in arrs)
            # Charged at the canonicalized device sizes; the derived
            # cache's HBM-budget tenant bounds the resident total.
            return placed, sum(int(getattr(a, "nbytes", 0)) for a in placed)
        if temporal._cache_enabled():
            placed = tuple(temporal._placed_put(a) for a in arrs)
            return placed, sum(int(getattr(a, "nbytes", 0)) for a in placed)
        return tuple(arrs), 0

    return temporal._derived(bf.grid, kind_tag, build)


# --------------------------------------------------------- lowering rules
#
# Each _lower_* rule emits the traced computation for one plan node.
# Everything here runs under jax trace: touching the host
# (np.asarray / device_get / .item()) would reintroduce the per-op
# dispatch this module exists to remove — m3lint's host-sync-in-plan
# rule gates it.

_MATH_JNP = {
    "abs": jnp.abs, "ceil": jnp.ceil, "floor": jnp.floor, "exp": jnp.exp,
    "sqrt": jnp.sqrt, "ln": jnp.log, "log2": jnp.log2, "log10": jnp.log10,
    "sgn": jnp.sign, "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "asinh": jnp.arcsinh, "acosh": jnp.arccosh, "atanh": jnp.arctanh,
    "deg": jnp.degrees, "rad": jnp.radians,
    "neg": lambda v: -v,
}

_BIN_JNP = {
    "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply, "/": jnp.divide,
    "==": lambda a, b: (a == b).astype(_F32),
    "!=": lambda a, b: (a != b).astype(_F32),
    "<": lambda a, b: (a < b).astype(_F32),
    ">": lambda a, b: (a > b).astype(_F32),
    "<=": lambda a, b: (a <= b).astype(_F32),
    ">=": lambda a, b: (a >= b).astype(_F32),
}


class _Ctx:
    """Trace-time emission context: staged inputs per fetch, bind-time
    index arrays per node path, scalar slots, per-node time widths,
    mesh-axis state."""

    def __init__(self, plan: Plan, geom: Geometry, fetch_ins, aux_ins,
                 slots, sharded: bool):
        self.plan = plan
        self.geom = geom
        self.fetch_ins = fetch_ins          # {Fetch: {kind: (arrays...)}}
        self.aux_ins = aux_ins              # {path: (arrays...)}
        self.slots = slots
        self.sharded = sharded
        self.cache: Dict[int, object] = {}
        nodes: List[PlanNode] = []
        _preorder(plan.root, nodes)
        self.path_of = {id(n): i for i, n in enumerate(nodes)}
        self.g_pad_of = dict(zip(
            (id(n) for n in nodes if isinstance(n, Aggregate)),
            geom.g_pads))
        self.rank_pads_of = dict(zip(
            (id(n) for n in nodes if isinstance(n, RankAgg)),
            geom.rank_pads))
        self.width_of, _ = _widths(plan.root, geom.t_pad, geom.sub_pads)
        self.root_agg: Optional[tuple] = None   # (s, cnt) for sum/avg root


def _lower_fetch(ctx: _Ctx, node: Fetch):
    """A bare selector consumed as values: the absolute f32 plane,
    sliced to this occurrence's padded grid width."""
    (value,) = ctx.fetch_ins[node]["value"]
    return value[:, :ctx.width_of[id(node)]]


def _range_body(ctx: _Ctx, f: str, ins: Dict[str, tuple], *, W: int,
                stride: int, step_s: float, range_s: float,
                params: Tuple[float, ...]):
    """The shared windowed-kernel ladder: one range function over
    prepared inputs (`ins` maps kind -> arrays already sliced/gathered
    to the window layout). Serves both RangeFunc (host-staged selector
    inputs) and SubqueryFunc (inner-plane inputs, possibly packed)."""
    if f in ("rate", "increase", "delta"):
        adj, finite = ins["diff"][0], ins["diff"][1]
        grid32 = ins["diff"][2] if f in _RATE_COUNTER else None
        return temporal.rate_math(
            adj, finite, grid32, W=W, step_s=step_s, range_s=range_s,
            is_counter=f in _RATE_COUNTER, is_rate=f == "rate",
            stride=stride)
    if f in ("irate", "idelta"):
        resid, grid32 = ins["instant"]
        return temporal.instant_math(
            resid, grid32, W=W, step_s=step_s, is_rate=f == "irate",
            stride=stride)
    resid, base32 = ins["resid"]
    if f == "quantile_over_time":
        return temporal.quantile_ot_math(resid, base32, W=W,
                                         q=float(params[0]), stride=stride)
    if f.endswith("_over_time"):
        return temporal.over_time_math(
            resid, base32, W=W, kind=f[:-len("_over_time")], stride=stride)
    if f in ("changes", "resets"):
        return temporal.changes_resets_math(
            resid, W=W, count_resets=f == "resets", stride=stride)
    if f == "deriv":
        return temporal.regression_math(
            resid, W=W, step_s=step_s, predict_offset_s=0.0,
            is_deriv=True, stride=stride)
    if f == "predict_linear":
        return temporal.regression_math(
            resid, W=W, step_s=step_s, predict_offset_s=float(params[0]),
            is_deriv=False, stride=stride) + base32[:, None]
    # holt_winters (lowering admits nothing else)
    return temporal.holt_winters_math(
        resid, W=W, sf=float(params[0]), tf=float(params[1]),
        stride=stride) + base32[:, None]


def _lower_rangefunc(ctx: _Ctx, node: RangeFunc):
    f = node.func
    fetch = node.arg
    W, stride = fetch.W, fetch.stride
    w_out = ctx.width_of[id(node)]
    ext = (w_out - 1) * stride + W
    staged = ctx.fetch_ins[fetch]

    if f == "absent_over_time":
        # Window presence counts, then ONE cross-row (and cross-shard)
        # reduce: 1 where NO series has a sample in the window.
        resid, _base32 = staged["resid"]
        cnt = temporal._wsum(jnp.isfinite(resid[:, :ext]), W, stride)
        total = cnt.sum(axis=0, keepdims=True)
        # DELIBERATE: static program structure (mesh mode + edge
        # sharding), same as the aggregate fan-in branches.
        if ctx.sharded and fetch.edge.sharding == qplan.SHARDED:  # m3lint: disable=jax-traced-branch
            total = jax.lax.psum(total, "shard")
        return jnp.where(total > 0, jnp.nan, 1.0)

    ins: Dict[str, tuple] = {}
    if f in ("rate", "increase", "delta"):
        kind = "ratec" if f in _RATE_COUNTER else "rated"
        ins["diff"] = tuple(a[:, :ext] for a in staged[kind])
    elif f in ("irate", "idelta"):
        resid, _base32 = staged["resid"]
        (value,) = staged["value"]
        ins["instant"] = (resid[:, :ext], value[:, :ext])
    else:
        resid, base32 = staged["resid"]
        ins["resid"] = (resid[:, :ext], base32)
    out = _range_body(ctx, f, ins, W=W, stride=stride,
                      step_s=node.step_ns / 1e9,
                      range_s=node.range_ns / 1e9, params=node.params)
    return out[:, :w_out]


def _sub_gather(arr, cols, fill):
    """Packed-window gather: [S, T_in] columns by the bind-time index
    map; lanes with col -1 (outside the window) take `fill`."""
    valid = (cols >= 0)[None, :]
    g = arr[:, jnp.maximum(cols, 0)]
    return jnp.where(valid, g, fill)


def _lower_subqueryfunc(ctx: _Ctx, node: SubqueryFunc):
    """f(expr[r:s]): window the inner plane. Direct selector inners read
    their host-staged exact-f64 preps (the same kinds RangeFunc uses, on
    the inner resolution grid); composite inners prep in-trace at the
    plane's f32 (temporal.center_math / rate_inputs_math — the lowering
    only admits difference-space planes there). Packed mode first
    gathers each output step's drifting window through the bind-time
    column map; shared mode reads contiguous strided windows."""
    f = node.func
    w_out = ctx.width_of[id(node)]
    inner_w = ctx.width_of[id(node.arg)]
    direct = isinstance(node.arg, Fetch)
    if node.packed:
        (cols,) = ctx.aux_ins[ctx.path_of[id(node)]]
        W = stride = node.W
    else:
        cols = None
        W, stride = node.W, node.stride

    def windowed(a, fill):
        a = a[:, :inner_w]
        return a if cols is None else _sub_gather(a, cols, fill)

    ins: Dict[str, tuple] = {}
    if f in ("rate", "increase", "delta"):
        counter = f in _RATE_COUNTER
        if direct:
            kind = "ratec" if counter else "rated"
            arrs = ctx.fetch_ins[node.arg][kind]
            adj, finite = arrs[0], arrs[1]
            grid32 = arrs[2] if counter else None
        else:
            plane = _emit(ctx, node.arg)
            adj, finite, z = temporal.rate_inputs_math(plane, counter)
            grid32 = z if counter else None
        ins["diff"] = (windowed(adj, 0.0), windowed(finite, False)) + (
            (windowed(grid32, 0.0),) if counter else ())
    elif f in ("irate", "idelta"):
        if direct:
            resid, _b = ctx.fetch_ins[node.arg]["resid"]
            (value,) = ctx.fetch_ins[node.arg]["value"]
        else:
            plane = _emit(ctx, node.arg)
            resid, _base = temporal.center_math(plane)
            value = plane
        ins["instant"] = (windowed(resid, jnp.nan),
                          windowed(value, jnp.nan))
    else:
        if direct:
            resid, base32 = ctx.fetch_ins[node.arg]["resid"]
        else:
            plane = _emit(ctx, node.arg)
            resid, base32 = temporal.center_math(plane)
        ins["resid"] = (windowed(resid, jnp.nan), base32)
    out = _range_body(ctx, f, ins, W=W, stride=stride,
                      step_s=node.res_ns / 1e9,
                      range_s=node.range_ns / 1e9, params=node.params)
    return out[:, :w_out]


def _lower_rankagg(ctx: _Ctx, node: RankAgg):
    """topk/bottomk/quantile: gather rows into the bind-time group
    packing, sort-select along the packed axis (ops/series_agg), k / q
    riding as a runtime slot. topk/bottomk return the argument plane
    masked to the per-step winners (the data-dependent surviving row SET
    is filtered on the host at the root finish)."""
    perm, inv = ctx.aux_ins[ctx.path_of[id(node)]]
    g_pad, smax_pad = ctx.rank_pads_of[id(node)]
    kq = ctx.slots[node.param.slot]
    if node.op == "quantile":
        v = _emit(ctx, node.arg)
        packed = series_agg.packed_gather_math(v, perm, g_pad, smax_pad)
        return series_agg.packed_quantile_math(packed, kq)
    if isinstance(node.arg, Fetch):
        # Raw selector plane: the host-staged exact double-f32 split —
        # sub-ulp counter differences must still rank like f64.
        hi, lo = ctx.fetch_ins[node.arg]["value2"]
        w = ctx.width_of[id(node.arg)]
        v, vlo = hi[:, :w], lo[:, :w]
    else:
        v = _emit(ctx, node.arg)
        vlo = jnp.zeros_like(v)
    packed_hi = series_agg.packed_gather_math(v, perm, g_pad, smax_pad)
    packed_lo = series_agg.packed_gather_math(vlo, perm, g_pad, smax_pad)
    # int(k) truncation parity with the interpreter's _const_param.
    keep = series_agg.packed_topk_keep_math(packed_hi, packed_lo,
                                            jnp.floor(kq),
                                            node.op == "topk")
    flat = keep.reshape(g_pad * smax_pad, keep.shape[-1])
    valid_row = (inv >= 0)[:, None]
    keep_rows = jnp.where(valid_row, flat[jnp.maximum(inv, 0)], False)
    return jnp.where(keep_rows, v, jnp.nan)


def _lower_instantfunc(ctx: _Ctx, node: InstantFunc):
    v = _emit(ctx, node.arg)
    if node.func == "timestamp":
        # Step times ride as a bind-time aux vector (f32 — documented
        # divergence: unix seconds round to ~128s granularity on the f32
        # value plane, far inside the oracle tolerance at 1.7e9).
        (times,) = ctx.aux_ins[ctx.path_of[id(node)]]
        return jnp.where(jnp.isfinite(v), times[None, :], jnp.nan)
    fn = _MATH_JNP.get(node.func)
    if fn is not None:
        return fn(v)
    params = [ctx.slots[p.slot] for p in node.params]
    if node.func == "round":
        # DELIBERATE: branches on the STATIC slot arity (plan structure),
        # not the traced slot values inside the list.
        if not params:  # m3lint: disable=jax-traced-branch
            return jnp.round(v)
        return jnp.round(v / params[0]) * params[0]
    if node.func == "clamp":
        return jnp.clip(v, params[0], params[1])
    if node.func == "clamp_min":
        return jnp.maximum(v, params[0])
    if node.func == "clamp_max":
        return jnp.minimum(v, params[0])
    raise PlanFallback(f"instant func {node.func}")  # pragma: no cover


def _lower_aggregate(ctx: _Ctx, node: Aggregate):
    """Cross-series reduce with collective fan-in (psum/pmin/pmax over
    the mesh shard axis). Returns the collapsed f32 [G_pad, t_pad] plane;
    a sum/avg ROOT additionally records its (residual-sum, count)
    components so the host can finish in exact f64."""
    (gids,) = ctx.aux_ins[ctx.path_of[id(node)]]
    g_pad = ctx.g_pad_of[id(node)]
    # Collectives only when the CHILD rows are partitioned over the mesh:
    # a replicated child (an inner aggregate's output) is already whole
    # on every device, and a psum would multiply it by the shard count.
    fan_in = ctx.sharded and node.arg.edge.sharding == qplan.SHARDED
    if node.exact:
        resid, _base32 = ctx.fetch_ins[node.arg]["resid"]
        v = resid[:, :ctx.width_of[id(node)]]
    else:
        v = _emit(ctx, node.arg)
    mask = jnp.isfinite(v)
    cnt = jax.ops.segment_sum(mask.astype(_F32), gids, num_segments=g_pad)
    op = node.op
    if op in ("stddev", "stdvar"):
        # Population moments (promql stddev/stdvar; series_agg's segment
        # kernel): mean first, then the squared-deviation reduce — each
        # stage fanning in across shards before the next reads it.
        z = jnp.where(mask, v, 0.0)
        s = jax.ops.segment_sum(z, gids, num_segments=g_pad)
        if fan_in:  # m3lint: disable=jax-traced-branch
            s = jax.lax.psum(s, "shard")
            cnt = jax.lax.psum(cnt, "shard")
        mu = s / jnp.maximum(cnt, 1)
        dev = jnp.where(mask, v - mu[gids], 0.0)
        m2 = jax.ops.segment_sum(dev * dev, gids, num_segments=g_pad)
        if fan_in:  # m3lint: disable=jax-traced-branch
            m2 = jax.lax.psum(m2, "shard")
        var = m2 / jnp.maximum(cnt, 1)
        out = jnp.sqrt(var) if op == "stddev" else var
        return jnp.where(cnt > 0, out, jnp.nan)
    if op in ("sum", "avg"):
        s = jax.ops.segment_sum(jnp.where(mask, v, 0.0), gids,
                                num_segments=g_pad)
        # DELIBERATE (x4 below): fan_in is static program structure — the
        # mesh mode and the child edge's sharding annotation — fixed at
        # trace time; the collectives are emitted or not per executable.
        if fan_in:  # m3lint: disable=jax-traced-branch
            s = jax.lax.psum(s, "shard")
            cnt = jax.lax.psum(cnt, "shard")
        if node is ctx.plan.root:
            ctx.root_agg = (s, cnt)
        out = s / jnp.maximum(cnt, 1) if op == "avg" else s
        return jnp.where(cnt > 0, out, jnp.nan)
    if fan_in:  # m3lint: disable=jax-traced-branch
        cnt = jax.lax.psum(cnt, "shard")
    if op == "count":
        return jnp.where(cnt > 0, cnt, jnp.nan)
    if op == "group":
        return jnp.where(cnt > 0, 1.0, jnp.nan)
    if op == "min":
        m = jax.ops.segment_min(jnp.where(mask, v, jnp.inf), gids,
                                num_segments=g_pad)
        if fan_in:  # m3lint: disable=jax-traced-branch
            m = jax.lax.pmin(m, "shard")
        return jnp.where(cnt > 0, m, jnp.nan)
    # max (lowering admits nothing else)
    m = jax.ops.segment_max(jnp.where(mask, v, -jnp.inf), gids,
                            num_segments=g_pad)
    if fan_in:  # m3lint: disable=jax-traced-branch
        m = jax.lax.pmax(m, "shard")
    return jnp.where(cnt > 0, m, jnp.nan)


def _lower_binary(ctx: _Ctx, node: Binary):
    le, re_ = node.lhs.edge, node.rhs.edge
    comparison = node.op in promql.COMPARISON_OPS
    fn = _BIN_JNP[node.op]
    if le.kind == SCALAR and re_.kind == SCALAR:
        lv = _emit(ctx, node.lhs)
        rv = _emit(ctx, node.rhs)
        out = fn(lv, rv)
        if comparison and not node.bool_mode:
            return jnp.where(out > 0, lv, jnp.nan)
        return out
    if le.kind == SERIES and re_.kind == SERIES:
        many_idx, one_idx = ctx.aux_ins[ctx.path_of[id(node)]]
        lhs_v = _emit(ctx, node.lhs)
        rhs_v = _emit(ctx, node.rhs)
        many_v = rhs_v if node.swap else lhs_v
        one_v = lhs_v if node.swap else rhs_v
        # Index rows past the real match count pad with -1: a 0-padded
        # gather would replay row 0's FINITE values into the padding lanes,
        # and a downstream aggregate would fold that garbage into group 0.
        valid = (many_idx >= 0)[:, None]
        a = many_v[jnp.maximum(many_idx, 0)]
        b = one_v[jnp.maximum(one_idx, 0)]
        out = fn(b, a) if node.swap else fn(a, b)
        if comparison and not node.bool_mode:
            return jnp.where(valid & (out > 0), a, jnp.nan)
        both = jnp.isfinite(a) & jnp.isfinite(b)
        return jnp.where(valid & both, out, jnp.nan)
    # vector <op> scalar (either side)
    vec_left = le.kind == SERIES
    vec = _emit(ctx, node.lhs if vec_left else node.rhs)
    sc = _emit(ctx, node.rhs if vec_left else node.lhs)
    out = fn(vec, sc) if vec_left else fn(sc, vec)
    if comparison:
        if node.bool_mode:
            return jnp.where(jnp.isfinite(vec), out, jnp.nan)
        return jnp.where(out > 0, vec, jnp.nan)
    return out


def _emit(ctx: _Ctx, node: PlanNode):
    key = id(node)
    # DELIBERATE: the memo is keyed on PLAN NODE identity (static DAG
    # structure), not on any traced value.
    if key in ctx.cache:  # m3lint: disable=jax-traced-branch
        return ctx.cache[key]
    if isinstance(node, Fetch):
        val = _lower_fetch(ctx, node)
    elif isinstance(node, RangeFunc):
        val = _lower_rangefunc(ctx, node)
    elif isinstance(node, SubqueryFunc):
        val = _lower_subqueryfunc(ctx, node)
    elif isinstance(node, RankAgg):
        val = _lower_rankagg(ctx, node)
    elif isinstance(node, InstantFunc):
        val = _lower_instantfunc(ctx, node)
    elif isinstance(node, Aggregate):
        val = _lower_aggregate(ctx, node)
    elif isinstance(node, Binary):
        val = _lower_binary(ctx, node)
    elif isinstance(node, ScalarConst):
        val = ctx.slots[node.slot]
    else:  # pragma: no cover
        raise PlanFallback(type(node).__name__)
    ctx.cache[key] = val
    return val


# -------------------------------------------------------------- compiler


def _aux_layout(root: PlanNode) -> List[Tuple[int, int]]:
    """(preorder path, arity) per aux-consuming node: aggregates take one
    group-id array; vector-vector binaries two index arrays; rank
    aggregations a perm + inverse-perm pair; packed subqueries one
    column map; timestamp() one step-time vector. The stager and the
    trace-time unflattener both follow this order."""
    nodes: List[PlanNode] = []
    _preorder(root, nodes)
    out = []
    for i, n in enumerate(nodes):
        if isinstance(n, Aggregate):
            out.append((i, 1))
        elif _is_vv(n):
            out.append((i, 2))
        elif isinstance(n, RankAgg):
            out.append((i, 2))
        elif isinstance(n, SubqueryFunc) and n.packed:
            out.append((i, 1))
        elif isinstance(n, InstantFunc) and n.func == "timestamp":
            out.append((i, 1))
    return out


@telemetry.jit_builder("plan")
@functools.lru_cache(maxsize=int(os.environ.get("M3_TPU_PLAN_CACHE", "128")))
def _plan_executable(stripped: PlanNode, geom: Geometry,
                     mesh: Optional[Mesh], kinds_sig: tuple):
    """Build + jit ONE program for a plan structure. Keyed on the
    matcher-stripped plan, the pow2 geometry bucket and the mesh — one
    executable serves every query (any metric, any threshold, any series
    count within the bucket) with this plan shape."""
    fetches = tuple(f for f, _ in kinds_sig)
    kinds_by_fetch = dict(kinds_sig)
    sharded = geom.n_shard > 1
    plan = Plan(stripped, 0, 0, fetches, sharded)
    layout = _aux_layout(stripped)
    root_is_sum = (isinstance(stripped, Aggregate)
                   and stripped.op in ("sum", "avg"))

    def body(fetch_flat, aux_flat, slots):
        fetch_ins = {}
        i = 0
        for f in fetches:
            per = {}
            for kind in kinds_by_fetch[f]:
                n = _KIND_ARITY[kind]
                per[kind] = tuple(fetch_flat[i:i + n])
                i += n
            fetch_ins[f] = per
        aux_ins = {}
        k = 0
        for path, arity in layout:
            aux_ins[path] = tuple(aux_flat[k:k + arity])
            k += arity
        ctx = _Ctx(plan, geom, fetch_ins, aux_ins, slots, sharded)
        root_val = _emit(ctx, plan.root)
        extras = ctx.root_agg if root_is_sum else ()
        return root_val, (extras if extras is not None else ())

    if not sharded:
        return jax.jit(body)

    from .ingest import shard_map_compat

    fetch_specs = []
    for f in fetches:
        for kind in kinds_by_fetch[f]:
            for j in range(_KIND_ARITY[kind]):
                # baseline vectors ([S]) shard on their only axis
                one_d = kind == "resid" and j == 1
                fetch_specs.append(P("shard") if one_d
                                   else P("shard", None))
    # agg group-id vectors shard with their child's rows; aggregates over
    # replicated children take replicated ids; every other aux kind
    # (subquery column maps, timestamp times) is a replicated index
    # vector (vv binaries and rank aggs never mesh — mesh_ok is False)
    nodes: List[PlanNode] = []
    _preorder(stripped, nodes)
    aux_specs: List = []
    for n in nodes:
        if isinstance(n, Aggregate):
            aux_specs.append(P("shard")
                             if n.arg.edge.sharding == qplan.SHARDED
                             else P())
        elif _is_vv(n) or isinstance(n, RankAgg):
            aux_specs += [P(), P()]
        elif isinstance(n, SubqueryFunc) and n.packed:
            aux_specs.append(P())
        elif isinstance(n, InstantFunc) and n.func == "timestamp":
            aux_specs.append(P())
    aux_specs = tuple(aux_specs)
    root_edge = stripped.edge
    out_root_spec = (P("shard", None)
                     if root_edge.kind == SERIES
                     and root_edge.sharding == qplan.SHARDED else P())
    extras_spec = (P(), P()) if root_is_sum else ()
    fn = shard_map_compat(
        body, mesh=mesh,
        in_specs=(tuple(fetch_specs), aux_specs, P()),
        out_specs=(out_root_spec, extras_spec))
    return jax.jit(fn)


# -------------------------------------------------------------- execution


def _bucket_sig(geom: Geometry) -> str:
    """Compact shape-bucket label for ANALYZE device stages: padded rows
    per fetch x padded steps @ shard count — a closed set (quarter-octave
    buckets), safe as a stage-name suffix."""
    rows = "+".join(str(s) for s in geom.s_pads) or "0"
    return f"s{rows}xt{geom.t_pad}@{geom.n_shard}"


@functools.lru_cache(maxsize=256)
def _compile_sig(root: PlanNode, fetches: Tuple[Fetch, ...]):
    """Matcher-stripped compile key + per-fetch staged-input kinds for a
    plan structure — pure of (root, fetches), memoized so a repeated
    query shape doesn't rebuild the projection every dispatch."""
    fetch_index = {f: i for i, f in enumerate(fetches)}
    kinds = fetch_kinds(root)
    stripped = qplan.strip(root, fetch_index)
    kinds_sig = tuple((qplan.strip(f, fetch_index), kinds[f])
                      for f in fetches)
    return stripped, kinds_sig, kinds


def execute(bound: "qplan.Bound", mesh: Optional[Mesh]):
    """Run one bound plan compiled: stage inputs, fetch (or build) the
    plan executable, dispatch ONE program, host-finish. Returns
    (values, tags, fetch_fn): scalar roots materialize `values` [steps]
    f64 directly; series roots return fetch_fn, a closure lazily
    materializing the [rows, steps] f64 plane (LazyBlock
    double-buffering across a dashboard burst)."""
    plan = bound.plan
    sharded = (mesh is not None and plan.mesh_ok
               and mesh.shape["shard"] > 1)
    use_mesh = mesh if sharded else None
    geom = geometry_for(bound, mesh.shape["shard"] if sharded else 1)
    stripped, kinds_sig, kinds = _compile_sig(plan.root, plan.fetches)

    # --- staged fetch inputs (device-resident via the derived cache)
    fetch_flat: List = []
    for fi, f in enumerate(plan.fetches):
        arrs = _stage_fetch(bound.fetches[f], kinds[f], geom.s_pads[fi],
                            geom.f_exts[fi], use_mesh)
        fetch_flat.extend(arrs)

    # --- aux inputs (bind-time host label algebra -> index arrays)
    nodes: List[PlanNode] = []
    _preorder(plan.root, nodes)
    pad_rows = _padded_rows_map(bound, geom, nodes)
    width_of, _ = _widths(plan.root, geom.t_pad, geom.sub_pads)
    aux_flat: List[np.ndarray] = []
    vv_i = rank_i = 0
    for n in nodes:
        if isinstance(n, Aggregate):
            a = bound.aux[id(n)]
            g = np.zeros(pad_rows[id(n.arg)], dtype=np.int32)
            g[:len(a["group_ids"])] = a["group_ids"]
            aux_flat.append(g)
        elif _is_vv(n):
            a = bound.aux[id(n)]
            r_pad = geom.r_pads[vv_i]
            vv_i += 1
            mi = np.full(r_pad, -1, dtype=np.int32)
            oi = np.full(r_pad, -1, dtype=np.int32)
            mi[:len(a["many_idx"])] = a["many_idx"]
            oi[:len(a["one_idx"])] = a["one_idx"]
            aux_flat += [mi, oi]
        elif isinstance(n, RankAgg):
            a = bound.aux[id(n)]
            g_pad, smax_pad = geom.rank_pads[rank_i]
            rank_i += 1
            gids = a["group_ids"].astype(np.int64)
            perm = np.full(g_pad * smax_pad, -1, dtype=np.int32)
            inv = np.full(pad_rows[id(n.arg)], -1, dtype=np.int32)
            if len(gids):
                # Stable order packs each group's rows in their original
                # row order (the interpreter's flatnonzero tie-break).
                order = np.argsort(gids, kind="stable")
                sorted_g = gids[order]
                starts = np.searchsorted(
                    sorted_g, np.arange(max(a["n_groups"], 1)))
                slots_in_g = np.arange(len(gids)) - starts[sorted_g]
                packed_idx = (sorted_g * smax_pad
                              + slots_in_g).astype(np.int32)
                perm[packed_idx] = order
                inv[order] = packed_idx
            aux_flat += [perm, inv]
        elif isinstance(n, SubqueryFunc) and n.packed:
            a = bound.aux[id(n)]
            cols = np.full(width_of[id(n)] * n.W, -1, dtype=np.int32)
            cols[:len(a["cols"])] = a["cols"]
            aux_flat.append(cols)
        elif isinstance(n, InstantFunc) and n.func == "timestamp":
            a = bound.aux[id(n)]
            times = np.zeros(width_of[id(n)], dtype=np.float32)
            times[:len(a["times"])] = a["times"]
            aux_flat.append(times)

    slots = np.asarray(bound.slots, dtype=np.float32)
    if slots.size == 0:
        slots = np.zeros(1, dtype=np.float32)

    # Shape-bucket key for the compute-fault quarantine: a bucket whose
    # executable faulted post-compile must route to the interpreter
    # WITHOUT rebuilding (lru_cache has no per-key eviction — the guard
    # clears the whole builder cache on quarantine, and this pre-builder
    # probe keeps the poisoned bucket from recompiling until its TTL).
    bucket = (_bucket_sig(geom), hash((stripped, kinds_sig)))
    if pguard.is_quarantined("plan", bucket):
        telemetry.compute_route("plan", primary=False)
        raise PlanFallback(
            f"quarantined shape bucket {bucket[0]}",
            reason=qplan.FallbackReason.DEVICE_FAULT)

    fn = _plan_executable(stripped, geom, use_mesh, kinds_sig)
    missed = isinstance(fn, telemetry._CompileTimed)
    if missed:
        telemetry.plan_cache_miss()
    else:
        telemetry.plan_cache_hit()
    if sharded:
        telemetry.mesh_dispatch("plan", cells=int(bound.total_cells))

    # ANALYZE: with a context active the dispatch synchronizes so the
    # stage records the true program wall (keyed by shape bucket); off,
    # the cost is this one thread-local read and the async pipeline is
    # untouched (obs_overhead_guard's ANALYZE section enforces it).
    actx = qexplain.current()
    sync = missed or actx is not None
    t0 = time.perf_counter() if sync else 0.0

    def _fault_fallback(err):
        # The interpreter is the plan route's proven oracle: surface the
        # typed DEVICE_FAULT reason so the executor's existing fallback
        # path counts it (telemetry.plan_fallback scope=runtime) and
        # EXPLAIN shows the route the execution actually took.
        raise PlanFallback(
            f"device fault: {err}" if err is not None
            else "plan route degraded",
            reason=qplan.FallbackReason.DEVICE_FAULT)

    root_val, extras = pguard.dispatch(
        "plan",
        lambda: fn(tuple(fetch_flat), tuple(aux_flat), slots),
        _fault_fallback,
        key=bucket, evict=_plan_executable.cache_clear)
    if sync:
        (root_val, extras) = jax.block_until_ready((root_val, extras))
        dt = time.perf_counter() - t0
        if missed:
            telemetry.plan_compile_recorded(dt)
        if actx is not None:
            # A cache miss's first invocation fuses trace+XLA compile
            # with the execution — name the stage so a one-time compile
            # can't be misread as steady-state program wall.
            name = f"device_program[{_bucket_sig(geom)}]"
            if missed:
                name += "+compile"
                actx.event("plan_cache_miss")
            actx.add(name, dt)

    # --- host finish
    steps = plan.steps
    root = plan.root
    if numwatch.installed():
        # Numerics witness (M3_TPU_NUMERICS=1, smoke tiers only):
        # observe the PADDED program output before the host slices it —
        # live lanes are the bound result rows x real steps, and every
        # padding ROW past them must still be NaN (a finite value there
        # means a padding lane's value survived the masks).
        numwatch.observe_result(
            "plan", root_val,
            live_rows=(None if root.edge.kind == SCALAR
                       else len(bound.out_tags)),
            live_cols=steps)
    if root.edge.kind == SCALAR:
        val = np.asarray(root_val, dtype=np.float64)
        return np.full(steps, float(val)), bound.out_tags, None

    n_rows = len(bound.out_tags)
    result_bytes = n_rows * steps * (
        8 if isinstance(root, Aggregate) and root.op in ("sum", "avg")
        else 4)

    if isinstance(root, RankAgg) and root.op in ("topk", "bottomk"):
        # Eager host finish: the surviving SERIES SET is data-dependent
        # (rows in the k best at any step), so the tags can only be
        # fixed after materialization — the interpreter's all-NaN row
        # drop, applied to the masked plane.
        t0f = time.perf_counter() if actx is not None else 0.0
        vals = np.asarray(root_val)[:n_rows, :steps]
        telemetry.count_d2h(result_bytes)
        keep = ~np.all(np.isnan(vals), axis=1)
        tags = [t for t, k in zip(bound.out_tags, keep) if k]
        vals = np.ascontiguousarray(vals[keep])
        if actx is not None:
            actx.add("result_materialize", time.perf_counter() - t0f)
            actx.event("d2h_bytes", result_bytes)
        return None, tags, (lambda: vals)

    if isinstance(root, Aggregate) and root.op in ("sum", "avg"):
        s_dev, cnt_dev = extras
        # The async D2H starts on the arrays fetch() actually reads (a
        # sum/avg root finishes from its (s, cnt) components, not the
        # collapsed root plane).
        temporal._copy_async(s_dev, cnt_dev)

        def fetch():
            t0 = time.perf_counter() if actx is not None else 0.0
            s = np.asarray(s_dev, dtype=np.float64)[:n_rows, :steps]
            cnt = np.asarray(cnt_dev, dtype=np.float64)[:n_rows, :steps]
            telemetry.count_d2h(result_bytes)
            if root.exact:
                s = s + _exact_base_contrib(bound, root, n_rows, steps)
            out = s / np.maximum(cnt, 1) if root.op == "avg" else s
            result = np.where(cnt > 0, out, np.nan)
            if actx is not None:
                actx.add("result_materialize", time.perf_counter() - t0)
                actx.event("d2h_bytes", result_bytes)
            return result

        return None, bound.out_tags, fetch

    temporal._copy_async(root_val)

    def fetch():
        t0 = time.perf_counter() if actx is not None else 0.0
        telemetry.count_d2h(result_bytes)
        # f32, like the per-op interpreter path's result planes: the
        # padded [rows_pad, t_pad] plane is sliced, not up-converted.
        result = np.asarray(root_val)[:n_rows, :steps]
        if actx is not None:
            actx.add("result_materialize", time.perf_counter() - t0)
            actx.event("d2h_bytes", result_bytes)
        return result

    return None, bound.out_tags, fetch


def _padded_rows_map(bound: "qplan.Bound", geom: Geometry,
                     nodes: List[PlanNode]) -> Dict[int, int]:
    """Padded row count of every series-valued node's output plane (the
    length its consumer's per-row index inputs must be padded to)."""
    plan = bound.plan
    g_iter = iter(geom.g_pads)
    r_iter = iter(geom.r_pads)
    rank_iter = iter(geom.rank_pads)
    g_of: Dict[int, int] = {}
    r_of: Dict[int, int] = {}
    rank_of: Dict[int, Tuple[int, int]] = {}
    for n in nodes:
        if isinstance(n, Aggregate):
            g_of[id(n)] = next(g_iter)
        elif _is_vv(n):
            r_of[id(n)] = next(r_iter)
        elif isinstance(n, RankAgg):
            rank_of[id(n)] = next(rank_iter)

    out: Dict[int, int] = {}

    def rows(n: PlanNode) -> int:
        key = id(n)
        if key in out:
            return out[key]
        if isinstance(n, Fetch):
            r = geom.s_pads[plan.fetches.index(n)]
        elif isinstance(n, RangeFunc):
            r = 1 if n.func == "absent_over_time" else rows(n.arg)
        elif isinstance(n, (SubqueryFunc, InstantFunc)):
            r = rows(n.arg)
        elif isinstance(n, Aggregate):
            r = g_of[key]
        elif isinstance(n, RankAgg):
            # quantile collapses to group rows; topk keeps arg rows.
            r = rank_of[key][0] if n.op == "quantile" else rows(n.arg)
        elif isinstance(n, Binary):
            if _is_vv(n):
                r = r_of[key]
            elif n.lhs.edge.kind == SERIES:
                r = rows(n.lhs)
            else:
                r = rows(n.rhs)
        else:
            r = 0
        out[key] = r
        return r

    for n in nodes:
        rows(n)
    return out


def _exact_base_contrib(bound: "qplan.Bound", root: Aggregate,
                        n_rows: int, steps: int) -> np.ndarray:
    """Exact-f64 baseline mass for a counter sum: per-group baseline
    totals minus the baselines of MISSING cells (host, f64 — the part
    where f32 device accumulation of 1e9-magnitude counters would lose
    the host-reduce semantics). The common fully-dense case costs one
    isfinite pass; only rows with gaps pay the correction."""
    fetch = root.arg
    bf = bound.fetches[fetch]
    grid = bf.grid[:, :steps]
    finite = np.isfinite(grid)
    _, base = temporal.center(bf.grid)
    gids = bound.aux[id(root)]["group_ids"].astype(np.int64)
    g = int(bound.aux[id(root)]["n_groups"])
    base_g = np.zeros(g, dtype=np.float64)
    np.add.at(base_g, gids, base)
    out = np.repeat(base_g[:n_rows, None], steps, axis=1)
    missing_rows = np.nonzero(~finite.all(axis=1))[0]
    if missing_rows.size:
        corr = np.zeros((g, steps), dtype=np.float64)
        sub = np.where(finite[missing_rows], 0.0,
                       base[missing_rows][:, None])
        np.add.at(corr, gids[missing_rows], sub)
        out = out - corr[:n_rows]
    return out
