"""Guarded accelerated dispatch: the compute-fault plane (reference:
dbnode survives storage-node faults through panic-recovery and bootstrap
retries — the process restarts and replays; a TPU serving floor cannot
restart its way out of a poisoned shape bucket or a device OOM, so the
equivalent discipline is TYPED degradation at every dispatch seam).

Every accelerated route the perf PRs built — the whole-plan pjit execute
(`parallel/compile.py`), the mesh agg flush (`parallel/agg_flush.py`),
the mesh flush encode (`parallel/ingest.py`), the Pallas codec kernels
(`ops/pallas_codec.py` via the `ops/tsz.py` / `utils/hashing.py` route
pickers), the block plane decode (`storage/block.py`), and the temporal
jit builders — dispatches through `dispatch()`:

  classify     the JAX exception zoo collapses to a closed ComputeError
               taxonomy: CompileError / DeviceOOM / KernelFault /
               DispatchTimeout. Anything unclassifiable (a shape bug, a
               programming error) RE-RAISES — the guard degrades on
               device misbehavior, it never masks bugs as device faults.
  breaker      per-route failure-rate Breaker (utils/retry.py): repeated
               classified faults trip the route OPEN and every dispatch
               short-circuits to the route's proven fallback (the XLA
               twin for Pallas, the interpreter for the plan route, the
               single-device/host path for mesh flushes) until the
               cooldown's half-open probe succeeds.
  OOM retry    DeviceOOM triggers ONE forced `HBMBudget.reclaim_pass()`
               (cross-tenant LRU eviction even when the host ledger is
               under budget) then a single retry before falling back.
  quarantine   a shape-bucket executable that faults post-compile is
               keyed into a TTL'd quarantine set and its cache entry
               dropped via the caller's evictor, so a poisoned bucket
               routes straight to fallback instead of recompile-crash-
               looping.

Degradation is surfaced, never silent: `telemetry.compute.*` counts
routes/faults/trips per route (span-tagged — EXPLAIN and the slow-query
log name the degraded route), `HealthTracker` gains a compute-degraded
probe (tripped breakers read DEGRADED, never SHEDDING on their own), and
`debug_snapshot()` feeds /debug/vars breaker states + quarantined
buckets.

The dispatch seam itself is installable (mirroring `persist/diskio.py`'s
`_io` pattern): `testing/faultcomp.py` swaps in a seeded fault injector
whose schedule is a pure function of (seed, route, call-index). Output
validators (`validate=`) run ONLY while an injector seam is installed —
in production, silent-corruption detection stays the job of the numerics
witness and the serve-time integrity checks; the guard adds no per-value
work to clean dispatches.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Hashable, Optional

from . import telemetry
from ..utils import retry as uretry

__all__ = [
    "ComputeError", "CompileError", "DeviceOOM", "KernelFault",
    "DispatchTimeout", "classify", "dispatch", "available",
    "set_disabled", "configure", "reset", "debug_snapshot",
    "install_seam", "uninstall_seam", "seam_active", "eager",
    "guarded_builder", "quarantined_keys", "poisoned",
    "GARBAGE_F", "GARBAGE_I",
]


# ------------------------------------------------------------- taxonomy


class ComputeError(Exception):
    """Base of the closed device/kernel fault taxonomy. `kind` values are
    telemetry tag values (closed set; m3lint `unbounded-telemetry-tag`
    applies to anything riding them)."""

    kind = "compute"

    def __init__(self, route: str, detail: str,
                 cause: Optional[BaseException] = None):
        super().__init__(f"{route}: {detail}")
        self.route = route
        self.detail = detail
        self.cause = cause


class CompileError(ComputeError):
    """Trace/lowering/XLA-compilation failure for a shape bucket."""
    kind = "compile"


class DeviceOOM(ComputeError):
    """Device RESOURCE_EXHAUSTED: allocation failed on-chip."""
    kind = "oom"


class KernelFault(ComputeError):
    """A dispatched program raised (or produced provably corrupt output
    under an injector seam) — the generic device-side execution fault."""
    kind = "kernel"


class DispatchTimeout(ComputeError):
    """A dispatch exceeded the route's wall-clock budget (hang/delay)."""
    kind = "timeout"


# Exception type names that mark a device/runtime-side failure. Matched
# by NAME (not import) so classification works against every jaxlib
# vintage and against the injector's stand-in when jaxlib's class cannot
# be constructed.
_DEVICE_EXC_NAMES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "InternalError",
    "FailedPreconditionError", "ResourceExhaustedError",
})

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")
_TIMEOUT_MARKERS = ("DEADLINE_EXCEEDED", "deadline exceeded", "timed out")
_COMPILE_MARKERS = ("compilation", "Compilation", "Mosaic",
                    "lowering", "UNIMPLEMENTED")


def _is_device_exc(exc: BaseException) -> bool:
    return any(t.__name__ in _DEVICE_EXC_NAMES
               for t in type(exc).__mro__)


def classify(exc: BaseException, route: str) -> Optional[ComputeError]:
    """Collapse an exception into the ComputeError taxonomy, or None if
    it is not a device/kernel fault (the caller must re-raise — a
    TypeError from a shape bug is a bug, not degradation). Idempotent:
    an already-typed ComputeError passes through."""
    if isinstance(exc, ComputeError):
        return exc
    msg = str(exc)
    if any(m in msg for m in _OOM_MARKERS):
        return DeviceOOM(route, msg, exc)
    if _is_device_exc(exc):
        if any(m in msg for m in _TIMEOUT_MARKERS):
            return DispatchTimeout(route, msg, exc)
        if any(m in msg for m in _COMPILE_MARKERS):
            return CompileError(route, msg, exc)
        return KernelFault(route, msg, exc)
    if any(m in msg for m in _TIMEOUT_MARKERS):
        return DispatchTimeout(route, msg, exc)
    return None


# ------------------------------------------------------------------ seam


class DispatchSeam:
    """The installable dispatch seam (the `diskio._io` pattern for
    compute): production is a transparent passthrough; faultcomp installs
    a subclass whose `call` injects seeded faults."""

    def call(self, route: str, fn: Callable[[], Any]) -> Any:
        return fn()


_DEFAULT_SEAM = DispatchSeam()
_seam: DispatchSeam = _DEFAULT_SEAM


def install_seam(seam: DispatchSeam):
    global _seam
    _seam = seam


def uninstall_seam():
    global _seam
    _seam = _DEFAULT_SEAM


def seam_active() -> bool:
    return _seam is not _DEFAULT_SEAM


# -------------------------------------------------------- route registry


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class GuardedRoute:
    """Per-route breaker + quarantine + kill switch."""

    def __init__(self, name: str,
                 opts: Optional[uretry.BreakerOptions] = None,
                 clock: Callable[[], float] = time.monotonic,
                 timeout_s: Optional[float] = None,
                 quarantine_ttl_s: Optional[float] = None,
                 oom_retry: bool = True):
        self.name = name
        self.clock = clock
        self.breaker = uretry.Breaker(
            opts or uretry.BreakerOptions(
                window=16, failure_ratio=0.5, min_samples=4,
                cooldown_s=_env_float("M3_TPU_COMPUTE_COOLDOWN_S", 5.0)),
            clock=clock, name=f"compute.{name}")
        self.timeout_s = (timeout_s if timeout_s is not None else
                          _env_float("M3_TPU_COMPUTE_TIMEOUT_S", 30.0))
        self.quarantine_ttl_s = (
            quarantine_ttl_s if quarantine_ttl_s is not None else
            _env_float("M3_TPU_COMPUTE_QUARANTINE_TTL_S", 300.0))
        self.oom_retry = oom_retry
        self.disabled = False
        self._qlock = threading.Lock()
        self._quarantine: Dict[Hashable, float] = {}

    # ---------------------------------------------------------- quarantine

    def quarantine_add(self, key: Hashable):
        with self._qlock:
            self._quarantine[key] = self.clock() + self.quarantine_ttl_s

    def quarantined(self, key: Hashable) -> bool:
        with self._qlock:
            exp = self._quarantine.get(key)
            if exp is None:
                return False
            if self.clock() >= exp:
                del self._quarantine[key]
                return False
            return True

    def quarantine_keys(self) -> list:
        now = self.clock()
        with self._qlock:
            expired = [k for k, exp in self._quarantine.items()
                       if now >= exp]
            for k in expired:
                del self._quarantine[k]
            return list(self._quarantine)

    # ------------------------------------------------------------- breaker

    def record_failure(self):
        before = self.breaker.state
        self.breaker.record_failure()
        after = self.breaker.state
        if after != before:
            telemetry.compute_trip(self.name, after)

    def record_success(self):
        before = self.breaker.state
        self.breaker.record_success()
        after = self.breaker.state
        if after != before:
            telemetry.compute_trip(self.name, after)


_LOCK = threading.Lock()
_ROUTES: Dict[str, GuardedRoute] = {}
_PROBE_WIRED = False


def _wire_health_probe_locked():
    # Lazy, once: tripped breakers read DEGRADED (0.8 sits between the
    # tracker's degraded_at=0.7 and shedding_at=0.95) — compute
    # degradation must never shed load on its own; the fallbacks still
    # serve correct results, just slower.
    global _PROBE_WIRED
    if _PROBE_WIRED:
        return
    from ..utils import health

    health.TRACKER.register("compute_degraded", _degradation)
    _PROBE_WIRED = True


def _degradation() -> float:
    with _LOCK:
        routes = list(_ROUTES.values())
    for r in routes:
        if r.disabled:
            continue  # an operator kill switch is policy, not an incident
        if r.breaker.state != uretry.Breaker.CLOSED:
            return 0.8
    return 0.0


def _route(name: str) -> GuardedRoute:
    with _LOCK:
        r = _ROUTES.get(name)
        if r is None:
            r = GuardedRoute(name)
            _ROUTES[name] = r
            _wire_health_probe_locked()
        return r


def configure(name: str, *,
              opts: Optional[uretry.BreakerOptions] = None,
              clock: Callable[[], float] = time.monotonic,
              timeout_s: Optional[float] = None,
              quarantine_ttl_s: Optional[float] = None,
              oom_retry: bool = True) -> GuardedRoute:
    """(Re)build a route with explicit breaker options / clock — the test
    surface for deterministic trip/half-open/quarantine-TTL campaigns."""
    with _LOCK:
        r = GuardedRoute(name, opts=opts, clock=clock, timeout_s=timeout_s,
                         quarantine_ttl_s=quarantine_ttl_s,
                         oom_retry=oom_retry)
        _ROUTES[name] = r
        _wire_health_probe_locked()
        return r


def set_disabled(name: str, disabled: bool):
    """Per-route kill switch (the per-kernel M3_TPU_PALLAS story: flip
    ONE codec kernel to its XLA twin mid-process without touching the
    global env)."""
    _route(name).disabled = bool(disabled)


def available(name: str) -> bool:
    """Cheap route-picker check: False when the route is killed or its
    breaker is OPEN. Does NOT consume a half-open probe slot — pickers
    that see True still dispatch through `dispatch()`, where the breaker
    does its bookkeeping."""
    with _LOCK:
        r = _ROUTES.get(name)
    if r is None:
        return True
    return not r.disabled and r.breaker.state != uretry.Breaker.OPEN


def quarantined_keys(name: str) -> list:
    with _LOCK:
        r = _ROUTES.get(name)
    return r.quarantine_keys() if r is not None else []


def is_quarantined(name: str, key: Hashable) -> bool:
    """Pre-builder quarantine probe: callers whose executable cache has
    no per-key eviction (functools.lru_cache) consult this BEFORE the
    builder so a poisoned bucket skips straight to fallback without
    rebuilding anything."""
    with _LOCK:
        r = _ROUTES.get(name)
    return r is not None and r.quarantined(key)


def reset():
    """Drop every route (breakers, quarantine, kill switches). Test
    hygiene only; the seam is managed separately (faultcomp.uninstall)."""
    with _LOCK:
        _ROUTES.clear()


# ------------------------------------------------------ corruption probe

# The poison values faultcomp writes into corrupted output planes. Guard
# owns the contract (faultcomp imports these) so call sites never import
# testing code: a fully-poisoned plane — every element NaN, or every
# element the garbage sentinel — is detectable without consulting the
# oracle, which is exactly what a hardware bit-smear on a whole tile
# looks like from the host.
GARBAGE_F = 6.02214076e23
GARBAGE_I = -559038737  # 0xDEADBEEF as int32


def _iter_leaves(out):
    if isinstance(out, (tuple, list)):
        for v in out:
            yield from _iter_leaves(v)
    elif isinstance(out, dict):
        for v in out.values():
            yield from _iter_leaves(v)
    elif hasattr(out, "dtype") and hasattr(out, "shape"):
        yield out


def poisoned(out) -> Optional[str]:
    """Default output validator: detail string when any array leaf is a
    fully-poisoned plane (all-NaN, or every element equal to the garbage
    sentinel cast to its dtype). Only consulted while an injector seam is
    installed — see `dispatch`."""
    import numpy as np

    for leaf in _iter_leaves(out):
        a = np.asarray(leaf)
        if a.size == 0:
            continue
        if a.dtype.kind == "f":
            if np.isnan(a).all():
                return f"all-NaN plane shape={a.shape}"
            if (a == np.asarray(GARBAGE_F).astype(a.dtype)).all():
                return f"garbage-filled plane shape={a.shape}"
        elif a.dtype.kind in "iu":
            if (a == np.asarray(GARBAGE_I).astype(a.dtype)).all():
                return f"garbage-filled plane shape={a.shape}"
    return None


# ------------------------------------------------------------- dispatch


def _oom_reclaim(route: str) -> int:
    from ..utils import hbm

    budget = hbm.shared_budget()
    freed = budget.reclaim()
    if freed == 0:
        # Host ledger under budget but the DEVICE said RESOURCE_EXHAUSTED:
        # force one cross-tenant LRU pass anyway.
        freed = budget.reclaim_pass()
    telemetry.compute_oom_reclaim(route, freed)
    return freed


def dispatch(route: str,
             primary: Callable[[], Any],
             fallback: Callable[[Optional[ComputeError]], Any],
             *,
             key: Optional[Hashable] = None,
             evict: Optional[Callable[[], None]] = None,
             validate: Optional[Callable[[Any], Optional[str]]] = poisoned):
    """Run `primary` through the guarded seam for `route`; on a
    classified fault, degrade to `fallback(err)`.

    `key` names the shape-bucket executable (quarantined on post-compile
    faults; `evict` drops its cache entry). `validate(out)` returns a
    detail string when the output is provably corrupt (default: the
    poisoned-plane probe) — consulted ONLY while an injector seam is
    installed (see module docstring). Unclassifiable exceptions re-raise
    untouched."""
    r = _route(route)
    if r.disabled:
        telemetry.compute_route(route, primary=False)
        return fallback(None)
    if key is not None and r.quarantined(key):
        telemetry.compute_route(route, primary=False)
        return fallback(KernelFault(route, f"quarantined bucket {key!r}"))
    if not r.breaker.allow():
        telemetry.compute_route(route, primary=False)
        return fallback(ComputeError(route, "breaker open"))

    # The allow() grant MUST settle exactly once (record_success /
    # record_failure / cancel) on every path — an unsettled grant leaks
    # the half-open probe slot and wedges the breaker half-open forever
    # (m3lint's lifecycle pass checks this). The finally below is the
    # backstop for exceptions raised between the grant and a settle
    # (telemetry, validate, the fallback itself).
    settled = False
    try:
        err: Optional[ComputeError] = None
        out: Any = None
        t0 = r.clock()
        try:
            out = _seam.call(route, primary)
        except ComputeError as exc:
            err = exc
        except Exception as exc:  # noqa: BLE001 — classified or re-raised
            err = classify(exc, route)
            if err is None:
                r.breaker.cancel()  # not a device fault: release the slot
                settled = True
                raise
        if err is None:
            elapsed = r.clock() - t0
            if validate is not None and seam_active():
                bad = validate(out)
                if bad is not None:
                    err = KernelFault(route, f"corrupted output: {bad}")
            if err is None and elapsed > r.timeout_s:
                # The result is VALID (the program finished) but the
                # route is hanging: count the fault against the breaker
                # and keep the answer — repeated delays trip the route
                # to the faster fallback.
                r.record_failure()
                settled = True
                telemetry.compute_fault(route, DispatchTimeout.kind)
                telemetry.compute_route(route, primary=True)
                return out
            if err is None:
                r.record_success()
                settled = True
                telemetry.compute_route(route, primary=True)
                return out

        telemetry.compute_fault(route, err.kind)

        if isinstance(err, DeviceOOM) and r.oom_retry:
            _oom_reclaim(route)
            try:
                out = _seam.call(route, primary)
            except ComputeError as exc:
                err = exc
                telemetry.compute_fault(route, err.kind)
            except Exception as exc:  # noqa: BLE001 — same contract
                err2 = classify(exc, route)
                if err2 is None:
                    r.breaker.cancel()
                    settled = True
                    raise
                err = err2
                telemetry.compute_fault(route, err.kind)
            else:
                bad = (validate(out)
                       if validate is not None and seam_active() else None)
                if bad is None:
                    r.record_success()
                    settled = True
                    telemetry.compute_route(route, primary=True)
                    return out
                err = KernelFault(route, f"corrupted output: {bad}")
                telemetry.compute_fault(route, err.kind)

        r.record_failure()
        settled = True
        if key is not None:
            r.quarantine_add(key)
            telemetry.compute_quarantine(route)
            if evict is not None:
                try:
                    evict()
                except Exception:  # noqa: BLE001 — eviction best-effort;
                    pass  # the quarantine set already blocks the bucket
        telemetry.compute_route(route, primary=False)
        return fallback(err)
    finally:
        if not settled:
            r.breaker.cancel()


# -------------------------------------------------- fallback conveniences


def eager(fn: Callable, *args, **kwargs):
    """Universal jit fallback: run an (already-jitted) callable eagerly.
    `jax.disable_jit()` is consulted at call time, so it works on cached
    executables without retracing machinery of our own."""
    import jax

    with jax.disable_jit():
        return fn(*args, **kwargs)


class _GuardedFn:
    """Wraps a builder-returned jitted callable: each invocation
    dispatches through the guard with the eager twin as fallback."""

    __slots__ = ("route", "fn")

    def __init__(self, route: str, fn: Callable):
        self.route = route
        self.fn = fn

    def __call__(self, *args, **kwargs):
        return dispatch(
            self.route,
            lambda: self.fn(*args, **kwargs),
            lambda _err: eager(self.fn, *args, **kwargs))


def guarded_builder(route: str):
    """Stack ABOVE `telemetry.jit_builder` on a temporal jit builder:

        @guard.guarded_builder("temporal.rate")
        @telemetry.jit_builder("rate")
        @functools.lru_cache(maxsize=256)
        def _rate_fn(...): ... return jax.jit(fn)

    The callables the builder returns are wrapped so every invocation
    dispatches through the guard with the eager (disable_jit) path as
    the route's fallback. cache_info/cache_clear stay forwarded for the
    callers and m3lint's discovery."""

    def deco(builder: Callable):
        def wrapper(*args, **kwargs):
            return _GuardedFn(route, builder(*args, **kwargs))

        wrapper.cache_info = getattr(builder, "cache_info", None)
        wrapper.cache_clear = getattr(builder, "cache_clear", None)
        wrapper.__wrapped__ = builder
        wrapper.__name__ = getattr(builder, "__name__", "guarded")
        wrapper.__doc__ = getattr(builder, "__doc__", None)
        return wrapper

    return deco


# ---------------------------------------------------------- observability


def debug_snapshot() -> dict:
    """Breaker states + quarantined buckets for /debug/vars."""
    with _LOCK:
        routes = list(_ROUTES.values())
    out = {}
    for r in routes:
        out[r.name] = {
            "state": r.breaker.state,
            "disabled": r.disabled,
            "quarantined": sorted(repr(k) for k in r.quarantine_keys()),
        }
    return out
