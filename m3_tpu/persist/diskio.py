"""Disk I/O seam for persist/ (reference: the reference platform's
persist/fs os wrappers, which the dtest disk-fault drills interpose on).

Every file operation persist/fs.py and persist/commitlog.py perform is
routed through a module-level `_io` that defaults to the passthrough
`DiskIO` below — one attribute lookup plus one delegating call when no
injector is installed (zero overhead when off, the faultnet seam
contract). `m3_tpu.testing.faultfs` swaps in a seeded `FaultIO` that
returns bit-flipped/short reads, raises EIO/ENOSPC on writes, lies on
fsync, and tears `os.replace` — the disk leg of the fault trilogy
(network: faultnet, crash: kill -9 drill, disk: this).

Typed error taxonomy (classification the lint tree enforces in
analysis/diskio_rules.py — persist callers must never fold these into a
bare `except Exception`):

  CorruptionError   bytes on disk diverge from their recorded checksum
                    (row adler, digest chain, chunk adler). Subclasses
                    IOError so pre-existing `except (IOError, ...)`
                    handlers keep working, and NonRetryableError so a
                    Retrier never re-reads rotten bytes — corruption is
                    repaired from peers, not retried.
  DiskWriteError    a write/flush/fsync failed (EIO et al). Retryable:
                    transient media errors clear; the flush path retries
                    with backoff and degrades health while they persist.
  DiskFullError     ENOSPC/EDQUOT — DiskWriteError specialization so
                    full-disk shows up typed in health/degradation.
"""

from __future__ import annotations

import errno
import os
from typing import Iterable, Optional, Sequence

import numpy as np

from ..utils.retry import NonRetryableError

__all__ = [
    "CorruptionError", "DiskWriteError", "DiskFullError",
    "classify_write_error", "DiskIO", "DEFAULT",
]


class CorruptionError(IOError, NonRetryableError):
    """On-disk bytes diverge from their recorded checksum. Carries the
    failing path and (when row-granular) the failing rows/ids so the
    quarantine sidecar can name them."""

    def __init__(self, message: str, path: Optional[str] = None,
                 rows: Sequence[int] = (), ids: Iterable[bytes] = ()):
        super().__init__(message)
        self.path = path
        self.rows = [int(r) for r in rows]
        self.ids = [bytes(i) for i in ids]


class DiskWriteError(IOError):
    """A write/flush/fsync to durable storage failed."""

    def __init__(self, message: str, path: Optional[str] = None,
                 errno_: Optional[int] = None):
        super().__init__(message)
        self.path = path
        self.errno = errno_


class DiskFullError(DiskWriteError):
    """ENOSPC/EDQUOT: the device is out of space, not merely flaky."""


_FULL_ERRNOS = {errno.ENOSPC, getattr(errno, "EDQUOT", errno.ENOSPC)}


def classify_write_error(e: OSError, path: Optional[str] = None
                         ) -> DiskWriteError:
    """Fold a raw OSError from a durable write into the typed taxonomy
    (ENOSPC/EDQUOT -> DiskFullError, anything else -> DiskWriteError).
    Already-typed errors pass through unchanged so a double classify is
    idempotent."""
    if isinstance(e, DiskWriteError):
        return e
    num = getattr(e, "errno", None)
    cls = DiskFullError if num in _FULL_ERRNOS else DiskWriteError
    return cls(f"{type(e).__name__}: {e}", path=path, errno_=num)


class DiskIO:
    """Passthrough file operations — the exact set persist/ uses. An
    injector subclasses this; the default is stateless and shared."""

    def open(self, path: str, mode: str = "r", **kw):
        return open(path, mode, **kw)

    def fsync(self, f) -> None:
        os.fsync(f.fileno())

    def replace(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def remove(self, path: str) -> None:
        os.remove(path)

    def memmap(self, path: str, dtype, shape) -> np.ndarray:
        return np.memmap(path, dtype=dtype, mode="r", shape=shape)


DEFAULT = DiskIO()
