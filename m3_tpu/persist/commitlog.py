"""Commit log WAL (reference: src/dbnode/persist/fs/commitlog).

Same invariants as the reference's chunked WAL (commit_log.go:69,205;
writer.go; chunk_reader.go):
  - entries buffer in memory and flush as length-prefixed chunks with an
    adler32 per chunk; a torn final chunk is detected and dropped on replay
  - per-file series dictionary: a series' {namespace, id} metadata is
    written once per file, entries reference it by index
    (docs/m3db/architecture/commitlogs.md:21-33)
  - strategies: WRITE_WAIT flushes synchronously on every write;
    WRITE_BEHIND flushes on the flush interval / explicit flush
    (commit_log.go:241-242)
  - rotation starts a new numbered file; one commit log serves ALL
    namespaces (commitlogs.md:5)
"""

from __future__ import annotations

import enum
import logging
import os
import struct
import threading
import time
import zlib
from typing import Callable, Dict, Iterator, List, NamedTuple, Optional, Tuple

import numpy as np

from . import diskio
from .diskio import classify_write_error

# The disk I/O seam (persist/diskio.py): every file operation below
# routes through this module-level indirection — one attribute lookup
# when no injector is installed. testing/faultfs.py swaps it.
_io = diskio.DEFAULT

# Per-file format header, written before the first chunk: replay
# refuses (skips, with a warning) files whose magic/version don't match
# instead of misparsing a foreign or older layout into garbage entries.
# v2 = meta entries carry encoded tags.
_FILE_MAGIC = b"M3TPUWAL"
_FILE_VERSION = 2
_FILE_HEADER = _FILE_MAGIC + struct.pack("<H", _FILE_VERSION)

_CHUNK_HEADER = struct.Struct("<II")      # payload_len, adler32
# tag=0, ns_len, id_len, tags_len — the tags bytes are the x/serialize
# tag codec (utils.serialize.encode_tags), written once per series per
# file like the rest of the metadata (the reference's commitlog series
# metadata carries EncodedTags the same way, commitlogs.md:21-33): WAL
# replay must be able to REBUILD the reverse index for series whose
# index blocks were never flushed, or recovered data is unreachable by
# query after kill -9.
_META_ENTRY = struct.Struct("<BHHH")
_DATA_ENTRY = struct.Struct("<BIqd")      # tag=1, series_ref, time_ns, value


class Strategy(enum.Enum):
    WRITE_WAIT = "write_wait"
    WRITE_BEHIND = "write_behind"


class CommitLog:
    def __init__(self, directory: str, strategy: Strategy = Strategy.WRITE_BEHIND,
                 flush_interval_ns: int = 1_000_000_000,
                 clock: Optional[Callable[[], int]] = None):
        self.directory = directory
        self.strategy = strategy
        self.flush_interval_ns = flush_interval_ns
        self.clock = clock or time.time_ns
        os.makedirs(directory, exist_ok=True)
        existing = [int(f.split("-")[1].split(".")[0]) for f in os.listdir(directory)
                    if f.startswith("commitlog-")]
        self._file_num = max(existing, default=-1) + 1
        self._f = None
        self._buf = bytearray()
        self._series_refs: Dict[Tuple[bytes, bytes], int] = {}
        # Per-file: keys whose emitted meta carried no tags (a later
        # tagged write re-emits), and the count of metas emitted (the
        # ref numbering replay's append-only table reproduces).
        self._untagged_keys: set = set()
        self._meta_count = 0
        self._last_flush = self.clock()
        # One appender file shared by every shard's write path: the commit
        # log serializes internally (commit_log.go's single writer loop)
        # now that the node no longer holds a global write mutex.
        self._lock = threading.RLock()
        self._open_new_file()

    # ----------------------------------------------------------------- files

    def _path(self, num: int) -> str:
        return os.path.join(self.directory, f"commitlog-{num:08d}.bin")

    def _open_new_file(self):
        if self._f is not None:
            self.flush()
            self._f.close()
        self._f = _io.open(self._path(self._file_num), "ab")
        try:
            if self._f.tell() == 0:
                # Fresh file: stamp the format header before any chunk.
                self._f.write(_FILE_HEADER)
                self._f.flush()
        except OSError:
            # Header write failed (EIO/ENOSPC): deferred — flush()
            # re-stamps before the first chunk, so a headerless file
            # never accumulates chunks replay would refuse to parse.
            pass
        self._series_refs.clear()
        self._untagged_keys.clear()
        self._meta_count = 0

    def rotate(self) -> int:
        """Start a new commit log file (rotation on flush/time window)."""
        with self._lock:
            old = self._file_num
            self._file_num += 1
            self._open_new_file()
            return old

    def active_file(self) -> str:
        return self._path(self._file_num)

    def files(self) -> List[str]:
        return sorted(
            os.path.join(self.directory, f) for f in os.listdir(self.directory)
            if f.startswith("commitlog-")
        )

    def remove_files_before(self, file_num: int):
        """Cleanup after flush durability (storage/cleanup.go)."""
        for f in self.files():
            num = int(os.path.basename(f).split("-")[1].split(".")[0])
            if num < file_num:
                _io.remove(f)

    # ---------------------------------------------------------------- writes

    @staticmethod
    def _encode_tags_safe(tags: Optional[dict]) -> bytes:
        """Best-effort x/serialize encoding: str keys/values (the JSON
        ingest surfaces hand those over) normalize to utf-8, and ANY
        encoding failure degrades to untagged metadata instead of
        raising — the write path has already applied the point to the
        shard buffer, so a tags problem must never abort the append and
        leave served data missing from the WAL."""
        if not tags:
            return b""
        from ..utils import serialize as tag_serialize

        try:
            norm = {
                (k.encode() if isinstance(k, str) else k):
                (v.encode() if isinstance(v, str) else v)
                for k, v in tags.items()}
            return tag_serialize.encode_tags(norm)
        except (tag_serialize.TagEncodeError, TypeError, ValueError,
                AttributeError, UnicodeError):
            return b""

    def _ref(self, namespace: bytes, series_id: bytes,
             tags: Optional[dict] = None) -> int:
        key = (namespace, series_id)
        ref = self._series_refs.get(key)
        if ref is not None and not (tags and key in self._untagged_keys):
            # Steady state (known ref, tags already logged or absent):
            # one dict probe, no per-datapoint tag encode.
            return ref
        encoded = self._encode_tags_safe(tags)
        if ref is not None:
            if not encoded:
                # Tags unencodable: keep the untagged ref, and stop
                # retrying the encode per DATAPOINT — dropping the key
                # from the untagged set means this series' tag upgrade
                # is attempted once per file, not once per write, under
                # the lock every shard's write path serializes on.
                self._untagged_keys.discard(key)
                return ref
            # The series' first sighting this file was UNTAGGED and a
            # tagged write has now arrived: emit a fresh tagged meta
            # (allocating a new ref — replay tables are append-only) so
            # recovery can still rebuild this series' index document.
            ref = None
        if ref is None:
            # Refs are assigned in META EMISSION order (replay's table
            # appends one entry per meta), which diverges from the
            # distinct-key count once a tagged re-emission happens.
            ref = self._meta_count
            self._meta_count += 1
            self._series_refs[key] = ref
            if encoded:
                self._untagged_keys.discard(key)
            else:
                self._untagged_keys.add(key)
            self._buf += _META_ENTRY.pack(0, len(namespace), len(series_id),
                                          len(encoded))
            self._buf += namespace
            self._buf += series_id
            self._buf += encoded
        return ref

    def write(self, namespace: bytes, series_id: bytes, t_ns: int, value: float,
              tags: Optional[dict] = None):
        with self._lock:
            if self._f is None:
                raise ValueError("commit log is closed")
            ref = self._ref(namespace, series_id, tags)
            self._buf += _DATA_ENTRY.pack(1, ref, t_ns, value)
            self._maybe_flush()

    def write_batch(self, namespace: bytes, ids, ts, vals, tags=None):
        with self._lock:
            if self._f is None:
                raise ValueError("commit log is closed")
            for i, (sid, t, v) in enumerate(zip(ids, ts, vals)):
                ref = self._ref(namespace, sid,
                                tags[i] if tags is not None else None)
                self._buf += _DATA_ENTRY.pack(1, ref, int(t), float(v))
            self._maybe_flush()

    def _maybe_flush(self):
        if self.strategy == Strategy.WRITE_WAIT:
            self.flush()
        elif self.clock() - self._last_flush >= self.flush_interval_ns:
            self.flush()

    def flush(self):
        """Write buffered entries as one checksummed chunk (writer.go).

        A failed write/fsync is an ACK failure, not a silent accept: the
        chunk is WITHDRAWN (truncated back, the file rotated so the
        per-file series dictionary can't dangle into the torn region)
        and the error re-raised TYPED — DiskWriteError for EIO-class
        media failures, DiskFullError for ENOSPC — so the write path
        propagates a classified error to the client instead of acking
        bytes that never became durable."""
        with self._lock:
            if not self._buf or self._f is None:
                return
            payload = bytes(self._buf)
            self._buf.clear()
            start = self._f.tell()
            try:
                if start < len(_FILE_HEADER):
                    # Header deferred by an earlier fault (or torn): the
                    # file must open with the format stamp or replay
                    # skips every chunk in it.
                    self._f.truncate(0)
                    start = 0
                    self._f.write(_FILE_HEADER)
                self._f.write(_CHUNK_HEADER.pack(len(payload),
                                                 zlib.adler32(payload)))
                self._f.write(payload)
                self._f.flush()
                _io.fsync(self._f)
            except OSError as e:
                path = self._path(self._file_num)
                self._withdraw_failed_chunk(start)
                raise classify_write_error(e, path) from e
            self._last_flush = self.clock()

    def _withdraw_failed_chunk(self, start: int):
        """Roll back a chunk whose write/fsync failed: truncate the file
        to its pre-chunk length (best effort — a torn half-chunk at EOF
        is dropped by replay either way) and rotate to a fresh file.
        Rotation is unconditional: the failed payload may have carried
        META entries the in-memory series dictionary already counted, so
        appending more chunks to this file would emit data entries whose
        refs dangle into the withdrawn region — replay would clean-stop
        there and strand every later (acked) chunk in the file."""
        try:
            self._f.truncate(start)
        except OSError:
            pass
        try:
            self._f.close()
        except OSError:
            pass
        self._f = None
        self._file_num += 1
        try:
            self._f = _io.open(self._path(self._file_num), "ab")
        except OSError:
            # Could not even open a fresh file: the log stays closed
            # (writes raise "commit log is closed") until rotate().
            self._f = None
        if self._f is not None:
            try:
                if self._f.tell() == 0:
                    self._f.write(_FILE_HEADER)
                    self._f.flush()
            except OSError:
                pass  # deferred: the next flush() re-stamps
        self._series_refs.clear()
        self._untagged_keys.clear()
        self._meta_count = 0

    def position(self) -> Tuple[int, int]:
        """Durable WAL position (file_num, byte offset) AFTER flushing
        the buffered entries: every entry written before this call is
        at or before the returned position, and the position lands on a
        chunk boundary (flush writes whole chunks). Snapshots record it
        so recovery replays only the WAL tail SINCE the snapshot
        (snapshot_metadata's CommitlogIdentifier in the reference)."""
        with self._lock:
            if self._f is None:
                raise ValueError("commit log is closed")
            self.flush()
            return self._file_num, self._f.tell()

    def close(self):
        with self._lock:
            if self._f is not None:
                try:
                    self.flush()
                finally:
                    # A typed flush failure may already have swapped or
                    # dropped the handle (_withdraw_failed_chunk).
                    if self._f is not None:
                        self._f.close()
                    self._f = None


def _iter_chunks(path: str) -> Iterator[Tuple[bytes, int]]:
    """Stream one file's valid chunk bodies in order as (body,
    end_offset), stopping at the first torn/corrupt chunk (reader.go
    chunk validation). Reads ONE chunk at a time, so replay RSS is
    bounded by the largest chunk, never the WAL file size. A file
    without this format's header (foreign layout, older version) is
    SKIPPED with a warning — misparsing would fabricate entries."""
    with _io.open(path, "rb") as f:
        header = f.read(len(_FILE_HEADER))
        if header != _FILE_HEADER:
            logging.getLogger("m3_tpu.persist.commitlog").warning(
                "skipping commitlog file %s: unrecognized format header "
                "%r (want %r)", path, header[:10], _FILE_HEADER)
            return
        offset = len(_FILE_HEADER)
        while True:
            header = f.read(_CHUNK_HEADER.size)
            if len(header) < _CHUNK_HEADER.size:
                return
            plen, checksum = _CHUNK_HEADER.unpack(header)
            body = f.read(plen)
            if len(body) < plen or zlib.adler32(body) != checksum:
                return  # torn/corrupt tail chunk: stop replaying this file
            offset += _CHUNK_HEADER.size + plen
            yield body, offset


# One decoded data entry (tag=1) viewed columnar: numpy's packed layout
# of this dtype is byte-identical to _DATA_ENTRY's struct layout, so a
# run of consecutive data entries decodes as ONE frombuffer view.
_DATA_DTYPE = np.dtype([("tag", "u1"), ("ref", "<u4"),
                        ("t", "<i8"), ("v", "<f8")])
assert _DATA_DTYPE.itemsize == _DATA_ENTRY.size


class ReplayBatch(NamedTuple):
    """One chunk's worth of replayed entries as parallel columns.

    (file_num, end_offset) is the chunk's position in the WAL stream:
    comparing it against a snapshot's recorded `CommitLog.position()`
    tells recovery whether every entry in this chunk predates that
    snapshot (positions are chunk-aligned — position() flushes first)."""

    namespaces: np.ndarray  # object [N] bytes
    ids: np.ndarray         # object [N] bytes
    t_ns: np.ndarray        # int64 [N]
    values: np.ndarray      # float64 [N]
    file_num: int = -1
    end_offset: int = 0
    # Per-entry decoded tag dicts (None for untagged series / undecodable
    # tag bytes): recovery re-indexes series whose index blocks were
    # never flushed.
    tags: Optional[np.ndarray] = None  # object [N] Optional[dict]

    def __len__(self) -> int:
        return len(self.t_ns)

    def before(self, position: Optional[Tuple[int, int]]) -> bool:
        """True when every entry in this chunk was durably logged at or
        before `position` (a (file_num, offset) from position())."""
        if position is None:
            return False
        return (self.file_num, self.end_offset) <= tuple(position)


def replay_batches(directory: str) -> Iterator[ReplayBatch]:
    """Columnar replay: decode each checksummed chunk into (namespaces,
    ids, t_ns[], values[]) ndarray columns, streamed chunk-at-a-time —
    the recovery data plane's input shape (one batch feeds one
    vectorized shard-route + per-shard buffer append downstream,
    instead of one host loop iteration per WAL entry).

    Entry-for-entry bit-identical to `replay_ref` (the retained
    per-entry oracle), including its behavior on corrupt streams that
    still pass the chunk checksum (a delete of exactly chunk-aligned
    bytes realigns the stream): a data entry referencing an unknown
    series, or a truncated entry, stops THIS FILE cleanly after the
    preceding entries are yielded — corruption is a clean stop, never
    a crash, and damage never leaks across files (the durability fuzz
    campaign's contract)."""
    if not os.path.isdir(directory):
        return
    files = sorted(f for f in os.listdir(directory) if f.startswith("commitlog-"))
    rec = _DATA_ENTRY.size
    from ..utils import serialize as tag_serialize

    for fname in files:
        file_num = int(fname.split("-")[1].split(".")[0])
        series_ns: List[bytes] = []
        series_id: List[bytes] = []
        series_tags: List[Optional[dict]] = []
        # Object-array views of the tables, rebuilt only when a chunk
        # appended metas: WRITE_WAIT logs one chunk per write, so
        # rebuilding per chunk would be O(chunks x series) — quadratic
        # over a big file's replay.
        tabs: List[Optional[np.ndarray]] = [None, None, None]

        def _tables() -> List[np.ndarray]:
            if tabs[0] is None or len(tabs[0]) != len(series_ns):
                tabs[0] = np.array(series_ns, object)
                tabs[1] = np.array(series_id, object)
                tag_tab = np.empty(len(series_tags), object)
                tag_tab[:] = series_tags
                tabs[2] = tag_tab
            return tabs

        for body, end_offset in _iter_chunks(os.path.join(directory, fname)):
            tags = np.frombuffer(body, np.uint8)
            pos = 0
            refs_parts: List[np.ndarray] = []
            t_parts: List[np.ndarray] = []
            v_parts: List[np.ndarray] = []
            # Length-1 runs (a fresh file's first chunk alternates meta
            # and data one-to-one) decode scalar into these pending
            # columns — numpy per-call overhead on 21-byte runs would
            # dominate the whole replay; flushed in arrival order.
            ref_s: List[int] = []
            t_s: List[int] = []
            v_s: List[float] = []

            def _flush_scalars():
                if ref_s:
                    refs_parts.append(np.array(ref_s, np.int64))
                    t_parts.append(np.array(t_s, np.int64))
                    v_parts.append(np.array(v_s, np.float64))
                    ref_s.clear()
                    t_s.clear()
                    v_s.clear()

            corrupt = False
            while pos < len(body) and not corrupt:
                if body[pos] == 0:
                    try:
                        _, ns_len, id_len, tags_len = \
                            _META_ENTRY.unpack_from(body, pos)
                    except struct.error:
                        # Truncated trailing meta entry inside a
                        # checksummed chunk (realigned corrupt stream):
                        # clean stop of this file after the preceding
                        # entries are yielded.
                        corrupt = True
                        break
                    pos += _META_ENTRY.size
                    series_ns.append(body[pos : pos + ns_len])
                    pos += ns_len
                    series_id.append(body[pos : pos + id_len])
                    pos += id_len
                    decoded = None
                    if tags_len:
                        try:
                            decoded = tag_serialize.decode_tags(
                                body[pos : pos + tags_len])
                        except tag_serialize.TagEncodeError:
                            decoded = None  # corrupt tag bytes: series
                            #                 still replays, just unindexed
                    series_tags.append(decoded)
                    pos += tags_len
                    continue
                avail = (len(body) - pos) // rec
                if avail == 0:
                    # Trailing partial data entry: same clean-stop
                    # contract as the meta case above.
                    corrupt = True
                    break
                if avail == 1 or body[pos + rec] == 0:
                    # Single data entry before the next meta: scalar
                    # decode, no numpy machinery.
                    _, ref, t_ns, value = _DATA_ENTRY.unpack_from(body, pos)
                    if ref >= len(series_ns):
                        corrupt = True
                        break
                    ref_s.append(ref)
                    t_s.append(t_ns)
                    v_s.append(value)
                    pos += rec
                    continue
                # Maximal run of consecutive data entries: entry
                # boundaries are pos + rec*k while every boundary's tag
                # byte stays nonzero, so the run length is a strided
                # probe and the run itself one structured view. The
                # probe window starts small and grows geometrically —
                # cost stays linear whether the chunk is one giant data
                # run or short mixed stretches.
                probe = 32
                while True:
                    w = min(avail, probe)
                    stops = np.flatnonzero(tags[pos : pos + w * rec : rec] == 0)
                    if len(stops):
                        cnt = int(stops[0])
                        break
                    if w == avail:
                        cnt = avail
                        break
                    probe *= 4
                run = np.frombuffer(body, dtype=_DATA_DTYPE, count=cnt,
                                    offset=pos)
                refs = run["ref"].astype(np.int64)
                # Refs resolve against the table as of THIS run (metas
                # between runs grow it); a fabricated out-of-range ref
                # truncates the run where the per-entry iterator stops.
                # (Refs are stable once assigned — the table only
                # appends — so resolution itself happens ONCE per chunk
                # below, not per run.)
                oob = np.flatnonzero(refs >= len(series_ns))
                if len(oob):
                    corrupt = True
                    refs = refs[: int(oob[0])]
                    run = run[: int(oob[0])]
                _flush_scalars()
                refs_parts.append(refs)
                t_parts.append(run["t"])
                v_parts.append(run["v"])
                pos += cnt * rec
            _flush_scalars()
            if t_parts and sum(map(len, t_parts)):
                refs_all = np.concatenate(refs_parts)
                ns_tab, id_tab, tag_tab = _tables()
                yield ReplayBatch(
                    ns_tab[refs_all], id_tab[refs_all],
                    np.concatenate(t_parts).astype(np.int64, copy=False),
                    np.concatenate(v_parts).astype(np.float64, copy=False),
                    file_num, end_offset, tag_tab[refs_all])
            if corrupt:
                break  # clean stop: skip the rest of THIS file only


def replay(directory: str) -> Iterator[Tuple[bytes, bytes, int, float]]:
    """Iterate all (namespace, series_id, time_ns, value) entries across
    commit log files in order, dropping any torn tail chunk
    (commitlog/reader.go + iterator.go). Streamed chunk-at-a-time over
    the columnar decoder: per-entry consumers keep this shape, the
    batched bootstrapper consumes `replay_batches` directly."""
    for batch in replay_batches(directory):
        for ns, sid, t, v in zip(batch.namespaces, batch.ids,
                                 batch.t_ns, batch.values):
            yield ns, sid, int(t), float(v)


def replay_ref(directory: str) -> Iterator[Tuple[bytes, bytes, int, float]]:
    """The pre-batching per-entry replay path, retained as the
    bit-identity ORACLE (tests/test_durability.py asserts replay and
    replay_batches entry-identical to this, corrupted inputs included).
    Reads each file whole; never used on the recovery path. Two edits
    against the historical verbatim form, matched by the batched
    decoder: the meta layout carries encoded tags (skipped here), and a
    truncated entry or unknown series ref inside a checksum-valid chunk
    (a realigned corrupt stream) is a CLEAN per-file stop instead of a
    raise — corruption must never crash replay (the fuzz campaign's
    contract)."""
    if not os.path.isdir(directory):
        return
    files = sorted(f for f in os.listdir(directory) if f.startswith("commitlog-"))
    for fname in files:
        series: List[Tuple[bytes, bytes]] = []
        with _io.open(os.path.join(directory, fname), "rb") as f:
            data = f.read()
        if not data.startswith(_FILE_HEADER):
            continue  # unrecognized format: same skip as _iter_chunks
        pos = len(_FILE_HEADER)
        corrupt = False
        while pos + _CHUNK_HEADER.size <= len(data) and not corrupt:
            plen, checksum = _CHUNK_HEADER.unpack_from(data, pos)
            body = data[pos + _CHUNK_HEADER.size : pos + _CHUNK_HEADER.size + plen]
            if len(body) < plen or zlib.adler32(body) != checksum:
                break  # torn/corrupt tail chunk: stop replaying this file
            pos += _CHUNK_HEADER.size + plen
            epos = 0
            while epos < len(body):
                tag = body[epos]
                if tag == 0:
                    try:
                        _, ns_len, id_len, tags_len = \
                            _META_ENTRY.unpack_from(body, epos)
                    except struct.error:
                        corrupt = True
                        break
                    epos += _META_ENTRY.size
                    ns = body[epos : epos + ns_len]
                    epos += ns_len
                    sid = body[epos : epos + id_len]
                    epos += id_len + tags_len
                    series.append((ns, sid))
                else:
                    try:
                        _, ref, t_ns, value = _DATA_ENTRY.unpack_from(body, epos)
                    except struct.error:
                        corrupt = True
                        break
                    if ref >= len(series):
                        corrupt = True
                        break
                    epos += _DATA_ENTRY.size
                    ns, sid = series[ref]
                    yield ns, sid, t_ns, value
