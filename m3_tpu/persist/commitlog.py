"""Commit log WAL (reference: src/dbnode/persist/fs/commitlog).

Same invariants as the reference's chunked WAL (commit_log.go:69,205;
writer.go; chunk_reader.go):
  - entries buffer in memory and flush as length-prefixed chunks with an
    adler32 per chunk; a torn final chunk is detected and dropped on replay
  - per-file series dictionary: a series' {namespace, id} metadata is
    written once per file, entries reference it by index
    (docs/m3db/architecture/commitlogs.md:21-33)
  - strategies: WRITE_WAIT flushes synchronously on every write;
    WRITE_BEHIND flushes on the flush interval / explicit flush
    (commit_log.go:241-242)
  - rotation starts a new numbered file; one commit log serves ALL
    namespaces (commitlogs.md:5)
"""

from __future__ import annotations

import enum
import os
import struct
import threading
import time
import zlib
from typing import Callable, Dict, Iterator, List, Optional, Tuple

_CHUNK_HEADER = struct.Struct("<II")      # payload_len, adler32
_META_ENTRY = struct.Struct("<BHH")       # tag=0, ns_len, id_len
_DATA_ENTRY = struct.Struct("<BIqd")      # tag=1, series_ref, time_ns, value


class Strategy(enum.Enum):
    WRITE_WAIT = "write_wait"
    WRITE_BEHIND = "write_behind"


class CommitLog:
    def __init__(self, directory: str, strategy: Strategy = Strategy.WRITE_BEHIND,
                 flush_interval_ns: int = 1_000_000_000,
                 clock: Optional[Callable[[], int]] = None):
        self.directory = directory
        self.strategy = strategy
        self.flush_interval_ns = flush_interval_ns
        self.clock = clock or time.time_ns
        os.makedirs(directory, exist_ok=True)
        existing = [int(f.split("-")[1].split(".")[0]) for f in os.listdir(directory)
                    if f.startswith("commitlog-")]
        self._file_num = max(existing, default=-1) + 1
        self._f = None
        self._buf = bytearray()
        self._series_refs: Dict[Tuple[bytes, bytes], int] = {}
        self._last_flush = self.clock()
        # One appender file shared by every shard's write path: the commit
        # log serializes internally (commit_log.go's single writer loop)
        # now that the node no longer holds a global write mutex.
        self._lock = threading.RLock()
        self._open_new_file()

    # ----------------------------------------------------------------- files

    def _path(self, num: int) -> str:
        return os.path.join(self.directory, f"commitlog-{num:08d}.bin")

    def _open_new_file(self):
        if self._f is not None:
            self.flush()
            self._f.close()
        self._f = open(self._path(self._file_num), "ab")
        self._series_refs.clear()

    def rotate(self) -> int:
        """Start a new commit log file (rotation on flush/time window)."""
        with self._lock:
            old = self._file_num
            self._file_num += 1
            self._open_new_file()
            return old

    def active_file(self) -> str:
        return self._path(self._file_num)

    def files(self) -> List[str]:
        return sorted(
            os.path.join(self.directory, f) for f in os.listdir(self.directory)
            if f.startswith("commitlog-")
        )

    def remove_files_before(self, file_num: int):
        """Cleanup after flush durability (storage/cleanup.go)."""
        for f in self.files():
            num = int(os.path.basename(f).split("-")[1].split(".")[0])
            if num < file_num:
                os.remove(f)

    # ---------------------------------------------------------------- writes

    def _ref(self, namespace: bytes, series_id: bytes) -> int:
        key = (namespace, series_id)
        ref = self._series_refs.get(key)
        if ref is None:
            ref = len(self._series_refs)
            self._series_refs[key] = ref
            self._buf += _META_ENTRY.pack(0, len(namespace), len(series_id))
            self._buf += namespace
            self._buf += series_id
        return ref

    def write(self, namespace: bytes, series_id: bytes, t_ns: int, value: float):
        with self._lock:
            if self._f is None:
                raise ValueError("commit log is closed")
            ref = self._ref(namespace, series_id)
            self._buf += _DATA_ENTRY.pack(1, ref, t_ns, value)
            self._maybe_flush()

    def write_batch(self, namespace: bytes, ids, ts, vals):
        with self._lock:
            if self._f is None:
                raise ValueError("commit log is closed")
            for sid, t, v in zip(ids, ts, vals):
                ref = self._ref(namespace, sid)
                self._buf += _DATA_ENTRY.pack(1, ref, int(t), float(v))
            self._maybe_flush()

    def _maybe_flush(self):
        if self.strategy == Strategy.WRITE_WAIT:
            self.flush()
        elif self.clock() - self._last_flush >= self.flush_interval_ns:
            self.flush()

    def flush(self):
        """Write buffered entries as one checksummed chunk (writer.go)."""
        with self._lock:
            if not self._buf or self._f is None:
                return
            payload = bytes(self._buf)
            self._buf.clear()
            self._f.write(_CHUNK_HEADER.pack(len(payload), zlib.adler32(payload)))
            self._f.write(payload)
            self._f.flush()
            os.fsync(self._f.fileno())
            self._last_flush = self.clock()

    def close(self):
        with self._lock:
            if self._f is not None:
                self.flush()
                self._f.close()
                self._f = None


def replay(directory: str) -> Iterator[Tuple[bytes, bytes, int, float]]:
    """Iterate all (namespace, series_id, time_ns, value) entries across
    commit log files in order, dropping any torn tail chunk
    (commitlog/reader.go + iterator.go)."""
    if not os.path.isdir(directory):
        return
    files = sorted(f for f in os.listdir(directory) if f.startswith("commitlog-"))
    for fname in files:
        series: List[Tuple[bytes, bytes]] = []
        with open(os.path.join(directory, fname), "rb") as f:
            data = f.read()
        pos = 0
        while pos + _CHUNK_HEADER.size <= len(data):
            plen, checksum = _CHUNK_HEADER.unpack_from(data, pos)
            body = data[pos + _CHUNK_HEADER.size : pos + _CHUNK_HEADER.size + plen]
            if len(body) < plen or zlib.adler32(body) != checksum:
                break  # torn/corrupt tail chunk: stop replaying this file
            pos += _CHUNK_HEADER.size + plen
            epos = 0
            while epos < len(body):
                tag = body[epos]
                if tag == 0:
                    _, ns_len, id_len = _META_ENTRY.unpack_from(body, epos)
                    epos += _META_ENTRY.size
                    ns = body[epos : epos + ns_len]
                    epos += ns_len
                    sid = body[epos : epos + id_len]
                    epos += id_len
                    series.append((ns, sid))
                else:
                    _, ref, t_ns, value = _DATA_ENTRY.unpack_from(body, epos)
                    epos += _DATA_ENTRY.size
                    ns, sid = series[ref]
                    yield ns, sid, t_ns, value
