"""Fileset persistence (reference: src/dbnode/persist/fs).

One fileset per (namespace, shard, block start), same seven-file invariant
structure as the reference's writer (persist/fs/write.go:53-78):

  info.json        fileset metadata (block start, window, time unit, counts)
  data.bin         packed u32 codewords, row-major [S, MW] (mmap-read)
  index.bin        per-series entries sorted by id: {id, row, nbits,
                   npoints, data checksum} (write.go:283-290 equivalent)
  summaries.bin    every Nth index entry for coarse seek (summaries file)
  bloom.bin        bloom filter over ids (bloom_filter.go)
  digest.json      adler32 of every file above (dbnode/digest)
  checkpoint.json  digest-of-digests, written LAST — a fileset without a
                   valid checkpoint is incomplete and ignored (write.go:44)

Readers mmap data.bin (np.memmap; x/mmap analog); the Seeker answers
point-id lookups via bloom -> summaries -> index binary search -> row slice
(seek.go:159,332 flow). Volumes: snapshots write the same structure under a
`snapshot-<version>` suffix with snapshot metadata (snapshot_metadata_write.go)."""

from __future__ import annotations

import dataclasses
import json
import os
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..storage.block import SealedBlock
from ..utils import xtime
from ..utils.bloom import BloomFilter
from ..utils.checksum import adler32_rows

INFO_FILE = "info.json"
DATA_FILE = "data.bin"
INDEX_FILE = "index.bin"
SUMMARIES_FILE = "summaries.bin"
BLOOM_FILE = "bloom.bin"
DIGEST_FILE = "digest.json"
CHECKPOINT_FILE = "checkpoint.json"
SUMMARY_EVERY = 32

_IDX_HEADER = struct.Struct("<IIiiI")  # id_len, row, nbits, npoints, checksum


def fileset_dir(root: str, namespace: bytes, shard: int, block_start: int,
                snapshot_version: Optional[int] = None) -> str:
    kind = f"snapshot-{snapshot_version}" if snapshot_version is not None else "fileset"
    return os.path.join(root, namespace.decode(), f"shard-{shard:05d}", f"{kind}-{block_start}")


def _adler(path: str) -> int:
    a = 1
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return a
            a = zlib.adler32(chunk, a)


class FilesetWriter:
    """persist/fs/write.go DataFileSetWriter equivalent."""

    def __init__(self, root: str):
        self.root = root

    def write(self, namespace: bytes, shard: int, blk: SealedBlock, registry,
              snapshot_version: Optional[int] = None,
              wal_position: Optional[Tuple[int, int]] = None) -> str:
        d = fileset_dir(self.root, namespace, shard, blk.block_start, snapshot_version)
        tmp = d + ".tmp"
        os.makedirs(tmp, exist_ok=True)

        words = np.ascontiguousarray(blk.words, np.uint32)
        with open(os.path.join(tmp, DATA_FILE), "wb") as f:
            f.write(words.tobytes())

        # Index entries sorted by series id (the write path buffers and sorts,
        # write.go WriteAll) with per-row data checksums — one vectorized
        # adler pass over the whole codeword matrix, not a per-row loop.
        ids = [registry.id_of(int(si)) for si in blk.series_indices]
        order = sorted(range(len(ids)), key=lambda i: ids[i])
        bloom = BloomFilter.for_capacity(len(ids))
        bloom.add_batch([ids[i] for i in order])
        row_sums = adler32_rows(words) if len(ids) else np.zeros(0, np.int64)
        index_offsets: List[Tuple[bytes, int]] = []
        with open(os.path.join(tmp, INDEX_FILE), "wb") as f:
            for i in order:
                entry = _IDX_HEADER.pack(
                    len(ids[i]), i, int(blk.nbits[i]), int(blk.npoints[i]),
                    int(row_sums[i]),
                )
                index_offsets.append((ids[i], f.tell()))
                f.write(entry)
                f.write(ids[i])
        with open(os.path.join(tmp, SUMMARIES_FILE), "wb") as f:
            for sid, off in index_offsets[::SUMMARY_EVERY]:
                f.write(struct.pack("<IQ", len(sid), off))
                f.write(sid)
        with open(os.path.join(tmp, BLOOM_FILE), "wb") as f:
            f.write(bloom.tobytes())

        info = {
            "block_start": blk.block_start,
            "window": blk.window,
            "time_unit": int(blk.time_unit),
            "num_series": len(ids),
            "max_words": int(words.shape[1]),
            "block_checksum": blk.checksum,
            "bloom_m": bloom.m,
            "bloom_k": bloom.k,
            "snapshot_version": snapshot_version,
            "volume_type": "snapshot" if snapshot_version is not None else "flush",
        }
        if wal_position is not None:
            # Chunk-aligned commit log position taken BEFORE the snapshot
            # read: recovery replays only WAL chunks past it (everything
            # earlier is provably inside this snapshot).
            info["wal_position"] = [int(wal_position[0]), int(wal_position[1])]
        with open(os.path.join(tmp, INFO_FILE), "w") as f:
            json.dump(info, f)

        digests = {
            name: _adler(os.path.join(tmp, name))
            for name in (INFO_FILE, DATA_FILE, INDEX_FILE, SUMMARIES_FILE, BLOOM_FILE)
        }
        with open(os.path.join(tmp, DIGEST_FILE), "w") as f:
            json.dump(digests, f)
        # Checkpoint LAST: its presence + matching digest-of-digests marks the
        # fileset durable (write.go checkpoint semantics).
        with open(os.path.join(tmp, CHECKPOINT_FILE), "w") as f:
            json.dump({"digest": _adler(os.path.join(tmp, DIGEST_FILE))}, f)

        if os.path.exists(d):
            import shutil

            shutil.rmtree(d)
        os.replace(tmp, d)
        return d


def fileset_complete(d: str) -> bool:
    """Checkpoint present and digest chain intact (read.go validation)."""
    cp = os.path.join(d, CHECKPOINT_FILE)
    dg = os.path.join(d, DIGEST_FILE)
    if not (os.path.exists(cp) and os.path.exists(dg)):
        return False
    try:
        with open(cp) as f:
            want = json.load(f)["digest"]
        return _adler(dg) == want
    except (ValueError, KeyError, OSError):
        return False


@dataclasses.dataclass
class IndexEntry:
    id: bytes
    row: int
    nbits: int
    npoints: int
    checksum: int


class FilesetReader:
    """persist/fs/read.go DataFileSetReader: full-fileset scans (bootstrap)."""

    def __init__(self, path: str, verify: bool = True):
        if not fileset_complete(path):
            raise FileNotFoundError(f"incomplete or missing fileset at {path}")
        self.path = path
        with open(os.path.join(path, INFO_FILE)) as f:
            self.info = json.load(f)
        if verify:
            with open(os.path.join(path, DIGEST_FILE)) as f:
                digests = json.load(f)
            for name, want in digests.items():
                if _adler(os.path.join(path, name)) != want:
                    raise IOError(f"digest mismatch for {name} in {path}")
        self._words = np.memmap(
            os.path.join(path, DATA_FILE), dtype=np.uint32, mode="r",
            shape=(self.info["num_series"], self.info["max_words"]),
        )
        self.entries = list(self._read_index())

    def wal_position(self) -> Optional[Tuple[int, int]]:
        """The commit log position recorded at snapshot time, or None
        (flush filesets, and snapshots from before the field existed)."""
        pos = self.info.get("wal_position")
        return (int(pos[0]), int(pos[1])) if pos else None

    def row_checksums(self) -> np.ndarray:
        """adler32 of every data row, int64 [S] — one vectorized pass
        over the whole codeword matrix (utils.checksum.adler32_rows)."""
        if not self.info["num_series"]:
            return np.zeros(0, np.int64)
        return adler32_rows(np.asarray(self._words))

    def verify_rows(self):
        """Row-granular verification, vectorized over the whole fileset:
        every index entry's recorded adler must match its data row, and
        the bloom filter must be exactly the one the writer would build
        over these ids (a divergent bloom silently turns Seeker lookups
        into false negatives — reads that miss durable data). Raises
        IOError naming the first divergence; the digest chain
        (construction-time verify=True) covers whole-file rot, this
        covers per-row attribution and index/data cross-wiring."""
        sums = self.row_checksums()
        if self.entries:
            rows = np.fromiter((e.row for e in self.entries), np.int64,
                               count=len(self.entries))
            want = np.fromiter((e.checksum for e in self.entries), np.int64,
                               count=len(self.entries))
            if rows.min(initial=0) < 0 or rows.max(initial=-1) >= len(sums):
                raise IOError(f"index entry row out of range in {self.path}")
            bad = np.flatnonzero(sums[rows] != want)
            if len(bad):
                e = self.entries[int(bad[0])]
                raise IOError(
                    f"row checksum mismatch for {e.id!r} (row {e.row}) "
                    f"in {self.path}")
        bloom = BloomFilter.for_capacity(len(self.entries))
        bloom.add_batch([e.id for e in self.entries])
        with open(os.path.join(self.path, BLOOM_FILE), "rb") as f:
            if f.read() != bloom.tobytes():
                raise IOError(f"bloom filter diverges from ids in {self.path}")

    def _read_index(self) -> Iterator[IndexEntry]:
        with open(os.path.join(self.path, INDEX_FILE), "rb") as f:
            data = f.read()
        pos = 0
        while pos < len(data):
            id_len, row, nbits, npoints, checksum = _IDX_HEADER.unpack_from(data, pos)
            pos += _IDX_HEADER.size
            sid = data[pos : pos + id_len]
            pos += id_len
            yield IndexEntry(sid, row, nbits, npoints, checksum)

    def to_block(self) -> Tuple[SealedBlock, List[bytes]]:
        """Load the whole fileset back as a SealedBlock + ids by row order.

        series_indices are row numbers; callers remap into their registry
        (Shard.load_block)."""
        info = self.info
        rows = sorted(self.entries, key=lambda e: e.row)
        nbits = np.array([e.nbits for e in rows], np.int32)
        npoints = np.array([e.npoints for e in rows], np.int32)
        blk = SealedBlock(
            block_start=info["block_start"],
            window=info["window"],
            series_indices=np.arange(len(rows), dtype=np.int32),
            words=np.asarray(self._words),
            nbits=nbits,
            npoints=npoints,
            time_unit=xtime.Unit(info["time_unit"]),
            checksum=info["block_checksum"],
        )
        return blk, [e.id for e in rows]


class Seeker:
    """persist/fs/seek.go: point-id lookup without loading the fileset.

    bloom (negative fast path) -> in-memory sorted index (summaries would
    page the index; ours is small enough to hold) -> mmap row slice."""

    def __init__(self, path: str):
        if not fileset_complete(path):
            raise FileNotFoundError(f"incomplete or missing fileset at {path}")
        self.path = path
        with open(os.path.join(path, INFO_FILE)) as f:
            self.info = json.load(f)
        with open(os.path.join(path, BLOOM_FILE), "rb") as f:
            self.bloom = BloomFilter.frombytes(f.read(), self.info["bloom_m"], self.info["bloom_k"])
        reader = FilesetReader(path, verify=False)
        self._entries = sorted(reader.entries, key=lambda e: e.id)
        self._ids = [e.id for e in self._entries]
        self._words = reader._words

    def seek(self, series_id: bytes) -> Optional[Tuple[np.ndarray, int, int]]:
        """-> (packed words row, nbits, npoints) or None (seek.go:332 SeekByID)."""
        if series_id not in self.bloom:
            return None
        import bisect

        i = bisect.bisect_left(self._ids, series_id)
        if i >= len(self._ids) or self._ids[i] != series_id:
            return None
        e = self._entries[i]
        row = np.asarray(self._words[e.row])
        if zlib.adler32(row.tobytes()) != e.checksum:
            raise IOError(f"checksum mismatch for {series_id!r} in {self.path}")
        return row, e.nbits, e.npoints


class PersistManager:
    """persist_manager.go: the flush-side entry point the database calls."""

    def __init__(self, root: str):
        self.root = root
        self.writer = FilesetWriter(root)

    def write_block(self, namespace: bytes, shard: int, blk: SealedBlock, registry) -> str:
        return self.writer.write(namespace, shard, blk, registry)

    def write_snapshot(self, namespace: bytes, shard: int, blk: SealedBlock, registry,
                       version: int,
                       wal_position: Optional[Tuple[int, int]] = None) -> str:
        return self.writer.write(namespace, shard, blk, registry,
                                 snapshot_version=version,
                                 wal_position=wal_position)

    def list_filesets(self, namespace: bytes, shard: int) -> List[Tuple[int, str]]:
        """Complete flush filesets for a shard: [(block_start, path)]."""
        d = os.path.join(self.root, namespace.decode(), f"shard-{shard:05d}")
        out = []
        if not os.path.isdir(d):
            return out
        for name in os.listdir(d):
            # '.tmp' staging dirs are mid-write crash residue (a SIGKILL
            # between the checkpoint write and os.replace): never a
            # servable fileset, and their suffix isn't a block start.
            if name.startswith("fileset-") and not name.endswith(".tmp"):
                path = os.path.join(d, name)
                if fileset_complete(path):
                    out.append((int(name.split("-")[-1]), path))
        return sorted(out)

    def list_snapshots(self, namespace: bytes, shard: int) -> List[Tuple[int, int, str]]:
        """[(block_start, version, path)] for complete snapshots."""
        d = os.path.join(self.root, namespace.decode(), f"shard-{shard:05d}")
        out = []
        if not os.path.isdir(d):
            return out
        for name in os.listdir(d):
            if name.startswith("snapshot-") and not name.endswith(".tmp"):
                path = os.path.join(d, name)
                if fileset_complete(path):
                    _, version, block_start = name.split("-")
                    out.append((int(block_start), int(version), path))
        return sorted(out)

    def shards_with_data(self, namespace: bytes) -> List[int]:
        d = os.path.join(self.root, namespace.decode())
        if not os.path.isdir(d):
            return []
        return sorted(
            int(name.split("-")[1]) for name in os.listdir(d) if name.startswith("shard-")
        )
