"""Fileset persistence (reference: src/dbnode/persist/fs).

One fileset per (namespace, shard, block start), same seven-file invariant
structure as the reference's writer (persist/fs/write.go:53-78):

  info.json        fileset metadata (block start, window, time unit, counts)
  data.bin         packed u32 codewords, row-major [S, MW] (mmap-read)
  index.bin        per-series entries sorted by id: {id, row, nbits,
                   npoints, data checksum} (write.go:283-290 equivalent)
  summaries.bin    every Nth index entry for coarse seek (summaries file)
  bloom.bin        bloom filter over ids (bloom_filter.go)
  digest.json      adler32 of every file above (dbnode/digest)
  checkpoint.json  digest-of-digests, written LAST — a fileset without a
                   valid checkpoint is incomplete and ignored (write.go:44)

Readers mmap data.bin (np.memmap; x/mmap analog); the Seeker answers
point-id lookups via bloom -> summaries -> index binary search -> row slice
(seek.go:159,332 flow). Volumes: snapshots write the same structure under a
`snapshot-<version>` suffix with snapshot metadata (snapshot_metadata_write.go)."""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..storage.block import SealedBlock
from ..utils import xtime
from ..utils.bloom import BloomFilter
from ..utils.checksum import adler32_rows
from ..utils.instrument import ROOT
from . import diskio
from .diskio import CorruptionError, DiskWriteError, classify_write_error

# The disk I/O seam: every file operation below routes through this
# module-level indirection (one attribute lookup when no injector is
# installed — zero overhead off). testing/faultfs.py swaps it.
_io = diskio.DEFAULT

# Serve-time integrity observability (quarantines, verify failures);
# shared by name with the storage-side readers (storage/retriever.py).
_CORRUPTION = ROOT.sub_scope("storage.corruption")

INFO_FILE = "info.json"
DATA_FILE = "data.bin"
INDEX_FILE = "index.bin"
SUMMARIES_FILE = "summaries.bin"
BLOOM_FILE = "bloom.bin"
DIGEST_FILE = "digest.json"
CHECKPOINT_FILE = "checkpoint.json"
SUMMARY_EVERY = 32

_IDX_HEADER = struct.Struct("<IIiiI")  # id_len, row, nbits, npoints, checksum


def fileset_dir(root: str, namespace: bytes, shard: int, block_start: int,
                snapshot_version: Optional[int] = None) -> str:
    kind = f"snapshot-{snapshot_version}" if snapshot_version is not None else "fileset"
    return os.path.join(root, namespace.decode(), f"shard-{shard:05d}", f"{kind}-{block_start}")


def _adler(path: str) -> int:
    a = 1
    with _io.open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return a
            a = zlib.adler32(chunk, a)


class FilesetWriter:
    """persist/fs/write.go DataFileSetWriter equivalent."""

    def __init__(self, root: str):
        self.root = root

    def write(self, namespace: bytes, shard: int, blk: SealedBlock, registry,
              snapshot_version: Optional[int] = None,
              wal_position: Optional[Tuple[int, int]] = None) -> str:
        d = fileset_dir(self.root, namespace, shard, blk.block_start, snapshot_version)
        tmp = d + ".tmp"
        try:
            return self._write(d, tmp, blk, registry, snapshot_version,
                               wal_position)
        except OSError as e:
            # Typed classification (EIO -> DiskWriteError, ENOSPC ->
            # DiskFullError): the flush path retries/degrades on these
            # instead of folding a raw OSError into a broad except.
            if isinstance(e, (CorruptionError, DiskWriteError)):
                raise
            raise classify_write_error(e, d) from e

    def _write(self, d: str, tmp: str, blk: SealedBlock, registry,
               snapshot_version: Optional[int],
               wal_position: Optional[Tuple[int, int]]) -> str:
        os.makedirs(tmp, exist_ok=True)

        words = np.ascontiguousarray(blk.words, np.uint32)
        with _io.open(os.path.join(tmp, DATA_FILE), "wb") as f:
            f.write(words.tobytes())

        # Index entries sorted by series id (the write path buffers and sorts,
        # write.go WriteAll) with per-row data checksums — one vectorized
        # adler pass over the whole codeword matrix, not a per-row loop.
        ids = [registry.id_of(int(si)) for si in blk.series_indices]
        order = sorted(range(len(ids)), key=lambda i: ids[i])
        bloom = BloomFilter.for_capacity(len(ids))
        bloom.add_batch([ids[i] for i in order])
        row_sums = adler32_rows(words) if len(ids) else np.zeros(0, np.int64)
        index_offsets: List[Tuple[bytes, int]] = []
        with _io.open(os.path.join(tmp, INDEX_FILE), "wb") as f:
            for i in order:
                entry = _IDX_HEADER.pack(
                    len(ids[i]), i, int(blk.nbits[i]), int(blk.npoints[i]),
                    int(row_sums[i]),
                )
                index_offsets.append((ids[i], f.tell()))
                f.write(entry)
                f.write(ids[i])
        with _io.open(os.path.join(tmp, SUMMARIES_FILE), "wb") as f:
            for sid, off in index_offsets[::SUMMARY_EVERY]:
                f.write(struct.pack("<IQ", len(sid), off))
                f.write(sid)
        with _io.open(os.path.join(tmp, BLOOM_FILE), "wb") as f:
            f.write(bloom.tobytes())

        info = {
            "block_start": blk.block_start,
            "window": blk.window,
            "time_unit": int(blk.time_unit),
            "num_series": len(ids),
            "max_words": int(words.shape[1]),
            "block_checksum": blk.checksum,
            "bloom_m": bloom.m,
            "bloom_k": bloom.k,
            "snapshot_version": snapshot_version,
            "volume_type": "snapshot" if snapshot_version is not None else "flush",
        }
        if wal_position is not None:
            # Chunk-aligned commit log position taken BEFORE the snapshot
            # read: recovery replays only WAL chunks past it (everything
            # earlier is provably inside this snapshot).
            info["wal_position"] = [int(wal_position[0]), int(wal_position[1])]
        with _io.open(os.path.join(tmp, INFO_FILE), "w") as f:
            json.dump(info, f)

        digests = {
            name: _adler(os.path.join(tmp, name))
            for name in (INFO_FILE, DATA_FILE, INDEX_FILE, SUMMARIES_FILE, BLOOM_FILE)
        }
        with _io.open(os.path.join(tmp, DIGEST_FILE), "w") as f:
            json.dump(digests, f)
        # Checkpoint LAST: its presence + matching digest-of-digests marks the
        # fileset durable (write.go checkpoint semantics).
        with _io.open(os.path.join(tmp, CHECKPOINT_FILE), "w") as f:
            json.dump({"digest": _adler(os.path.join(tmp, DIGEST_FILE))}, f)

        if os.path.exists(d):
            shutil.rmtree(d)
        _io.replace(tmp, d)
        return d


def fileset_complete(d: str) -> bool:
    """Checkpoint present and digest chain intact (read.go validation)."""
    cp = os.path.join(d, CHECKPOINT_FILE)
    dg = os.path.join(d, DIGEST_FILE)
    if not (os.path.exists(cp) and os.path.exists(dg)):
        return False
    try:
        with _io.open(cp) as f:
            want = json.load(f)["digest"]
        return _adler(dg) == want
    except (ValueError, KeyError, OSError):
        return False


# --------------------------------------------------------------- quarantine

QUARANTINE_DIR = "quarantine"


def quarantine_fileset(path: str, reason: str, rows: Sequence[int] = (),
                       ids: Sequence[bytes] = ()) -> Optional[str]:
    """Move a corrupt fileset out of the servable namespace: rename it
    into `<shard-dir>/quarantine/<name>` (outside `list_filesets`'
    `fileset-` prefix by construction) with a JSON sidecar naming the
    failing rows, so an operator — or the scrubber's repair pass — can
    attribute the rot before the copy is replaced from peers. Uses the
    RAW os layer, not the `_io` seam: quarantine is the remediation
    path and must not itself be fault-injected. Returns the quarantine
    path, or None when the rename failed (counted, never raised — the
    caller is already on a corruption error path)."""
    path = os.path.abspath(path)
    parent, name = os.path.split(path)
    qdir = os.path.join(parent, QUARANTINE_DIR)
    dst = os.path.join(qdir, name)
    try:
        os.makedirs(qdir, exist_ok=True)
        if os.path.lexists(dst):
            shutil.rmtree(dst, ignore_errors=True)
        os.replace(path, dst)
        with open(dst + ".json", "w") as f:
            json.dump({
                "reason": reason,
                "source": path,
                "rows": [int(r) for r in rows],
                "ids": [i.decode("utf-8", "replace") for i in ids],
            }, f)
    except OSError:
        _CORRUPTION.counter("quarantine_failed").inc()
        return None
    _CORRUPTION.counter("quarantined").inc()
    return dst


@dataclasses.dataclass
class IndexEntry:
    id: bytes
    row: int
    nbits: int
    npoints: int
    checksum: int


class FilesetReader:
    """persist/fs/read.go DataFileSetReader: full-fileset scans (bootstrap)."""

    def __init__(self, path: str, verify: bool = True):
        if not fileset_complete(path):
            raise FileNotFoundError(f"incomplete or missing fileset at {path}")
        self.path = path
        with _io.open(os.path.join(path, INFO_FILE)) as f:
            self.info = json.load(f)
        # The recorded whole-file adlers ride every reader (cheap: one
        # small json), so each consumer verifies the EXACT bytes it read
        # — a re-read-and-compare pass would leave a window where the
        # verification read is clean and the consuming read is not.
        try:
            with _io.open(os.path.join(path, DIGEST_FILE)) as f:
                self.digests: Dict[str, int] = json.load(f)
        except (OSError, ValueError):
            self.digests = {}
        if verify:
            for name, want in self.digests.items():
                if _adler(os.path.join(path, name)) != want:
                    raise CorruptionError(
                        f"digest mismatch for {name} in {path}", path=path)
        self._words = _io.memmap(
            os.path.join(path, DATA_FILE), dtype=np.uint32,
            shape=(self.info["num_series"], self.info["max_words"]),
        )
        self.entries = list(self._read_index())

    def wal_position(self) -> Optional[Tuple[int, int]]:
        """The commit log position recorded at snapshot time, or None
        (flush filesets, and snapshots from before the field existed)."""
        pos = self.info.get("wal_position")
        return (int(pos[0]), int(pos[1])) if pos else None

    def row_checksums(self) -> np.ndarray:
        """adler32 of every data row, int64 [S] — one vectorized pass
        over the whole codeword matrix (utils.checksum.adler32_rows)."""
        if not self.info["num_series"]:
            return np.zeros(0, np.int64)
        return adler32_rows(np.asarray(self._words))

    def verify_rows(self):
        """Row-granular verification, vectorized over the whole fileset:
        every index entry's recorded adler must match its data row, and
        the bloom filter must be exactly the one the writer would build
        over these ids (a divergent bloom silently turns Seeker lookups
        into false negatives — reads that miss durable data). Raises
        IOError naming the first divergence; the digest chain
        (construction-time verify=True) covers whole-file rot, this
        covers per-row attribution and index/data cross-wiring."""
        sums = self.row_checksums()
        if self.entries:
            rows = np.fromiter((e.row for e in self.entries), np.int64,
                               count=len(self.entries))
            want = np.fromiter((e.checksum for e in self.entries), np.int64,
                               count=len(self.entries))
            if rows.min(initial=0) < 0 or rows.max(initial=-1) >= len(sums):
                raise CorruptionError(
                    f"index entry row out of range in {self.path}",
                    path=self.path)
            bad = np.flatnonzero(sums[rows] != want)
            if len(bad):
                bad_entries = [self.entries[int(b)] for b in bad]
                raise CorruptionError(
                    f"row checksum mismatch for {bad_entries[0].id!r} "
                    f"(row {bad_entries[0].row}) in {self.path}",
                    path=self.path,
                    rows=[e.row for e in bad_entries],
                    ids=[e.id for e in bad_entries])
        bloom = BloomFilter.for_capacity(len(self.entries))
        bloom.add_batch([e.id for e in self.entries])
        with _io.open(os.path.join(self.path, BLOOM_FILE), "rb") as f:
            if f.read() != bloom.tobytes():
                raise CorruptionError(
                    f"bloom filter diverges from ids in {self.path}",
                    path=self.path)

    def _read_index(self) -> Iterator[IndexEntry]:
        with _io.open(os.path.join(self.path, INDEX_FILE), "rb") as f:
            data = f.read()
        want = self.digests.get(INDEX_FILE)
        if want is not None and zlib.adler32(data) != want:
            # Verify the bytes ABOUT to be parsed: rotten index entries
            # otherwise fail silently (a garbled id misses the binary
            # search — a read that quietly skips durable data).
            raise CorruptionError(
                f"index digest mismatch in {self.path}", path=self.path)
        pos = 0
        while pos < len(data):
            id_len, row, nbits, npoints, checksum = _IDX_HEADER.unpack_from(data, pos)
            pos += _IDX_HEADER.size
            sid = data[pos : pos + id_len]
            pos += id_len
            yield IndexEntry(sid, row, nbits, npoints, checksum)

    def to_block(self) -> Tuple[SealedBlock, List[bytes]]:
        """Load the whole fileset back as a SealedBlock + ids by row order.

        series_indices are row numbers; callers remap into their registry
        (Shard.load_block)."""
        info = self.info
        rows = sorted(self.entries, key=lambda e: e.row)
        nbits = np.array([e.nbits for e in rows], np.int32)
        npoints = np.array([e.npoints for e in rows], np.int32)
        blk = SealedBlock(
            block_start=info["block_start"],
            window=info["window"],
            series_indices=np.arange(len(rows), dtype=np.int32),
            words=np.asarray(self._words),
            nbits=nbits,
            npoints=npoints,
            time_unit=xtime.Unit(info["time_unit"]),
            checksum=info["block_checksum"],
        )
        # Serve-time integrity: the index entries' recorded row adlers
        # ride the block, and SealedBlock.read/read_all verify the data
        # rows against them lazily on first touch — once per generation
        # (verified flag cached on the block object), so the hot path
        # pays one vectorized adler pass per loaded block, ever.
        if rows:
            blk.expected_row_sums = np.fromiter(
                (e.checksum for e in rows), np.int64, count=len(rows))
            blk.expected_row_ids = [e.id for e in rows]
            blk.source_path = self.path
        return blk, [e.id for e in rows]


class Seeker:
    """persist/fs/seek.go: point-id lookup without loading the fileset.

    bloom (negative fast path) -> in-memory sorted index (summaries would
    page the index; ours is small enough to hold) -> mmap row slice."""

    def __init__(self, path: str):
        reader = FilesetReader(path, verify=False)
        self.path = path
        self.info = reader.info
        with _io.open(os.path.join(path, BLOOM_FILE), "rb") as f:
            raw = f.read()
        want = reader.digests.get(BLOOM_FILE)
        if want is not None and zlib.adler32(raw) != want:
            # A rotten bloom is the nastiest fileset fault: every lookup
            # turns into a silent false negative. Verify the exact bytes
            # read before trusting a single membership answer.
            raise CorruptionError(
                f"bloom digest mismatch in {path}", path=path)
        self.bloom = BloomFilter.frombytes(raw, self.info["bloom_m"],
                                           self.info["bloom_k"])
        self._entries = sorted(reader.entries, key=lambda e: e.id)
        self._ids = [e.id for e in self._entries]
        self._words = reader._words

    def seek(self, series_id: bytes) -> Optional[Tuple[np.ndarray, int, int]]:
        """-> (packed words row, nbits, npoints) or None (seek.go:332 SeekByID)."""
        if series_id not in self.bloom:
            return None
        import bisect

        i = bisect.bisect_left(self._ids, series_id)
        if i >= len(self._ids) or self._ids[i] != series_id:
            return None
        e = self._entries[i]
        row = np.asarray(self._words[e.row])
        if zlib.adler32(row.tobytes()) != e.checksum:
            _CORRUPTION.counter("seek_mismatch").inc()
            raise CorruptionError(
                f"checksum mismatch for {series_id!r} in {self.path}",
                path=self.path, rows=[e.row], ids=[series_id])
        return row, e.nbits, e.npoints


class PersistManager:
    """persist_manager.go: the flush-side entry point the database calls."""

    def __init__(self, root: str):
        self.root = root
        self.writer = FilesetWriter(root)

    def write_block(self, namespace: bytes, shard: int, blk: SealedBlock, registry) -> str:
        return self.writer.write(namespace, shard, blk, registry)

    def write_snapshot(self, namespace: bytes, shard: int, blk: SealedBlock, registry,
                       version: int,
                       wal_position: Optional[Tuple[int, int]] = None) -> str:
        return self.writer.write(namespace, shard, blk, registry,
                                 snapshot_version=version,
                                 wal_position=wal_position)

    def list_filesets(self, namespace: bytes, shard: int) -> List[Tuple[int, str]]:
        """Complete flush filesets for a shard: [(block_start, path)]."""
        d = os.path.join(self.root, namespace.decode(), f"shard-{shard:05d}")
        out = []
        if not os.path.isdir(d):
            return out
        for name in os.listdir(d):
            # '.tmp' staging dirs are mid-write crash residue (a SIGKILL
            # between the checkpoint write and os.replace): never a
            # servable fileset, and their suffix isn't a block start.
            if name.startswith("fileset-") and not name.endswith(".tmp"):
                path = os.path.join(d, name)
                if fileset_complete(path):
                    out.append((int(name.split("-")[-1]), path))
        return sorted(out)

    def list_snapshots(self, namespace: bytes, shard: int) -> List[Tuple[int, int, str]]:
        """[(block_start, version, path)] for complete snapshots."""
        d = os.path.join(self.root, namespace.decode(), f"shard-{shard:05d}")
        out = []
        if not os.path.isdir(d):
            return out
        for name in os.listdir(d):
            if name.startswith("snapshot-") and not name.endswith(".tmp"):
                path = os.path.join(d, name)
                if fileset_complete(path):
                    _, version, block_start = name.split("-")
                    out.append((int(block_start), int(version), path))
        return sorted(out)

    def list_quarantined(self, namespace: bytes, shard: int
                         ) -> List[Tuple[int, str]]:
        """Quarantined flush filesets for a shard: [(block_start, path)].
        The scrubber routes these into repair and clears them once a
        fresh replica-sourced fileset has replaced them."""
        d = os.path.join(self.root, namespace.decode(),
                         f"shard-{shard:05d}", QUARANTINE_DIR)
        out = []
        if not os.path.isdir(d):
            return out
        for name in os.listdir(d):
            if name.startswith("fileset-") and not name.endswith(".json"):
                out.append((int(name.split("-")[-1]), os.path.join(d, name)))
        return sorted(out)

    def clear_quarantined(self, namespace: bytes, shard: int,
                          block_start: int) -> bool:
        """Drop a quarantined fileset (+ sidecar) after repair rewrote a
        healthy copy — the un-quarantine step. Returns True when one was
        removed."""
        d = os.path.join(self.root, namespace.decode(),
                         f"shard-{shard:05d}", QUARANTINE_DIR)
        path = os.path.join(d, f"fileset-{block_start}")
        if not os.path.isdir(path):
            return False
        shutil.rmtree(path, ignore_errors=True)
        if os.path.exists(path + ".json"):
            try:
                os.remove(path + ".json")
            except OSError:
                pass
        return True

    def shards_with_data(self, namespace: bytes) -> List[int]:
        d = os.path.join(self.root, namespace.decode())
        if not os.path.isdir(d):
            return []
        return sorted(
            int(name.split("-")[1]) for name in os.listdir(d) if name.startswith("shard-")
        )
