"""Binary wire format + framing for the node RPC data plane.

The reference speaks TChannel+Thrift with a forked pooled-binary decoder
(src/dbnode/network/server/tchannelthrift, glide.yaml:40-44 fork note).
The TPU build keeps the same shape — a compact self-describing binary
codec over length-prefixed TCP frames — but the bulk payloads are numpy
arrays (packed u32 TSZ codewords, i64 timestamp / f64 value columns)
serialized as raw buffers so a fetch response can be fed straight into
the batched device decode kernel without per-element marshalling.

Frame: <u32 length><body>, body = encode(value). Values: None, bool,
int (i64), float (f64), bytes, str, list, dict, ndarray.
"""

from __future__ import annotations

import socket
import struct
from typing import Any

import numpy as np

_NIL = 0
_FALSE = 1
_TRUE = 2
_I64 = 3
_F64 = 4
_BYTES = 5
_STR = 6
_LIST = 7
_DICT = 8
_NDARRAY = 9

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_I64S = struct.Struct("<q")
_F64S = struct.Struct("<d")

MAX_FRAME = 1 << 31  # 2 GiB hard cap against corrupt length prefixes


class WireTruncated(ConnectionError):
    """The peer died MID-FRAME: EOF inside the length prefix or body, so
    some bytes of a frame arrived and the rest never will. One typed
    error (instead of struct.error / short-read garbage) so retriers can
    classify it as a retryable transport failure, distinct from both a
    clean between-frames close (plain ConnectionError) and a malformed
    but complete frame (ValueError — NOT retryable: the stream is
    desynced and a re-send lands on garbage)."""


def _enc(out: bytearray, v: Any, depth: int = 0) -> None:
    if depth > MAX_DEPTH:
        raise ValueError(f"wire: nesting deeper than {MAX_DEPTH}")
    if v is None:
        out += b"\x00"
    elif v is True:
        out += b"\x02"
    elif v is False:
        out += b"\x01"
    elif isinstance(v, (int, np.integer)):
        out += _U8.pack(_I64)
        out += _I64S.pack(int(v))
    elif isinstance(v, (float, np.floating)):
        out += _U8.pack(_F64)
        out += _F64S.pack(float(v))
    elif isinstance(v, (bytes, bytearray, memoryview)):
        out += _U8.pack(_BYTES)
        out += _U32.pack(len(v))
        out += v
    elif isinstance(v, str):
        b = v.encode()
        out += _U8.pack(_STR)
        out += _U32.pack(len(b))
        out += b
    elif isinstance(v, np.ndarray):
        a = np.ascontiguousarray(v)
        dt = a.dtype.str.encode()
        out += _U8.pack(_NDARRAY)
        out += _U8.pack(len(dt))
        out += dt
        out += _U8.pack(a.ndim)
        for s in a.shape:
            out += _I64S.pack(s)
        buf = a.tobytes()
        out += _U32.pack(len(buf))
        out += buf
    elif isinstance(v, (list, tuple)):
        out += _U8.pack(_LIST)
        out += _U32.pack(len(v))
        for item in v:
            _enc(out, item, depth + 1)
    elif isinstance(v, dict):
        out += _U8.pack(_DICT)
        out += _U32.pack(len(v))
        for k, item in v.items():
            _enc(out, k, depth + 1)
            _enc(out, item, depth + 1)
    else:
        raise TypeError(f"wire: cannot encode {type(v)!r}")


def encode(v: Any) -> bytes:
    out = bytearray()
    _enc(out, v)
    return bytes(out)


# Containers deeper than this are rejected ON BOTH SIDES: encode fails
# fast at the sender with a clear error instead of the receiver dropping
# the connection as if the peer were malicious, and decode keeps a ~10KB
# frame of nested list tags from killing a handler thread with
# RecursionError. 64 is an order of magnitude above any real payload
# (recursive query trees cost 2 levels per node).
MAX_DEPTH = 64


def _dec(buf: memoryview, pos: int, depth: int = 0):
    if depth > MAX_DEPTH:
        raise ValueError(f"wire: nesting deeper than {MAX_DEPTH}")
    tag = buf[pos]
    pos += 1
    if tag == _NIL:
        return None, pos
    if tag == _FALSE:
        return False, pos
    if tag == _TRUE:
        return True, pos
    if tag == _I64:
        return _I64S.unpack_from(buf, pos)[0], pos + 8
    if tag == _F64:
        return _F64S.unpack_from(buf, pos)[0], pos + 8
    if tag == _BYTES:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        return bytes(buf[pos : pos + n]), pos + n
    if tag == _STR:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        return bytes(buf[pos : pos + n]).decode(), pos + n
    if tag == _NDARRAY:
        dtn = buf[pos]
        pos += 1
        dt = np.dtype(bytes(buf[pos : pos + dtn]).decode())
        pos += dtn
        ndim = buf[pos]
        pos += 1
        shape = []
        for _ in range(ndim):
            shape.append(_I64S.unpack_from(buf, pos)[0])
            pos += 8
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        a = np.frombuffer(buf[pos : pos + n], dtype=dt).reshape(shape).copy()
        return a, pos + n
    if tag == _LIST:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        out = []
        for _ in range(n):
            item, pos = _dec(buf, pos, depth + 1)
            out.append(item)
        return out, pos
    if tag == _DICT:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        d = {}
        for _ in range(n):
            k, pos = _dec(buf, pos, depth + 1)
            v, pos = _dec(buf, pos, depth + 1)
            d[k] = v
        return d, pos
    raise ValueError(f"wire: bad tag {tag}")


def decode(buf: bytes) -> Any:
    try:
        v, pos = _dec(memoryview(buf), 0)
    except (struct.error, IndexError, TypeError) as e:
        # truncated fixed-width field, out-of-range read, or garbage
        # ndarray dtype string: surface the SAME error type as every
        # other malformed-buffer case so callers catch one thing
        raise ValueError(f"wire: malformed buffer ({e})")
    if pos != len(buf):
        raise ValueError(f"wire: trailing bytes ({len(buf) - pos})")
    return v


# ------------------------------------------------------------------- framing


def write_frame(sock: socket.socket, value: Any) -> None:
    body = encode(value)
    sock.sendall(_U32.pack(len(body)) + body)


def _read_exact(sock: socket.socket, n: int, mid_frame: bool = False) -> bytes:
    """Read exactly n bytes. EOF before the first byte is a clean close
    (plain ConnectionError) unless `mid_frame` — a frame header already
    committed the peer to a body — and EOF after a partial read is always
    WireTruncated: the peer died inside a frame."""
    want = n
    parts = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            if mid_frame or n != want:
                raise WireTruncated(
                    f"wire: peer closed mid-frame ({want - n}/{want} bytes)")
            raise ConnectionError("wire: peer closed")
        parts.append(chunk)
        n -= len(chunk)
    return b"".join(parts)


def read_frame(sock: socket.socket) -> Any:
    (n,) = _U32.unpack(_read_exact(sock, 4))
    if n > MAX_FRAME:
        raise ValueError(f"wire: frame too large ({n})")
    return decode(_read_exact(sock, n, mid_frame=True))


def read_dict_frame(sock: socket.socket) -> dict:
    """read_frame + top-level shape check: every server protocol in this
    codebase frames dict messages, and a well-formed frame with the wrong
    top type must surface as the SAME ValueError every handler loop
    already treats as drop-the-connection (not an AttributeError
    traceback at the first .get)."""
    v = read_frame(sock)
    if not isinstance(v, dict):
        raise ValueError(f"wire: expected dict frame, got {type(v).__name__}")
    return v


# ------------------------------------------------------ deadline propagation

# Optional request-frame key carrying the caller's REMAINING time budget
# in nanoseconds (a relative budget, not an absolute timestamp: monotonic
# clocks don't compare across hosts and wall clocks skew). Every server
# loop re-anchors it against its own clock on receipt.
DEADLINE_KEY = "d"


def deadline_from_frame(req: dict):
    """Deadline from a request frame's budget field, or None. A malformed
    budget (wrong type, negative) is treated as absent: deadline metadata
    must never be the thing that kills an otherwise-valid request."""
    from ..utils.retry import Deadline

    budget = req.get(DEADLINE_KEY)
    if not isinstance(budget, int) or isinstance(budget, bool) or budget < 0:
        return None
    return Deadline.from_wire(budget)


# ------------------------------------------------------ trace propagation

# Optional request-frame key carrying the caller's span context (trace id
# + parent span id) — only attached for SAMPLED traces, so its presence
# is the sampling decision and the server never rolls its own. Rides the
# frame beside the deadline "d" and priority "pri" hints. The matching
# RESPONSE key "sp" carries the server's finished span tree back for the
# client to graft, making one cross-process tree per request.
TRACE_KEY = "tr"
SPAN_KEY = "sp"


def trace_from_frame(req: dict):
    """SpanContext from a request frame, or None. Malformed trace
    metadata is treated as absent (same contract as the deadline field)."""
    from ..utils.tracing import SpanContext

    return SpanContext.from_wire(req.get(TRACE_KEY))


# -------------------------------------------------- index query serialization


def query_to_wire(q) -> dict:
    """index.Query <-> plain dict (thrift rpc.thrift Query equivalent)."""
    from ..index import query as iq

    if isinstance(q, iq.AllQuery):
        return {"t": "all"}
    if isinstance(q, iq.TermQuery):
        return {"t": "term", "f": q.field, "v": q.value}
    if isinstance(q, iq.RegexpQuery):
        return {"t": "regexp", "f": q.field, "v": q.pattern}
    if isinstance(q, iq.ConjunctionQuery):
        return {"t": "conj", "qs": [query_to_wire(s) for s in q.queries]}
    if isinstance(q, iq.DisjunctionQuery):
        return {"t": "disj", "qs": [query_to_wire(s) for s in q.queries]}
    if isinstance(q, iq.NegationQuery):
        return {"t": "neg", "q": query_to_wire(q.query)}
    raise TypeError(f"unknown query {type(q)!r}")


def query_from_wire(d: dict):
    from ..index import query as iq

    t = d["t"]
    if t == "all":
        return iq.AllQuery()
    if t == "term":
        return iq.TermQuery(d["f"], d["v"])
    if t == "regexp":
        return iq.RegexpQuery(d["f"], d["v"])
    if t == "conj":
        return iq.ConjunctionQuery(tuple(query_from_wire(s) for s in d["qs"]))
    if t == "disj":
        return iq.DisjunctionQuery(tuple(query_from_wire(s) for s in d["qs"]))
    if t == "neg":
        return iq.NegationQuery(query_from_wire(d["q"]))
    raise ValueError(f"unknown query type {t!r}")
