"""HTTP/JSON mirror of the node RPC (reference:
src/dbnode/network/server/httpjson — every thrift method exposed as POST
/<method> with a JSON body, used for debugging and simple integrations;
server.go:555 wires it next to the tchannel listener), plus the dbnode
/debug surface: GET /debug/vars (instrument snapshot), /debug/traces
(span trees + slow-query log), /debug/pprof/profile (shared capped
background sampler) and /debug/pprof/threads|goroutine (all-threads
stack dump) — the same endpoints every reference service exposes
(dbnode/server/server.go:575 debug listener).

Numpy columns serialize as lists; bytes as latin-1-safe strings."""

from __future__ import annotations

import base64
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import numpy as np

from ..utils import tracing
from ..utils.instrument import ROOT
from ..utils.limits import ResourceExhausted
from .node_server import NodeService


def _to_json(v: Any):
    if isinstance(v, dict):
        return {_key(k): _to_json(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_json(x) for x in v]
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, bytes):
        return {"b64": base64.b64encode(v).decode()}
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


def _key(k):
    return k.decode(errors="replace") if isinstance(k, bytes) else k


def _from_json(v: Any):
    if isinstance(v, dict):
        if set(v) == {"b64"}:
            return base64.b64decode(v["b64"])
        return {k: _from_json(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_from_json(x) for x in v]
    return v


class HTTPJSONServer:
    def __init__(self, service: NodeService, host: str = "127.0.0.1",
                 port: int = 0):
        svc = service

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                method = self.path.strip("/")
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b"{}"
                try:
                    args = _from_json(json.loads(body or b"{}"))
                    # JSON callers pass strings where the wire uses bytes.
                    args = {k: (v.encode() if isinstance(v, str) and
                                k in ("ns", "id") else v)
                            for k, v in args.items()}
                    result = svc.dispatch(method, args)
                    out = {"ok": True, "r": _to_json(result)}
                    code = 200
                except ResourceExhausted as e:
                    # typed shed: 429 so HTTP producers back off (the
                    # JSON mirror of the wire's resource_exhausted frame)
                    out, code = {"ok": False, "err": str(e),
                                 "kind": "resource_exhausted"}, 429
                except Exception as e:  # noqa: BLE001
                    out, code = {"ok": False, "err": str(e)}, 400
                data = json.dumps(out).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                """dbnode /debug surface (everything else is POST rpc)."""
                parsed = urllib.parse.urlsplit(self.path)
                params = urllib.parse.parse_qs(parsed.query)
                path = parsed.path
                ctype = "application/json"
                code = 200
                try:
                    if path == "/debug/vars":
                        from ..parallel import guard

                        out = json.dumps(
                            {"metrics": ROOT.snapshot(),
                             "compute": guard.debug_snapshot()}).encode()
                    elif path == "/debug/traces":
                        tid = params.get("trace_id", [None])[0]
                        out = json.dumps(tracing.debug_traces_payload(
                            int(tid) if tid else None)).encode()
                    elif path == "/debug/pprof/profile":
                        out = json.dumps(tracing.debug_profile_payload(
                            float(params.get("seconds", ["1"])[0]))).encode()
                    elif path in ("/debug/pprof/threads",
                                  "/debug/pprof/goroutine"):
                        ctype = "text/plain; charset=utf-8"
                        out = tracing.thread_stacks().encode()
                    else:
                        self.send_response(404)
                        self.end_headers()
                        return
                except Exception as e:  # noqa: BLE001 — bad params
                    # (seconds=abc, trace_id=xyz) must answer a typed
                    # 400 like do_POST, not drop the connection with a
                    # handler traceback.
                    ctype = "application/json"
                    out = json.dumps({"ok": False, "err": str(e)}).encode()
                    code = 400
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

        self._server = ThreadingHTTPServer((host, port), Handler)

    @property
    def endpoint(self) -> str:
        h, p = self._server.server_address
        return f"http://{h}:{p}"

    def start(self) -> "HTTPJSONServer":
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    def close(self):
        self._server.shutdown()
        self._server.server_close()
