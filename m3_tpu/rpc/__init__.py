"""RPC transport layer (reference: src/dbnode/network/server/tchannelthrift).

Length-prefixed binary frames over TCP; node service method parity with
the thrift `Node` service IDL (src/dbnode/generated/thrift/rpc.thrift)."""

from .node_server import NodeServer, NodeService, RPCError
from .wire import (
    WireTruncated,
    decode,
    deadline_from_frame,
    encode,
    query_from_wire,
    query_to_wire,
    read_frame,
    write_frame,
)

__all__ = [
    "NodeServer",
    "NodeService",
    "RPCError",
    "WireTruncated",
    "deadline_from_frame",
    "decode",
    "encode",
    "query_from_wire",
    "query_to_wire",
    "read_frame",
    "write_frame",
]
