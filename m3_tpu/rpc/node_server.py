"""Node RPC service + TCP server (reference:
src/dbnode/network/server/tchannelthrift/node/service.go).

Method parity with the thrift `Node` service: Write (:743),
WriteBatchRaw (:827), WriteTaggedBatchRaw (:900), Fetch (:323),
FetchTagged (:396), FetchBlocksRaw (:535), FetchBlocksMetadataRawV2
(:608), Query (:255), Truncate (:993), Health (:210). The key design
point is preserved: FetchTagged / FetchBlocks return *encoded* block
segments (packed u32 TSZ codewords) plus raw mutable-buffer columns —
decompression happens in the client with the batched device decode
kernel, exactly as the reference decodes client-side
(docs/m3db/architecture/engine.md:167)."""

from __future__ import annotations

import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..storage.database import Database
from ..storage.series import charge_read
from ..utils import limits as xlimits
from ..utils import tracing
from ..utils.health import AdmissionGate, Priority
from ..utils.limits import ResourceExhausted
from ..utils.retry import Deadline, DeadlineExceeded
from . import wire


class RPCError(Exception):
    """Server-side error carried back over the wire."""


# Priority classification for admission control: the traffic whose loss
# turns an overload into an outage is CRITICAL and is never shed —
# health/admin probes (operators must see INTO an overloaded node) and
# replication/bootstrap streams (shedding them converts one overloaded
# replica into an under-replicated shard). Everything else is NORMAL
# serving traffic unless the request frame marks itself "bulk"
# (backfill), which sheds first at the high watermark.
_CRITICAL_METHODS = frozenset({
    "health", "namespaces", "truncate",
    "fetch_blocks", "fetch_blocks_metadata", "fetch_block_tiles",
    "fetch_block_metadata_tiles",
})


def method_priority(method: str, hint: Optional[str] = None) -> Priority:
    if method in _CRITICAL_METHODS:
        return Priority.CRITICAL
    if hint == "bulk":
        return Priority.BULK
    return Priority.NORMAL


class NodeService:
    """Dispatchable method table over a storage.Database, fronted by a
    bounded admission gate: in-flight requests past the high watermark
    shed bulk backfill, past capacity shed normal serving traffic too —
    with typed Backpressure so producers back off — while health/admin
    and replication always get through."""

    def __init__(self, db: Database, gate: Optional[AdmissionGate] = None,
                 limits: Optional[xlimits.QueryLimits] = None):
        self.db = db
        # monotonic, not wall clock: uptime is an ELAPSED measurement and
        # must not jump with NTP steps (m3lint wall-clock-latency).
        self.start_ns = time.monotonic_ns()
        # Default gate is generous (threaded server, sub-ms dispatches:
        # 1024 in flight means the node is drowning) but FINITE — overload
        # protection must be on by default, not a config opt-in.
        self.gate = gate if gate is not None else AdmissionGate(
            capacity=1024, name="rpc.node")
        self._limits = limits
        # Per-request deadline, thread-local because the ThreadingTCPServer
        # dispatches each connection on its own thread: rpc_* methods read
        # it to bail out of long loops once the caller's budget is gone.
        self._local = threading.local()

    # --------------------------------------------------------------- dispatch

    def dispatch(self, method: str, args: dict,
                 deadline: Optional[Deadline] = None,
                 priority_hint: Optional[str] = None,
                 trace_ctx=None):
        result, _sp = self.dispatch_traced(method, args, deadline,
                                           priority_hint, trace_ctx)
        return result

    def dispatch_traced(self, method: str, args: dict,
                        deadline: Optional[Deadline] = None,
                        priority_hint: Optional[str] = None,
                        trace_ctx=None):
        """dispatch + span plumbing: returns (result, finished span dict
        or None). A request frame carrying the "tr" context gets a
        remote-parented span around its whole dispatch — QueryScope exit
        annotates it with the request's cost tallies — and the finished
        tree rides the response frame back for the caller to graft
        (tracing module docstring). Untraced requests pay one NOOP test."""
        fn = getattr(self, "rpc_" + method, None)
        if fn is None:
            raise RPCError(f"unknown method {method!r}")
        # Check BEFORE the work: a request whose budget is already spent
        # in queueing/transit must not run an expensive fetch whose result
        # the caller stopped waiting for.
        if deadline is not None:
            deadline.check(method)
        ql = self._limits if self._limits is not None else xlimits.get_global()
        # Admission THEN limits scope: a shed request must cost nothing
        # beyond the gate check. The scope's child enforcers chain every
        # storage/index charge below this request to the global budgets
        # and release them all on the way out — 1k rejected queries leak
        # zero budget (asserted by scripts/overload_smoke.py).
        priority = method_priority(method, priority_hint)
        sp = tracing.TRACER.span_from(trace_ctx, "rpc." + method)
        # A shed BEFORE the scope runs (gate full) must log empty costs,
        # not the previous request's on this reused serving thread.
        xlimits.reset_last_totals()
        t0 = time.perf_counter_ns()
        try:
            with sp:
                with self.gate.held(priority=priority):
                    with ql.scope(f"rpc.{method}"):
                        self._local.deadline = deadline
                        # Down-stack admission (shard insert queues) sheds
                        # by the same priority the gate admitted at — BULK
                        # backfill that squeezed past the gate still sheds
                        # first at a full queue, and CRITICAL replication
                        # never sheds.
                        self._local.priority = priority
                        try:
                            result = fn(**args)
                        finally:
                            self._local.deadline = None
                            self._local.priority = None
        except ResourceExhausted:
            tracing.SLOW_QUERIES.maybe(
                "rpc", method, time.perf_counter_ns() - t0,
                costs=xlimits.last_scope_totals(), reason="limit-shed",
                trace_id=sp.trace_id or None)
            raise
        except DeadlineExceeded:
            tracing.SLOW_QUERIES.maybe(
                "rpc", method, time.perf_counter_ns() - t0,
                costs=xlimits.last_scope_totals(), reason="deadline",
                trace_id=sp.trace_id or None)
            raise
        dur = time.perf_counter_ns() - t0
        tracing.SLOW_QUERIES.maybe(
            "rpc", method, dur,
            # Sampled: lazy subtree rollup (cache events live on storage
            # child spans); unsampled: the scope's charge totals.
            costs=((lambda: tracing.collect_costs(sp)) if sp.sampled
                   else xlimits.last_scope_totals()),
            trace_id=sp.trace_id or None)
        return result, (sp.to_dict() if sp.sampled else None)

    def _check_deadline(self, what: str):
        dl = getattr(self._local, "deadline", None)
        if dl is not None:
            dl.check(what)

    def _request_priority(self) -> Priority:
        pri = getattr(self._local, "priority", None)
        return Priority.NORMAL if pri is None else pri

    # ----------------------------------------------------------------- health

    def rpc_health(self):
        return {
            "ok": True,
            "bootstrapped": self.db.bootstrapped,
            "uptime_ns": time.monotonic_ns() - self.start_ns,
        }

    # ----------------------------------------------------------------- writes

    def rpc_write(self, ns: bytes, id: bytes, t_ns: int, value: float,
                  tags: Optional[dict] = None):
        """Concurrency is per shard, not global: the storage layer holds a
        per-shard write lock (storage/shard.py write_lock, the reference's
        shard.go:769 per-shard RWMutex), the reverse index and commit log
        serialize internally, so writes to different shards proceed in
        parallel across server threads."""
        self.db.write(ns, id, t_ns, value, tags,
                      priority=self._request_priority())
        return True

    def rpc_write_batch(self, ns: bytes, ids: list, ts: np.ndarray, vals: np.ndarray,
                        tags: Optional[list] = None):
        self.db.write_batch(ns, ids, ts, vals, tags,
                            priority=self._request_priority())
        return len(ids)

    # ------------------------------------------------------------------ reads

    def rpc_fetch(self, ns: bytes, id: bytes, start_ns: int, end_ns: int):
        t, v = self.db.read(ns, id, start_ns, end_ns)
        return {"t": t, "v": v}

    def rpc_fetch_tagged(self, ns: bytes, query: dict, start_ns: int, end_ns: int,
                         fetch_data: bool = True, limit: int = 0):
        """FetchTagged with a COLUMNAR result frame: per-series entries
        carry only identity (id + tags — host label algebra); the data
        plane rides beside them as ONE buffer sidecar (concatenated
        mutable-buffer columns + an offsets vector) and one TILE per
        (shard, sealed block) — the requested rows fancy-indexed out of
        the block's word matrix in one numpy op, the same tile shape
        peer streaming moves (rpc_fetch_block_tiles) and the client's
        batched device decode consumes (client/decode.decode_tile).
        Pre-change this loop built one dict of segments per series —
        per-row python materialization on the hot read fan-in."""
        q = wire.query_from_wire(query)
        nsobj = self.db.namespace(ns)
        ids = self.db.query_ids(ns, q, start_ns, end_ns, limit=limit)
        out = []
        by_shard: Dict[int, List[Tuple[int, int]]] = {}  # -> (idx, pos)
        for sid in ids:
            # Mid-loop budget check: fetch_tagged is the expensive fan-in;
            # a dead caller's request must stop here, not run the whole
            # result set to completion.
            self._check_deadline("fetch_tagged")
            shard_id = self.db.shard_set.lookup(sid)
            shard = nsobj.shards.get(shard_id)
            if shard is None:
                continue
            idx = shard.registry.get(sid)
            if idx is None:
                # Indexed on another replica's time range but not written
                # here: identity-only row, no buffer/tile contribution.
                out.append({"id": sid, "tags": {}})
                continue
            # identity cost (id + tag pairs) charges bytes-read before the
            # segment payloads do — a tags-only fetch is still metered
            charge_read(n_bytes=shard.registry.entry_bytes(idx))
            if fetch_data:
                by_shard.setdefault(shard_id, []).append((idx, len(out)))
            out.append({"id": sid, "tags": shard.registry.tags_of(idx) or {}})
        n = len(out)
        buf_t = [np.zeros(0, np.int64)] * n
        buf_v = [np.zeros(0, np.float64)] * n
        tiles: List[dict] = []
        for shard_id in sorted(by_shard):
            shard = nsobj.shards[shard_id]
            members = by_shard[shard_id]
            # Buffer reads take the shard write lock in bounded CHUNKS —
            # a dashboard-sized member set must not stall every
            # concurrent write for one uninterrupted sweep (the
            # per-series path re-acquired per row; chunking keeps that
            # bound without paying the lock once per series). The block
            # snapshot MERGES under every chunk's acquisition: a tick
            # sealing the buffer between chunks moves later chunks'
            # points into a block the first snapshot predates — the
            # union sees it (earlier chunks may then appear in both
            # their buffer read and the new block's tile; duplicate
            # timestamps carry identical values and the client's
            # replica merge dedups them, same as a replica overlap).
            # Each chunk charges its buffer bytes BEFORE the next
            # materializes (query_limits.go bytes-read: reject an
            # oversized fetch mid fan-in).
            blocks: Dict[int, object] = {}
            chunk = 256
            for c0 in range(0, len(members), chunk):
                self._check_deadline("fetch_tagged")
                part = members[c0:c0 + chunk]
                with shard.write_lock:  # snapshot racing tick's expiry/seal
                    blocks.update(shard.blocks)
                    for idx, pos in part:
                        buf_t[pos], buf_v[pos] = shard.buffer.read(
                            idx, start_ns, end_ns)
                charge_read(n_bytes=sum(
                    buf_t[pos].nbytes + buf_v[pos].nbytes
                    for _, pos in part))
            for bs in sorted(blocks):
                blk = blocks[bs]
                if bs + shard.opts.block_size_ns <= start_ns or bs >= end_ns:
                    continue
                rows, poss = [], []
                for idx, pos in members:
                    row = blk.row_of(idx)
                    if row is not None:
                        rows.append(row)
                        poss.append(pos)
                if not rows:
                    continue
                self._check_deadline("fetch_tagged")
                # Charge BEFORE the tile materializes (query_limits.go
                # bytes-read): an oversized result must be rejected mid
                # fan-in, not after every tile copy has been allocated —
                # the same incremental guard the per-series path had.
                all_words = np.asarray(blk.words)
                rows_a = np.asarray(rows, np.int64)
                charge_read(
                    n_bytes=len(rows) * all_words.shape[-1]
                    * all_words.itemsize)
                tiles.append({
                    "bs": bs,
                    "rows": np.asarray(poss, np.int32),
                    "words": all_words[rows_a],
                    "nbits": np.asarray(blk.nbits)[rows_a].astype(np.int32),
                    "npoints": np.asarray(blk.npoints)[rows_a].astype(
                        np.int32),
                    "window": int(blk.window),
                    "time_unit": int(blk.time_unit),
                })
        offs = np.zeros(n + 1, np.int64)
        if n:
            offs[1:] = np.cumsum([t.size for t in buf_t])
        bufs = {
            "offs": offs,
            "t": (np.concatenate(buf_t) if n else np.zeros(0, np.int64)),
            "v": (np.concatenate(buf_v) if n else np.zeros(0, np.float64)),
        }
        return {"series": out, "bufs": bufs, "tiles": tiles,
                "exhaustive": True}

    def rpc_query(self, ns: bytes, query: dict, start_ns: int, end_ns: int):
        """service.go:255 Query: ids + tags only (no data)."""
        r = self.rpc_fetch_tagged(ns, query, start_ns, end_ns, fetch_data=False)
        return {"series": [{"id": s["id"], "tags": s["tags"]} for s in r["series"]]}

    def rpc_aggregate(self, ns: bytes, query: dict, start_ns: int, end_ns: int,
                      name_only: bool = False, field_filter: list = (),
                      term_limit: int = 0):
        """AggregateRaw analog (service.go:474 Aggregate / AggregateRaw):
        distinct tag names (and optionally values) for series matching the
        query, computed server-side from the reverse index — no datapoints
        shipped. An AllQuery short-circuits to the index's field/term
        dictionaries instead of materializing postings."""
        fields = self.db.aggregate_tags(
            ns, wire.query_from_wire(query), start_ns, end_ns,
            name_only=name_only, filter_names=field_filter)
        out = []
        for name in sorted(fields):
            vals = sorted(fields[name])
            if term_limit:
                vals = vals[:term_limit]
            out.append({"name": name, "values": vals})
        return {"fields": out, "name_only": bool(name_only)}

    # -------------------------------------------- block/metadata peer streaming

    def rpc_fetch_blocks_metadata(self, ns: bytes, shard: int, start_ns: int,
                                  end_ns: int, page_token: int = 0,
                                  limit: int = 1024):
        """FetchBlocksMetadataRawV2: paged per-series sealed block metadata."""
        nsobj = self.db.namespace(ns)
        sh = nsobj.shards.get(shard)
        if sh is None:
            return {"series": [], "next_page_token": None}
        all_ids = sh.registry.all_ids()
        out = []
        i = page_token
        with sh.write_lock:  # snapshot racing tick's expiry/seal
            shard_blocks = dict(sh.blocks)
        while i < len(all_ids) and len(out) < limit:
            sid = all_ids[i]
            idx = sh.registry.get(sid)
            blocks = []
            for bs in sorted(shard_blocks):
                blk = shard_blocks[bs]
                if bs + sh.opts.block_size_ns <= start_ns or bs >= end_ns:
                    continue
                row = blk.row_of(idx)
                if row is None:
                    continue
                blocks.append({
                    "bs": bs,
                    "nbits": int(blk.nbits[row]),
                    "npoints": int(blk.npoints[row]),
                    "checksum": blk.row_checksum(row),
                })
            out.append({"id": sid, "tags": sh.registry.tags_of(idx) or {},
                        "blocks": blocks})
            i += 1
        next_token = i if i < len(all_ids) else None
        return {"series": out, "next_page_token": next_token}

    def rpc_fetch_blocks(self, ns: bytes, shard: int, requests: list):
        """FetchBlocksRaw: encoded rows for [(id, [block_starts])] requests."""
        nsobj = self.db.namespace(ns)
        sh = nsobj.shards.get(shard)
        out = []
        if sh is not None:
            with sh.write_lock:  # snapshot racing tick's expiry/seal
                shard_blocks = dict(sh.blocks)
        for req in requests:
            sid = req["id"]
            entry = {"id": sid, "blocks": []}
            if sh is not None:
                idx = sh.registry.get(sid)
                if idx is not None:
                    for bs in req["block_starts"]:
                        blk = shard_blocks.get(bs)
                        if blk is None:
                            continue
                        row = blk.row_of(idx)
                        if row is None:
                            continue
                        entry["blocks"].append({
                            "bs": bs,
                            "words": np.asarray(blk.words[row]),
                            "nbits": int(blk.nbits[row]),
                            "npoints": int(blk.npoints[row]),
                            "window": int(blk.window),
                            "time_unit": int(blk.time_unit),
                        })
            out.append(entry)
        return {"series": out}

    def rpc_fetch_block_metadata_tiles(self, ns: bytes, shard: int,
                                       start_ns: int, end_ns: int,
                                       page_token: int = 0,
                                       limit: int = 8192):
        """Columnar FetchBlocksMetadataRawV2: one page covers a
        contiguous registry-index window [page_token, page_token+limit)
        and returns the page's ids/tags plus, per sealed block, the
        positions (into the page's ids) and row checksums as ARRAYS —
        no per-series dicts on the wire. Registry indices are assigned
        densely in insertion order and block series_indices are sorted,
        so each block's page rows are one searchsorted slice."""
        nsobj = self.db.namespace(ns)
        sh = nsobj.shards.get(shard)
        if sh is None:
            return {"ids": [], "tags": [], "blocks": [],
                    "next_page_token": None}
        all_ids = sh.registry.all_ids()
        i0 = int(page_token)
        i1 = min(len(all_ids), i0 + int(limit))
        ids = all_ids[i0:i1]
        tags = [sh.registry.tags_of(i0 + j) or {} for j in range(len(ids))]
        with sh.write_lock:  # snapshot racing tick's expiry/seal
            shard_blocks = dict(sh.blocks)
        blocks = []
        total_bytes = sum(len(s) for s in ids)
        for bs in sorted(shard_blocks):
            self._check_deadline("fetch_block_metadata_tiles")
            blk = shard_blocks[bs]
            if bs + sh.opts.block_size_ns <= start_ns or bs >= end_ns:
                continue
            si = blk.series_indices
            lo = int(np.searchsorted(si, i0))
            hi = int(np.searchsorted(si, i1))
            if lo == hi:
                continue
            # Memoized per-block row checksums: repeated metadata pages
            # (every repair sweep, every bootstrap) reuse one pass.
            sums = blk.row_checksums()[lo:hi]
            total_bytes += sums.nbytes
            blocks.append({
                "bs": bs,
                "pos": np.ascontiguousarray(si[lo:hi] - i0, np.int32),
                "sums": sums,
            })
        charge_read(n_bytes=int(total_bytes))
        next_token = i1 if i1 < len(all_ids) else None
        return {"ids": ids, "tags": tags, "blocks": blocks,
                "next_page_token": next_token}

    def rpc_fetch_block_tiles(self, ns: bytes, shard: int, blocks: list):
        """Columnar FetchBlocksRaw: for [{"bs", "ids": [...]}] requests,
        return per-block TILES — one [rows, max_words] word matrix plus
        nbits/npoints columns and the row-aligned id list — instead of
        one dict per series. The whole tile is three fancy-indexes into
        the sealed block's arrays, and the client applies it as one
        batched registry insert + one block install (the peer-streaming
        data plane's unit of work; ids absent locally or rows the block
        doesn't hold are simply absent from the response ids)."""
        nsobj = self.db.namespace(ns)
        sh = nsobj.shards.get(shard)
        out = []
        if sh is None:
            return {"blocks": out}
        with sh.write_lock:  # snapshot racing tick's expiry/seal
            shard_blocks = dict(sh.blocks)
        for req in blocks:
            self._check_deadline("fetch_block_tiles")
            bs = int(req["bs"])
            blk = shard_blocks.get(bs)
            if blk is None:
                continue
            ids = req["ids"]
            idxs = sh.registry.lookup_batch(ids)
            known = idxs >= 0
            # Row resolve for every known id in one vectorized search
            # (series_indices is sorted).
            cand = np.searchsorted(blk.series_indices, idxs[known])
            cand = np.minimum(cand, len(blk.series_indices) - 1)
            present = blk.series_indices[cand] == idxs[known]
            rows = cand[present]
            if not len(rows):
                continue
            kpos = np.flatnonzero(known)[present]
            words = np.ascontiguousarray(blk.words[rows])
            charge_read(n_bytes=int(words.nbytes))
            out.append({
                "bs": bs,
                "ids": [ids[int(i)] for i in kpos],
                "words": words,
                "nbits": np.ascontiguousarray(blk.nbits[rows]),
                "npoints": np.ascontiguousarray(blk.npoints[rows]),
                "window": int(blk.window),
                "time_unit": int(blk.time_unit),
            })
        return {"blocks": out}

    # ------------------------------------------------------------------ admin

    def rpc_truncate(self, ns: bytes):
        nsobj = self.db.namespace(ns)
        n = sum(sh.num_series() for sh in nsobj.shards.values())
        shard_ids = list(nsobj.shards)
        for sid in shard_ids:
            nsobj.remove_shard(sid)
            nsobj.assign_shard(sid)
        return n

    def rpc_namespaces(self):
        out = []
        for name, nsobj in list(self.db.namespaces.items()):
            out.append({
                "name": name,
                "retention_ns": nsobj.opts.retention_ns,
                "block_size_ns": nsobj.opts.block_size_ns,
                "index_enabled": nsobj.opts.index_enabled,
                "num_shards": len(nsobj.shards),
            })
        return out


class NodeServer:
    """Threaded TCP listener dispatching wire frames to a NodeService
    (tchannelthrift NewServer + ListenAndServe equivalent)."""

    def __init__(self, service: NodeService, host: str = "127.0.0.1", port: int = 0):
        self.service = service
        svc = self.service

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                try:
                    while True:
                        req = wire.read_dict_frame(sock)
                        msg_id = req.get("id", 0)
                        # Optional deadline budget (ns remaining at send
                        # time) rides the request frame as "d"; re-anchored
                        # on this host's monotonic clock.
                        deadline = wire.deadline_from_frame(req)
                        try:
                            pri = req.get("pri")
                            result, sp = svc.dispatch_traced(
                                req["m"], req.get("a", {}),
                                deadline=deadline,
                                priority_hint=pri if
                                isinstance(pri, str) else None,
                                trace_ctx=wire.trace_from_frame(req))
                            resp = {"id": msg_id, "ok": True, "r": result}
                            if sp is not None:
                                # Finished server-side span tree for the
                                # caller to graft (one cross-process tree
                                # per request).
                                resp[wire.SPAN_KEY] = sp
                            wire.write_frame(sock, resp)
                        except DeadlineExceeded as e:
                            # Typed error frame: the caller distinguishes
                            # "server killed it for MY deadline" (stop
                            # waiting, don't retry) from app errors.
                            wire.write_frame(sock, {"id": msg_id, "ok": False,
                                                    "kind": "deadline",
                                                    "err": str(e)})
                        except ResourceExhausted as e:
                            # Typed shed frame: a query limit or the
                            # admission gate rejected this request. The
                            # client classifies it retryable-with-backoff
                            # (the condition clears as windows expire and
                            # in-flight work drains) — the opposite of
                            # "deadline", which never retries.
                            wire.write_frame(sock, {
                                "id": msg_id, "ok": False,
                                "kind": "resource_exhausted", "err": str(e)})
                        # DELIBERATE broad except: the dispatch contract is
                        # to relay ANY server-side application error to the
                        # caller as a typed error frame — the wire write in
                        # the try is the success path, and its own failures
                        # hit the outer typed handler when the error frame
                        # write below also fails.
                        except Exception as e:  # noqa: BLE001  # m3lint: disable=broad-except-wire-io
                            wire.write_frame(
                                sock, {"id": msg_id, "ok": False, "err": f"{type(e).__name__}: {e}"}
                            )
                except (ConnectionError, OSError, ValueError):
                    # ValueError = malformed/truncated frame (wire.decode
                    # normalizes every corrupt-buffer case to it): the
                    # stream is desynchronized, so drop the connection —
                    # don't let the handler thread die with a traceback.
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        return f"{self.address[0]}:{self.address[1]}"

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def close(self):
        self._server.shutdown()
        self._server.server_close()
