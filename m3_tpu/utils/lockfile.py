"""PID lockfile (reference: src/x/lockfile — one process per data
directory; m3dbnode takes it on startup so two nodes can't share a dir)."""

from __future__ import annotations

import fcntl
import os
from typing import Optional


class LockError(RuntimeError):
    pass


class Lockfile:
    def __init__(self, path: str):
        self.path = path
        self._fd: Optional[int] = None

    def acquire(self) -> "Lockfile":
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            raise LockError(f"lockfile {self.path} held by another process")
        os.ftruncate(fd, 0)
        os.write(fd, str(os.getpid()).encode())
        self._fd = fd
        return self

    def release(self):
        if self._fd is not None:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()
