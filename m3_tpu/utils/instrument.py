"""Metrics instrumentation (reference: uber-go/tally scopes used in 136
files + instrument.Options carried in every component's options;
m3 reports its own metrics through itself).

A Scope is a tagged namespace of counters/gauges/histograms; snapshot()
feeds the /debug/vars HTTP endpoint and, dogfooding like the reference,
can be scraped straight into the coordinator's ingest path."""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1):
        with self._lock:
            self._value += n

    def value(self) -> int:
        return self._value


class Gauge:
    __slots__ = ("_value",)

    def __init__(self):
        self._value = 0.0

    def update(self, v: float):
        self._value = v

    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-boundary histogram (tally histogram with duration buckets)."""

    def __init__(self, boundaries: Tuple[float, ...] = (
            0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10)):
        self.boundaries = boundaries
        self._counts = [0] * (len(boundaries) + 1)
        self._lock = threading.Lock()
        self._sum = 0.0
        self._n = 0

    def record(self, v: float):
        i = 0
        while i < len(self.boundaries) and v > self.boundaries[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._n += 1

    def snapshot(self) -> dict:
        # One consistent (counts, sum, n) triple under this histogram's
        # own lock — snapshot() is called OUTSIDE the root registry lock
        # (Scope.snapshot), so a racing record() must not tear the read.
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._n
        return {"buckets": dict(zip([str(b) for b in self.boundaries] + ["+Inf"],
                                    counts)),
                "sum": total, "count": n}


class Timer:
    """Context-manager stopwatch recording seconds into a histogram."""

    def __init__(self, hist: Histogram):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.record(time.perf_counter() - self._t0)


class Scope:
    def __init__(self, prefix: str = "", tags: Optional[Dict[str, str]] = None,
                 _root: Optional["Scope"] = None):
        self.prefix = prefix
        self.tags = dict(tags or {})
        self._root = _root or self
        if _root is None:
            self._metrics: Dict[str, object] = {}
            self._lock = threading.Lock()

    def sub_scope(self, name: str, **tags) -> "Scope":
        prefix = f"{self.prefix}.{name}" if self.prefix else name
        return Scope(prefix, {**self.tags, **tags}, _root=self._root)

    def _key(self, name: str) -> str:
        full = f"{self.prefix}.{name}" if self.prefix else name
        if self.tags:
            tag_s = ",".join(f"{k}={v}" for k, v in sorted(self.tags.items()))
            full = f"{full}{{{tag_s}}}"
        return full

    def _get(self, name: str, factory):
        root = self._root
        key = self._key(name)
        with root._lock:
            m = root._metrics.get(key)
            if m is None:
                m = root._metrics[key] = factory()
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, boundaries=None) -> Histogram:
        return self._get(name, lambda: Histogram(boundaries)
                         if boundaries else Histogram())

    def timer(self, name: str) -> Timer:
        return Timer(self.histogram(name))

    def snapshot(self) -> Dict[str, object]:
        # Copy metric REFS under the registry lock, snapshot OUTSIDE it:
        # Histogram.snapshot() takes its own lock, and holding the root
        # lock across every histogram made /debug/vars an O(metrics)
        # critical section that serialized against every _get() on the
        # hot path (plus a nested root->histogram lock acquisition).
        root = self._root
        with root._lock:
            metrics = sorted(root._metrics.items())
        out = {}
        for key, m in metrics:
            if isinstance(m, (Counter, Gauge)):
                out[key] = m.value()
            else:
                out[key] = m.snapshot()
        return out


ROOT = Scope()
