"""Runtime numerics witness (the numerics-plane analog of
utils/lockdep.py): opt-in with M3_TPU_NUMERICS=1, auto-installed by the
package init so it is armed before any query runs. Costs nothing when
unset — the serving hooks read one module bool.

What it witnesses, at the jit-builder entry points' host-materialization
boundaries (the post-program observation points of the `plan` and
`agg_flush_reducer` builders — parallel/compile.py::execute and
parallel/agg_flush.py::exact_quantile_values):

  nan-live      a NaN in a NON-padding output lane. Legal only where the
                static pass proves the module treats NaN as its
                missing-value domain (numeric_rules.accepted_witness).
  inf-live      an inf in a live lane. Legal only where the lowered op
                table emits an unguarded divide (PromQL `x/0` is +Inf).
  pad-finite    a FINITE value in a padding ROW of a compiled plan's
                output plane — a padding lane's value survived to the
                materialized result (an unmasked -1 gather wraps a live
                row into padding; a missing `where` lets pad lanes fold
                forward). NEVER accepted.
  pad-nonzero   a non-zero value in a count-0 row of the aggregator's
                exact quantile output (stream.go:145-146 empty
                convention). NEVER accepted.

Findings aggregate per (site, kind) with first-occurrence detail and a
count, JSON-dumped at exit to M3_TPU_NUMERICS_OUT (one file per
process). scripts/numerics_check.py re-runs the plan and agg smokes
under the witness and asserts witnessed ⊆ the static pass's accepted
set — closing the same static/runtime loop lockdep closes for lock
discipline.

The witness is a SMOKE-TIER tool: observation materializes the padded
output plane (one extra D2H per query), which is exactly the transfer
the serving path exists to avoid — never enable it in production
serving.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "enabled", "installed", "install", "uninstall", "reset", "findings",
    "observed_count", "observe_result", "observe_rows", "dump_now",
    "unaccepted", "KINDS",
]

KINDS = ("nan-live", "inf-live", "pad-finite", "pad-nonzero")

_LOCK = threading.Lock()
_INSTALLED = False
_OBSERVED = 0
_FINDINGS: Dict[Tuple[str, str], Dict] = {}
_MAX_SITES = 256  # bound the table; the kinds x sites product is tiny


def enabled() -> bool:
    return os.environ.get("M3_TPU_NUMERICS", "") not in ("", "0")


def installed() -> bool:
    return _INSTALLED


def install():
    """Arm the witness hooks (idempotent) and register the exit dump."""
    global _INSTALLED
    with _LOCK:
        if _INSTALLED:
            return
        _INSTALLED = True
    atexit.register(_atexit_dump)


def uninstall():
    global _INSTALLED
    with _LOCK:
        _INSTALLED = False


def reset():
    global _OBSERVED
    with _LOCK:
        _OBSERVED = 0
        _FINDINGS.clear()


def _record(site: str, kind: str, detail: str):
    with _LOCK:
        key = (site, kind)
        entry = _FINDINGS.get(key)
        if entry is None:
            if len(_FINDINGS) >= _MAX_SITES:
                return
            _FINDINGS[key] = {"site": site, "kind": kind, "count": 1,
                              "detail": detail}
        else:
            entry["count"] += 1


def findings() -> List[Dict]:
    with _LOCK:
        return [dict(v) for v in _FINDINGS.values()]


def observed_count() -> int:
    return _OBSERVED


def observe_result(site: str, arr, live_rows: Optional[int] = None,
                   live_cols: Optional[int] = None):
    """Witness one materialized result plane. `live_rows`/`live_cols`
    bound the non-padding region (None = the whole extent is live; the
    padding check applies to ROWS — the NaN row-padding contract; column
    padding is time-axis slack the host slices and presence-style
    outputs legitimately fill)."""
    global _OBSERVED
    if not _INSTALLED:
        return
    a = np.asarray(arr)
    with _LOCK:
        _OBSERVED += 1
    if a.ndim == 0:
        a = a.reshape(1, 1)
    elif a.ndim == 1:
        a = a.reshape(1, -1)
    rows = a.shape[0] if live_rows is None else min(live_rows, a.shape[0])
    cols = a.shape[1] if live_cols is None else min(live_cols, a.shape[1])
    live = a[:rows, :cols]
    if live.size:
        if np.isinf(live).any():
            _record(site, "inf-live",
                    f"inf in live lanes of a [{a.shape[0]}x{a.shape[1]}] "
                    f"plane (live {rows}x{cols})")
        if np.isnan(live).any():
            _record(site, "nan-live",
                    f"NaN in live lanes of a [{a.shape[0]}x{a.shape[1]}] "
                    f"plane (live {rows}x{cols})")
    if live_rows is not None and rows < a.shape[0]:
        # FULL-width pad-row scan: a leak can land in a padding row at a
        # padding COLUMN too (an unclamped gather wraps anywhere), and
        # in-tree padding rows are NaN across the whole time extent.
        pad = a[rows:, :]
        if pad.size and np.isfinite(pad).any():
            _record(site, "pad-finite",
                    f"finite value in padding rows [{rows}:{a.shape[0]}] "
                    f"— a padding lane's value reached the materialized "
                    "result")


def observe_rows(site: str, vals, live_mask):
    """Witness a row-keyed output where liveness is per row (the
    aggregator's quantile gather: live rows have count > 0; count-0 rows
    must be exactly zero)."""
    global _OBSERVED
    if not _INSTALLED:
        return
    v = np.asarray(vals)
    m = np.asarray(live_mask, dtype=bool)
    with _LOCK:
        _OBSERVED += 1
    live = v[m]
    if live.size:
        if np.isinf(live).any():
            _record(site, "inf-live", f"inf in {int(m.sum())} live row(s)")
        if np.isnan(live).any():
            _record(site, "nan-live", f"NaN in {int(m.sum())} live row(s)")
    pad = v[~m]
    if pad.size and np.any(pad != 0):
        _record(site, "pad-nonzero",
                f"non-zero value in {int((~m).sum())} empty row(s) — the "
                "count-0 zero convention (stream.go:145-146) was violated")


# ----------------------------------------------------------------- dumps


def default_out_dir() -> str:
    return os.environ.get("M3_TPU_NUMERICS_OUT", "")


def dump_now(path: str = "") -> str:
    """Write this process's witness state as JSON; returns the path
    ('' when no output dir is configured and none was given)."""
    if not path:
        out_dir = default_out_dir()
        if not out_dir:
            return ""
        path = os.path.join(out_dir, f"numerics-{os.getpid()}.json")
    payload = {
        "pid": os.getpid(),
        "observed": observed_count(),
        "findings": findings(),
    }
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
    except OSError:
        return ""
    return path


def _atexit_dump():
    if _INSTALLED:
        dump_now()


# ------------------------------------------------------------ gate logic


def unaccepted(witnessed: List[Dict], accepted) -> List[Dict]:
    """Witness findings not covered by the static pass's accepted set
    of (site, kind) pairs — the numerics_check contract: this list must
    be empty."""
    acc = set(accepted)
    return [f for f in witnessed if (f["site"], f["kind"]) not in acc]
