"""Bloom filter over series IDs (reference: m3db/bloom used by fileset
seekers, src/dbnode/persist/fs/bloom_filter.go) — numpy bit array with k
murmur3 hashes derived from two base hashes (Kirsch-Mitzenmacher)."""

from __future__ import annotations

import math

import numpy as np

from .hashing import hash_batch, murmur3_32


class BloomFilter:
    def __init__(self, m_bits: int, k: int):
        self.m = max(int(m_bits), 8)
        self.k = max(int(k), 1)
        self.bits = np.zeros((self.m + 7) // 8, np.uint8)

    @staticmethod
    def for_capacity(n: int, false_positive_rate: float = 0.02) -> "BloomFilter":
        n = max(n, 1)
        m = int(-n * math.log(false_positive_rate) / (math.log(2) ** 2)) + 1
        k = max(int(round(m / n * math.log(2))), 1)
        return BloomFilter(m, k)

    def _positions(self, item: bytes) -> np.ndarray:
        h1 = murmur3_32(item)
        h2 = murmur3_32(item, seed=0x9747B28C)
        i = np.arange(self.k, dtype=np.uint64)
        return ((h1 + i * h2) % np.uint64(self.m)).astype(np.int64)

    def add(self, item: bytes):
        pos = self._positions(item)
        np.bitwise_or.at(self.bits, pos >> 3, (1 << (pos & 7)).astype(np.uint8))

    def add_batch(self, items):
        if not len(items):
            return
        h1 = hash_batch(items).astype(np.uint64)
        h2 = hash_batch(items, seed=0x9747B28C).astype(np.uint64)
        i = np.arange(self.k, dtype=np.uint64)[None, :]
        pos = ((h1[:, None] + i * h2[:, None]) % np.uint64(self.m)).astype(np.int64).ravel()
        np.bitwise_or.at(self.bits, pos >> 3, (1 << (pos & 7)).astype(np.uint8))

    def __contains__(self, item: bytes) -> bool:
        pos = self._positions(item)
        return bool(((self.bits[pos >> 3] >> (pos & 7)) & 1).all())

    def tobytes(self) -> bytes:
        return self.bits.tobytes()

    @classmethod
    def frombytes(cls, data: bytes, m_bits: int, k: int) -> "BloomFilter":
        bf = cls(m_bits, k)
        bf.bits = np.frombuffer(data, np.uint8).copy()
        return bf
