"""Tag wire codec (reference: src/x/serialize/encoder.go — the
length-prefixed binary tag encoding used on the dbnode write path and in
fileset index entries: header magic + tag count, then per-tag
u16-length-prefixed name/value byte strings)."""

from __future__ import annotations

import struct
from typing import Dict, Iterator, Tuple

# Same u16 header marker value as the reference (encoder.go
# headerMagicNumber = 10101); the surrounding format is reference-shaped
# (little-endian u16 lengths), not byte-for-byte identical.
HEADER_MAGIC = 10101
_U16 = struct.Struct("<H")

MAX_TAGS = 0xFFFF
MAX_LEN = 0xFFFF


class TagEncodeError(ValueError):
    pass


def encode_tags(tags: Dict[bytes, bytes]) -> bytes:
    """serialize.TagEncoder#Encode."""
    if len(tags) > MAX_TAGS:
        raise TagEncodeError(f"too many tags ({len(tags)})")
    out = bytearray()
    out += _U16.pack(HEADER_MAGIC)
    out += _U16.pack(len(tags))
    for name in sorted(tags):
        value = tags[name]
        for part in (name, value):
            if len(part) > MAX_LEN:
                raise TagEncodeError("tag component too long")
            out += _U16.pack(len(part))
            out += part
    return bytes(out)


def decode_tags(buf: bytes) -> Dict[bytes, bytes]:
    """serialize.TagDecoder: validates the magic + structure."""
    return dict(iter_tags(buf))


def iter_tags(buf: bytes) -> Iterator[Tuple[bytes, bytes]]:
    if len(buf) < 4:
        raise TagEncodeError("short tag buffer")
    (magic,) = _U16.unpack_from(buf, 0)
    if magic != HEADER_MAGIC:
        raise TagEncodeError(f"bad tag header {magic:#x}")
    (count,) = _U16.unpack_from(buf, 2)
    pos = 4
    for _ in range(count):
        parts = []
        for _ in range(2):
            if pos + 2 > len(buf):
                raise TagEncodeError("truncated tag length")
            (n,) = _U16.unpack_from(buf, pos)
            pos += 2
            if pos + n > len(buf):
                raise TagEncodeError("truncated tag bytes")
            parts.append(buf[pos:pos + n])
            pos += n
        yield parts[0], parts[1]
    if pos != len(buf):
        raise TagEncodeError("trailing bytes after tags")
