"""Murmur3-32 hashing for shard assignment (reference:
src/dbnode/sharding/shardset.go:30 uses murmur3.Sum32(id) % numShards, via
the stack-allocated m3db/stackmurmur3 fork).

Scalar path is pure Python (control-plane rates); `hash_batch` vectorizes
over many IDs with numpy for bulk shard routing of write batches."""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_M32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 x86 32-bit, bit-exact with the reference's murmur3.Sum32."""
    h = seed & _M32
    n = len(data)
    full = n - n % 4
    for i in range(0, full, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        k = (k * _C1) & _M32
        k = _rotl32(k, 15)
        k = (k * _C2) & _M32
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _M32
    k = 0
    tail = data[full:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _M32
        k = _rotl32(k, 15)
        k = (k * _C2) & _M32
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


# Bounded memo for hot-ID shard routing: the aggregator's timed wire
# hashes the same metric IDs once per datapoint (every window), and the
# pure-Python block mixer was ~13% of per-entry dispatch. Bounded in
# BOTH dimensions — entry count (lru) and key size (oversize IDs skip
# the cache entirely), because the wire calls this on client-supplied
# ids before any validation and 64k pinned multi-MB keys would be an
# unbounded-memory hazard, not a cache. 64k x <=256B is <= ~16MB.
_MURMUR_CACHE_MAX_KEY = 256
# the public wrapper below normalizes every non-bytes buffer before this
# memo sees it, so the unhashable/mutable-key hazard cannot reach it
_murmur3_32_lru = functools.lru_cache(maxsize=65536)(murmur3_32)  # m3lint: disable=cache-key-buffer


def murmur3_32_cached(data: bytes, seed: int = 0) -> int:
    if type(data) is not bytes:
        # bytearray/memoryview hash the same bytes but are unhashable (or
        # mutable — a cache key that can change underneath the memo), so
        # normalize before the cached path; mirrors the oversize bypass.
        data = bytes(data)
    if len(data) > _MURMUR_CACHE_MAX_KEY:
        return murmur3_32(data, seed)
    return _murmur3_32_lru(data, seed)


def hash_batch(ids: Sequence[bytes], seed: int = 0) -> np.ndarray:
    """Vectorized murmur3-32 over variable-length IDs.

    IDs are padded into a [N, maxlen] byte matrix; the 4-byte block mixing
    runs columnwise in numpy with per-row active masks, so throughput scales
    with the longest ID rather than per-ID Python loops."""
    n = len(ids)
    if n == 0:
        return np.zeros(0, np.uint32)
    lens = np.fromiter(map(len, ids), np.int64, n)
    maxlen = int(lens.max(initial=1))
    padded = maxlen + (-maxlen) % 4
    buf = np.zeros((n, padded), np.uint8)
    # One concatenated buffer + boolean scatter instead of a frombuffer
    # per id: row-major mask order equals concatenation order (the
    # TermDict padding trick) — this runs per write batch on the shard
    # routing path, so the per-id Python loop was measurable.
    joined = b"".join(ids)
    if joined:
        mask = np.arange(padded)[None, :] < lens[:, None]
        buf[mask] = np.frombuffer(joined, np.uint8)
    words = buf.view("<u4")  # [n, padded // 4]

    # Pallas route (ops.pallas_codec.hash_words, lane-parallel murmur3):
    # same padded-buffer layout, bit-identical output; gated on the codec
    # dispatch switch plus a column bound past which the VMEM tile stops
    # paying. The numpy loop below stays the fallback AND the oracle.
    try:
        from ..ops import pallas_codec
    except Exception:  # jax-less contexts keep the pure-numpy path
        pallas_codec = None
    if pallas_codec is not None:
        from ..parallel import guard

        use = (pallas_codec.enabled()
               and 0 < words.shape[1] <= pallas_codec.HASH_MAX_COLS
               and guard.available("codec.hash"))
        pallas_codec.route("hash", use)
        if use:
            out = guard.dispatch(
                "codec.hash",
                lambda: np.asarray(pallas_codec.hash_words(
                    words, lens, seed)),
                lambda _err: None)
            if out is not None:
                return out
            # Guarded fallback: fall through to the numpy loop below —
            # the declared oracle for this kernel.

    h = np.full(n, seed, np.uint32)
    nblocks = lens // 4
    with np.errstate(over="ignore"):
        for j in range(words.shape[1]):
            active = nblocks > j
            k = words[:, j] * np.uint32(_C1)
            k = (k << np.uint32(15)) | (k >> np.uint32(17))
            k = k * np.uint32(_C2)
            h2 = h ^ k
            h2 = (h2 << np.uint32(13)) | (h2 >> np.uint32(19))
            h2 = h2 * np.uint32(5) + np.uint32(0xE6546B64)
            h = np.where(active, h2, h)

        # Tail bytes.
        full = (lens - lens % 4).astype(np.int64)
        tail_len = (lens % 4).astype(np.int64)
        idx = np.minimum(full[:, None] + np.arange(3)[None, :], padded - 1)
        tb = np.take_along_axis(buf, idx, axis=1).astype(np.uint32)
        k = np.zeros(n, np.uint32)
        k = np.where(tail_len >= 3, k ^ (tb[:, 2] << np.uint32(16)), k)
        k = np.where(tail_len >= 2, k ^ (tb[:, 1] << np.uint32(8)), k)
        has_tail = tail_len >= 1
        k = np.where(has_tail, k ^ tb[:, 0], k)
        k = k * np.uint32(_C1)
        k = (k << np.uint32(15)) | (k >> np.uint32(17))
        k = k * np.uint32(_C2)
        h = np.where(has_tail, h ^ k, h)

        h ^= lens.astype(np.uint32)
        h ^= h >> np.uint32(16)
        h = h * np.uint32(0x85EBCA6B)
        h ^= h >> np.uint32(13)
        h = h * np.uint32(0xC2B2AE35)
        h ^= h >> np.uint32(16)
    return h
