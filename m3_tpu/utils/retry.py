"""Unified resilience primitives for every networked layer (reference:
src/x/retry/retry.go — exponential backoff with jitter, retryable-error
classification, per-attempt hooks — plus the connection-pool health
checking in src/dbnode/client/connection_pool.go and the host breaker
shape the reference gets from hailocab/go-hostpool).

Three cooperating pieces, shared by client/session, msg/producer,
query/remote and cluster/kv_service:

  Retrier   exponential backoff with decorrelating jitter, max attempts
            and max cumulative duration, pluggable retryable-error
            classification, and an on_retry hook for instrumentation.
  Breaker   closed -> open on failure-rate trip over a sliding outcome
            window; open -> half-open after a cooldown; a bounded number
            of half-open probes either close it again or re-open it.
            Stops retry storms from hammering a dead endpoint.
  Deadline  a remaining-time budget that rides RPC request frames as a
            nanosecond budget (not an absolute timestamp, so clock skew
            between hosts cannot corrupt it) and is re-anchored against
            the receiver's monotonic clock on arrival.

Everything takes an injectable clock/sleep/rng so the chaos suite
(tests/test_resilience.py) runs deterministic schedules.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

__all__ = [
    "RetryableError", "NonRetryableError", "DeadlineExceeded",
    "RetryOptions", "Retrier",
    "BreakerOptions", "Breaker", "BreakerOpen",
    "Deadline", "HostHealth", "default_is_retryable",
]


class RetryableError(Exception):
    """Marker base: raising (a subclass of) this tells every Retrier the
    operation is safe to re-attempt regardless of its concrete type."""


class NonRetryableError(Exception):
    """Marker base: never re-attempted even if a subclass also inherits
    from a retryable family (classification checks this first)."""


class DeadlineExceeded(Exception):
    """The operation's time budget ran out (client-observed or relayed
    from a server's typed deadline error frame). Never retried: the
    budget that expired is the caller's whole budget."""


class BreakerOpen(ConnectionError):
    """Raised instead of attempting I/O while a breaker is open. A
    ConnectionError subclass so quorum fanout / host-failure paths treat
    the endpoint exactly like a connect failure — just without paying
    for the socket."""


def default_is_retryable(e: BaseException) -> bool:
    """x/retry's classification adapted to this wire stack: transport
    errors retry, application/typed errors don't.

    Retryable: RetryableError, ConnectionError (covers WireTruncated),
    OSError (connect failures, socket timeouts). Not retryable:
    NonRetryableError, DeadlineExceeded (the budget is gone), BreakerOpen
    (the breaker's cooldown far exceeds any sane backoff, so re-asking
    the SAME breaker is guaranteed-futile sleeping — retrying a different
    host belongs to the quorum/fanout layer above), and everything else
    (server-side application errors relayed over the wire, protocol
    desyncs surfaced as ValueError — retrying a desynced exchange
    re-sends into garbage)."""
    if isinstance(e, (NonRetryableError, DeadlineExceeded, BreakerOpen)):
        return False
    return isinstance(e, (RetryableError, ConnectionError, OSError))


# ---------------------------------------------------------------- deadline


_NS = 1_000_000_000


class Deadline:
    """Monotonic time budget. Created from seconds (or a wire budget in
    ns), carried across RPC hops as `remaining_ns`, re-anchored on the
    receiving side's own clock."""

    __slots__ = ("_t_end", "_clock")

    def __init__(self, t_end: float, clock: Callable[[], float] = time.monotonic):
        self._t_end = t_end
        self._clock = clock

    @classmethod
    def after(cls, seconds: float,
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(clock() + seconds, clock)

    @classmethod
    def from_wire(cls, budget_ns: Optional[int],
                  clock: Callable[[], float] = time.monotonic
                  ) -> Optional["Deadline"]:
        """None passes through: requests without a deadline stay unbounded."""
        if budget_ns is None:
            return None
        return cls(clock() + budget_ns / _NS, clock)

    def to_wire(self) -> int:
        """Remaining budget in ns (>= 0) to ride a request frame."""
        return max(0, int(self.remaining() * _NS))

    def remaining(self) -> float:
        return self._t_end - self._clock()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, what: str = "operation") -> None:
        """Raise if the budget is spent. The raised error is tagged
        `pre_io=True`: a check() fires BEFORE work starts (lock waits,
        queueing, backoff), so breakers must not blame the endpoint for
        it — deadline expiry DURING I/O surfaces as a socket timeout or
        a server-relayed deadline frame instead."""
        rem = self.remaining()
        if rem <= 0:
            e = DeadlineExceeded(f"{what}: deadline exceeded "
                                 f"({-rem * 1e3:.1f}ms past)")
            e.pre_io = True
            raise e

    def min_timeout(self, timeout_s: float) -> float:
        """Socket timeout capped by the remaining budget (never <= 0 —
        callers check() first, so a tiny positive floor only bounds the
        final read instead of disabling timeouts)."""
        return max(1e-3, min(timeout_s, self.remaining()))


# ----------------------------------------------------------------- retrier


@dataclasses.dataclass(frozen=True)
class RetryOptions:
    """x/retry options.go equivalent. Defaults here are an order of
    magnitude tighter than the reference's (see DIVERGENCES.md): this
    stack's RPCs are LAN-or-localhost with sub-ms service times, and the
    chaos suite needs trip/recovery cycles to fit in test wall-time."""

    initial_backoff_s: float = 0.05
    backoff_factor: float = 2.0
    max_backoff_s: float = 2.0
    max_attempts: int = 3          # total tries, not extra retries
    max_duration_s: float = 0.0    # 0 = unbounded (bounded by attempts)
    jitter: bool = True
    forever: bool = False          # retry until deadline/duration instead
    seed: Optional[int] = None     # deterministic jitter for tests


class Retrier:
    """Run an operation with classified retries and backoff
    (x/retry retrier.go Attempt/AttemptWhile).

    `is_retryable` overrides the default classification; `on_retry` fires
    before every sleep with (attempt_number, delay_s, exception) — the
    instrumentation hook the reference exposes as retry metrics scope."""

    def __init__(self, opts: RetryOptions = RetryOptions(),
                 is_retryable: Optional[Callable[[BaseException], bool]] = None,
                 on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        self.opts = opts
        self._is_retryable = is_retryable or default_is_retryable
        self._on_retry = on_retry
        self._sleep = sleep
        self._clock = clock
        self._rng = random.Random(opts.seed) if opts.seed is not None else random
        self.attempts = 0   # lifetime attempt counter (instrumentation)
        self.retries = 0    # lifetime retry (re-attempt) counter

    def backoff_for(self, attempt: int) -> float:
        """Delay before re-attempt number `attempt` (1-based: the delay
        after the first failure is backoff_for(1)). x/retry retry.go
        BackoffNanos: base = initial * factor^(attempt-1) capped at max;
        with jitter the delay is uniform in [base/2, base] (half fixed,
        half random — the reference's jitter shape)."""
        o = self.opts
        # iterate instead of `factor ** (attempt-1)`: unbounded attempt
        # counters (per-message send attempts, watch reconnect failures)
        # would overflow float's 2**1024 ceiling long before the cap —
        # grow until the cap bites, never exponentiate blind
        base = min(o.initial_backoff_s, o.max_backoff_s)
        for _ in range(max(0, attempt - 1)):
            nxt = min(base * o.backoff_factor, o.max_backoff_s)
            if nxt <= base:
                break  # cap reached (or non-growing factor): stop early
            base = nxt
        if o.jitter and base > 0:
            half = base / 2.0
            return half + self._rng.uniform(0, half)
        return base

    def schedule(self, n: int) -> List[float]:
        """First n backoff delays (deterministic when seeded) — what the
        chaos suite asserts bounded-latency against."""
        return [self.backoff_for(i) for i in range(1, n + 1)]

    def attempt(self, fn: Callable, *args,
                deadline: Optional[Deadline] = None, **kwargs):
        """Call fn until it succeeds, the classification says stop, the
        attempt/duration budget is spent, or the deadline expires."""
        o = self.opts
        started = self._clock()
        attempt = 0
        while True:
            if deadline is not None:
                deadline.check("retry")
            attempt += 1
            self.attempts += 1
            try:
                return fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — classified below
                if not self._is_retryable(e):
                    raise
                out_of_attempts = (not o.forever
                                   and attempt >= max(1, o.max_attempts))
                delay = self.backoff_for(attempt)
                elapsed = self._clock() - started
                out_of_time = (o.max_duration_s > 0
                               and elapsed + delay > o.max_duration_s)
                dead = (deadline is not None
                        and deadline.remaining() <= delay)
                if out_of_attempts or out_of_time or dead:
                    if dead:
                        raise DeadlineExceeded(
                            f"retry: next backoff ({delay * 1e3:.0f}ms) "
                            "exceeds remaining deadline") from e
                    # x/retry parity: the caller gets the LAST error with
                    # its own type (quorum fanout, health checks and tests
                    # all classify on concrete exception types).
                    raise
                self.retries += 1
                if self._on_retry is not None:
                    self._on_retry(attempt, delay, e)
                self._sleep(delay)


# ----------------------------------------------------------------- breaker


@dataclasses.dataclass(frozen=True)
class BreakerOptions:
    """Failure-rate trip over a sliding window of outcomes, cooldown to
    half-open, bounded concurrent probes, successes required to close."""

    window: int = 16               # outcomes remembered
    failure_ratio: float = 0.5     # trip when failures/window >= ratio...
    min_samples: int = 4           # ...and at least this many outcomes seen
    cooldown_s: float = 0.5        # open -> half-open
    half_open_probes: int = 1      # concurrent probes allowed half-open
    success_to_close: int = 1      # half-open successes that close it


class Breaker:
    """closed / open / half-open circuit breaker. Thread-safe; every
    state transition is appended to `.transitions` (old, new, monotonic
    time) so tests and instrumentation can assert the lifecycle."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, opts: BreakerOptions = BreakerOptions(),
                 clock: Callable[[], float] = time.monotonic,
                 name: str = ""):
        self.opts = opts
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._outcomes: Deque[bool] = deque(maxlen=max(1, opts.window))
        self._opened_at = 0.0
        self._probes_inflight = 0
        self._half_open_successes = 0
        self.transitions: List[Tuple[str, str, float]] = []

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _transition_locked(self, new: str):
        if new != self._state:
            self.transitions.append((self._state, new, self._clock()))
            self._state = new

    def _maybe_half_open_locked(self):
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.opts.cooldown_s):
            self._transition_locked(self.HALF_OPEN)
            self._probes_inflight = 0
            self._half_open_successes = 0

    def allow(self) -> bool:
        """May a request proceed right now? Half-open admits at most
        `half_open_probes` in-flight probes; callers that got True MUST
        report record_success/record_failure or the probe slot leaks."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                return False
            if self._probes_inflight >= self.opts.half_open_probes:
                return False
            self._probes_inflight += 1
            return True

    def record_success(self):
        with self._lock:
            self._outcomes.append(True)
            if self._state == self.HALF_OPEN:
                self._probes_inflight = max(0, self._probes_inflight - 1)
                self._half_open_successes += 1
                if self._half_open_successes >= self.opts.success_to_close:
                    self._transition_locked(self.CLOSED)
                    self._outcomes.clear()

    def cancel(self):
        """Release an allow() grant WITHOUT recording an outcome: the
        operation was abandoned before any I/O touched the endpoint
        (client-side deadline expiry, local queueing). Required so a
        granted half-open probe slot cannot leak — an unreleased slot
        wedges the breaker half-open forever."""
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._probes_inflight = max(0, self._probes_inflight - 1)

    def record_failure(self):
        with self._lock:
            self._outcomes.append(False)
            if self._state == self.HALF_OPEN:
                # a failed probe re-opens immediately (probe recovery)
                self._probes_inflight = max(0, self._probes_inflight - 1)
                self._transition_locked(self.OPEN)
                self._opened_at = self._clock()
                return
            if self._state != self.CLOSED:
                return
            n = len(self._outcomes)
            fails = sum(1 for ok in self._outcomes if not ok)
            if (n >= self.opts.min_samples
                    and fails / n >= self.opts.failure_ratio):
                self._transition_locked(self.OPEN)
                self._opened_at = self._clock()

    def call(self, fn: Callable, *args, **kwargs):
        """Guarded call: BreakerOpen without I/O when open, outcome
        recorded otherwise. DeadlineExceeded counts as a failure (the
        endpoint burned the whole budget); server-relayed application
        errors should be recorded as success by callers that can tell —
        this convenience wrapper treats any exception as failure."""
        if not self.allow():
            raise BreakerOpen(
                f"breaker {self.name or id(self):} open: endpoint shed")
        try:
            result = fn(*args, **kwargs)
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return result


# ------------------------------------------------------------- host health


class HostHealth:
    """Per-endpoint breaker + outcome counters shared by a client's host
    pool (connection_pool.go health check + go-hostpool shape). One
    HostHealth serves a whole Session/Producer; breakers are created
    lazily per endpoint and share options/clock."""

    def __init__(self, opts: BreakerOptions = BreakerOptions(),
                 clock: Callable[[], float] = time.monotonic):
        self.opts = opts
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: Dict[str, Breaker] = {}
        self._stats: Dict[str, Dict[str, int]] = {}

    def breaker(self, endpoint: str) -> Breaker:
        with self._lock:
            b = self._breakers.get(endpoint)
            if b is None:
                b = Breaker(self.opts, clock=self._clock, name=endpoint)
                self._breakers[endpoint] = b
                self._stats[endpoint] = {"success": 0, "failure": 0}
            return b

    def count(self, endpoint: str, ok: bool):
        """Outcome counter only — for callers that drive the (shared)
        breaker themselves, like HostClient."""
        self.breaker(endpoint)  # ensure registered
        with self._lock:
            self._stats[endpoint]["success" if ok else "failure"] += 1

    def record(self, endpoint: str, ok: bool):
        b = self.breaker(endpoint)
        self.count(endpoint, ok)
        if ok:
            b.record_success()
        else:
            b.record_failure()

    def healthy(self, endpoint: str) -> bool:
        return self.breaker(endpoint).state != Breaker.OPEN

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {
                ep: {"state": self._breakers[ep].state, **self._stats[ep]}
                for ep in self._breakers
            }
