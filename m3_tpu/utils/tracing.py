"""Tracing + profiling (reference: the x/instrument + net/http/pprof
surface the reference exposes on every service — opentracing spans via
instrument.Options tracing, goroutine/profile dumps on /debug/pprof).

Spans: context-manager tree with wall-clock timings, thread-local current
span, trace/span ids, and a ring buffer of recent finished roots for
/debug/traces. Cross-process propagation rides request frames as a
compact `"tr"` context (rpc/wire.py TRACE_KEY) exactly like the deadline
`"d"` and priority `"pri"` hints; the server side opens a remote-parented
span and, on success, returns its finished tree in the response frame so
the CLIENT grafts it as a child — one request yields ONE span tree even
when its storage work ran three processes away (the in-process analog of
jaeger's collector assembling spans by trace id; DIVERGENCES.md).

Sampling: root spans are sampled at `M3_TPU_TRACE_SAMPLE` (default 1.0);
an unsampled root is the shared no-op span, children of no span are
no-ops too (`child_span`), and unsampled requests never attach a wire
context — so the hot path's cost when tracing is off is one thread-local
read (proven <3% on the write/index benches by
scripts/obs_overhead_guard.py even with tracing ON).

Slow queries: a bounded ring of {name, duration, typed reason, costs}
entries (`SLOW_QUERIES`) — reasons are `limit-shed` (ResourceExhausted),
`deadline` (DeadlineExceeded), `cold-cache` (the span's cost tags show
block/grid-cache misses), or plain `slow` past the threshold
(`M3_TPU_SLOW_QUERY_MS`, default 500).

Profiling: a sampling profiler (the statistical CPU profile analog of
/debug/pprof/profile) that samples every thread's Python stack at a fixed
interval and aggregates flattened stack counts, plus an all-threads stack
dump (the goroutine-dump analog of /debug/pprof/goroutine?debug=2).
`PROFILER` runs the sampling loop on ONE shared background thread with a
hard seconds cap (`M3_TPU_PROFILE_MAX_S`) so a /debug/pprof/profile
request can neither stall a serving thread past its deadline nor stack N
concurrent sampling loops."""

from __future__ import annotations

import collections
import contextlib
import os
import random as _random
import sys
import threading
import time
import traceback
from typing import Dict, List, NamedTuple, Optional

# ---------------------------------------------------------------- spans


class SpanContext(NamedTuple):
    """Wire-portable span identity. Only SAMPLED spans ever produce one
    (context presence implies sampled), so the two ids are the whole
    context — the compact `"tr"` frame field."""

    trace_id: int
    span_id: int

    def to_wire(self) -> dict:
        return {"t": self.trace_id, "s": self.span_id}

    @classmethod
    def from_wire(cls, d) -> Optional["SpanContext"]:
        """Parse a frame's trace field; malformed metadata is treated as
        absent — tracing must never be the thing that kills an
        otherwise-valid request (same contract as deadline_from_frame)."""
        if not isinstance(d, dict):
            return None
        t, s = d.get("t"), d.get("s")
        if isinstance(t, bool) or isinstance(s, bool) or \
                not isinstance(t, int) or not isinstance(s, int):
            return None
        return cls(t, s)


_ID_LOCK = threading.Lock()
_ID_RNG = _random.Random()


def _new_id() -> int:
    with _ID_LOCK:
        return _ID_RNG.getrandbits(63) or 1


class Span:
    __slots__ = ("name", "tags", "start_ns", "end_ns", "children", "costs",
                 "trace_id", "span_id", "remote_parent", "_tracer", "_parent")

    sampled = True  # real spans exist only when sampled

    def __init__(self, name: str, tracer: "Tracer", parent: Optional["Span"],
                 tags: Optional[dict] = None,
                 remote: Optional[SpanContext] = None):
        self.name = name
        self.tags = dict(tags or {})
        self.start_ns = time.perf_counter_ns()
        self.end_ns: Optional[int] = None
        self.children: List = []  # Span or grafted remote dicts
        self.costs: Dict[str, float] = {}
        if parent is not None:
            self.trace_id = parent.trace_id
        elif remote is not None:
            self.trace_id = remote.trace_id
        else:
            self.trace_id = _new_id()
        self.span_id = _new_id()
        self.remote_parent = remote.span_id if remote is not None else None
        self._tracer = tracer
        self._parent = parent

    @property
    def duration_ns(self) -> int:
        return (self.end_ns or time.perf_counter_ns()) - self.start_ns

    def set_tag(self, key: str, value) -> "Span":
        self.tags[key] = value
        return self

    def add_cost(self, kind: str, n: float = 1) -> "Span":
        """Accumulate one QueryScope-style cost tally onto this span
        (docs_matched / bytes_read / block_cache_hit / ...)."""
        self.costs[kind] = self.costs.get(kind, 0) + n
        return self

    def attach(self, child: dict):
        """Graft a REMOTE span tree (a finished to_dict from another
        process, returned in a response frame) as a child. list.append is
        GIL-atomic, so fanout worker threads may attach concurrently."""
        self.children.append(child)

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def __enter__(self) -> "Span":
        self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end_ns = time.perf_counter_ns()
        if exc_type is not None:
            self.tags["error"] = repr(exc)
        self._tracer._pop(self)
        return False

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "duration_us": round(self.duration_ns / 1000, 1),
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            **({"remote_parent": self.remote_parent}
               if self.remote_parent is not None else {}),
            **({"tags": self.tags} if self.tags else {}),
            **({"costs": self.costs} if self.costs else {}),
            **({"children": [c if isinstance(c, dict) else c.to_dict()
                             for c in self.children]}
               if self.children else {}),
        }


class _NoopSpan:
    """Shared do-nothing span for unsampled work: every mutator is a
    no-op, so hot paths hold one object test instead of branches."""

    __slots__ = ()
    sampled = False
    name = ""
    tags: dict = {}
    costs: dict = {}
    children: tuple = ()
    trace_id = 0
    span_id = 0

    def set_tag(self, key, value):
        return self

    def add_cost(self, kind, n=1):
        return self

    def attach(self, child):
        pass

    def context(self) -> Optional[SpanContext]:
        return None

    @property
    def duration_ns(self) -> int:
        return 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def to_dict(self) -> dict:
        return {"name": "", "noop": True}


NOOP_SPAN = _NoopSpan()


def _env_rate() -> float:
    try:
        return min(1.0, max(0.0, float(
            os.environ.get("M3_TPU_TRACE_SAMPLE", "1"))))
    except ValueError:
        return 1.0


class Tracer:
    """Per-process tracer; thread-local span stacks, bounded root history,
    head-based root sampling."""

    def __init__(self, max_traces: int = 128,
                 sample_rate: Optional[float] = None):
        self._local = threading.local()
        self._lock = threading.Lock()
        self._recent = collections.deque(maxlen=max_traces)
        self.sample_rate = _env_rate() if sample_rate is None else sample_rate

    def set_sample_rate(self, rate: float):
        self.sample_rate = min(1.0, max(0.0, float(rate)))

    def span(self, name: str, **tags):
        """New span: child of the current span when one is active, else a
        sampling-gated new root. Entry points (query execute, session
        calls, rpc dispatch) use this; internals use child_span."""
        parent = getattr(self._local, "current", None)
        if parent is None:
            rate = self.sample_rate
            if rate <= 0.0 or (rate < 1.0 and _random.random() >= rate):
                return NOOP_SPAN
            return Span(name, self, None, tags)
        return Span(name, self, parent, tags)

    def child_span(self, name: str, **tags):
        """A span ONLY when sampled work is already in flight — the
        hot-path-safe form for storage/index internals: with no active
        span (benchmarks, bare calls) the cost is one thread-local read."""
        parent = getattr(self._local, "current", None)
        if parent is None:
            return NOOP_SPAN
        return Span(name, self, parent, tags)

    def span_from(self, ctx: Optional[SpanContext], name: str, **tags):
        """Remote-parented root for a propagated wire context (rpc
        dispatch, msg consume, kv ops); NOOP when the request carried no
        context (the caller was unsampled or untraced)."""
        if ctx is None:
            return NOOP_SPAN
        return Span(name, self, None, tags, remote=ctx)

    def current(self) -> Optional[Span]:
        return getattr(self._local, "current", None)

    @contextlib.contextmanager
    def activate(self, span):
        """Install `span` as this THREAD's current span (restoring the
        previous on exit) without opening a new one — explicit
        propagation into pool workers, where thread-local stacks don't
        follow the submitting thread."""
        prev = getattr(self._local, "current", None)
        self._local.current = span if isinstance(span, Span) else None
        try:
            yield span
        finally:
            self._local.current = prev

    def _push(self, span: Span):
        if span._parent is not None:
            span._parent.children.append(span)
        self._local.current = span

    def _pop(self, span: Span):
        self._local.current = span._parent
        if span._parent is None:
            with self._lock:
                self._recent.append(span)

    def recent_traces(self, trace_id: Optional[int] = None) -> List[dict]:
        with self._lock:
            roots = list(self._recent)
        out = [s.to_dict() for s in roots]
        if trace_id is not None:
            out = [d for d in out if d.get("trace_id") == trace_id]
        return out


TRACER = Tracer()  # process default, like the global opentracing tracer


def span(name: str, **tags):
    return TRACER.span(name, **tags)


def child_span(name: str, **tags):
    return TRACER.child_span(name, **tags)


def count_cost(kind: str, n: float = 1):
    """Tally a cost/cache event onto the active span, if any — the
    charge-site hook block/grid caches and QueryScope exits use. One
    thread-local read when no span is active."""
    cur = getattr(TRACER._local, "current", None)
    if cur is not None:
        cur.add_cost(kind, n)


def collect_costs(span) -> Dict[str, float]:
    """Sum cost tallies over a whole span SUBTREE (local Span children
    and grafted remote dicts alike). Cache events accrue on the
    innermost span that saw them — storage.read's child, or a remote
    dbnode span grafted from the response frame — so a root-level
    consumer (the slow-query log's cold-cache classification) must roll
    the subtree up, not read the root's own costs."""
    out: Dict[str, float] = {}

    def walk(node):
        costs = node.get("costs") if isinstance(node, dict) else node.costs
        if costs:
            for k, v in costs.items():
                out[k] = out.get(k, 0) + v
        kids = (node.get("children") or ()) if isinstance(node, dict) \
            else node.children
        for c in kids:
            walk(c)

    walk(span)
    return out


# ---------------------------------------------------------- slow queries


class SlowQueryLog:
    """Bounded ring of slow/shed query records with typed reasons and
    per-query cost attribution (the dbnode slow-query-log analog).

    `limit-shed` and `deadline` entries record regardless of duration —
    they ARE the interesting events; threshold gating applies only to
    completed work ("slow" / "cold-cache")."""

    REASONS = ("limit-shed", "deadline", "cold-cache", "slow")
    _COLD_KEYS = ("block_cache_miss", "grid_cache_miss")

    def __init__(self, threshold_ms: Optional[float] = None,
                 maxlen: int = 128):
        if threshold_ms is None:
            try:
                threshold_ms = float(
                    os.environ.get("M3_TPU_SLOW_QUERY_MS", "500"))
            except ValueError:
                threshold_ms = 500.0
        self.threshold_ns = int(threshold_ms * 1e6)
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=maxlen)

    def record(self, kind: str, name: str, duration_ns: int, reason: str,
               costs: Optional[dict] = None, trace_id: Optional[int] = None,
               route: Optional[dict] = None):
        entry = {
            "kind": kind,
            "name": name,
            "duration_ms": round(duration_ns / 1e6, 3),
            "reason": reason,
            "costs": dict(costs) if costs else {},
        }
        if trace_id:
            entry["trace_id"] = trace_id
        if route:
            # The executor's route record: a slow INTERPRETED query's
            # entry says WHY it missed the compiled path (typed
            # plan.FallbackReason value), not just that it was slow.
            entry["route"] = route.get("route")
            if route.get("fallback_reason"):
                entry["plan_fallback"] = route["fallback_reason"]
        with self._lock:
            self._ring.append(entry)

    def maybe(self, kind: str, name: str, duration_ns: int,
              costs=None, trace_id: Optional[int] = None,
              reason: Optional[str] = None, route: Optional[dict] = None):
        """Record when `reason` is a typed failure (always) or the
        duration crosses the threshold (reason inferred: cold-cache when
        the costs show cache misses, else slow). `costs` may be a dict
        or a zero-arg callable — callables are only evaluated once the
        entry WILL record, so hot fast queries never pay a subtree
        cost rollup."""
        if reason is None and duration_ns < self.threshold_ns:
            return
        if callable(costs):
            costs = costs()
        if reason is None:
            reason = "cold-cache" if costs and any(
                costs.get(k) for k in self._COLD_KEYS) else "slow"
        self.record(kind, name, duration_ns, reason, costs, trace_id,
                    route=route)

    def entries(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


SLOW_QUERIES = SlowQueryLog()


# ---------------------------------------------------------------- profiling


def thread_stacks() -> str:
    """All-threads stack dump (goroutine-dump analog)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(l.rstrip("\n") for l in traceback.format_stack(frame))
    return "\n".join(out)


def profile(seconds: float = 1.0, hz: int = 100,
            top: int = 40) -> List[dict]:
    """Statistical CPU profile: sample every thread's stack at `hz` for
    `seconds`, aggregate by flattened stack. Returns the hottest stacks
    with sample counts (the /debug/pprof/profile analog; sampling has the
    same bias/overhead profile as pprof's SIGPROF sampling). BLOCKS the
    calling thread for the window — serving endpoints go through
    `PROFILER.run` instead, which runs this on one shared capped
    background thread."""
    counts: Dict[tuple, int] = collections.Counter()
    me = threading.get_ident()
    interval = 1.0 / hz
    deadline = time.perf_counter() + seconds
    total = 0
    while time.perf_counter() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
                f = f.f_back
            counts[tuple(reversed(stack))] += 1
            total += 1
        time.sleep(interval)
    out = []
    for stack, n in sorted(counts.items(), key=lambda kv: -kv[1])[:top]:
        out.append({"samples": n,
                    "fraction": round(n / max(total, 1), 4),
                    "stack": list(stack)})
    return out


class _ProfileJob:
    __slots__ = ("seconds", "hz", "top", "done", "result")

    def __init__(self, seconds: float, hz: int, top: int):
        self.seconds = seconds
        self.hz = hz
        self.top = top
        self.done = threading.Event()
        self.result: Optional[List[dict]] = None


class ProfileRunner:
    """Background-thread profile driver for the /debug/pprof/profile
    endpoint: the sampling loop runs on ONE daemon thread with a hard
    per-request seconds cap (`M3_TPU_PROFILE_MAX_S`, default 5), and
    concurrent requests SHARE the in-flight window instead of stacking N
    sys._current_frames() loops. The serving thread waits on the result
    with a bounded timeout, so a profile request can never stall it past
    the cap (the pre-fix tracing.profile() blocked for an arbitrary
    caller-chosen window)."""

    def __init__(self, max_seconds: Optional[float] = None):
        if max_seconds is None:
            try:
                max_seconds = float(
                    os.environ.get("M3_TPU_PROFILE_MAX_S", "5"))
            except ValueError:
                max_seconds = 5.0
        self.max_seconds = max(0.05, max_seconds)
        self._lock = threading.Lock()
        self._job: Optional[_ProfileJob] = None
        self.shared = 0  # requests that joined an in-flight window

    def _run_job(self, job: _ProfileJob):
        try:
            job.result = profile(job.seconds, job.hz, job.top)
        except Exception:  # noqa: BLE001 — a failed sample pass must
            job.result = []    # never wedge waiters past their timeout
        finally:
            job.done.set()

    def run(self, seconds: float = 1.0, hz: int = 100,
            top: int = 40) -> List[dict]:
        seconds = min(max(float(seconds), 0.05), self.max_seconds)
        with self._lock:
            job = self._job
            if job is None or job.done.is_set():
                job = self._job = _ProfileJob(seconds, hz, top)
                threading.Thread(target=self._run_job, args=(job,),
                                 name="profile-runner", daemon=True).start()
            else:
                self.shared += 1
        # Bounded wait: cap + slack. A hung sampler yields an empty
        # profile, not a hung serving thread.
        job.done.wait(timeout=self.max_seconds + 2.0)
        return job.result if job.result is not None else []


PROFILER = ProfileRunner()


# ------------------------------------------------- debug endpoint payloads
#
# ONE definition of the /debug response shapes: the coordinator HTTP API
# and the dbnode httpjson server both serve these, and two hand-rolled
# copies would drift (params, keys) the first time either grows a field.


def debug_traces_payload(trace_id: Optional[int] = None) -> dict:
    """/debug/traces body: recent span trees (optionally one trace) +
    the slow-query ring."""
    return {"traces": TRACER.recent_traces(trace_id=trace_id),
            "slow": SLOW_QUERIES.entries()}


def debug_profile_payload(seconds: float) -> dict:
    """/debug/pprof/profile body: the shared capped background sampler's
    hottest stacks, plus the cap actually applied to the request."""
    return {"profile": PROFILER.run(seconds=seconds),
            "capped_seconds": min(max(float(seconds), 0.05),
                                  PROFILER.max_seconds)}
