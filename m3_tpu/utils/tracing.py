"""Tracing + profiling (reference: the x/instrument + net/http/pprof
surface the reference exposes on every service — opentracing spans via
instrument.Options tracing, goroutine/profile dumps on /debug/pprof).

Spans: context-manager tree with wall-clock timings, thread-local current
span, and a ring buffer of recent finished roots for /debug/traces.

Profiling: a sampling profiler (the statistical CPU profile analog of
/debug/pprof/profile) that samples every thread's Python stack at a fixed
interval and aggregates flattened stack counts, plus an all-threads stack
dump (the goroutine-dump analog of /debug/pprof/goroutine?debug=2)."""

from __future__ import annotations

import collections
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional

# ---------------------------------------------------------------- spans


class Span:
    __slots__ = ("name", "tags", "start_ns", "end_ns", "children", "_tracer",
                 "_parent")

    def __init__(self, name: str, tracer: "Tracer", parent: Optional["Span"],
                 tags: Optional[dict] = None):
        self.name = name
        self.tags = dict(tags or {})
        self.start_ns = time.perf_counter_ns()
        self.end_ns: Optional[int] = None
        self.children: List[Span] = []
        self._tracer = tracer
        self._parent = parent

    @property
    def duration_ns(self) -> int:
        return (self.end_ns or time.perf_counter_ns()) - self.start_ns

    def set_tag(self, key: str, value) -> "Span":
        self.tags[key] = value
        return self

    def __enter__(self) -> "Span":
        self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end_ns = time.perf_counter_ns()
        if exc_type is not None:
            self.tags["error"] = repr(exc)
        self._tracer._pop(self)
        return False

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "duration_us": round(self.duration_ns / 1000, 1),
            **({"tags": self.tags} if self.tags else {}),
            **({"children": [c.to_dict() for c in self.children]}
               if self.children else {}),
        }


class Tracer:
    """Per-process tracer; thread-local span stacks, bounded root history."""

    def __init__(self, max_traces: int = 128):
        self._local = threading.local()
        self._lock = threading.Lock()
        self._recent = collections.deque(maxlen=max_traces)

    def span(self, name: str, **tags) -> Span:
        parent = getattr(self._local, "current", None)
        return Span(name, self, parent, tags)

    def current(self) -> Optional[Span]:
        return getattr(self._local, "current", None)

    def _push(self, span: Span):
        if span._parent is not None:
            span._parent.children.append(span)
        self._local.current = span

    def _pop(self, span: Span):
        self._local.current = span._parent
        if span._parent is None:
            with self._lock:
                self._recent.append(span)

    def recent_traces(self) -> List[dict]:
        with self._lock:
            return [s.to_dict() for s in self._recent]


TRACER = Tracer()  # process default, like the global opentracing tracer


def span(name: str, **tags) -> Span:
    return TRACER.span(name, **tags)


# ---------------------------------------------------------------- profiling


def thread_stacks() -> str:
    """All-threads stack dump (goroutine-dump analog)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(l.rstrip("\n") for l in traceback.format_stack(frame))
    return "\n".join(out)


def profile(seconds: float = 1.0, hz: int = 100,
            top: int = 40) -> List[dict]:
    """Statistical CPU profile: sample every thread's stack at `hz` for
    `seconds`, aggregate by flattened stack. Returns the hottest stacks
    with sample counts (the /debug/pprof/profile analog; sampling has the
    same bias/overhead profile as pprof's SIGPROF sampling)."""
    counts: Dict[tuple, int] = collections.Counter()
    me = threading.get_ident()
    interval = 1.0 / hz
    deadline = time.perf_counter() + seconds
    total = 0
    while time.perf_counter() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
                f = f.f_back
            counts[tuple(reversed(stack))] += 1
            total += 1
        time.sleep(interval)
    out = []
    for stack, n in sorted(counts.items(), key=lambda kv: -kv[1])[:top]:
        out.append({"samples": n,
                    "fraction": round(n / max(total, 1), 4),
                    "stack": list(stack)})
    return out
