"""Overload health: bounded-work admission gates with priority shedding
and the process degradation state machine (reference: the coordinator's
m3msg ingest worker pools + dbnode queue watermarks; shedding discipline
per "The Tail at Scale" and DAGOR-style priority admission — drop the
cheapest traffic first, never the traffic that keeps the cluster alive).

  AdmissionGate   a bounded in-flight work budget with watermarks.
                  Below the high watermark everything is admitted; from
                  the high watermark to capacity BULK traffic (backfill)
                  is shed; at capacity NORMAL traffic is shed too.
                  CRITICAL traffic (health/admin probes, replication —
                  the traffic whose loss turns an overload into an
                  outage) is ALWAYS admitted and merely counted, so the
                  depth can exceed capacity by the critical overshoot.
                  Shedding raises the typed `Backpressure` so producers
                  back off instead of retrying hot.

  HealthTracker   ok -> degraded -> shedding state machine over
                  registered saturation sources (gate depths, enforcer
                  saturation from utils.limits) with hysteresis, exported
                  through instrument gauges and the coordinator/aggregator
                  HTTP health endpoints.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .instrument import ROOT
from .limits import Backpressure

__all__ = ["Priority", "AdmissionGate", "HealthTracker", "DiskHealth",
           "TRACKER", "OK", "DEGRADED", "SHEDDING"]


class Priority(enum.IntEnum):
    """Shed order is highest value first: BULK backfill goes before
    NORMAL serving traffic; CRITICAL is never shed."""

    CRITICAL = 0   # health/admin probes, replication/bootstrap streams
    NORMAL = 1     # serving reads/writes
    BULK = 2       # backfill / batch imports


OK, DEGRADED, SHEDDING = "ok", "degraded", "shedding"
_STATE_ORDER = {OK: 0, DEGRADED: 1, SHEDDING: 2}


class AdmissionGate:
    """Bounded in-flight work counter with watermark shedding. `admit`
    raises Backpressure for shed work; every successful admit MUST be
    paired with `release` (use `held()` for scoped work)."""

    def __init__(self, capacity: int, high_watermark: float = 0.75,
                 name: str = "", tracker: Optional["HealthTracker"] = None,
                 tenant_weights: Optional[Dict[bytes, float]] = None):
        if capacity <= 0:
            raise ValueError(f"gate capacity must be positive, got {capacity}")
        self.capacity = capacity
        self.high = max(1.0, high_watermark * capacity)
        self.name = name
        self._lock = threading.Lock()
        self._depth = 0
        self._max_depth = 0
        self._metrics = ROOT.sub_scope(f"admission.{name}" if name
                                       else "admission")
        self.admitted = 0
        self.shed: Dict[str, int] = {p.name.lower(): 0 for p in Priority}
        # Per-tenant weighted fair-share (DAGOR-style), engaged only past
        # the high watermark: each tenant's in-flight depth is capped at
        # weight/(Σ active weights + one reserve share) of capacity, so
        # one noisy tenant saturates its OWN share of the gate and a
        # quiet tenant arriving mid-burst is still admitted. CRITICAL
        # is never tenant-shed. Tenants are tracked only while they hold
        # depth, so the map is bounded by concurrent tenants.
        self._tenant_weights = dict(tenant_weights or {})
        self._tenant_depth: Dict[bytes, int] = {}
        self.shed_tenant = 0
        # Named gates auto-register as health sources (same-named gates
        # overwrite, so re-created services stay bounded in the tracker);
        # anonymous gates are ephemeral (tests, scoped tools) and must
        # not accumulate dead probes in the process-global tracker.
        if name:
            (tracker if tracker is not None else TRACKER).register(
                name, self.saturation)

    def _weight(self, tenant: bytes) -> float:
        return self._tenant_weights.get(tenant, 1.0)

    def _tenant_share_locked(self, tenant: bytes) -> float:
        """Fair share of capacity for `tenant`: capacity * w_t /
        (Σ weights of tenants holding depth + w_t + one reserve share).
        The reserve keeps a lone noisy tenant capped below the whole
        gate, so a newcomer always finds room (lock held)."""
        w = self._weight(tenant)
        active = sum(self._weight(t) for t, d in self._tenant_depth.items()
                     if d > 0 and t != tenant)
        return self.capacity * w / (active + w + 1.0)

    def try_admit(self, n: int = 1, priority: Priority = Priority.NORMAL,
                  tenant: Optional[bytes] = None) -> bool:
        with self._lock:
            depth = self._depth + n
            # Semaphore convention: a single request larger than the whole
            # budget is admitted when the gate is IDLE (it runs alone) —
            # otherwise an oversized batch frame would be deterministically
            # shed forever, a permanent drop no backoff can clear.
            if priority != Priority.CRITICAL and self._depth > 0:
                if depth > self.capacity or \
                        (priority == Priority.BULK and depth > self.high):
                    self.shed[priority.name.lower()] += n
                    self._metrics.counter(
                        f"shed.{priority.name.lower()}").inc(n)
                    return False
                # Past the high watermark the gate is contended: cap each
                # tenant at its weighted fair share of capacity, so one
                # noisy tenant saturates its own share, never the gate.
                if tenant is not None and depth > self.high:
                    td = self._tenant_depth.get(tenant, 0)
                    if td + n > self._tenant_share_locked(tenant):
                        self.shed[priority.name.lower()] += n
                        self.shed_tenant += n
                        self._metrics.counter("shed.tenant").inc(n)
                        return False
            self._depth = depth
            self._max_depth = max(self._max_depth, depth)
            self.admitted += n
            if tenant is not None:
                self._tenant_depth[tenant] = \
                    self._tenant_depth.get(tenant, 0) + n
            return True

    def admit(self, n: int = 1, priority: Priority = Priority.NORMAL,
              tenant: Optional[bytes] = None):
        if not self.try_admit(n, priority, tenant=tenant):
            raise Backpressure(
                f"{self.name or 'admission'}: {priority.name.lower()} work "
                f"shed at depth {self._depth}/{self.capacity} "
                f"(high watermark {self.high:g}"
                + (f", tenant {tenant!r}" if tenant is not None
                   else "") + ")")

    def release(self, n: int = 1, tenant: Optional[bytes] = None):
        with self._lock:
            self._depth = max(0, self._depth - n)
            if tenant is not None:
                td = self._tenant_depth.get(tenant, 0) - n
                if td > 0:
                    self._tenant_depth[tenant] = td
                else:
                    self._tenant_depth.pop(tenant, None)
            self._metrics.gauge("depth").update(self._depth)

    def tenant_depth(self, tenant: bytes) -> int:
        with self._lock:
            return self._tenant_depth.get(tenant, 0)

    def held(self, n: int = 1, priority: Priority = Priority.NORMAL,
             tenant: Optional[bytes] = None):
        """Context manager: admit on enter (raising Backpressure when
        shed), release on every exit path."""
        return _Held(self, n, priority, tenant)

    def depth(self) -> int:
        with self._lock:
            return self._depth

    def max_depth(self) -> int:
        """High-water mark of in-flight depth (memory-bound assertions)."""
        with self._lock:
            return self._max_depth

    def saturation(self) -> float:
        with self._lock:
            return min(1.0, self._depth / self.capacity)

    def stats(self) -> dict:
        with self._lock:
            return {"depth": self._depth, "max_depth": self._max_depth,
                    "capacity": self.capacity, "high": self.high,
                    "admitted": self.admitted, "shed": dict(self.shed),
                    "shed_tenant": self.shed_tenant,
                    "tenants": dict(self._tenant_depth)}


class _Held:
    __slots__ = ("_gate", "_n", "_priority", "_tenant")

    def __init__(self, gate: AdmissionGate, n: int, priority: Priority,
                 tenant: Optional[bytes] = None):
        self._gate = gate
        self._n = n
        self._priority = priority
        self._tenant = tenant

    def __enter__(self):
        self._gate.admit(self._n, self._priority, tenant=self._tenant)
        return self._gate

    def __exit__(self, *exc):
        self._gate.release(self._n, tenant=self._tenant)
        return False


class DiskHealth:
    """Consecutive-failure breaker over durable-write health (the disk
    leg of the degradation story; the reference's fs bootstrapping +
    commitlog failure policies fold into its health reporting the same
    way). WAL append/fsync and fileset-flush failures call `failure()`;
    any durable-write success clears the streak.

    After `trip_after` CONSECUTIVE failures the node takes a READ-ONLY
    posture: `read_only()` is True — the write path sheds NORMAL/BULK
    writes with typed Backpressure while CRITICAL traffic and reads keep
    flowing — and `saturation()` reads 1.0 so a registered HealthTracker
    degrades the exported state. Recovery is automatic: the first
    successful durable write (flush retries keep probing via Retrier
    backoff) resets the streak and lifts the posture."""

    def __init__(self, trip_after: int = 3, name: str = "",
                 tracker: Optional["HealthTracker"] = None):
        if trip_after <= 0:
            raise ValueError(f"trip_after must be positive, got {trip_after}")
        self.trip_after = trip_after
        self._lock = threading.Lock()
        self._consecutive = 0
        self.failures = 0
        self.trips = 0
        if name:
            (tracker if tracker is not None else TRACKER).register(
                name, self.saturation)

    def failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            self.failures += 1
            tripped = self._consecutive == self.trip_after
        if tripped:
            self.trips += 1
            ROOT.sub_scope("health").counter("disk_read_only_trips").inc()

    def success(self) -> None:
        if self._consecutive == 0:
            return  # hot-path fast out: nothing to clear, skip the lock
        with self._lock:
            self._consecutive = 0

    def read_only(self) -> bool:
        with self._lock:
            return self._consecutive >= self.trip_after

    def saturation(self) -> float:
        with self._lock:
            return min(1.0, self._consecutive / self.trip_after)


class HealthTracker:
    """Degradation state machine over saturation sources in [0, 1].

    State is the max source saturation mapped through thresholds, with
    hysteresis: entering a worse state is immediate (overload must be
    visible NOW), leaving one requires dropping `recover_margin` below
    the threshold (so a gate oscillating at the boundary doesn't flap
    the exported state every sample)."""

    def __init__(self, degraded_at: float = 0.7, shedding_at: float = 0.95,
                 recover_margin: float = 0.1,
                 clock: Callable[[], float] = time.monotonic):
        self.degraded_at = degraded_at
        self.shedding_at = shedding_at
        self.recover_margin = recover_margin
        self._clock = clock
        self._lock = threading.Lock()
        self._sources: Dict[str, Callable[[], float]] = {}
        self._state = OK
        self.transitions: List[Tuple[str, str, float]] = []

    def register(self, name: str, fn: Callable[[], float]):
        with self._lock:
            self._sources[name] = fn

    def unregister(self, name: str):
        with self._lock:
            self._sources.pop(name, None)

    def _sample(self) -> Dict[str, float]:
        with self._lock:
            sources = dict(self._sources)
        out = {}
        for name, fn in sources.items():
            try:
                out[name] = max(0.0, min(1.0, float(fn())))
            except Exception:  # noqa: BLE001 — a dead probe reads saturated
                # A source that cannot answer is treated as fully
                # saturated: health must fail toward caution, not toward
                # green.
                out[name] = 1.0
        return out

    def _target_state(self, sat: float, current: str) -> str:
        # entering worse states: plain thresholds; leaving: margin below
        if sat >= self.shedding_at:
            return SHEDDING
        if current == SHEDDING and sat >= self.shedding_at - self.recover_margin:
            return SHEDDING
        if sat >= self.degraded_at:
            return DEGRADED
        if current in (DEGRADED, SHEDDING) and \
                sat >= self.degraded_at - self.recover_margin:
            return DEGRADED
        return OK

    def evaluate(self, sample: Optional[Dict[str, float]] = None) -> str:
        if sample is None:
            sample = self._sample()
        sat = max(sample.values()) if sample else 0.0
        with self._lock:
            new = self._target_state(sat, self._state)
            if new != self._state:
                self.transitions.append((self._state, new, self._clock()))
                self._state = new
            state = self._state
        scope = ROOT.sub_scope("health")
        scope.gauge("state").update(_STATE_ORDER[state])
        scope.gauge("saturation").update(sat)
        return state

    def state(self) -> str:
        return self.evaluate()

    def snapshot(self) -> dict:
        """One probe pass feeds BOTH the returned sources and the state
        transition: every /health hit samples once, and the reported
        state can never disagree with the saturations beside it."""
        sample = self._sample()
        return {"state": self.evaluate(sample), "sources": sample,
                "saturation": max(sample.values()) if sample else 0.0}


# Process-default tracker: gates auto-register here; the coordinator and
# aggregator HTTP health endpoints read it.
TRACKER = HealthTracker()
