"""Process watchdog (reference: src/x/panicmon/executor.go — exec a child,
report its exit status/signal to handlers, restart on crash if asked)."""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Callable, List, Optional, Sequence


class Panicmon:
    def __init__(self, argv: Sequence[str],
                 on_exit: Optional[Callable[[int], None]] = None,
                 restart_on_crash: bool = False,
                 max_restarts: int = 3,
                 backoff_s: float = 0.5):
        self.argv = list(argv)
        self.on_exit = on_exit
        self.restart_on_crash = restart_on_crash
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.restarts = 0
        self.exit_codes: List[int] = []
        self._proc: Optional[subprocess.Popen] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Panicmon":
        self._proc = subprocess.Popen(self.argv)
        self._thread = threading.Thread(target=self._watch, daemon=True)
        self._thread.start()
        return self

    def _watch(self):
        while not self._stop.is_set():
            rc = self._proc.wait()
            self.exit_codes.append(rc)
            if self.on_exit is not None:
                self.on_exit(rc)
            crashed = rc != 0
            if (self._stop.is_set() or not crashed
                    or not self.restart_on_crash
                    or self.restarts >= self.max_restarts):
                return
            self.restarts += 1
            # Interruptible backoff + re-check: stop() during the sleep
            # must not be answered with a fresh child it never sees.
            if self._stop.wait(self.backoff_s):
                return
            self._proc = subprocess.Popen(self.argv)

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def stop(self, grace_s: float = 5.0):
        self._stop.set()
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        if self._thread is not None:
            self._thread.join(timeout=grace_s)
