"""Query limits: per-second sliding windows + global concurrent budgets
(reference: src/dbnode/storage/limits/query_limits.go — docs-matched /
series / bytes-read lookback limits backed by x/cost, each with a
per-second global window — and src/x/cost/enforcer.go's parent/child
chain so one query cannot starve the process).

Two mechanisms per resource kind, composed in one `QueryLimits`
registry:

  sliding window   a rate limit over the trailing second (bucketized —
                   see DIVERGENCES.md vs the reference's reset ticker).
                   Window charges are never released; they expire.
  concurrent       an in-flight budget backed by cost.Enforcer. Charged
                   only through a QueryScope (per-query child enforcer
                   chained to the global parent) so every admit has a
                   matching release at scope exit — budget charged
                   outside any scope hits the window only, because
                   nothing would ever credit it back.

Exceeding either raises `ResourceExhausted`, a RetryableError: the
server sheds THIS request, but the condition is transient (windows
expire, scopes release), so clients classify it retryable-with-backoff
— unlike DeadlineExceeded, where the budget that expired was the
caller's whole budget. `Backpressure` is the ingest-side subclass
raised by admission gates (utils/health.py) past their watermarks.

Charge sites (index postings evaluation, storage reads, RPC fan-ins)
call the module-level `charge(kind, n)`, which routes to the innermost
thread-local QueryScope when one is installed (query executor, node
RPC dispatch) and to the global registry otherwise.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple

from .cost import CostLimitExceeded, Enforcer
from .instrument import ROOT
from .retry import RetryableError

__all__ = [
    "ResourceExhausted", "Backpressure", "LimitOptions", "SlidingWindow",
    "QueryLimits", "QueryScope", "KINDS", "tenant_of",
    "charge", "get_global", "set_global", "last_scope_totals",
]


def tenant_of(metric_id: bytes) -> bytes:
    """Tenant extracted from a metric id: the first dot-delimited segment
    of the NAME component ('tenantA.requests;host=x' -> b'tenantA').
    Frames/requests may override with an explicit hint; this is the
    fallback the aggregator/server charge sites use. An id without a dot
    is its own tenant (single-tenant deployments degrade to the global
    behavior: one tenant, full share)."""
    name = metric_id.split(b";", 1)[0]
    return name.split(b".", 1)[0]

# Resource kinds, matching the reference's query limit trio plus the
# datapoint budget the query engine already meters:
#   docs_matched        postings matched during index evaluation, charged
#                       BEFORE materialization (a regexp that matches the
#                       world is rejected before it allocates the world)
#   series_fetched      series ids materialized for a read
#   datapoints_decoded  decoded datapoints handed to the query layer
#   bytes_read          encoded block/buffer bytes touched by a fetch
KINDS = ("docs_matched", "series_fetched", "datapoints_decoded", "bytes_read")

_scope_metrics = ROOT.sub_scope("limits")


class ResourceExhausted(RetryableError):
    """A query/ingest limit rejected this request. Retryable: the limit
    is a per-second window or an in-flight budget, both of which clear
    on their own — clients should back off and re-attempt, not fail the
    caller outright (distinct from DeadlineExceeded, which never
    retries)."""


class Backpressure(ResourceExhausted):
    """An ingest admission gate shed this write: the bounded work queue
    is past its watermark for this priority class. Producers back off
    (the Retrier classifies it retryable) instead of retrying hot."""


@dataclasses.dataclass(frozen=True)
class LimitOptions:
    """Per-kind limit knobs. None disables that mechanism.

    per_second     sliding-window rate cap over the trailing `window_s`
    concurrent     global in-flight budget (enforcer parent limit)
    per_query      per-scope child enforcer limit (defaults to the global
                   concurrent budget when unset, i.e. one query may use
                   the whole budget if nothing else is in flight)
    tenant_fair    weighted per-tenant fair-share over the sliding
                   window (DAGOR-style): a tenant's charges are capped at
                   weight/(Σ active weights + one reserve share) of the
                   window limit, so one noisy tenant saturates its OWN
                   share and never the whole window — a quiet tenant
                   arriving mid-burst always finds budget. Charges
                   without a tenant, or marked critical, bypass the
                   tenant cap (never the global window).
    tenant_weights tenant id -> weight (unlisted tenants weigh 1.0)
    """

    per_second: Optional[float] = None
    concurrent: Optional[float] = None
    per_query: Optional[float] = None
    tenant_fair: bool = False
    tenant_weights: Optional[Tuple[Tuple[bytes, float], ...]] = None

    def weight(self, tenant: bytes) -> float:
        if self.tenant_weights:
            for t, w in self.tenant_weights:
                if t == tenant:
                    return w
        return 1.0


class SlidingWindow:
    """Bucketized trailing-window rate limit. The reference resets a
    global counter on a per-second ticker (query_limits.go started
    lookback ticker); here the trailing second is `buckets` sub-second
    buckets that expire individually, so saturation decays smoothly and
    an idle `window_s` always empties it exactly (property-tested)."""

    def __init__(self, limit: float, window_s: float = 1.0, buckets: int = 10,
                 clock: Callable[[], float] = time.monotonic):
        if limit <= 0:
            raise ValueError(f"window limit must be positive, got {limit}")
        self.limit = limit
        self.window_s = window_s
        self._bucket_s = window_s / max(1, buckets)
        self._nbuckets = max(1, buckets)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Deque[Tuple[int, float]] = deque()  # (bucket idx, n)
        self._total = 0.0

    def _expire_locked(self, now_bucket: int):
        floor = now_bucket - self._nbuckets + 1
        while self._buckets and self._buckets[0][0] < floor:
            _, n = self._buckets.popleft()
            self._total -= n

    def try_charge(self, n: float) -> bool:
        """Admit-and-count, or refuse without counting. A refused charge
        does not consume window budget: the work was never done, so the
        next second must not inherit phantom load from rejections."""
        now_bucket = int(self._clock() / self._bucket_s)
        with self._lock:
            self._expire_locked(now_bucket)
            if self._total + n > self.limit:
                return False
            if self._buckets and self._buckets[-1][0] == now_bucket:
                idx, cur = self._buckets[-1]
                self._buckets[-1] = (idx, cur + n)
            else:
                self._buckets.append((now_bucket, float(n)))
            self._total += n
            return True

    def current(self) -> float:
        now_bucket = int(self._clock() / self._bucket_s)
        with self._lock:
            self._expire_locked(now_bucket)
            return self._total


class _Limit:
    """One resource kind: optional sliding window + optional global
    concurrent enforcer + optional per-tenant weighted fair-share over
    the window."""

    def __init__(self, kind: str, opts: LimitOptions,
                 clock: Callable[[], float]):
        self.kind = kind
        self.opts = opts
        self.window = (SlidingWindow(opts.per_second, clock=clock)
                       if opts.per_second is not None else None)
        self.enforcer = Enforcer(limit=opts.concurrent, name=kind)
        self._fair = bool(opts.tenant_fair) and self.window is not None
        self._clock = clock
        self._tenant_lock = threading.Lock()
        # tenant -> usage window (limit inf: a pure per-tenant usage
        # recorder; the SHARE check below is what rejects). Pruned of
        # idle tenants on every share computation, so it is bounded by
        # the tenants active within one trailing window.
        self._tenant_use: Dict[bytes, SlidingWindow] = {}

    def _tenant_share_locked(self, tenant: bytes) -> Tuple[float, SlidingWindow]:
        """This tenant's fair share of the window limit, DAGOR-style:
        limit * w_t / (Σ active weights + w_t + one reserve share). The
        reserve keeps a lone noisy tenant capped BELOW the full window,
        so a quiet tenant arriving mid-burst always finds budget ("its
        own share, never the whole window"). _tenant_lock held."""
        w = self.opts.weight(tenant)
        tw = self._tenant_use.get(tenant)
        if tw is None:
            tw = self._tenant_use[tenant] = SlidingWindow(
                float("inf"), clock=self._clock)
        active = 0.0
        dead = []
        for t, win in self._tenant_use.items():
            if t == tenant:
                continue
            if win.current() > 0:
                active += self.opts.weight(t)
            else:
                dead.append(t)
        for t in dead:
            del self._tenant_use[t]
        return self.window.limit * w / (active + w + 1.0), tw

    def charge_window(self, n: float, tenant: Optional[bytes] = None,
                      critical: bool = False):
        if self.window is None:
            return
        if self._fair and tenant is not None and not critical:
            # Share check, window charge, and usage recording are ONE
            # atomic step under the tenant lock: two racing charges of
            # the same tenant can't both read the pre-charge usage and
            # blow through the fair share. The global window has its own
            # inner lock; nothing ever takes the tenant lock after it,
            # so the nesting can't invert.
            with self._tenant_lock:
                share, tw = self._tenant_share_locked(tenant)
                if tw.current() + n > share:
                    _scope_metrics.counter(
                        f"{self.kind}.tenant_exceeded").inc()
                    raise ResourceExhausted(
                        f"{self.kind}: tenant {tenant!r} charge {n:g} "
                        f"would exceed its fair share {share:g} of the "
                        f"per-second limit {self.window.limit:g} "
                        f"(tenant current {tw.current():g})")
                if not self.window.try_charge(n):
                    _scope_metrics.counter(f"{self.kind}.exceeded").inc()
                    raise ResourceExhausted(
                        f"{self.kind}: {n:g} would exceed per-second "
                        f"limit {self.window.limit:g} "
                        f"(current {self.window.current():g})")
                # usage recorded only for ADMITTED work (the try_charge
                # invariant: a rejection leaves nothing charged anywhere)
                tw.try_charge(n)
            return
        if not self.window.try_charge(n):
            _scope_metrics.counter(f"{self.kind}.exceeded").inc()
            raise ResourceExhausted(
                f"{self.kind}: {n:g} would exceed per-second limit "
                f"{self.window.limit:g} (current {self.window.current():g})")

    def tenant_usage(self, tenant: bytes) -> float:
        with self._tenant_lock:
            tw = self._tenant_use.get(tenant)
        return tw.current() if tw is not None else 0.0

    def saturation(self) -> float:
        """In-flight concurrent usage as a fraction of the budget (0 when
        unlimited) — the health tracker's input signal."""
        limit = self.opts.concurrent
        if not limit:
            return 0.0
        return max(0.0, min(1.0, self.enforcer.current() / limit))


class QueryScope:
    """Per-query child enforcers chained to the registry's global
    parents (x/cost child enforcer). Context manager: entering installs
    it thread-local so storage/index charge sites inside the query
    route through it; exiting releases every child's full charge back
    up the chain (relying on Enforcer.release(None) crediting the
    parent) and restores the previous scope."""

    def __init__(self, limits: "QueryLimits", name: str,
                 tenant: Optional[bytes] = None):
        self.name = name
        self.tenant = tenant
        self._limits = limits
        # Cumulative per-kind charges for THIS scope's lifetime (the
        # enforcers only know in-flight): the span/slow-query cost
        # attribution — what did this request actually touch.
        self.totals: Dict[str, float] = {}
        self._children: Dict[str, Enforcer] = {
            kind: lim.enforcer.child(
                lim.opts.per_query
                if lim.opts.per_query is not None else lim.opts.concurrent,
                name=f"{name}.{kind}")
            for kind, lim in limits._limits.items()
        }
        self._prev = None

    def charge(self, kind: str, n: float):
        # Enforcer first (a rejected add rolls back at every level), THEN
        # the window — and an enforcer charge whose window refuses is
        # released again. Either rejection leaves NOTHING charged, so a
        # retry storm of rejected queries cannot poison the next second
        # with phantom window load (try_charge's documented invariant).
        lim = self._limits._limits[kind]
        try:
            self._children[kind].add(n)
        except CostLimitExceeded as e:
            _scope_metrics.counter(f"{kind}.exceeded").inc()
            raise ResourceExhausted(str(e)) from e
        try:
            lim.charge_window(n, tenant=self.tenant)
        except ResourceExhausted:
            self._children[kind].release(n)
            raise
        self.totals[kind] = self.totals.get(kind, 0) + n
        _scope_metrics.counter(f"{kind}.charged").inc(int(n))

    def current(self, kind: str) -> float:
        return self._children[kind].current()

    def release_all(self):
        for child in self._children.values():
            child.release(None)

    def __enter__(self) -> "QueryScope":
        self._prev = getattr(_TLS, "scope", None)
        _TLS.scope = self
        return self

    def __exit__(self, *exc):
        _TLS.scope = self._prev
        # Cost attribution on the way out: tag the active span with this
        # scope's cumulative charges (per-span docs/bytes/datapoints) and
        # stash them thread-local so the slow-query log can attribute
        # costs even for UNSAMPLED requests (outermost scope wins — it
        # exits last).
        if self.totals:
            from . import tracing

            sp = tracing.TRACER.current()
            if sp is not None:
                for kind, n in self.totals.items():
                    sp.add_cost(kind, n)
        _TLS.last_totals = self.totals
        self.release_all()
        return False


class QueryLimits:
    """Registry of per-kind limits. Default-constructed, every kind is
    unlimited (charges are no-ops beyond counters) so wiring it through
    hot paths costs nothing until a deployment configures budgets."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 **kinds: LimitOptions):
        unknown = set(kinds) - set(KINDS)
        if unknown:
            raise ValueError(f"unknown limit kinds: {sorted(unknown)}")
        self._limits: Dict[str, _Limit] = {
            kind: _Limit(kind, kinds.get(kind, LimitOptions()), clock)
            for kind in KINDS
        }

    def charge(self, kind: str, n: float, tenant: Optional[bytes] = None,
               critical: bool = False):
        """Global (scope-less) charge: sliding window only — concurrent
        budgets need a release point, which only scopes have. With a
        `tenant` (and the kind configured tenant_fair), the charge is
        additionally capped at the tenant's weighted fair share;
        `critical` work bypasses the tenant cap (never the global
        window)."""
        self._limits[kind].charge_window(n, tenant=tenant,
                                         critical=critical)
        _scope_metrics.counter(f"{kind}.charged").inc(int(n))

    def scope(self, name: str = "query",
              tenant: Optional[bytes] = None) -> QueryScope:
        return QueryScope(self, name, tenant=tenant)

    def tenant_usage(self, kind: str, tenant: bytes) -> float:
        """This tenant's trailing-window usage for one kind (tests,
        /debug introspection)."""
        return self._limits[kind].tenant_usage(tenant)

    def enforcer(self, kind: str) -> Enforcer:
        return self._limits[kind].enforcer

    def saturation(self) -> float:
        """Max in-flight saturation across kinds — feeds HealthTracker."""
        return max(lim.saturation() for lim in self._limits.values())

    def stats(self) -> Dict[str, dict]:
        out = {}
        for kind, lim in self._limits.items():
            out[kind] = {
                "in_flight": lim.enforcer.current(),
                "concurrent_limit": lim.opts.concurrent,
                "window_current": (lim.window.current()
                                   if lim.window is not None else None),
                "per_second": lim.opts.per_second,
            }
        return out


# ------------------------------------------------- thread-local scope routing

_TLS = threading.local()
_GLOBAL = QueryLimits()
_GLOBAL_LOCK = threading.Lock()


def get_global() -> QueryLimits:
    return _GLOBAL


def set_global(limits: QueryLimits) -> QueryLimits:
    """Swap the process-global registry (service startup / tests);
    returns the previous one so tests can restore it."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        prev, _GLOBAL = _GLOBAL, limits
    return prev


def current_scope() -> Optional[QueryScope]:
    return getattr(_TLS, "scope", None)


def last_scope_totals() -> Dict[str, float]:
    """Cumulative charges of the most recently EXITED scope on this
    thread — the slow-query log's cost source (a dispatch reads it right
    after its scope closes, before any other scope runs on the thread)."""
    return getattr(_TLS, "last_totals", None) or {}


def reset_last_totals():
    """Clear this thread's last-scope totals. Dispatchers call it BEFORE
    admission/scope entry so a request shed before its scope ever runs
    (admission gate full) attributes EMPTY costs, not the previous
    request's — serving threads are reused."""
    _TLS.last_totals = None


def charge(kind: str, n: float, tenant: Optional[bytes] = None,
           critical: bool = False):
    """Charge-site entry point: the innermost thread-local QueryScope
    when one is installed (query executor / node RPC dispatch), else the
    global registry's window. Raises ResourceExhausted on rejection.
    `tenant`/`critical` feed the per-tenant fair-share cap on scope-less
    charges (a scope carries its own tenant from construction)."""
    if n <= 0:
        return
    scope = getattr(_TLS, "scope", None)
    if scope is not None:
        scope.charge(kind, n)
    else:
        _GLOBAL.charge(kind, n, tenant=tenant, critical=critical)
