"""Runtime lock-order witness (the Python analog of the kernel's
lockdep, standing in for the Go race detector the reference leans on).

Opt-in via ``M3_TPU_LOCKDEP=1``: `install()` (called automatically by
``m3_tpu/__init__`` when the env var is set) replaces
``threading.Lock`` / ``RLock`` / ``Condition`` with factories that wrap
locks ALLOCATED FROM m3_tpu CODE in a witness proxy — foreign callers
(stdlib queue, jax, concurrent.futures) get the real primitive
untouched, so only this repo's locks are observed and the overhead
stays inside the paths we own.

Each witnessed lock is named by its ALLOCATION SITE, with the same
identity scheme the static analyzer's program-wide lock graph uses
(analysis/callgraph.py): ``Class.attr`` for ``self.attr =
threading.Lock()`` in a method, ``modbase.name`` for module-level
locks. That shared naming is the whole point — the witnessed
acquisition-order graph and the static graph are directly comparable,
and scripts/lockdep_check.py asserts every witnessed edge is present
in (or explicitly reconciled against) the static model.

What is recorded, per process:

  * the ACQUISITION-ORDER graph: on acquiring lock B while holding A
    (innermost held, different object), the edge A -> B with its first
    observed site and a count. Reentrant re-acquisition of the same
    OBJECT records nothing.
  * HELD-WHILE-BLOCKING: when the acquire actually contended (the
    non-blocking probe failed and the thread parked), the edge is
    additionally flagged ``blocked`` — these are the edges that turn
    an inversion into a real stall.
  * CYCLES, detected ONLINE: adding edge A -> B runs a reachability
    check B ~> A over the witnessed graph; a hit records the full
    cycle path at witness time (same-NAME edges between different
    objects — parent/child Enforcer chains — are hierarchy edges and
    are exempt from cycle detection, matching lockdep's nesting
    classes).

On process exit (or `dump_now()`), the graph is written as JSON into
``M3_TPU_LOCKDEP_OUT`` (a directory; one file per pid) for the
check_all lockdep tier to verify: zero cycles, and witnessed ⊆ static
∪ reconciliation (analysis/lockdep_reconcile.txt).

Conditions: a no-arg ``threading.Condition()`` from m3_tpu code gets a
witnessed RLock underneath (named from the Condition's site);
``Condition(existing_lock)`` keeps the caller's (possibly witnessed)
lock — waits release and re-acquire through the proxy, so the held
stack stays balanced across ``cond.wait()``.
"""

from __future__ import annotations

import atexit
import json
import linecache
import os
import re
import sys
import threading
import time

__all__ = ["enabled", "install", "installed", "witness_graph", "dump_now",
           "LockdepGraph"]

# Real primitives captured at import time: the proxies and the graph's
# own bookkeeping must never recurse through the patched factories.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_ASSIGN_RE = re.compile(
    r"(?:(?P<self>self)\.)?(?P<name>\w+)\s*(?::[^=]+)?=\s*"
    r"(?:threading\.)?(?:Lock|RLock|Condition)\s*\(")


def enabled() -> bool:
    return os.environ.get("M3_TPU_LOCKDEP", "") not in ("", "0")


class LockdepGraph:
    """Process-wide witnessed acquisition-order graph."""

    def __init__(self):
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        # (a, b) -> {"count", "blocked", "site"}
        self.edges: dict = {}
        self.adj: dict = {}            # a -> set of b (cycle detection)
        self.cycles: list = []         # recorded cycle paths
        self.nodes: dict = {}          # name -> kind

    # ------------------------------------------------------------- held stack

    def _held(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def note_node(self, name: str, kind: str):
        with self._mu:
            self.nodes.setdefault(name, kind)

    def on_acquire(self, name: str, obj, blocked: bool, site: str,
                   record_edge: bool = True):
        held = self._held()
        if record_edge and not any(o is obj for _n, o in held):
            if held:
                self._edge(held[-1][0], name, blocked, site)
        held.append((name, obj))

    def on_block(self, name: str, obj, site: str) -> bool:
        """Record the (innermost-held -> name) edge BEFORE the thread
        parks on a contended acquire — a real deadlock never returns
        from the park, so waiting until after the acquire would witness
        nothing. Returns True when this edge closes a cycle (the caller
        dumps diagnostics before parking)."""
        held = self._held()
        if not held or any(o is obj for _n, o in held):
            return False
        with self._mu:
            ncycles = len(self.cycles)
        self._edge(held[-1][0], name, True, site)
        with self._mu:
            return len(self.cycles) > ncycles

    def on_release(self, name: str, obj):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is obj:
                del held[i]
                return

    # ------------------------------------------------------------------ edges

    def _edge(self, a: str, b: str, blocked: bool, site: str):
        if a == b:
            # same NAME, different objects: a hierarchy edge
            # (parent/child Enforcer chain); recorded, never a cycle
            with self._mu:
                e = self.edges.setdefault(
                    (a, b), {"count": 0, "blocked": 0, "site": site})
                e["count"] += 1
                e["blocked"] += int(blocked)
            return
        with self._mu:
            e = self.edges.get((a, b))
            if e is None:
                self.edges[(a, b)] = {"count": 1, "blocked": int(blocked),
                                      "site": site}
                self.adj.setdefault(a, set()).add(b)
                path = self._path(b, a)
                if path is not None:
                    self.cycles.append([a] + path)
            else:
                e["count"] += 1
                e["blocked"] += int(blocked)

    def _path(self, src: str, dst: str):
        """A path src ~> dst over witnessed edges (None when dst is
        unreachable). _mu held."""
        stack = [(src, [src])]
        seen = set()
        while stack:
            cur, path = stack.pop()
            if cur == dst:
                return path
            if cur in seen:
                continue
            seen.add(cur)
            for nxt in self.adj.get(cur, ()):
                if nxt != cur:
                    stack.append((nxt, path + [nxt]))
        return None

    # ------------------------------------------------------------------- dump

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "pid": os.getpid(),
                "argv": sys.argv,
                "time": time.time(),
                "nodes": dict(self.nodes),
                "edges": [
                    {"from": a, "to": b, **info}
                    for (a, b), info in sorted(self.edges.items())
                ],
                "cycles": [list(c) for c in self.cycles],
            }

    def dump(self, path: str):
        snap = self.snapshot()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f, indent=1, sort_keys=True)
        os.replace(tmp, path)


_GRAPH = LockdepGraph()


def witness_graph() -> LockdepGraph:
    return _GRAPH


# ------------------------------------------------------------------- proxies


class _WitnessedLock:
    """Proxy over a real Lock/RLock: every acquisition path — acquire,
    context manager, Condition's _release_save/_acquire_restore — feeds
    the witness graph. Unknown attributes delegate to the inner lock."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self._name = name

    # -- core protocol ----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(False)
        if got:
            _GRAPH.on_acquire(self._name, self, False, _call_site())
            return True
        if not blocking:
            return False
        # contended: record the held-while-blocking edge BEFORE parking
        # — a deadlocked park never returns, and this edge is the one
        # that proves it. If it closes a witnessed cycle, dump NOW so
        # the hang leaves diagnostics on disk even though atexit will
        # never run.
        site = _call_site()
        if _GRAPH.on_block(self._name, self, site):
            try:
                dump_now()
            except Exception:  # noqa: BLE001 — diagnostics must never
                pass               # turn a deadlock into a crash
        got = self._inner.acquire(True, timeout)
        if got:
            _GRAPH.on_acquire(self._name, self, True, site,
                              record_edge=False)
        return got

    def release(self):
        _GRAPH.on_release(self._name, self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()
        return False

    # -- Condition integration -------------------------------------------
    # Condition(wait) swaps the lock out and back; these keep the held
    # stack balanced whether the inner lock is an RLock (has the save/
    # restore protocol) or a plain Lock (emulated, as Condition does).

    def _release_save(self):
        _GRAPH.on_release(self._name, self)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state):
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        _GRAPH.on_acquire(self._name, self, False, _call_site())

    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def __repr__(self):
        return f"<witnessed {self._name} {self._inner!r}>"


# ------------------------------------------------------- site-derived naming


def _in_repo(filename: str) -> bool:
    return "m3_tpu" in filename.replace(os.sep, "/").split("/")


def _call_site(depth: int = 2) -> str:
    try:
        f = sys._getframe(depth)
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    except Exception:  # noqa: BLE001 — witness must never kill the caller
        return "?"


def _defining_class(self_obj, code) -> str:
    """The class that DEFINES the method whose code object is `code`,
    walking the MRO — the static graph names inherited lock attrs by
    the defining class (`MemStore._lock`), not the runtime subclass
    (`FileStore._lock`), and the witness must agree."""
    for cls in type(self_obj).__mro__:
        fn = vars(cls).get(code.co_name)
        fn = getattr(fn, "__func__", fn)
        if getattr(fn, "__code__", None) is code:
            return cls.__name__
    return type(self_obj).__name__


def _site_name(frame) -> str:
    """The static-graph identity for a lock allocated at `frame`:
    'Class.attr' when the source line assigns `self.attr = ...Lock()`
    inside a method (Class = the DEFINING class of that method),
    'modbase.name' for module/local assignments, an anonymous site
    marker otherwise."""
    filename = frame.f_code.co_filename
    lineno = frame.f_lineno
    modbase = os.path.basename(filename)
    if modbase.endswith(".py"):
        modbase = modbase[:-3]
    if modbase == "__init__":
        # static identities strip __init__ (module_dotted): name by the
        # package so pkg/__init__.py locks match `pkg.X` on both sides
        modbase = os.path.basename(os.path.dirname(filename)) or modbase
    line = linecache.getline(filename, lineno)
    m = _ASSIGN_RE.search(line)
    if m is None:
        return f"{modbase}.anon@{lineno}"
    attr = m.group("name")
    if m.group("self"):
        self_obj = frame.f_locals.get("self")
        if self_obj is not None:
            return f"{_defining_class(self_obj, frame.f_code)}.{attr}"
        return f"{modbase}.{attr}"
    return f"{modbase}.{attr}"


# ----------------------------------------------------------------- factories


def _lock_factory():
    frame = sys._getframe(1)
    if not _in_repo(frame.f_code.co_filename):
        return _REAL_LOCK()
    name = _site_name(frame)
    _GRAPH.note_node(name, "lock")
    return _WitnessedLock(_REAL_LOCK(), name)


def _rlock_factory():
    frame = sys._getframe(1)
    if not _in_repo(frame.f_code.co_filename):
        return _REAL_RLOCK()
    name = _site_name(frame)
    _GRAPH.note_node(name, "rlock")
    return _WitnessedLock(_REAL_RLOCK(), name)


def _condition_factory(lock=None):
    frame = sys._getframe(1)
    if not _in_repo(frame.f_code.co_filename):
        return _REAL_CONDITION(lock)
    if lock is None:
        name = _site_name(frame)
        _GRAPH.note_node(name, "cond")
        lock = _WitnessedLock(_REAL_RLOCK(), name)
    # a caller-supplied lock keeps its own identity (witnessed or not)
    return _REAL_CONDITION(lock)


_INSTALLED = False


def installed() -> bool:
    return _INSTALLED


def default_out_dir() -> str:
    return os.environ.get("M3_TPU_LOCKDEP_OUT",
                          os.path.join("artifacts", "lockdep"))


def dump_now(path: str = "") -> str:
    """Write this process's witnessed graph; returns the file path."""
    if not path:
        out = default_out_dir()
        os.makedirs(out, exist_ok=True)
        path = os.path.join(out, f"lockdep-{os.getpid()}.json")
    _GRAPH.dump(path)
    return path


def _atexit_dump():
    try:
        dump_now()
    except Exception:  # noqa: BLE001 — a failed dump must not mask the
        pass               # process's own exit status


def install() -> LockdepGraph:
    """Patch the threading lock factories (idempotent). Only locks
    allocated from m3_tpu source files are witnessed; everyone else
    gets the real primitive."""
    global _INSTALLED
    if _INSTALLED:
        return _GRAPH
    _INSTALLED = True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    atexit.register(_atexit_dump)
    return _GRAPH


def uninstall():
    """Restore the real factories (tests). Already-witnessed locks keep
    their proxies; only NEW allocations revert."""
    global _INSTALLED
    if not _INSTALLED:
        return
    _INSTALLED = False
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
