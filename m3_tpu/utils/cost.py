"""Query cost enforcement (reference: src/x/cost/enforcer.go — per-query and
global cost accounting with limits, used by the query engine to bound
datapoints processed)."""

from __future__ import annotations

import threading
from typing import Optional


class CostLimitExceeded(RuntimeError):
    pass


class Enforcer:
    """Tracks charged cost against a limit (cost.Enforcer). Child enforcers
    chain to a parent (per-query -> global) so one query can't starve the
    process."""

    def __init__(self, limit: Optional[float] = None,
                 parent: Optional["Enforcer"] = None, name: str = "query"):
        self.limit = limit
        self.parent = parent
        self.name = name
        self._lock = threading.Lock()
        self._current = 0.0

    def add(self, cost: float) -> float:
        """Charge cost; raises CostLimitExceeded past the limit
        (enforcer.go Add). A rejected charge is rolled back at every level
        so callers can continue within the remaining budget."""
        with self._lock:
            self._current += cost
            current = self._current
        try:
            if self.limit is not None and current > self.limit:
                raise CostLimitExceeded(
                    f"{self.name} cost {current:g} exceeds limit {self.limit:g}")
            if self.parent is not None:
                self.parent.add(cost)
        except CostLimitExceeded:
            with self._lock:
                self._current -= cost
            raise
        return current

    def current(self) -> float:
        with self._lock:
            return self._current

    def release(self, cost: Optional[float] = None):
        """Return capacity when a query finishes (enforcer.go Remove).

        cost=None releases this enforcer's FULL current charge. The
        amount actually released is captured BEFORE the local decrement
        and propagated to the parent: a full release must credit the
        whole chain, or every completed query would permanently leak
        its charge from the global budget (the release(None) parent
        leak — regression-tested in tests/test_overload.py)."""
        with self._lock:
            released = self._current if cost is None else cost
            self._current -= released
        if self.parent is not None and released:
            self.parent.release(released)

    def child(self, limit: Optional[float] = None, name: str = "query"
              ) -> "Enforcer":
        return Enforcer(limit, parent=self, name=name)


NOOP = Enforcer(limit=None, name="noop")
