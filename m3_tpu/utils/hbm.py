"""Shared device-memory (HBM) budget for every cache that pins device or
host buffers on the serving path (reference: the byte-bounded WiredList of
src/dbnode/storage/block/wired_list.go:77, generalized to ONE budget over
every resident tier the way dbnode's cache policies share the wired-list
capacity).

Before this, each cache carried its own ceiling (`M3_TPU_UPLOAD_CACHE_BYTES`,
`M3_TPU_DERIVED_CACHE_BYTES`, ...) and nothing bounded their SUM — three
caches at their individual limits could pin more HBM than the chip has,
starving the kernels they exist to feed. `HBMBudget` is the process-wide
cap: tenants register a usage probe plus an evict-one callback, and
`reclaim()` rotates across tenants evicting least-recently-used entries
until the total fits (per-tenant ceilings still apply first, so existing
knobs keep their meaning as shares of the global budget).

Accounting is PULL-based — the budget reads each tenant's live byte
counter instead of mirroring charges — so a tenant that clears itself
(tests monkeypatching a cache, a namespace drop) can never leave phantom
bytes behind in a push-ledger.

Locking: the budget lock is only ever held to snapshot the tenant table;
evict callbacks run with NO budget lock held, so a tenant is free to take
its own lock inside them (tenant lock -> budget lock is the one permitted
order; callers must invoke `reclaim()` only outside their own locks when
their evictor takes that lock).

`budgeted_put` is the raw-`jax.device_put` replacement for one-shot
uploads on the storage/query serving path (m3lint's `unbudgeted-device-put`
rule flags the raw calls): it charges the ACTUAL device-buffer size to a
transient tenant and releases it when the array is garbage-collected, so
memory pressure from in-flight uploads is visible to the same budget that
governs the resident caches.

Saturation exports through instrument gauges (`hbm.bytes`,
`hbm.saturation`) and `pressure()` registers as a HealthTracker probe:
pressure stays 0.0 while reclaim keeps the total inside the budget (a full
LRU cache is a HEALTHY steady state, not an incident) and rises only when
pinned bytes exceed the budget and eviction cannot free them — the
memory-pressure analog of the admission gates' depth saturation.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Callable, Dict, Optional

from .instrument import ROOT

__all__ = ["HBMBudget", "shared_budget", "budgeted_put"]

DEFAULT_BUDGET_BYTES = 2 * 1024 * 1024 * 1024


class HBMBudget:
    """One byte budget across every registered resident-memory tenant."""

    def __init__(self, limit_bytes: int, name: str = "hbm"):
        if limit_bytes <= 0:
            raise ValueError(f"budget must be positive, got {limit_bytes}")
        self.limit = int(limit_bytes)
        self.name = name
        self._lock = threading.Lock()
        self._usage: Dict[str, Callable[[], int]] = {}
        self._evictors: Dict[str, Callable[[], int]] = {}
        # Rotation cursor: reclaim starts each pass one tenant further
        # along, approximating global LRU without a cross-tenant clock.
        self._rotation = 0
        self._metrics = ROOT.sub_scope(name)
        self._transient = 0
        # Releases arrive from weakref finalizers, which the cyclic GC may
        # run at ANY bytecode boundary — including while this thread holds
        # self._lock. A finalizer must therefore never acquire a lock:
        # it appends to this list (list.append is GIL-atomic) and the
        # usage probe drains it under the lock.
        self._transient_released: list = []
        self.register("transient", self._transient_usage)

    # ---------------------------------------------------------------- tenants

    def register(self, tenant: str, usage: Callable[[], int],
                 evict_one: Optional[Callable[[], int]] = None):
        """Add a tenant: `usage()` returns its current resident bytes;
        `evict_one()` (optional) drops its least-recently-used entry and
        returns the bytes freed (0 when it cannot shrink further)."""
        with self._lock:
            self._usage[tenant] = usage
            if evict_one is not None:
                self._evictors[tenant] = evict_one
            else:
                self._evictors.pop(tenant, None)

    def unregister(self, tenant: str):
        with self._lock:
            self._usage.pop(tenant, None)
            self._evictors.pop(tenant, None)

    # --------------------------------------------------------------- readings

    def total(self) -> int:
        with self._lock:
            probes = list(self._usage.values())
        total = 0
        for fn in probes:
            try:
                total += max(0, int(fn()))
            except Exception:  # noqa: BLE001 — a dead probe contributes 0
                pass
        return total

    def usage(self) -> Dict[str, int]:
        with self._lock:
            probes = dict(self._usage)
        out = {}
        for tenant, fn in probes.items():
            try:
                out[tenant] = max(0, int(fn()))
            except Exception:  # noqa: BLE001
                out[tenant] = 0
        return out

    def saturation(self) -> float:
        return min(1.0, self.total() / self.limit)

    def pressure(self) -> float:
        """Health-probe reading: 0 while the budget holds (a full cache is
        healthy), rising toward 1 as unreclaimable bytes exceed the limit
        (at 2x the budget the probe reads fully saturated)."""
        total = self.total()
        if total <= self.limit:
            return 0.0
        return min(1.0, (total - self.limit) / self.limit)

    # --------------------------------------------------------------- reclaim

    def reclaim(self) -> int:
        """Evict LRU entries across tenants (rotating the starting tenant
        so no single cache absorbs all evictions) until the total fits the
        budget or a full pass frees nothing. Returns bytes freed. Called
        with NO tenant locks held (evictors take their own)."""
        freed = 0
        while self.total() > self.limit:
            with self._lock:
                names = list(self._evictors)
                if not names:
                    break
                start = self._rotation % len(names)
                self._rotation += 1
                evictors = [(n, self._evictors[n])
                            for n in names[start:] + names[:start]]
            pass_freed = 0
            for _name, evict in evictors:
                try:
                    pass_freed += max(0, int(evict()))
                except Exception:  # noqa: BLE001 — one tenant's failure
                    pass               # must not wedge global reclaim
                if self.total() <= self.limit:
                    break
            if pass_freed == 0:
                break
            freed += pass_freed
        self._metrics.gauge("bytes").update(self.total())
        self._metrics.gauge("saturation").update(self.saturation())
        return freed

    def reclaim_pass(self) -> int:
        """ONE forced eviction rotation regardless of the tracked total:
        a device-reported OOM (`RESOURCE_EXHAUSTED`) means the chip is out
        of memory even if the host-side ledger is under budget (fragmentation,
        untracked scratch, another process), so the compute-fault guard
        frees one LRU entry per tenant before its single dispatch retry.
        Returns bytes freed. Same locking contract as reclaim()."""
        with self._lock:
            names = list(self._evictors)
            if not names:
                return 0
            start = self._rotation % len(names)
            self._rotation += 1
            evictors = [(n, self._evictors[n])
                        for n in names[start:] + names[:start]]
        freed = 0
        for _name, evict in evictors:
            try:
                freed += max(0, int(evict()))
            except Exception:  # noqa: BLE001 — one tenant's failure
                pass               # must not wedge the OOM retry
        self._metrics.gauge("bytes").update(self.total())
        self._metrics.gauge("saturation").update(self.saturation())
        return freed

    # ------------------------------------------------------- transient puts

    def _release_transient(self, n: int):
        # Finalizer context: lock-free by contract (see __init__).
        self._transient_released.append(n)

    def _transient_usage(self) -> int:
        with self._lock:
            while self._transient_released:
                self._transient -= self._transient_released.pop()
            if self._transient < 0:
                self._transient = 0
            return self._transient

    def device_put(self, arr, dst=None):
        """jax.device_put charged to the budget for the LIFETIME of the
        device array: the actual (canonicalized) device-buffer size is
        charged on upload and released when the array is collected, so
        transient query uploads show up as real memory pressure."""
        import jax

        dev = jax.device_put(arr, dst) if dst is not None \
            else jax.device_put(arr)  # m3lint: disable=unbudgeted-device-put
        # DELIBERATE raw put above: this IS the budget API's charge point.
        n = int(getattr(dev, "nbytes", getattr(arr, "nbytes", 0)))
        # Transfer telemetry at the same choke point the budget charges
        # (lazy import: utils must stay importable without parallel).
        from ..parallel import telemetry

        telemetry.count_h2d(n)
        with self._lock:
            self._transient += n
        try:
            weakref.finalize(dev, self._release_transient, n)
        except TypeError:
            # Backend arrays that refuse weakrefs: release immediately
            # (accounting degrades to charge-at-upload only).
            self._release_transient(n)
        self.reclaim()
        return dev


_SHARED: Optional[HBMBudget] = None
_SHARED_LOCK = threading.Lock()


def shared_budget() -> HBMBudget:
    """The process-wide budget (`M3_TPU_HBM_BUDGET_BYTES`, default 2GiB).
    First use wires `pressure()` into the process HealthTracker as the
    memory-pressure probe beside the admission gates' depth probes."""
    global _SHARED
    with _SHARED_LOCK:
        if _SHARED is None:
            _SHARED = HBMBudget(int(os.environ.get(
                "M3_TPU_HBM_BUDGET_BYTES", str(DEFAULT_BUDGET_BYTES))))
            from .health import TRACKER

            TRACKER.register("hbm_pressure", _SHARED.pressure)
        return _SHARED


def budgeted_put(arr, dst=None):
    """Module-level convenience over shared_budget().device_put."""
    return shared_budget().device_put(arr, dst)
