"""Time units and duration helpers (reference: m3x/time xtime.Unit).

Timestamps throughout the framework are int64 nanoseconds; a Unit scales
them to the wire/storage precision (the reference stores per-namespace
precision in namespace options and encodes the unit in the M3TSZ stream's
time-unit markers, src/dbnode/encoding/m3tsz/encoder.go:167-202).
"""

from __future__ import annotations

import enum
import re


class Unit(enum.IntEnum):
    NONE = 0
    SECOND = 1
    MILLISECOND = 2
    MICROSECOND = 3
    NANOSECOND = 4
    MINUTE = 5
    HOUR = 6
    DAY = 7

    @property
    def nanos(self) -> int:
        return _UNIT_NANOS[self]

    @classmethod
    def from_duration_ns(cls, ns: int) -> "Unit":
        """Largest unit that evenly divides ns (m3x xtime.UnitFromDuration)."""
        for u in (Unit.DAY, Unit.HOUR, Unit.MINUTE, Unit.SECOND, Unit.MILLISECOND, Unit.MICROSECOND):
            if ns and ns % _UNIT_NANOS[u] == 0:
                return u
        return Unit.NANOSECOND


_UNIT_NANOS = {
    Unit.NONE: 0,
    Unit.NANOSECOND: 1,
    Unit.MICROSECOND: 1_000,
    Unit.MILLISECOND: 1_000_000,
    Unit.SECOND: 1_000_000_000,
    Unit.MINUTE: 60 * 1_000_000_000,
    Unit.HOUR: 3600 * 1_000_000_000,
    Unit.DAY: 24 * 3600 * 1_000_000_000,
}

_SUFFIX_NANOS = {
    "ns": 1,
    "us": 1_000,
    "ms": 1_000_000,
    "s": 1_000_000_000,
    "m": 60 * 1_000_000_000,
    "h": 3600 * 1_000_000_000,
    "d": 24 * 3600 * 1_000_000_000,
    "w": 7 * 24 * 3600 * 1_000_000_000,
    "y": 365 * 24 * 3600 * 1_000_000_000,
}

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|ms|s|m|h|d|w|y)")

SECOND = _SUFFIX_NANOS["s"]
MINUTE = _SUFFIX_NANOS["m"]
HOUR = _SUFFIX_NANOS["h"]
DAY = _SUFFIX_NANOS["d"]


def parse_duration(s: str) -> int:
    """'10s' / '1m' / '2h30m' / '40d' -> nanoseconds."""
    s = s.strip()
    if not s:
        raise ValueError("empty duration")
    pos, total = 0, 0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration {s!r}")
        total += int(float(m.group(1)) * _SUFFIX_NANOS[m.group(2)])
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"invalid duration {s!r}")
    return total


def format_duration(ns: int) -> str:
    """Nanoseconds -> compact duration string ('90s' -> '1m30s')."""
    if ns == 0:
        return "0s"
    out = []
    for suffix in ("d", "h", "m", "s", "ms", "us", "ns"):
        n = _SUFFIX_NANOS[suffix]
        if ns >= n:
            q, ns = divmod(ns, n)
            out.append(f"{q}{suffix}")
    return "".join(out)


def truncate(t_ns: int, window_ns: int) -> int:
    """Floor t to a window boundary (blockstart alignment, storage/series)."""
    return t_ns - t_ns % window_ns
