"""Vectorized adler32 (reference: src/dbnode/digest — adler32 is the
digest convention of the whole persistence layer: per-chunk commitlog
checksums, per-row fileset index entries, per-file digest chains).

`adler32_rows` computes the checksum of EVERY row of a byte matrix in
one pass of numpy reductions instead of a Python loop of
zlib.adler32 calls — the unit recovery verification and repair
metadata pay per block, per fileset, per sweep. Bit-identical to
zlib.adler32 row-by-row (tests/test_durability.py property-checks it).

adler32 of a buffer d[0..n) from the (A0=1, B0=0) seed:

  A = (1 + sum(d))            mod 65521
  B = (n + sum((n - i) d_i))  mod 65521
  adler = (B << 16) | A

Width-adaptive: NARROW rows (the per-series stream matrices this
repo checksums — where a Python loop of zlib calls pays call overhead
per row, not bandwidth) run as ONE float64 gemv; every term
(n - i) * d_i <= 255n is exactly representable and all terms are
non-negative, so the accumulated sum is exact below 2^53. WIDE rows
take one zlib C call per row — zlib streams >1 GB/s, while the gemv
pays an 8x u8->f64 conversion in memory traffic, so past a width
threshold the C loop is strictly faster AND exact at any width. Both
paths are bit-identical to `zlib.adler32(row.tobytes())`
(tests/test_durability.py property-checks across the threshold)."""

from __future__ import annotations

import zlib

import numpy as np

_MOD = 65521
# Crossover measured on this host: the gemv wins below ~128 bytes/row
# (call overhead dominates the zlib loop), loses past it (conversion
# traffic dominates the gemv). Far below the f64-exactness bound of
# ~8.4e6 bytes (255 * n^2 / 2 < 2^53).
_GEMV_MAX_ROW_BYTES = 128


def adler32_rows(rows: np.ndarray) -> np.ndarray:
    """adler32 of every row of a [S, N] byte matrix -> int64 [S].

    Accepts any row-contiguous dtype (u32 codeword rows included);
    rows are checksummed over their little-endian byte representation,
    matching `zlib.adler32(row.tobytes())` on a C-contiguous row."""
    mat = np.ascontiguousarray(rows)
    if mat.ndim != 2:
        raise ValueError(f"adler32_rows wants [S, N], got shape {mat.shape}")
    u8 = mat.view(np.uint8).reshape(mat.shape[0], -1)
    n = u8.shape[1]
    if n > _GEMV_MAX_ROW_BYTES:
        return np.fromiter((zlib.adler32(r.tobytes()) for r in u8),
                           np.int64, count=len(u8))
    d = u8.astype(np.float64)
    a = (1 + d.sum(axis=1).astype(np.int64)) % _MOD
    weights = np.arange(n, 0, -1, dtype=np.float64)
    b = (n + (d @ weights).astype(np.int64)) % _MOD
    return (b << 16) | a
