"""Runtime race witness (the dynamic half of the `go test -race`
parity story; the static half is analysis/race_rules.py).

Opt-in via ``M3_TPU_RACEWATCH=1``: `install()` (called automatically by
``m3_tpu/__init__`` when the env var is set) arms attribute
instrumentation on the shared-state attributes product modules have
REGISTERED — the attrs the static race pass flags or the lock-free
ledger (analysis/lockfree_ledger.txt) declares. Costs nothing when
unset: `register()` at a module bottom appends one tuple; no descriptor
is installed until the witness is armed.

Each witnessed attribute is named ``Class.attr`` — the SAME identity
scheme as the static rule family, the global lock graph, and the
lockdep witness, so the three planes compare directly. Every
instrumented access records an access PROFILE:

    (thread id, locks held by this thread, read|write)

with the held-lock snapshot taken from the lockdep witness
(utils/lockdep.py, installed as a dependency — its per-thread held
stack names locks with the same ``Class.attr`` scheme). Profiles
deduplicate per attribute, so steady-state instrumented access is one
GIL-atomic set probe; only a NEVER-SEEN profile takes the table lock.

At exit (or `dump_now()`), the observation table is written as JSON
into ``M3_TPU_RACEWATCH_OUT`` (a directory; one file per pid) for
scripts/race_check.py, which asserts every witnessed CROSS-THREAD
access pair either shares a common held lock (inside the static
protection model) or sits on the reviewed lock-free ledger — and
REFUSES vacuous passes: a run that never observed a cross-thread access
on any instrumented attribute fails rather than passing by silence.

The first write a given instance makes to a watched attribute is not
recorded: `__init__` assignment precedes publication (the static pass
owns mid-`__init__` escapes via unsafe-publication), and recording it
would charge every constructor thread with a spurious write profile.

Like lockdep/numwatch this is a SMOKE-TIER tool: a watched attribute
becomes a Python descriptor (one extra call per access) — never enable
it in production serving.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Dict, FrozenSet, List, Tuple

from . import lockdep

__all__ = [
    "enabled", "installed", "install", "uninstall", "reset", "register",
    "watch", "findings", "observed_count", "dump_now", "racy_pairs",
]

# racewatch's own mutex must be a REAL lock even under lockdep's patched
# factories: the witness must never witness itself.
_MU = lockdep._REAL_LOCK()
_INSTALLED = False
_PENDING: List[Tuple[type, Tuple[str, ...]]] = []  # register() backlog
_WATCHED: Dict[str, type] = {}                     # ident -> class
# ident -> set of (thread id, locks frozenset, is_write)
_PROFILES: Dict[str, set] = {}
_SEEN: set = set()          # (ident, tid, locks, write) lock-free probe
_MAX_PROFILES = 4096        # bound the table; profiles dedup hard


def enabled() -> bool:
    return os.environ.get("M3_TPU_RACEWATCH", "") not in ("", "0")


def installed() -> bool:
    return _INSTALLED


def install():
    """Arm the witness (idempotent): installs lockdep for held-lock
    snapshots, instruments every registered attribute, and registers
    the exit dump."""
    global _INSTALLED
    with _MU:
        if _INSTALLED:
            return
        _INSTALLED = True
        pending = list(_PENDING)
        _PENDING.clear()
    if not lockdep.installed():
        lockdep.install()
    for cls, attrs in pending:
        _instrument(cls, attrs)
    atexit.register(_atexit_dump)


def uninstall():
    """Disarm recording. Installed descriptors stay in place (removing
    them cannot restore original slots safely) but record nothing."""
    global _INSTALLED
    with _MU:
        _INSTALLED = False


def reset():
    with _MU:
        _PROFILES.clear()
        _SEEN.clear()


# ------------------------------------------------------------ registration


def register(cls: type, *attrs: str):
    """Declare `cls.attr...` as witness-instrumented shared state.
    Product modules call this at module bottom for the attrs the static
    race pass flags or the lock-free ledger declares. No-op (one list
    append) until the witness is installed."""
    with _MU:
        if not _INSTALLED:
            _PENDING.append((cls, tuple(attrs)))
            return
    _instrument(cls, attrs)


def watch(cls: type, *attrs: str) -> type:
    """Instrument unconditionally (tests): wraps the attrs now, whether
    or not the witness is armed, and returns the class."""
    _instrument(cls, attrs)
    return cls


def _instrument(cls: type, attrs):
    for attr in attrs:
        ident = f"{cls.__name__}.{attr}"
        with _MU:
            if ident in _WATCHED:
                continue
            _WATCHED[ident] = cls
        inner = cls.__dict__.get(attr)  # slot/property descriptor, if any
        if inner is not None and not hasattr(inner, "__set__"):
            inner = None  # plain class attr default: shadow per-instance
        setattr(cls, attr, _WatchedAttr(ident, attr, inner))


class _WatchedAttr:
    """Data descriptor recording (thread, locks-held, kind) per access,
    delegating storage to the wrapped slot descriptor or (for plain
    instance attrs) an instance-dict key."""

    def __init__(self, ident: str, attr: str, inner):
        self._ident = ident
        self._attr = attr
        self._inner = inner
        self._key = "_racewatch_" + attr

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        _note(self._ident, False)
        if self._inner is not None:
            return self._inner.__get__(obj, objtype)
        try:
            return obj.__dict__[self._key]
        except KeyError:
            raise AttributeError(self._attr) from None

    def __set__(self, obj, value):
        if self._has(obj):
            _note(self._ident, True)
        # else: first write = construction, pre-publication by contract
        if self._inner is not None:
            self._inner.__set__(obj, value)
        else:
            obj.__dict__[self._key] = value

    def __delete__(self, obj):
        _note(self._ident, True)
        if self._inner is not None:
            self._inner.__delete__(obj)
        else:
            obj.__dict__.pop(self._key, None)

    def _has(self, obj) -> bool:
        if self._inner is not None:
            try:
                self._inner.__get__(obj, type(obj))
                return True
            except AttributeError:
                return False
        return self._key in getattr(obj, "__dict__", {})


# --------------------------------------------------------------- recording


def _held_locks() -> FrozenSet[str]:
    if not lockdep.installed():
        return frozenset()
    return frozenset(n for n, _o in lockdep.witness_graph()._held())


def _note(ident: str, write: bool):
    if not _INSTALLED:
        return
    tid = threading.get_ident()
    locks = _held_locks()
    key = (ident, tid, locks, write)
    if key in _SEEN:  # GIL-atomic probe: steady state takes no lock
        return
    with _MU:
        if key in _SEEN:
            return
        if len(_SEEN) >= _MAX_PROFILES:
            return
        _SEEN.add(key)
        _PROFILES.setdefault(ident, set()).add((tid, locks, write))


def observed_count() -> int:
    """Distinct access profiles witnessed (0 = the witness saw nothing:
    a vacuous run)."""
    with _MU:
        return sum(len(v) for v in _PROFILES.values())


def racy_pairs(profiles) -> List[Tuple[Dict, Dict]]:
    """Cross-thread pairs with at least one write and NO common held
    lock, from one attr's profile list (dicts with thread/locks/write).
    These are the pairs that must sit on the lock-free ledger."""
    out = []
    for i, a in enumerate(profiles):
        for b in profiles[i + 1:]:
            if a["thread"] == b["thread"]:
                continue
            if not (a["write"] or b["write"]):
                continue
            if set(a["locks"]) & set(b["locks"]):
                continue
            out.append((a, b))
    return out


def findings() -> List[Dict]:
    """Per-attr observation summary: profiles, distinct thread count,
    and the racy (disjoint-lock cross-thread) pairs."""
    with _MU:
        snap = {k: sorted(v) for k, v in _PROFILES.items()}
    out = []
    for ident in sorted(snap):
        profiles = [{"thread": t, "locks": sorted(locks), "write": w}
                    for t, locks, w in snap[ident]]
        threads = {p["thread"] for p in profiles}
        out.append({
            "attr": ident,
            "threads": len(threads),
            "profiles": profiles,
            "racy": [[a, b] for a, b in racy_pairs(profiles)],
        })
    return out


# ----------------------------------------------------------------- dumps


def default_out_dir() -> str:
    return os.environ.get("M3_TPU_RACEWATCH_OUT", "")


def dump_now(path: str = "") -> str:
    """Write this process's witness state as JSON; returns the path
    ('' when no output dir is configured and none was given)."""
    if not path:
        out_dir = default_out_dir()
        if not out_dir:
            return ""
        path = os.path.join(out_dir, f"racewatch-{os.getpid()}.json")
    payload = {
        "pid": os.getpid(),
        "observed": observed_count(),
        "attrs": findings(),
    }
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
    except OSError:
        return ""
    return path


def _atexit_dump():
    if _INSTALLED:
        dump_now()
