"""Smart replicating client (reference: src/dbnode/client)."""

from .decode import ConflictStrategy, decode_segment_groups, merge_replica_points
from .session import (
    ConsistencyError,
    HostClient,
    RemoteError,
    Session,
    SessionOptions,
)

__all__ = [
    "ConflictStrategy",
    "ConsistencyError",
    "HostClient",
    "RemoteError",
    "Session",
    "SessionOptions",
    "decode_segment_groups",
    "merge_replica_points",
]
