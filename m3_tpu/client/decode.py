"""Client-side decode + replica reconciliation.

The reference dbnode returns *compressed* segments; the client's
MultiReaderIterator / SeriesIterator decode and k-way merge across
replicas with same-timestamp conflict strategies
(src/dbnode/encoding/series_iterator.go:76,176, iterators.go:60-105).

TPU-first twist: instead of a per-series pull iterator, segments from a
fetch are *stacked by window size* and decoded in one batched device
kernel call (ops.tsz.decode), then merged per series on host."""

from __future__ import annotations

import enum
from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..ops import tsz
from ..parallel import telemetry
from ..utils import xtime


class ConflictStrategy(enum.Enum):
    """Cross-replica same-timestamp resolution (encoding/iterators.go:60-105).

    4/4 parity with the reference's IterateLastPushed / IterateHighest /
    IterateLowest / IterateHighestFrequencyValue: HIGHEST_FREQUENCY_VALUE
    picks the value the most replicas agree on at a timestamp, and a
    frequency tie falls back to the last-pushed value among the tied
    candidates, matching the reference's tie behavior."""

    LAST_PUSHED = "last_pushed"
    HIGHEST_VALUE = "highest_value"
    LOWEST_VALUE = "lowest_value"
    HIGHEST_FREQUENCY_VALUE = "highest_frequency_value"


def decode_segment_groups(segments: Sequence[dict]) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Decode wire segments -> [(t[int64], v[f64])] aligned with input order.

    Groups by (window, words-width) so each distinct block geometry costs
    exactly one batched kernel invocation."""
    out: List = [None] * len(segments)
    groups: Dict[Tuple[int, int], List[int]] = {}
    for i, seg in enumerate(segments):
        if seg["npoints"] == 0:
            out[i] = (np.zeros(0, np.int64), np.zeros(0, np.float64))
            continue
        key = (int(seg["window"]), int(np.asarray(seg["words"]).shape[-1]),
               int(seg.get("time_unit", int(xtime.Unit.NANOSECOND))))
        groups.setdefault(key, []).append(i)
    for (window, mw, unit), idxs in groups.items():
        # Shape-bucket the batch: pad rows to a power of two so one compiled
        # decode kernel serves every fetch with this block geometry.
        rows = len(idxs)
        rp = 1 << (max(rows, 1) - 1).bit_length()
        words = np.zeros((rp, mw), np.uint32)
        npoints = np.zeros(rp, np.int32)
        for r, i in enumerate(idxs):
            words[r] = np.asarray(segments[i]["words"])
            npoints[r] = segments[i]["npoints"]
        # Shape-bucket telemetry: a first-seen (rows-pow2, width, window)
        # geometry means a fresh decode-kernel compile for this fetch.
        telemetry.record_bucket("client.decode", (rp, mw, window, unit))
        # Unit scaling fuses into the decode program (one launch; no host
        # multiply pass over the plane).
        ts, vs = tsz.decode_plane(words, npoints, window=window,
                                  unit_nanos=xtime.Unit(unit).nanos)
        for row, i in enumerate(idxs):
            n = int(npoints[row])
            out[i] = (ts[row, :n].copy(), vs[row, :n].copy())
    return out


def decode_tile(words, npoints, window: int, time_unit: int
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Decode one columnar block tile ([rows, max_words] words +
    per-row npoints) in a single batched kernel launch, rows padded to a
    power of two so one compiled decode serves every tile with this
    geometry (the decode-side twin of encode_block's shape bucketing —
    same bucketing SealedBlock._decode_plane uses).

    Returns dense ([rows, window] ts_ns, [rows, window] vals) planes;
    row i's valid points are the first npoints[i] columns."""
    words = np.asarray(words)
    npoints = np.asarray(npoints, np.int32)
    n = words.shape[0]
    rp = 1 << (max(n, 1) - 1).bit_length()
    if rp != n:
        words = np.concatenate([words, np.repeat(words[:1], rp - n, 0)])
        np_pad = np.concatenate([npoints, np.repeat(npoints[:1], rp - n)])
    else:
        np_pad = npoints
    telemetry.record_bucket("client.decode_tile",
                            (rp, int(words.shape[-1]), int(window)))
    # Fused decode: tick cumsum + time-unit scaling happen inside the one
    # decode program; the host just slices the padded rows back off.
    ts, vs = tsz.decode_plane(words, np_pad, window=window,
                              unit_nanos=xtime.Unit(time_unit).nanos)
    return np.asarray(ts[:n]), np.asarray(vs[:n])


def merge_replica_points(
    ts_parts: Sequence[np.ndarray],
    vs_parts: Sequence[np.ndarray],
    strategy: ConflictStrategy = ConflictStrategy.LAST_PUSHED,
) -> Tuple[np.ndarray, np.ndarray]:
    """Merge datapoint runs from multiple replicas of one series: sort by
    timestamp, resolve duplicate timestamps per strategy."""
    ts_parts = [t for t in ts_parts if len(t)]
    if not ts_parts:
        return np.zeros(0, np.int64), np.zeros(0, np.float64)
    vs_parts = [v for v in vs_parts if len(v)]
    t = np.concatenate(ts_parts)
    v = np.concatenate(vs_parts)
    # Stable sort keeps replica arrival order within equal timestamps, so
    # "last occurrence" == last pushed.
    order = np.argsort(t, kind="stable")
    t, v = t[order], v[order]
    if len(t) < 2:
        return t, v
    uniq, inverse = np.unique(t, return_inverse=True)
    if len(uniq) == len(t):
        return t, v
    if strategy == ConflictStrategy.LAST_PUSHED:
        picked = np.zeros(len(uniq), np.float64)
        picked[inverse] = v  # later writes overwrite earlier per slot
    elif strategy == ConflictStrategy.HIGHEST_FREQUENCY_VALUE:
        # Majority vote per timestamp, resolved for ALL slots in one
        # vectorized grouping pass (with full replica overlap EVERY slot
        # is conflicted, so a per-slot Python scan would be quadratic):
        # group points into (slot, value) runs, count each run, then per
        # slot keep the run with the highest count — ties by the run
        # whose last push arrived latest (last-pushed fallback).
        arrival = np.arange(len(v))
        order = np.lexsort((arrival, v, inverse))
        sv, si, sa = v[order], inverse[order], arrival[order]
        new_run = np.empty(len(sv), bool)
        new_run[0] = True
        np.logical_or(si[1:] != si[:-1], sv[1:] != sv[:-1],
                      out=new_run[1:])
        run_starts = np.flatnonzero(new_run)
        run_slot = si[run_starts]
        run_val = sv[run_starts]
        run_count = np.diff(np.append(run_starts, len(sv)))
        run_last_arrival = sa[np.append(run_starts[1:], len(sv)) - 1]
        # Per slot take the lexicographically greatest (count, last
        # arrival) run: sort runs so it lands last within each slot.
        sel = np.lexsort((run_last_arrival, run_count, run_slot))
        slot_sorted = run_slot[sel]
        last_of_slot = np.empty(len(sel), bool)
        np.not_equal(slot_sorted[1:], slot_sorted[:-1],
                     out=last_of_slot[:-1])
        last_of_slot[-1] = True
        picked = np.zeros(len(uniq), np.float64)
        picked[slot_sorted[last_of_slot]] = run_val[sel[last_of_slot]]
    elif strategy == ConflictStrategy.HIGHEST_VALUE:
        picked = np.full(len(uniq), -np.inf)
        np.maximum.at(picked, inverse, v)
    else:
        picked = np.full(len(uniq), np.inf)
        np.minimum.at(picked, inverse, v)
    return uniq, picked


# (series_points, the per-series segments+buffer decoder, retired in
# round 16: fetch_tagged frames are columnar — tiles + one buffer
# sidecar — decoded by Session._columnar_points via decode_tile.
# decode_segment_groups stays: the bootstrap path still stacks wire
# segments by geometry.)
