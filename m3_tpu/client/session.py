"""Replicating smart client (reference: src/dbnode/client/session.go).

Session parity: topology-watching (session.go:536-543), per-host queues
with op batching (host_queue.go), connection pools
(connection_pool.go), write fanout to all shard replicas with quorum
wait (session.go:867 Write -> :903 writeAttempt, majority :609),
FetchTagged with consistency accumulation
(fetch_tagged_results_accumulator.go), and the AdminSession peer
metadata/block streaming used by bootstrap & repair
(FetchBootstrapBlocksFromPeers; docs/m3db/architecture/peer_streaming.md)."""

from __future__ import annotations

import dataclasses
import socket
import threading
import time
from collections import Counter
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait as futures_wait
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..cluster.topology import (
    ConsistencyLevel,
    ReadConsistencyLevel,
    required_acks,
    required_reads,
)
from ..parallel.sharding import ShardSet
from ..rpc import wire
from ..utils.limits import ResourceExhausted
from ..utils.retry import (
    Breaker,
    BreakerOpen,
    BreakerOptions,
    Deadline,
    DeadlineExceeded,
    HostHealth,
    Retrier,
    RetryOptions,
)
from .decode import ConflictStrategy, merge_replica_points, series_points


class ConsistencyError(Exception):
    """Not enough replica acks/responses to satisfy the consistency level."""


# ------------------------------------------------------------------ transport


class Connection:
    """One framed TCP connection (connection_pool.go conn)."""

    def __init__(self, endpoint: str, connect_timeout: float = 10.0,
                 request_timeout: float = 10.0):
        host, port = endpoint.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)),
                                             timeout=connect_timeout)
        self.sock.settimeout(request_timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.request_timeout = request_timeout
        self._msg_id = 0

    def call(self, method: str, args: dict,
             deadline: Optional[Deadline] = None):
        self._msg_id += 1
        req = {"m": method, "id": self._msg_id, "a": args}
        if deadline is not None:
            deadline.check(method)
            req[wire.DEADLINE_KEY] = deadline.to_wire()
            # The read must give up when the BUDGET does, not at the
            # connection's default request timeout past it.
            self.sock.settimeout(deadline.min_timeout(self.request_timeout))
        else:
            self.sock.settimeout(self.request_timeout)
        wire.write_frame(self.sock, req)
        try:
            resp = wire.read_dict_frame(self.sock)
        except socket.timeout:
            # The response may still land later: this stream is desynced
            # for any further request/response pairing — drop it.
            self.close()
            if deadline is not None and deadline.expired:
                raise DeadlineExceeded(f"{method}: deadline exceeded "
                                       "waiting for reply")
            raise
        except ValueError as e:
            # malformed reply = desync: this connection is unusable; close
            # it and surface a CONNECTION error so quorum fanout treats
            # the node as failed instead of retrying on a broken stream.
            self.close()
            raise ConnectionError(f"node reply desync: {e}")
        if not resp.get("ok"):
            if resp.get("kind") == "deadline":
                raise DeadlineExceeded(resp.get("err", "deadline exceeded"))
            if resp.get("kind") == "resource_exhausted":
                # Server shed this request (query limit / admission gate).
                # ResourceExhausted is a RetryableError: the Retrier backs
                # off and re-attempts, because the overload clears on its
                # own — distinct from deadline, which stays non-retryable.
                raise ResourceExhausted(
                    resp.get("err", "server resource exhausted"))
            raise RemoteError(resp.get("err", "unknown remote error"))
        return resp["r"]

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class RemoteError(Exception):
    """Server-side failure relayed to the caller (not a transport error)."""


class HostClient:
    """Connection pool for one host (client/connection_pool.go) fronted
    by a circuit breaker and a retrier: transport failures retry with
    backoff, repeated failures trip the breaker so a dead host is shed
    instead of hammered, and a half-open probe restores it."""

    def __init__(self, endpoint: str, pool_size: int = 4, timeout: float = 10.0,
                 connect_timeout: Optional[float] = None,
                 retry_opts: RetryOptions = RetryOptions(),
                 breaker: Optional[Breaker] = None,
                 on_outcome: Optional[Callable[[bool], None]] = None):
        self.endpoint = endpoint
        self.timeout = timeout
        self.connect_timeout = timeout if connect_timeout is None else connect_timeout
        self.breaker = breaker if breaker is not None else Breaker(name=endpoint)
        self._on_outcome = on_outcome  # e.g. HostHealth.count
        self.retrier = Retrier(retry_opts)
        self._free: List[Connection] = []
        self._lock = threading.Lock()
        self._sema = threading.Semaphore(pool_size)

    def _record(self, ok: bool):
        if ok:
            self.breaker.record_success()
        else:
            self.breaker.record_failure()
        if self._on_outcome is not None:
            self._on_outcome(ok)

    def call(self, method: str, _deadline: Optional[Deadline] = None, **args):
        return self.retrier.attempt(self._call_once, method, args,
                                    _deadline, deadline=_deadline)

    def _call_once(self, method: str, args: dict, deadline: Optional[Deadline]):
        if self.breaker.state == Breaker.OPEN:
            # fast shed: no pool-slot wait, no grant claimed
            raise BreakerOpen(f"host {self.endpoint} shed by open breaker")
        with self._sema:
            # Claim the breaker grant only once a pool slot is held: a
            # half-open probe stuck waiting behind a busy pool would
            # otherwise hold the ONLY probe slot while doing no probe
            # I/O, shedding every other caller for the whole wait.
            if not self.breaker.allow():
                raise BreakerOpen(f"host {self.endpoint} shed by open breaker")
            # Past allow(), EVERY exit must settle the grant exactly
            # once — an unsettled exit leaks the half-open probe slot
            # and wedges the breaker half-open forever (allow()'s
            # contract).
            recorded = [False]

            def record(ok: bool):
                if not recorded[0]:
                    recorded[0] = True
                    self._record(ok)

            try:
                return self._call_on_conn(method, args, deadline, record)
            except DeadlineExceeded as e:
                if getattr(e, "pre_io", False) and not recorded[0]:
                    # budget died in CLIENT-side queueing (retry backoff,
                    # connect gate) before any bytes reached the host:
                    # release the grant without blaming the endpoint
                    recorded[0] = True
                    self.breaker.cancel()
                else:
                    record(False)
                raise
            except BaseException:
                record(False)  # safety net for paths the branches miss
                raise

    def _call_on_conn(self, method: str, args: dict,
                      deadline: Optional[Deadline], record):
        """One attempt on a pooled connection (pool semaphore + breaker
        grant both held by _call_once)."""
        with self._lock:
            conn = self._free.pop() if self._free else None
        if conn is None:
            # the connect phase consumes deadline budget too: a
            # blackholed host (SYN drop) must not stall a 100ms-budget
            # call for the full connect timeout
            ct = self.connect_timeout
            if deadline is not None:
                deadline.check(method)
                ct = deadline.min_timeout(ct)
            try:
                conn = Connection(self.endpoint, ct, self.timeout)
            except (OSError, ConnectionError):
                record(False)
                raise
        try:
            result = conn.call(method, args, deadline)
        except RemoteError:
            # The HOST is healthy — it parsed, ran, and answered; the
            # application errored. Keep the connection and the breaker
            # must not trip on it.
            with self._lock:
                self._free.append(conn)
            record(True)
            raise
        except ResourceExhausted:
            # Deliberate shed by a healthy host: the stream is synced and
            # poolable, and the breaker must not trip (tripping it would
            # turn a load-shedding node into a "dead" one and dogpile its
            # replicas). The retrier above backs off and re-attempts —
            # exactly the producer behavior shedding asks for.
            with self._lock:
                self._free.append(conn)
            record(True)
            raise
        except DeadlineExceeded as e:
            # conn.call already dropped a desynced stream (reply never
            # read); a server-relayed deadline frame leaves the stream
            # synced and poolable. The breaker records a failure when
            # the HOST burned the budget; a pre-I/O expiry (tagged by
            # Deadline.check — budget died before any bytes went out)
            # falls through for _call_once to cancel the grant.
            if conn.sock.fileno() != -1:
                with self._lock:
                    self._free.append(conn)
            if not getattr(e, "pre_io", False):
                record(False)
            raise
        except Exception:
            conn.close()
            record(False)
            raise
        with self._lock:
            self._free.append(conn)
        record(True)
        return result

    def health(self) -> bool:
        try:
            return bool(self.call("health")["ok"])
        except Exception:  # noqa: BLE001
            return False

    def close(self):
        with self._lock:
            for c in self._free:
                c.close()
            self._free.clear()


# ------------------------------------------------------------------- batching


class _Completion:
    """Quorum wait for one logical write (session writeState)."""

    __slots__ = ("required", "total", "acks", "errors", "errs", "_cond")

    def __init__(self, required: int, total: int):
        self.required = required
        self.total = total
        self.acks = 0
        self.errors = 0
        self.errs: List[str] = []
        self._cond = threading.Condition()

    def ack(self):
        with self._cond:
            self.acks += 1
            self._cond.notify_all()

    def error(self, err: str):
        with self._cond:
            self.errors += 1
            self.errs.append(err)
            self._cond.notify_all()

    def wait(self, timeout: float):
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                if self.acks >= self.required:
                    return
                if self.acks + self.errors >= self.total:
                    raise ConsistencyError(
                        f"{self.acks}/{self.total} acks, need {self.required}: {self.errs}"
                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConsistencyError(
                        f"timeout: {self.acks}/{self.total} acks, need {self.required}"
                    )
                self._cond.wait(remaining)


@dataclasses.dataclass
class _WriteOp:
    ns: bytes
    id: bytes
    t_ns: int
    value: float
    tags: Optional[dict]
    completion: _Completion


class HostQueue:
    """Per-host op queue: batches writes into write_batch RPCs
    (client/host_queue.go). Drains whatever is queued on each wake, so
    batching emerges under load without adding idle latency."""

    def __init__(self, client: HostClient, max_batch: int = 256):
        self.client = client
        self.max_batch = max_batch
        self._ops: List[_WriteOp] = []
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def enqueue(self, op: _WriteOp):
        with self._cond:
            if self._closed:
                raise ConnectionError("host queue closed")
            self._ops.append(op)
            self._cond.notify()

    def _run(self):
        while True:
            with self._cond:
                while not self._ops and not self._closed:
                    self._cond.wait()
                if self._closed and not self._ops:
                    return
                batch, self._ops = self._ops[: self.max_batch], self._ops[self.max_batch :]
            self._flush(batch)

    def _flush(self, batch: List[_WriteOp]):
        by_ns: Dict[bytes, List[_WriteOp]] = {}
        for op in batch:
            by_ns.setdefault(op.ns, []).append(op)
        for ns, ops in by_ns.items():
            try:
                self.client.call(
                    "write_batch",
                    ns=ns,
                    ids=[o.id for o in ops],
                    ts=np.array([o.t_ns for o in ops], np.int64),
                    vals=np.array([o.value for o in ops], np.float64),
                    tags=[o.tags for o in ops],
                )
            except Exception as e:  # noqa: BLE001 — propagate via completion
                for o in ops:
                    o.completion.error(f"{self.client.endpoint}: {e}")
            else:
                for o in ops:
                    o.completion.ack()

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5)


# -------------------------------------------------------------------- session


@dataclasses.dataclass
class SessionOptions:
    write_consistency: ConsistencyLevel = ConsistencyLevel.MAJORITY
    read_consistency: ReadConsistencyLevel = ReadConsistencyLevel.UNSTRICT_MAJORITY
    conflict_strategy: ConflictStrategy = ConflictStrategy.LAST_PUSHED
    timeout_s: float = 30.0
    pool_size: int = 4
    max_batch: int = 256
    # resilience knobs (no more hard-coded connect timeout): per-host
    # transport retries, breaker trip/recovery, and connection timeouts.
    # None = inherit timeout_s, preserving the pre-xresil behavior where
    # the per-RPC socket timeout WAS the session timeout — a user setting
    # only timeout_s must not be silently capped by a tighter default.
    connect_timeout_s: Optional[float] = None
    request_timeout_s: Optional[float] = None
    retry: RetryOptions = RetryOptions(max_attempts=3, initial_backoff_s=0.05)
    breaker: BreakerOptions = BreakerOptions()

    @property
    def effective_request_timeout_s(self) -> float:
        return self.timeout_s if self.request_timeout_s is None \
            else self.request_timeout_s

    @property
    def effective_connect_timeout_s(self) -> float:
        return self.effective_request_timeout_s if self.connect_timeout_s \
            is None else self.connect_timeout_s


class Session:
    """client.Session: Write/WriteTagged/Fetch/FetchTagged over a topology."""

    def __init__(self, topology, opts: SessionOptions = SessionOptions()):
        self.topology = topology
        self.opts = opts
        self.health = HostHealth(opts.breaker)  # per-endpoint breakers/stats
        self._clients: Dict[str, HostClient] = {}
        self._queues: Dict[str, HostQueue] = {}
        self._lock = threading.RLock()  # _queue -> _client nest on this lock
        self._pool = ThreadPoolExecutor(max_workers=16)
        self._shard_set: Optional[ShardSet] = None
        if hasattr(topology, "subscribe"):
            topology.subscribe(lambda _m: None)  # keep map fresh

    # ---------------------------------------------------------------- routing

    def _map(self):
        m = self.topology.get()
        if m is None:
            raise ConnectionError("no topology available")
        return m

    def _shards(self) -> ShardSet:
        m = self._map()
        if self._shard_set is None or self._shard_set.num_shards != m.num_shards:
            self._shard_set = ShardSet(m.num_shards)
        return self._shard_set

    def _client(self, host) -> HostClient:
        with self._lock:
            c = self._clients.get(host.id)
            if c is None or c.endpoint != host.endpoint:
                if c is not None:
                    c.close()  # endpoint moved: release the old socket pool
                ep = host.endpoint
                c = HostClient(ep, self.opts.pool_size,
                               self.opts.effective_request_timeout_s,
                               connect_timeout=self.opts.effective_connect_timeout_s,
                               retry_opts=self.opts.retry,
                               breaker=self.health.breaker(ep),
                               on_outcome=lambda ok, _ep=ep:
                                   self.health.count(_ep, ok))
                self._clients[host.id] = c
            return c

    def _queue(self, host) -> HostQueue:
        with self._lock:
            q = self._queues.get(host.id)
            if q is None or q.client.endpoint != host.endpoint:
                if q is not None:
                    q.close()
                    q.client.close()
                q = HostQueue(self._client(host), self.opts.max_batch)
                self._queues[host.id] = q
            return q

    # ----------------------------------------------------------------- writes

    def write(self, ns: bytes, id: bytes, t_ns: int, value: float,
              tags: Optional[dict] = None):
        """session.go:867 Write: fan out to all shard replicas, wait quorum."""
        m = self._map()
        shard = self._shards().lookup(id)
        hosts = m.route_shard(shard)
        if not hosts:
            raise ConsistencyError(f"no hosts own shard {shard}")
        required = required_acks(self.opts.write_consistency, m.replica_factor)
        completion = _Completion(required=min(required, len(hosts)), total=len(hosts))
        op = _WriteOp(ns, id, t_ns, value, tags, completion)
        for h in hosts:
            self._queue(h).enqueue(op)
        completion.wait(self.opts.timeout_s)

    def write_tagged(self, ns: bytes, id: bytes, tags: dict, t_ns: int, value: float):
        self.write(ns, id, t_ns, value, tags)

    def write_batch(self, ns: bytes, ids: Sequence[bytes], ts, vals,
                    tags: Optional[Sequence[Optional[dict]]] = None):
        """Batched write: one quorum completion per datapoint, ops fanned
        through the same host queues (host queues re-batch per host)."""
        ts = np.asarray(ts, np.int64)
        vals = np.asarray(vals, np.float64)
        m = self._map()
        required = required_acks(self.opts.write_consistency, m.replica_factor)
        completions = []
        ss = self._shards()
        for i, sid in enumerate(ids):
            hosts = m.route_shard(ss.lookup(sid))
            if not hosts:
                raise ConsistencyError(f"no hosts own shard for {sid!r}")
            c = _Completion(required=min(required, len(hosts)), total=len(hosts))
            completions.append(c)
            op = _WriteOp(ns, sid, int(ts[i]), float(vals[i]),
                          tags[i] if tags else None, c)
            for h in hosts:
                self._queue(h).enqueue(op)
        for c in completions:
            c.wait(self.opts.timeout_s)

    # ------------------------------------------------------------------ reads

    def fetch(self, ns: bytes, id: bytes, start_ns: int, end_ns: int
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Fetch decoded + replica-merged datapoints for one series."""
        m = self._map()
        hosts = m.route_shard_readable(self._shards().lookup(id))
        required = min(required_reads(self.opts.read_consistency, m.replica_factor),
                       len(hosts)) or 1
        results, errs = [], []
        # One deadline bounds the whole quorum read and rides every RPC
        # frame: a faulted/slow replica returns DeadlineExceeded instead
        # of stalling past the caller's budget.
        dl = Deadline.after(self.opts.timeout_s)
        pending = {self._pool.submit(self._client(h).call, "fetch", _deadline=dl,
                                     ns=ns, id=id,
                                     start_ns=start_ns, end_ns=end_ns) for h in hosts}
        # Return as soon as the read consistency level is satisfied — a dead
        # replica must not stall a quorum-satisfiable read.
        while pending and len(results) < required:
            done, pending = futures_wait(
                pending, timeout=max(0.0, dl.remaining()),
                return_when=FIRST_COMPLETED)
            if not done:
                break
            for fut in done:
                try:
                    results.append(fut.result())
                except Exception as e:  # noqa: BLE001
                    errs.append(str(e))
        if len(results) < required:
            raise ConsistencyError(f"{len(results)}/{len(hosts)} reads, need {required}: {errs}")
        return merge_replica_points([r["t"] for r in results], [r["v"] for r in results],
                                    self.opts.conflict_strategy)

    def fetch_tagged(self, ns: bytes, query, start_ns: int, end_ns: int,
                     limit: int = 0) -> Dict[bytes, dict]:
        """session.go:1091 FetchTagged: fan out, accumulate per-shard
        consistency, decode + merge replicas. Returns id -> {tags, t, v}."""
        m = self._map()
        q = wire.query_to_wire(query)
        hosts = list(m.hosts.values())
        required = required_reads(self.opts.read_consistency, m.replica_factor)

        def coverage_met(ok_ids):
            # Per-shard accumulation (fetch_tagged_results_accumulator.go):
            # every owned shard needs >= required responders among its
            # READABLE owners — an initializing owner has no data and
            # must neither count toward nor be awaited for coverage.
            for shard in range(m.num_shards):
                owners = m.route_shard_readable(shard)
                if not owners:
                    continue
                got = sum(1 for h in owners if h.id in ok_ids)
                if got < min(required, len(owners)):
                    return False
            return True

        results, errs = [], []
        ok_ids = set()
        dl = Deadline.after(self.opts.timeout_s)
        pending = {self._pool.submit(self._client(h).call, "fetch_tagged",
                                     _deadline=dl, ns=ns,
                                     query=q, start_ns=start_ns, end_ns=end_ns,
                                     limit=limit): h for h in hosts}
        while pending and not coverage_met(ok_ids):
            done, _ = futures_wait(
                set(pending), timeout=max(0.0, dl.remaining()),
                return_when=FIRST_COMPLETED)
            if not done:
                break
            for fut in done:
                h = pending.pop(fut)
                try:
                    results.append(fut.result())
                    ok_ids.add(h.id)
                except Exception as e:  # noqa: BLE001
                    errs.append(f"{h.id}: {e}")
        if not coverage_met(ok_ids):
            raise ConsistencyError(
                f"insufficient replica coverage ({len(ok_ids)} responders, "
                f"need {required} per shard): {errs}")
        merged: Dict[bytes, dict] = {}
        for r in results:
            for entry in r["series"]:
                sid = entry["id"]
                t, v = series_points(entry, self.opts.conflict_strategy)
                cur = merged.get(sid)
                if cur is None:
                    merged[sid] = {"tags": entry["tags"], "t": t, "v": v}
                else:
                    if not cur["tags"] and entry["tags"]:
                        cur["tags"] = entry["tags"]
                    cur["t"], cur["v"] = merge_replica_points(
                        [cur["t"], t], [cur["v"], v], self.opts.conflict_strategy
                    )
        return merged

    def aggregate(self, ns: bytes, query, start_ns: int, end_ns: int,
                  name_only: bool = False, field_filter=(),
                  term_limit: int = 0) -> Dict[bytes, set]:
        """session.go Aggregate: fan out the tags-only aggregate RPC and
        union-merge per-host field dictionaries (no datapoints cross the
        wire). Requires at least one responsive host; results are
        best-effort-complete like query_ids."""
        m = self._map()
        q = wire.query_to_wire(query)
        merged: Dict[bytes, set] = {}
        ok = 0
        errs: List[str] = []
        for h in m.hosts.values():
            try:
                r = self._client(h).call(
                    "aggregate", ns=ns, query=q, start_ns=start_ns,
                    end_ns=end_ns, name_only=name_only,
                    field_filter=list(field_filter), term_limit=term_limit)
            except Exception as e:  # noqa: BLE001
                errs.append(f"{h.id}: {e}")
                continue
            ok += 1
            for f in r["fields"]:
                merged.setdefault(f["name"], set()).update(f["values"])
        if not ok:
            raise ConsistencyError(f"aggregate: no hosts responded: {errs}")
        if term_limit:
            merged = {k: set(sorted(v)[:term_limit]) for k, v in merged.items()}
        return merged

    def query_ids(self, ns: bytes, query, start_ns: int, end_ns: int) -> Dict[bytes, dict]:
        """ids + tags only (thrift Query / FetchTagged fetchData=false)."""
        m = self._map()
        out: Dict[bytes, dict] = {}
        for h in m.hosts.values():
            try:
                r = self._client(h).call("query", ns=ns, query=wire.query_to_wire(query),
                                         start_ns=start_ns, end_ns=end_ns)
            except Exception:  # noqa: BLE001
                continue
            for s in r["series"]:
                out.setdefault(s["id"], {"tags": s["tags"]})
        return out

    # ------------------------------------------------------------------ admin

    def fetch_blocks_metadata_from_peers(self, ns: bytes, shard: int, start_ns: int,
                                         end_ns: int, exclude_host: Optional[str] = None):
        """AdminSession peer metadata streaming: paged metadata from every
        replica of a shard -> {host_id: {series_id: {tags, blocks}}}."""
        m = self._map()
        out: Dict[str, Dict[bytes, dict]] = {}
        # Peer streaming reads block data: only readable owners hold any
        # (an initializing peer is itself still bootstrapping).
        for h in m.route_shard_readable(shard):
            if h.id == exclude_host:
                continue
            series: Dict[bytes, dict] = {}
            token = 0
            while token is not None:
                try:
                    r = self._client(h).call(
                        "fetch_blocks_metadata", ns=ns, shard=shard,
                        start_ns=start_ns, end_ns=end_ns, page_token=token)
                except Exception:  # noqa: BLE001 — peer down: skip
                    series = None
                    break
                for s in r["series"]:
                    series[s["id"]] = {"tags": s["tags"], "blocks": s["blocks"]}
                token = r["next_page_token"]
            if series is not None:
                out[h.id] = series
        return out

    def fetch_bootstrap_blocks_from_peers(self, ns: bytes, shard: int, start_ns: int,
                                          end_ns: int, exclude_host: Optional[str] = None
                                          ) -> Dict[bytes, dict]:
        """Peer bootstrap streaming (session FetchBootstrapBlocksFromPeers):
        diff peer metadata, pick the best peer per block by checksum
        agreement (majority checksum first, else any), stream the blocks.

        Returns {series_id: {"tags": .., "blocks": [wire block dicts]}}."""
        meta = self.fetch_blocks_metadata_from_peers(ns, shard, start_ns, end_ns,
                                                     exclude_host)
        # (series, block_start) -> {checksum -> [host_ids]}
        wanted: Dict[bytes, dict] = {}
        plan: Dict[str, Dict[bytes, List[int]]] = {}
        for sid in {s for hs in meta.values() for s in hs}:
            per_block: Dict[int, Counter] = {}
            tags = {}
            for host_id, hseries in meta.items():
                e = hseries.get(sid)
                if e is None:
                    continue
                tags = tags or e["tags"]
                for b in e["blocks"]:
                    per_block.setdefault(b["bs"], Counter())[(b["checksum"], host_id)] = 1
            wanted[sid] = {"tags": tags, "blocks": []}
            for bs, ck in per_block.items():
                by_sum = Counter()
                hosts_by_sum: Dict[int, List[str]] = {}
                for (checksum, host_id), _n in ck.items():
                    by_sum[checksum] += 1
                    hosts_by_sum.setdefault(checksum, []).append(host_id)
                best_sum, _cnt = by_sum.most_common(1)[0]
                host_id = hosts_by_sum[best_sum][0]
                plan.setdefault(host_id, {}).setdefault(sid, []).append(bs)
        m = self._map()
        hosts = {h.id: h for h in m.hosts.values()}
        for host_id, reqs in plan.items():
            r = self._client(hosts[host_id]).call(
                "fetch_blocks", ns=ns, shard=shard,
                requests=[{"id": sid, "block_starts": bss} for sid, bss in reqs.items()])
            for s in r["series"]:
                wanted[s["id"]]["blocks"].extend(s["blocks"])
        return {sid: e for sid, e in wanted.items() if e["blocks"]}

    def fetch_blocks_from_host(self, host_id: str, ns: bytes, shard: int,
                               requests: List[dict]) -> dict:
        """Raw encoded blocks from one specific replica (repair path)."""
        m = self._map()
        host = m.hosts.get(host_id)
        if host is None:
            raise ConnectionError(f"unknown host {host_id}")
        return self._client(host).call("fetch_blocks", ns=ns, shard=shard,
                                       requests=requests)

    def truncate(self, ns: bytes) -> int:
        m = self._map()
        total = 0
        for h in m.hosts.values():
            total += self._client(h).call("truncate", ns=ns)
        return total

    def close(self):
        with self._lock:
            for q in self._queues.values():
                q.close()
            for c in self._clients.values():
                c.close()
            self._queues.clear()
            self._clients.clear()
        self._pool.shutdown(wait=False)
