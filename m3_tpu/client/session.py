"""Replicating smart client (reference: src/dbnode/client/session.go).

Session parity: topology-watching (session.go:536-543), per-host queues
with op batching (host_queue.go), connection pools
(connection_pool.go), write fanout to all shard replicas with quorum
wait (session.go:867 Write -> :903 writeAttempt, majority :609),
FetchTagged with consistency accumulation
(fetch_tagged_results_accumulator.go), and the AdminSession peer
metadata/block streaming used by bootstrap & repair
(FetchBootstrapBlocksFromPeers; docs/m3db/architecture/peer_streaming.md)."""

from __future__ import annotations

import dataclasses
import socket
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait as futures_wait
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..cluster.topology import (
    ConsistencyLevel,
    ReadConsistencyLevel,
    required_acks,
    required_reads,
)
from ..parallel.sharding import ShardSet
from ..rpc import wire
from ..utils import tracing
from ..utils.instrument import ROOT
from ..utils.limits import ResourceExhausted
from ..utils.retry import (
    Breaker,
    BreakerOpen,
    BreakerOptions,
    Deadline,
    DeadlineExceeded,
    HostHealth,
    Retrier,
    RetryOptions,
)
from .decode import ConflictStrategy, merge_replica_points


class ConsistencyError(Exception):
    """Not enough replica acks/responses to satisfy the consistency level."""


# The typed ways a peer RPC fails without implicating this process's own
# logic: transport death (ConnectionError covers WireTruncated and
# BreakerOpen), socket/connect errors, an expired budget, or a deliberate
# shed by a healthy-but-overloaded peer. Peer-streaming paths classify on
# exactly this set — anything else is a programming error and propagates.
PEER_SKIP_ERRORS = (ConnectionError, OSError, DeadlineExceeded,
                    ResourceExhausted)

# AdminSession peer-streaming instrumentation (bootstrap/repair observe
# peer failures through these instead of silent except/continue).
_PEER_METRICS = ROOT.sub_scope("session.peers")


# ------------------------------------------------------------------ transport


class Connection:
    """One framed TCP connection (connection_pool.go conn)."""

    def __init__(self, endpoint: str, connect_timeout: float = 10.0,
                 request_timeout: float = 10.0):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self.sock = socket.create_connection((host, int(port)),
                                             timeout=connect_timeout)
        self.sock.settimeout(request_timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.request_timeout = request_timeout
        self._msg_id = 0

    def call(self, method: str, args: dict,
             deadline: Optional[Deadline] = None,
             priority: Optional[str] = None):
        self._msg_id += 1
        req = {"m": method, "id": self._msg_id, "a": args}
        if priority is not None:
            # Admission hint for the server's gate ("bulk" sheds first at
            # the high watermark); rides the frame, not the args.
            req["pri"] = priority
        # Trace context rides the frame beside "d"/"pri" — only when a
        # SAMPLED span is active on this thread, so unsampled traffic
        # costs one thread-local read and no wire bytes. The server's
        # finished span tree comes back under "sp" and is grafted below.
        cur_span = tracing.TRACER.current()
        if cur_span is not None:
            req[wire.TRACE_KEY] = cur_span.context().to_wire()
        if deadline is not None:
            deadline.check(method)
            req[wire.DEADLINE_KEY] = deadline.to_wire()
            # The read must give up when the BUDGET does, not at the
            # connection's default request timeout past it.
            self.sock.settimeout(deadline.min_timeout(self.request_timeout))
        else:
            self.sock.settimeout(self.request_timeout)
        wire.write_frame(self.sock, req)
        try:
            while True:
                resp = wire.read_dict_frame(self.sock)
                rid = resp.get("id", self._msg_id)
                if rid == self._msg_id:
                    break
                if rid > self._msg_id:
                    # A response from the future: the stream is not
                    # request/response-paired anymore — unusable.
                    self.close()
                    raise ConnectionError(
                        f"node reply desync: got id {rid}, "
                        f"expected {self._msg_id}")
                # rid < current: a STALE response — a duplicated request
                # frame (at-least-once delivery) made the server answer
                # an earlier exchange twice. Discard and keep reading;
                # matching on id restores pairing instead of handing the
                # caller another method's result. Re-arm the socket
                # timeout to the REMAINING budget each iteration: stale
                # frames dripping in just under the timeout must not
                # extend a deadlined call past its budget (the unread
                # real response leaves the stream desynced — drop it).
                if deadline is not None:
                    if deadline.expired:
                        self.close()
                        raise DeadlineExceeded(
                            f"{method}: deadline exceeded draining "
                            "stale responses")
                    self.sock.settimeout(
                        deadline.min_timeout(self.request_timeout))
        except socket.timeout:
            # The response may still land later: this stream is desynced
            # for any further request/response pairing — drop it.
            self.close()
            if deadline is not None and deadline.expired:
                raise DeadlineExceeded(f"{method}: deadline exceeded "
                                       "waiting for reply")
            raise
        except ValueError as e:
            # malformed reply = desync: this connection is unusable; close
            # it and surface a CONNECTION error so quorum fanout treats
            # the node as failed instead of retrying on a broken stream.
            self.close()
            raise ConnectionError(f"node reply desync: {e}")
        if not resp.get("ok"):
            if resp.get("kind") == "deadline":
                raise DeadlineExceeded(resp.get("err", "deadline exceeded"))
            if resp.get("kind") == "resource_exhausted":
                # Server shed this request (query limit / admission gate).
                # ResourceExhausted is a RetryableError: the Retrier backs
                # off and re-attempts, because the overload clears on its
                # own — distinct from deadline, which stays non-retryable.
                raise ResourceExhausted(
                    resp.get("err", "server resource exhausted"))
            raise RemoteError(resp.get("err", "unknown remote error"))
        if cur_span is not None and cur_span.end_ns is None:
            # Graft the server-side tree under the calling span, tagged
            # with the endpoint it ran on — the cross-process hop becomes
            # one child in the caller's tree. A FINISHED span (quorum met
            # and returned while this replica straggled) never mutates:
            # it may already be published in the tracer's recent ring.
            sp = resp.get(wire.SPAN_KEY)
            if isinstance(sp, dict):
                sp.setdefault("tags", {})["endpoint"] = self.endpoint
                cur_span.attach(sp)
        return resp["r"]

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class RemoteError(Exception):
    """Server-side failure relayed to the caller (not a transport error)."""


class HostClient:
    """Connection pool for one host (client/connection_pool.go) fronted
    by a circuit breaker and a retrier: transport failures retry with
    backoff, repeated failures trip the breaker so a dead host is shed
    instead of hammered, and a half-open probe restores it."""

    def __init__(self, endpoint: str, pool_size: int = 4, timeout: float = 10.0,
                 connect_timeout: Optional[float] = None,
                 retry_opts: RetryOptions = RetryOptions(),
                 breaker: Optional[Breaker] = None,
                 on_outcome: Optional[Callable[[bool], None]] = None):
        self.endpoint = endpoint
        self.timeout = timeout
        self.connect_timeout = timeout if connect_timeout is None else connect_timeout
        self.breaker = breaker if breaker is not None else Breaker(name=endpoint)
        self._on_outcome = on_outcome  # e.g. HostHealth.count
        self.retrier = Retrier(retry_opts)
        self._free: List[Connection] = []
        self._lock = threading.Lock()
        self._sema = threading.Semaphore(pool_size)

    def _record(self, ok: bool):
        if ok:
            self.breaker.record_success()
        else:
            self.breaker.record_failure()
        if self._on_outcome is not None:
            self._on_outcome(ok)

    def call(self, method: str, _deadline: Optional[Deadline] = None,
             _priority: Optional[str] = None, **args):
        return self.retrier.attempt(self._call_once, method, args,
                                    _deadline, _priority, deadline=_deadline)

    def _call_once(self, method: str, args: dict, deadline: Optional[Deadline],
                   priority: Optional[str] = None):
        if self.breaker.state == Breaker.OPEN:
            # fast shed: no pool-slot wait, no grant claimed
            raise BreakerOpen(f"host {self.endpoint} shed by open breaker")
        with self._sema:
            # Claim the breaker grant only once a pool slot is held: a
            # half-open probe stuck waiting behind a busy pool would
            # otherwise hold the ONLY probe slot while doing no probe
            # I/O, shedding every other caller for the whole wait.
            if not self.breaker.allow():
                raise BreakerOpen(f"host {self.endpoint} shed by open breaker")
            # Past allow(), EVERY exit must settle the grant exactly
            # once — an unsettled exit leaks the half-open probe slot
            # and wedges the breaker half-open forever (allow()'s
            # contract).
            recorded = [False]

            def record(ok: bool):
                if not recorded[0]:
                    recorded[0] = True
                    self._record(ok)

            try:
                return self._call_on_conn(method, args, deadline, record,
                                          priority)
            except DeadlineExceeded as e:
                if getattr(e, "pre_io", False) and not recorded[0]:
                    # budget died in CLIENT-side queueing (retry backoff,
                    # connect gate) before any bytes reached the host:
                    # release the grant without blaming the endpoint
                    recorded[0] = True
                    self.breaker.cancel()
                else:
                    record(False)
                raise
            except BaseException:
                record(False)  # safety net for paths the branches miss
                raise

    def _call_on_conn(self, method: str, args: dict,
                      deadline: Optional[Deadline], record,
                      priority: Optional[str] = None):
        """One attempt on a pooled connection (pool semaphore + breaker
        grant both held by _call_once)."""
        with self._lock:
            conn = self._free.pop() if self._free else None
        if conn is None:
            # the connect phase consumes deadline budget too: a
            # blackholed host (SYN drop) must not stall a 100ms-budget
            # call for the full connect timeout
            ct = self.connect_timeout
            if deadline is not None:
                deadline.check(method)
                ct = deadline.min_timeout(ct)
            try:
                conn = Connection(self.endpoint, ct, self.timeout)
            except (OSError, ConnectionError):
                record(False)
                raise
        try:
            result = conn.call(method, args, deadline, priority)
        except RemoteError:
            # The HOST is healthy — it parsed, ran, and answered; the
            # application errored. Keep the connection and the breaker
            # must not trip on it.
            with self._lock:
                self._free.append(conn)
            record(True)
            raise
        except ResourceExhausted:
            # Deliberate shed by a healthy host: the stream is synced and
            # poolable, and the breaker must not trip (tripping it would
            # turn a load-shedding node into a "dead" one and dogpile its
            # replicas). The retrier above backs off and re-attempts —
            # exactly the producer behavior shedding asks for.
            with self._lock:
                self._free.append(conn)
            record(True)
            raise
        except DeadlineExceeded as e:
            # conn.call already dropped a desynced stream (reply never
            # read); a server-relayed deadline frame leaves the stream
            # synced and poolable. The breaker records a failure when
            # the HOST burned the budget; a pre-I/O expiry (tagged by
            # Deadline.check — budget died before any bytes went out)
            # falls through for _call_once to cancel the grant.
            if conn.sock.fileno() != -1:
                with self._lock:
                    self._free.append(conn)
            if not getattr(e, "pre_io", False):
                record(False)
            raise
        except Exception:
            conn.close()
            record(False)
            raise
        with self._lock:
            self._free.append(conn)
        record(True)
        return result

    def health(self) -> bool:
        try:
            return bool(self.call("health")["ok"])
        except Exception:  # noqa: BLE001
            return False

    def close(self):
        with self._lock:
            for c in self._free:
                c.close()
            self._free.clear()


# ------------------------------------------------------------------- batching


class _Completion:
    """Quorum wait for one logical write (session writeState)."""

    __slots__ = ("required", "total", "acks", "errors", "errs", "_cond")

    def __init__(self, required: int, total: int):
        self.required = required
        self.total = total
        self.acks = 0
        self.errors = 0
        self.errs: List[str] = []
        self._cond = threading.Condition()

    def ack(self):
        with self._cond:
            self.acks += 1
            self._cond.notify_all()

    def error(self, err: str):
        with self._cond:
            self.errors += 1
            self.errs.append(err)
            self._cond.notify_all()

    def wait(self, timeout: float):
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                if self.acks >= self.required:
                    return
                if self.acks + self.errors >= self.total:
                    raise ConsistencyError(
                        f"{self.acks}/{self.total} acks, need {self.required}: {self.errs}"
                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConsistencyError(
                        f"timeout: {self.acks}/{self.total} acks, need {self.required}"
                    )
                self._cond.wait(remaining)


@dataclasses.dataclass
class _WriteOp:
    ns: bytes
    id: bytes
    t_ns: int
    value: float
    tags: Optional[dict]
    completion: _Completion
    # Wire admission hint ("bulk" backfill sheds first server-side); None
    # is NORMAL serving traffic.
    priority: Optional[str] = None


class HostQueue:
    """Per-host op queue: batches writes into write_batch RPCs
    (client/host_queue.go). Drains whatever is queued on each wake, so
    batching emerges under load without adding idle latency."""

    def __init__(self, client: HostClient, max_batch: int = 256):
        self.client = client
        self.max_batch = max_batch
        self._ops: List[_WriteOp] = []
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def enqueue(self, op: _WriteOp):
        with self._cond:
            if self._closed:
                raise ConnectionError("host queue closed")
            self._ops.append(op)
            self._cond.notify()

    def _run(self):
        while True:
            with self._cond:
                while not self._ops and not self._closed:
                    self._cond.wait()
                if self._closed and not self._ops:
                    return
                batch, self._ops = self._ops[: self.max_batch], self._ops[self.max_batch :]
            self._flush(batch)

    def _flush(self, batch: List[_WriteOp]):
        by_ns: Dict[Tuple[bytes, Optional[str]], List[_WriteOp]] = {}
        for op in batch:
            by_ns.setdefault((op.ns, op.priority), []).append(op)
        for (ns, pri), ops in by_ns.items():
            try:
                self.client.call(
                    "write_batch",
                    _priority=pri,
                    ns=ns,
                    ids=[o.id for o in ops],
                    ts=np.array([o.t_ns for o in ops], np.int64),
                    vals=np.array([o.value for o in ops], np.float64),
                    tags=[o.tags for o in ops],
                )
            except Exception as e:  # noqa: BLE001 — propagate via completion
                for o in ops:
                    o.completion.error(f"{self.client.endpoint}: {e}")
            else:
                for o in ops:
                    o.completion.ack()

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5)


# -------------------------------------------------------------------- session


@dataclasses.dataclass
class SessionOptions:
    write_consistency: ConsistencyLevel = ConsistencyLevel.MAJORITY
    read_consistency: ReadConsistencyLevel = ReadConsistencyLevel.UNSTRICT_MAJORITY
    conflict_strategy: ConflictStrategy = ConflictStrategy.LAST_PUSHED
    timeout_s: float = 30.0
    pool_size: int = 4
    max_batch: int = 256
    # resilience knobs (no more hard-coded connect timeout): per-host
    # transport retries, breaker trip/recovery, and connection timeouts.
    # None = inherit timeout_s, preserving the pre-xresil behavior where
    # the per-RPC socket timeout WAS the session timeout — a user setting
    # only timeout_s must not be silently capped by a tighter default.
    connect_timeout_s: Optional[float] = None
    request_timeout_s: Optional[float] = None
    retry: RetryOptions = RetryOptions(max_attempts=3, initial_backoff_s=0.05)
    breaker: BreakerOptions = BreakerOptions()
    # Read-fanout worker pool: open-loop traffic with slow/faulted
    # replicas queues here before any socket — size it for the offered
    # concurrency, not just the host count.
    fanout_workers: int = 16

    @property
    def effective_request_timeout_s(self) -> float:
        return self.timeout_s if self.request_timeout_s is None \
            else self.request_timeout_s

    @property
    def effective_connect_timeout_s(self) -> float:
        return self.effective_request_timeout_s if self.connect_timeout_s \
            is None else self.connect_timeout_s


class Session:
    """client.Session: Write/WriteTagged/Fetch/FetchTagged over a topology."""

    def __init__(self, topology, opts: SessionOptions = SessionOptions()):
        self.topology = topology
        self.opts = opts
        self.health = HostHealth(opts.breaker)  # per-endpoint breakers/stats
        self._clients: Dict[str, HostClient] = {}
        self._queues: Dict[str, HostQueue] = {}
        self._lock = threading.RLock()  # _queue -> _client nest on this lock
        self._pool = ThreadPoolExecutor(max_workers=opts.fanout_workers)
        self._shard_set: Optional[ShardSet] = None
        if hasattr(topology, "subscribe"):
            topology.subscribe(lambda _m: None)  # keep map fresh

    # ---------------------------------------------------------------- routing

    def _map(self):
        m = self.topology.get()
        if m is None:
            raise ConnectionError("no topology available")
        return m

    def _shards(self) -> ShardSet:
        m = self._map()
        if self._shard_set is None or self._shard_set.num_shards != m.num_shards:
            self._shard_set = ShardSet(m.num_shards)
        return self._shard_set

    def _client(self, host) -> HostClient:
        with self._lock:
            c = self._clients.get(host.id)
            if c is None or c.endpoint != host.endpoint:
                if c is not None:
                    c.close()  # endpoint moved: release the old socket pool
                ep = host.endpoint
                c = HostClient(ep, self.opts.pool_size,
                               self.opts.effective_request_timeout_s,
                               connect_timeout=self.opts.effective_connect_timeout_s,
                               retry_opts=self.opts.retry,
                               breaker=self.health.breaker(ep),
                               on_outcome=lambda ok, _ep=ep:
                                   self.health.count(_ep, ok))
                self._clients[host.id] = c
            return c

    def _queue(self, host) -> HostQueue:
        with self._lock:
            q = self._queues.get(host.id)
            if q is None or q.client.endpoint != host.endpoint:
                if q is not None:
                    q.close()
                    q.client.close()
                q = HostQueue(self._client(host), self.opts.max_batch)
                self._queues[host.id] = q
            return q

    # ----------------------------------------------------------------- writes

    def write(self, ns: bytes, id: bytes, t_ns: int, value: float,
              tags: Optional[dict] = None, priority: Optional[str] = None):
        """session.go:867 Write: fan out to all shard replicas, wait quorum."""
        m = self._map()
        shard = self._shards().lookup(id)
        hosts = m.route_shard(shard)
        if not hosts:
            raise ConsistencyError(f"no hosts own shard {shard}")
        required = required_acks(self.opts.write_consistency, m.replica_factor)
        completion = _Completion(required=min(required, len(hosts)), total=len(hosts))
        op = _WriteOp(ns, id, t_ns, value, tags, completion, priority)
        for h in hosts:
            self._queue(h).enqueue(op)
        completion.wait(self.opts.timeout_s)

    def write_tagged(self, ns: bytes, id: bytes, tags: dict, t_ns: int, value: float):
        self.write(ns, id, t_ns, value, tags)

    def write_batch(self, ns: bytes, ids: Sequence[bytes], ts, vals,
                    tags: Optional[Sequence[Optional[dict]]] = None,
                    priority: Optional[str] = None):
        """Batched write: one quorum completion per datapoint, ops fanned
        through the same host queues (host queues re-batch per host)."""
        ts = np.asarray(ts, np.int64)
        vals = np.asarray(vals, np.float64)
        m = self._map()
        required = required_acks(self.opts.write_consistency, m.replica_factor)
        completions = []
        ss = self._shards()
        for i, sid in enumerate(ids):
            hosts = m.route_shard(ss.lookup(sid))
            if not hosts:
                raise ConsistencyError(f"no hosts own shard for {sid!r}")
            c = _Completion(required=min(required, len(hosts)), total=len(hosts))
            completions.append(c)
            op = _WriteOp(ns, sid, int(ts[i]), float(vals[i]),
                          tags[i] if tags else None, c, priority)
            for h in hosts:
                self._queue(h).enqueue(op)
        for c in completions:
            c.wait(self.opts.timeout_s)

    # ------------------------------------------------------------------ reads

    def _traced_call(self, span, client: HostClient, method: str, **kwargs):
        """HostClient call with `span` active on the worker thread: the
        fanout pool's threads don't inherit the submitting thread's
        span stack, so propagation into the wire frames (and the graft
        of server spans back onto `span`) needs the explicit handoff."""
        with tracing.TRACER.activate(span):
            return client.call(method, **kwargs)

    def fetch(self, ns: bytes, id: bytes, start_ns: int, end_ns: int
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Fetch decoded + replica-merged datapoints for one series."""
        m = self._map()
        hosts = m.route_shard_readable(self._shards().lookup(id))
        required = min(required_reads(self.opts.read_consistency, m.replica_factor),
                       len(hosts)) or 1
        results, errs = [], []
        # One deadline bounds the whole quorum read and rides every RPC
        # frame: a faulted/slow replica returns DeadlineExceeded instead
        # of stalling past the caller's budget.
        dl = Deadline.after(self.opts.timeout_s)
        with tracing.span("client.fetch", replicas=len(hosts)) as csp:
            pending = {self._pool.submit(
                self._traced_call, csp, self._client(h), "fetch",
                _deadline=dl, ns=ns, id=id,
                start_ns=start_ns, end_ns=end_ns) for h in hosts}
            results, errs = self._await_quorum(pending, dl, required, results,
                                               errs)
        if len(results) < required:
            raise ConsistencyError(f"{len(results)}/{len(hosts)} reads, need {required}: {errs}")
        return merge_replica_points([r["t"] for r in results], [r["v"] for r in results],
                                    self.opts.conflict_strategy)

    def _await_quorum(self, pending, dl, required, results, errs):
        # Return as soon as the read consistency level is satisfied — a dead
        # replica must not stall a quorum-satisfiable read.
        while pending and len(results) < required:
            done, pending = futures_wait(
                pending, timeout=max(0.0, dl.remaining()),
                return_when=FIRST_COMPLETED)
            if not done:
                break
            for fut in done:
                try:
                    results.append(fut.result())
                except Exception as e:  # noqa: BLE001
                    errs.append(str(e))
        return results, errs

    def fetch_tagged(self, ns: bytes, query, start_ns: int, end_ns: int,
                     limit: int = 0) -> Dict[bytes, dict]:
        """session.go:1091 FetchTagged: fan out, accumulate per-shard
        consistency, decode + merge replicas. Returns id -> {tags, t, v}."""
        m = self._map()
        q = wire.query_to_wire(query)
        hosts = list(m.hosts.values())
        required = required_reads(self.opts.read_consistency, m.replica_factor)

        def coverage_met(ok_ids):
            # Per-shard accumulation (fetch_tagged_results_accumulator.go):
            # every owned shard needs >= required responders among its
            # READABLE owners — an initializing owner has no data and
            # must neither count toward nor be awaited for coverage.
            for shard in range(m.num_shards):
                owners = m.route_shard_readable(shard)
                if not owners:
                    continue
                got = sum(1 for h in owners if h.id in ok_ids)
                if got < min(required, len(owners)):
                    return False
            return True

        results, errs = [], []
        ok_ids = set()
        dl = Deadline.after(self.opts.timeout_s)
        with tracing.span("client.fetch_tagged", hosts=len(hosts)) as csp:
            pending = {self._pool.submit(
                self._traced_call, csp, self._client(h), "fetch_tagged",
                _deadline=dl, ns=ns,
                query=q, start_ns=start_ns, end_ns=end_ns,
                limit=limit): h for h in hosts}
            while pending and not coverage_met(ok_ids):
                done, _ = futures_wait(
                    set(pending), timeout=max(0.0, dl.remaining()),
                    return_when=FIRST_COMPLETED)
                if not done:
                    break
                for fut in done:
                    h = pending.pop(fut)
                    try:
                        results.append(fut.result())
                        ok_ids.add(h.id)
                    except Exception as e:  # noqa: BLE001
                        errs.append(f"{h.id}: {e}")
            csp.set_tag("responders", len(ok_ids))
        if not coverage_met(ok_ids):
            raise ConsistencyError(
                f"insufficient replica coverage ({len(ok_ids)} responders, "
                f"need {required} per shard): {errs}")
        merged: Dict[bytes, dict] = {}
        for r in results:
            for entry, (t, v) in zip(r["series"],
                                     self._columnar_points(r)):
                sid = entry["id"]
                cur = merged.get(sid)
                if cur is None:
                    merged[sid] = {"tags": entry["tags"], "t": t, "v": v}
                else:
                    if not cur["tags"] and entry["tags"]:
                        cur["tags"] = entry["tags"]
                    cur["t"], cur["v"] = merge_replica_points(
                        [cur["t"], t], [cur["v"], v], self.opts.conflict_strategy
                    )
        return merged

    def _columnar_points(self, r: dict) -> List[tuple]:
        """Per-series (t, v) from one host's COLUMNAR fetch_tagged frame:
        each sealed-block tile decodes in ONE batched kernel call
        (decode.decode_tile — the wire twin of peer streaming's block
        tiles) and scatters row slices to its series; the buffer sidecar
        contributes offset-sliced views of the concatenated columns.
        Order per series is sealed blocks (ascending start) then the
        mutable buffer — the same precedence the per-series segment path
        had, so LAST_PUSHED conflict resolution is unchanged."""
        from .decode import decode_tile

        n = len(r["series"])
        parts_t: List[list] = [[] for _ in range(n)]
        parts_v: List[list] = [[] for _ in range(n)]
        for tile in sorted(r.get("tiles", ()), key=lambda d: d["bs"]):
            ts, vs = decode_tile(tile["words"], tile["npoints"],
                                 int(tile["window"]),
                                 int(tile["time_unit"]))
            npts = np.asarray(tile["npoints"]).tolist()
            for j, pos in enumerate(np.asarray(tile["rows"]).tolist()):
                k = npts[j]
                parts_t[pos].append(ts[j, :k])
                parts_v[pos].append(vs[j, :k])
        bufs = r.get("bufs")
        if bufs is not None:
            offs = np.asarray(bufs["offs"]).tolist()
            bt, bv = bufs["t"], bufs["v"]
            for j in range(n):
                if offs[j + 1] > offs[j]:
                    parts_t[j].append(bt[offs[j]:offs[j + 1]])
                    parts_v[j].append(bv[offs[j]:offs[j + 1]])
        strategy = self.opts.conflict_strategy
        return [merge_replica_points(parts_t[j], parts_v[j], strategy)
                for j in range(n)]

    def aggregate(self, ns: bytes, query, start_ns: int, end_ns: int,
                  name_only: bool = False, field_filter=(),
                  term_limit: int = 0) -> Dict[bytes, set]:
        """session.go Aggregate: fan out the tags-only aggregate RPC and
        union-merge per-host field dictionaries (no datapoints cross the
        wire). Requires at least one responsive host; results are
        best-effort-complete like query_ids."""
        m = self._map()
        q = wire.query_to_wire(query)
        merged: Dict[bytes, set] = {}
        ok = 0
        errs: List[str] = []
        for h in m.hosts.values():
            try:
                r = self._client(h).call(
                    "aggregate", ns=ns, query=q, start_ns=start_ns,
                    end_ns=end_ns, name_only=name_only,
                    field_filter=list(field_filter), term_limit=term_limit)
            except Exception as e:  # noqa: BLE001
                errs.append(f"{h.id}: {e}")
                continue
            ok += 1
            for f in r["fields"]:
                merged.setdefault(f["name"], set()).update(f["values"])
        if not ok:
            raise ConsistencyError(f"aggregate: no hosts responded: {errs}")
        if term_limit:
            merged = {k: set(sorted(v)[:term_limit]) for k, v in merged.items()}
        return merged

    def query_ids(self, ns: bytes, query, start_ns: int, end_ns: int) -> Dict[bytes, dict]:
        """ids + tags only (thrift Query / FetchTagged fetchData=false)."""
        m = self._map()
        out: Dict[bytes, dict] = {}
        for h in m.hosts.values():
            try:
                r = self._client(h).call("query", ns=ns, query=wire.query_to_wire(query),
                                         start_ns=start_ns, end_ns=end_ns)
            except Exception:  # noqa: BLE001
                continue
            for s in r["series"]:
                out.setdefault(s["id"], {"tags": s["tags"]})
        return out

    # ------------------------------------------------------------------ admin

    def fetch_blocks_metadata_from_peers(self, ns: bytes, shard: int, start_ns: int,
                                         end_ns: int, exclude_host: Optional[str] = None,
                                         deadline: Optional[Deadline] = None,
                                         errors: Optional[Dict[str, str]] = None):
        """AdminSession peer metadata streaming: paged metadata from every
        replica of a shard -> {host_id: {series_id: {tags, blocks}}}.

        A peer that fails in one of the typed transport ways (connection
        death, expired budget, deliberate shed) or relays a server-side
        error is SKIPPED — counted in the `session.peers` scope and
        reported into `errors` (host_id -> message) when the caller passes
        a dict — so bootstrap/repair see partial coverage instead of a
        silently smaller quorum. Anything untyped propagates."""
        m = self._map()
        out: Dict[str, Dict[bytes, dict]] = {}
        # Peer streaming reads block data: only readable owners hold any
        # (an initializing peer is itself still bootstrapping).
        for h in m.route_shard_readable(shard):
            if h.id == exclude_host:
                continue
            series: Dict[bytes, dict] = {}
            token = 0
            try:
                while token is not None:
                    r = self._client(h).call(
                        "fetch_blocks_metadata", _deadline=deadline, ns=ns,
                        shard=shard, start_ns=start_ns, end_ns=end_ns,
                        page_token=token)
                    for s in r["series"]:
                        series[s["id"]] = {"tags": s["tags"],
                                           "blocks": s["blocks"]}
                    token = r["next_page_token"]
            except PEER_SKIP_ERRORS + (RemoteError,) as e:
                _PEER_METRICS.counter("metadata_peer_errors").inc()
                if errors is not None:
                    errors[h.id] = f"{type(e).__name__}: {e}"
                continue
            out[h.id] = series
        return out

    def fetch_block_metadata_tiles_from_peers(
            self, ns: bytes, shard: int, start_ns: int, end_ns: int,
            exclude_host: Optional[str] = None,
            deadline: Optional[Deadline] = None,
            errors: Optional[Dict[str, str]] = None) -> Dict[str, dict]:
        """Columnar peer metadata streaming: per responding host,
        {"ids": [...], "tags": [...], "blocks": [{"bs", "pos", "sums"}]}
        with pages concatenated (block `pos` re-based onto the combined
        ids list). Same typed skip/count semantics as the per-series
        form."""
        m = self._map()
        out: Dict[str, dict] = {}
        for h in m.route_shard_readable(shard):
            if h.id == exclude_host:
                continue
            ids: List[bytes] = []
            tags: List[dict] = []
            blocks: List[dict] = []
            token = 0
            try:
                while token is not None:
                    r = self._client(h).call(
                        "fetch_block_metadata_tiles", _deadline=deadline,
                        ns=ns, shard=shard, start_ns=start_ns,
                        end_ns=end_ns, page_token=token)
                    offset = len(ids)
                    ids.extend(r["ids"])
                    tags.extend(r["tags"])
                    for b in r["blocks"]:
                        pos = np.asarray(b["pos"], np.int64)
                        blocks.append({"bs": int(b["bs"]),
                                       "pos": pos + offset,
                                       "sums": np.asarray(b["sums"],
                                                          np.int64)})
                    token = r["next_page_token"]
            except PEER_SKIP_ERRORS + (RemoteError,) as e:
                _PEER_METRICS.counter("metadata_peer_errors").inc()
                if errors is not None:
                    errors[h.id] = f"{type(e).__name__}: {e}"
                continue
            out[h.id] = {"ids": ids, "tags": tags, "blocks": blocks}
        return out

    @staticmethod
    def plan_block_majority(meta: Dict[str, dict]):
        """Vectorized checksum-majority planning over COLUMNAR peer
        metadata: group every (series, block, checksum) observation,
        vote per (series, block), and return, per block start, the
        winning checksum + the lowest-ranked host actually holding it
        for every series — plus the per-checksum ranked host lists a
        consumer needs to build failover chains.

        Returns (tags_by_sid, sids, hosts_list, per_bs) where per_bs maps
        block_start -> {"gids": int64[], "sums": int64[] (majority
        checksum per gid), "primary": int64[] (host rank holding it),
        "by_sum": {checksum: [host_id ranked]}} and `sids[g]` resolves a
        gid back to its series id."""
        tags_by_sid: Dict[bytes, dict] = {}
        gmap: Dict[bytes, int] = {}
        sids: List[bytes] = []
        hosts_list = list(meta)
        per_bs_rows: Dict[int, List[Tuple[int, np.ndarray, np.ndarray]]] = {}
        for rank, (host_id, m) in enumerate(meta.items()):
            ids = m["ids"]
            for sid, tg in zip(ids, m["tags"]):
                if tg and not tags_by_sid.get(sid):
                    tags_by_sid[sid] = tg
            garr = np.empty(len(ids), np.int64)
            for j, sid in enumerate(ids):
                g = gmap.get(sid)
                if g is None:
                    g = gmap[sid] = len(sids)
                    sids.append(sid)
                garr[j] = g
            for b in m["blocks"]:
                per_bs_rows.setdefault(int(b["bs"]), []).append(
                    (rank, garr[np.asarray(b["pos"], np.int64)],
                     np.asarray(b["sums"], np.int64)))
        per_bs: Dict[int, dict] = {}
        for bs, entries in per_bs_rows.items():
            g_all = np.concatenate([g for _r, g, _s in entries])
            c_all = np.concatenate([s for _r, _g, s in entries])
            r_all = np.concatenate([np.full(len(g), r, np.int64)
                                    for r, g, _s in entries])
            order = np.lexsort((r_all, c_all, g_all))
            g, c, r = g_all[order], c_all[order], r_all[order]
            # (gid, checksum) runs: count = votes, first host = the
            # lowest-ranked holder of that copy.
            new = np.empty(len(g), bool)
            new[0] = True
            np.logical_or(g[1:] != g[:-1], c[1:] != c[:-1], out=new[1:])
            starts = np.flatnonzero(new)
            run_g = g[starts]
            run_c = c[starts]
            run_r0 = r[starts]
            run_n = np.diff(np.append(starts, len(g)))
            # Winner per gid: the run sorting LAST under (gid, count,
            # checksum) — max votes, deterministic checksum tie-break.
            sel = np.lexsort((run_c, run_n, run_g))
            gs = run_g[sel]
            last = np.empty(len(sel), bool)
            if len(sel):
                np.not_equal(gs[1:], gs[:-1], out=last[:-1])
                last[-1] = True
            win = sel[last]
            # Ranked host list per checksum (any row with that checksum
            # in this block) for failover chains.
            pairs = np.unique(np.stack([c_all, r_all], 1), axis=0)
            by_sum: Dict[int, List[str]] = {}
            for cc, rr in pairs:
                by_sum.setdefault(int(cc), []).append(hosts_list[int(rr)])
            per_bs[bs] = {"gids": run_g[win], "sums": run_c[win],
                          "primary": run_r0[win], "by_sum": by_sum,
                          # EVERY (gid, checksum) observation (not just
                          # the winner): repair merges all distinct peer
                          # copies so divergence converges in one sweep
                          # per node instead of pairwise over many.
                          "run_g": run_g, "run_c": run_c,
                          "run_r0": run_r0}
        return tags_by_sid, sids, hosts_list, per_bs

    @staticmethod
    def holder_chain_builder(p: dict, hosts_list: List[str],
                             cross_checksum_tail: bool):
        """Memoized failover-chain factory over one plan_block_majority
        block entry: chain(checksum, first_holder_rank) -> ranked host
        list ([first holder] + every other host with the SAME checksum,
        then — when `cross_checksum_tail` — every remaining holder of
        any copy: any copy beats no copy once the whole same-sum set is
        dead). Chains are SHARED per (checksum, rank) combo and the
        cross-checksum tail is built once per block: per-series list
        construction is quadratic when checksums are per-row distinct.
        The single definition both the bootstrap and repair planners
        rank holders with."""
        by_sum = p["by_sum"]
        all_hosts = [h for h in hosts_list
                     if any(h in hl for hl in by_sum.values())] \
            if cross_checksum_tail and len(by_sum) > 1 else []
        combos: Dict[Tuple[int, int], List[str]] = {}

        def chain(cc: int, rr: int) -> List[str]:
            key = (cc, rr)
            lst = combos.get(key)
            if lst is None:
                first = hosts_list[rr]
                lst = [first] + [h for h in by_sum[cc] if h != first]
                if all_hosts:
                    lst += [h for h in all_hosts if h not in lst]
                combos[key] = lst
            return lst

        return chain

    def fetch_block_tiles(self, ns: bytes, shard: int,
                     holders: Dict[Tuple[bytes, int], List[str]],
                     deadline: Optional[Deadline] = None,
                     errors: Optional[Dict[str, str]] = None):
        """Stream columnar block tiles for a holder plan, one wave per
        holder rank: rank-0 requests batch per host; anything a host
        failed to serve (typed transport error, shed, or a row that
        vanished server-side) re-plans onto each key's next holder. Only
        keys every holder failed come back in `failed`."""
        m = self._map()
        hosts = {h.id: h for h in m.hosts.values()}
        tiles: Dict[int, List[dict]] = {}
        remaining = dict.fromkeys(holders)
        max_rank = max((len(v) for v in holders.values()), default=0)
        for rank in range(max_rank):
            if not remaining:
                break
            wave: Dict[str, Dict[int, List[bytes]]] = {}
            for (sid, bs) in remaining:
                hlist = holders[(sid, bs)]
                if rank < len(hlist) and hlist[rank] in hosts:
                    wave.setdefault(hlist[rank], {}).setdefault(
                        bs, []).append(sid)
            for host_id, by_bs in wave.items():
                reqs = [{"bs": bs, "ids": sids} for bs, sids in by_bs.items()]
                try:
                    r = self._client(hosts[host_id]).call(
                        "fetch_block_tiles", _deadline=deadline, ns=ns,
                        shard=shard, blocks=reqs)
                except PEER_SKIP_ERRORS + (RemoteError,) as e:
                    # This host's whole wave re-plans onto the next
                    # holders (keys stay in `remaining`).
                    _PEER_METRICS.counter("block_fetch_peer_errors").inc()
                    if errors is not None:
                        errors[host_id] = f"{type(e).__name__}: {e}"
                    continue
                for tile in r["blocks"]:
                    ids = tile["ids"]
                    if not len(ids):
                        continue
                    tiles.setdefault(int(tile["bs"]), []).append(tile)
                    for sid in ids:
                        remaining.pop((sid, int(tile["bs"])), None)
        failed = sorted(remaining)
        if failed:
            _PEER_METRICS.counter("blocks_unfetchable").inc(len(failed))
        return tiles, failed

    def fetch_block_tiles_from_peers(self, ns: bytes, shard: int, start_ns: int,
                                     end_ns: int,
                                     exclude_host: Optional[str] = None,
                                     deadline: Optional[Deadline] = None,
                                     errors: Optional[Dict[str, str]] = None,
                                     meta_errors: Optional[Dict[str, str]]
                                     = None):
        """Columnar peer bootstrap streaming: diff peer metadata, plan by
        checksum majority, stream whole-block tiles ([rows, max_words]
        word matrices + per-row nbits/npoints columns — one ndarray per
        (host, block) instead of one dict per series).

        Returns (tiles, tags_by_sid, failed):
          tiles        {block_start: [tile dict]}
          tags_by_sid  {series_id: tags} from the metadata phase
          failed       [(series_id, block_start)] every holder failed —
                       the partial-coverage surface bootstrap subtracts
                       from its claim.

        `meta_errors` collects METADATA-phase peer failures separately
        from block-fetch failures (`errors`): a peer skipped during
        metadata may have held blocks nobody else has, so its loss means
        the plan itself — not just some fetches — is incomplete, and
        callers claiming coverage must treat it as such."""
        meta = self.fetch_block_metadata_tiles_from_peers(
            ns, shard, start_ns, end_ns, exclude_host, deadline,
            meta_errors if meta_errors is not None else errors)
        tags_by_sid, sids, hosts_list, per_bs = self.plan_block_majority(meta)
        holders: Dict[Tuple[bytes, int], List[str]] = {}
        for bs, p in per_bs.items():
            chain = self.holder_chain_builder(p, hosts_list,
                                              cross_checksum_tail=True)
            for gi, cc, rr in zip(p["gids"].tolist(), p["sums"].tolist(),
                                  p["primary"].tolist()):
                holders[(sids[gi], bs)] = chain(cc, rr)
        tiles, failed = self.fetch_block_tiles(ns, shard, holders, deadline,
                                               errors)
        return tiles, tags_by_sid, failed

    def fetch_bootstrap_blocks_from_peers(self, ns: bytes, shard: int, start_ns: int,
                                          end_ns: int, exclude_host: Optional[str] = None,
                                          deadline: Optional[Deadline] = None
                                          ) -> Dict[bytes, dict]:
        """Per-series view of peer bootstrap streaming (the session
        FetchBootstrapBlocksFromPeers shape, kept for callers that want
        row dicts): same tile fetch + holder fallback underneath.

        Returns {series_id: {"tags": .., "blocks": [wire block dicts]}}."""
        tiles, tags_by_sid, _failed = self.fetch_block_tiles_from_peers(
            ns, shard, start_ns, end_ns, exclude_host, deadline)
        out: Dict[bytes, dict] = {}
        for bs, tlist in sorted(tiles.items()):
            for tile in tlist:
                words = np.asarray(tile["words"])
                nbits = np.asarray(tile["nbits"])
                npoints = np.asarray(tile["npoints"])
                for i, sid in enumerate(tile["ids"]):
                    e = out.setdefault(
                        sid, {"tags": tags_by_sid.get(sid) or {}, "blocks": []})
                    e["blocks"].append({
                        "bs": bs, "words": words[i], "nbits": int(nbits[i]),
                        "npoints": int(npoints[i]),
                        "window": int(tile["window"]),
                        "time_unit": int(tile["time_unit"]),
                    })
        return out

    def fetch_block_tiles_from_host(self, host_id: str, ns: bytes, shard: int,
                                    blocks: List[dict],
                                    deadline: Optional[Deadline] = None) -> dict:
        """Columnar tiles from one specific replica (repair streams from
        the host holding the majority checksum); blocks =
        [{"bs": block_start, "ids": [series_id]}]."""
        m = self._map()
        host = m.hosts.get(host_id)
        if host is None:
            raise ConnectionError(f"unknown host {host_id}")
        return self._client(host).call("fetch_block_tiles", _deadline=deadline,
                                       ns=ns, shard=shard, blocks=blocks)

    def fetch_blocks_from_host(self, host_id: str, ns: bytes, shard: int,
                               requests: List[dict]) -> dict:
        """Raw encoded blocks from one specific replica (per-series
        request shape; the batched repair path uses
        fetch_block_tiles_from_host)."""
        m = self._map()
        host = m.hosts.get(host_id)
        if host is None:
            raise ConnectionError(f"unknown host {host_id}")
        return self._client(host).call("fetch_blocks", ns=ns, shard=shard,
                                       requests=requests)

    def truncate(self, ns: bytes) -> int:
        m = self._map()
        total = 0
        for h in m.hosts.values():
            total += self._client(h).call("truncate", ns=ns)
        return total

    def close(self):
        with self._lock:
            for q in self._queues.values():
                q.close()
            for c in self._clients.values():
                c.close()
            self._queues.clear()
            self._clients.clear()
        self._pool.shutdown(wait=False)
