"""Ops tools (reference: src/cmd/tools — fileset inspection / verification
CLIs built on the persist readers). Run as
`python -m m3_tpu.tools <tool> [args]`."""
