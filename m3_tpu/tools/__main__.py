"""Tools CLI dispatcher (reference: src/cmd/tools/*/main):
python -m m3_tpu.tools read_data_files --root R --namespace ns --shard 0
"""

from __future__ import annotations

import argparse
import json
import sys

from . import fileset_tools as ft


def main(argv=None):
    p = argparse.ArgumentParser(prog="m3_tpu.tools")
    sub = p.add_subparsers(dest="tool", required=True)

    def common(sp, commitlog=False):
        if commitlog:
            sp.add_argument("--dir", required=True)
            return
        sp.add_argument("--root", required=True)
        sp.add_argument("--namespace", required=True)
        sp.add_argument("--shard", type=int, required=True)
        sp.add_argument("--block-start", type=int, default=None)

    common(sub.add_parser("read_data_files"))
    common(sub.add_parser("read_index_files"))
    common(sub.add_parser("read_ids"))
    common(sub.add_parser("verify_index_files"))
    common(sub.add_parser("verify_commitlogs"), commitlog=True)
    cp = sub.add_parser("clone_fileset")
    common(cp)
    cp.add_argument("--dst", required=True)

    args = p.parse_args(argv)
    ns = args.namespace.encode() if hasattr(args, "namespace") else None

    if args.tool == "read_data_files":
        for sid, t, v in ft.read_data_files(args.root, ns, args.shard,
                                            args.block_start):
            for ts, val in zip(t, v):
                print(f"{sid.decode(errors='replace')} {ts} {val}")
    elif args.tool == "read_ids":
        for sid in ft.read_ids(args.root, ns, args.shard):
            print(sid.decode(errors="replace"))
    elif args.tool == "read_index_files":
        out = ft.read_index_files(args.root, ns, args.shard)
        for fs in out:
            print(json.dumps({**fs, "entries": [
                {**e, "id": e["id"].decode(errors="replace")}
                for e in fs["entries"]]}))
    elif args.tool == "verify_index_files":
        out = ft.verify_index_files(args.root, ns, args.shard)
        print(json.dumps(out))
        return 1 if out["corrupt"] else 0
    elif args.tool == "verify_commitlogs":
        out = ft.verify_commitlogs(args.dir)
        print(json.dumps(out))
        return 1 if out["errors"] else 0
    elif args.tool == "clone_fileset":
        cloned = ft.clone_fileset(args.root, args.dst, ns, args.shard,
                                  args.block_start)
        for path in cloned:
            print(path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
