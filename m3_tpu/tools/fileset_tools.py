"""Fileset/commitlog inspection + verification tools (reference:
src/cmd/tools/{read_data_files,read_index_files,read_ids,
verify_commitlogs,verify_index_files,clone_fileset}/main/main.go).

Each function returns structured results (and the CLI prints them), so the
same code serves tests, scripts, and operators."""

from __future__ import annotations

import os
import shutil
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..ops import tsz
from ..persist import commitlog as cl
from ..persist.fs import FilesetReader, PersistManager, fileset_complete


def read_data_files(root: str, namespace: bytes, shard: int,
                    block_start: Optional[int] = None
                    ) -> Iterator[Tuple[bytes, np.ndarray, np.ndarray]]:
    """Yield (series_id, timestamps, values) for every series in the shard's
    filesets (read_data_files: decode every entry)."""
    pm = PersistManager(root)
    for bs, path in pm.list_filesets(namespace, shard):
        if block_start is not None and bs != block_start:
            continue
        reader = FilesetReader(path)
        blk, ids = reader.to_block()
        for i, sid in enumerate(ids):
            t, v = blk.read(i)
            yield sid, t, v


def read_ids(root: str, namespace: bytes, shard: int) -> List[bytes]:
    """Just the series IDs (read_ids)."""
    pm = PersistManager(root)
    out: List[bytes] = []
    for _bs, path in pm.list_filesets(namespace, shard):
        reader = FilesetReader(path)
        _blk, ids = reader.to_block()
        out.extend(ids)
    return sorted(set(out))


def read_index_files(root: str, namespace: bytes, shard: int) -> List[dict]:
    """Per-fileset index summaries: entries with offsets/sizes/checksums
    (read_index_files)."""
    pm = PersistManager(root)
    out = []
    for bs, path in pm.list_filesets(namespace, shard):
        reader = FilesetReader(path)
        entries = [
            {"id": e.series_id, "offset": e.offset, "size": e.size,
             "checksum": e.checksum}
            for e in reader._read_index()
        ]
        out.append({"block_start": bs, "path": path, "entries": entries})
    return out


def verify_index_files(root: str, namespace: bytes, shard: int) -> dict:
    """Digest + structural verification of every fileset
    (verify_index_files: catch corruption before a node serves it)."""
    pm = PersistManager(root)
    ok, bad = [], []
    for bs, path in pm.list_filesets(namespace, shard):
        try:
            if not fileset_complete(path):
                raise IOError("incomplete fileset (no checkpoint)")
            reader = FilesetReader(path, verify=True)
            blk, ids = reader.to_block()
            for i in range(len(ids)):
                blk.read(i)  # decodes; raises on corrupt streams
            ok.append(path)
        except Exception as e:  # noqa: BLE001
            bad.append((path, str(e)))
    return {"ok": ok, "corrupt": bad}


def verify_commitlogs(directory: str) -> dict:
    """Replay every commitlog chunk, counting entries + corruption
    (verify_commitlogs)."""
    entries = 0
    namespaces = set()
    series = set()
    errors: List[str] = []
    try:
        for ns, sid, t_ns, value in cl.replay(directory):
            entries += 1
            namespaces.add(ns)
            series.add((ns, sid))
    except Exception as e:  # noqa: BLE001
        errors.append(str(e))
    return {"entries": entries, "namespaces": sorted(namespaces),
            "num_series": len(series), "errors": errors}


def clone_fileset(src_root: str, dst_root: str, namespace: bytes, shard: int,
                  block_start: Optional[int] = None) -> List[str]:
    """Copy filesets between roots, re-verifying digests on the way
    (clone_fileset: used to seed test environments from prod data)."""
    pm = PersistManager(src_root)
    cloned = []
    for bs, path in pm.list_filesets(namespace, shard):
        if block_start is not None and bs != block_start:
            continue
        FilesetReader(path, verify=True)  # verify before copying
        rel = os.path.relpath(path, src_root)
        dst = os.path.join(dst_root, rel)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copytree(path, dst, dirs_exist_ok=True)
        FilesetReader(dst, verify=True)  # and after
        cloned.append(dst)
    return cloned


def carbon_load(endpoint: str, paths: List[bytes], qps: float,
                duration_s: float, value_fn=None, clock=None) -> int:
    """Tiny carbon load generator (cmd/tools/carbon_load): writes lines at
    a target rate; returns lines sent."""
    import socket
    import time as _time

    clock = clock or _time.time
    host, _, port = endpoint.rpartition(":")
    sent = 0
    interval = 1.0 / qps if qps > 0 else 0
    deadline = _time.monotonic() + duration_s
    with socket.create_connection((host, int(port)), timeout=5.0) as sock:
        i = 0
        while _time.monotonic() < deadline:
            path = paths[i % len(paths)]
            value = value_fn(i) if value_fn else float(i % 100)
            line = b"%s %f %d\n" % (path, value, int(clock()))
            sock.sendall(line)
            sent += 1
            i += 1
            if interval:
                _time.sleep(interval)
    return sent
