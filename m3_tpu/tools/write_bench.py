"""Write benchmark harness against the coordinator HTTP surface (reference:
src/query/benchmark — the influxdb-comparisons-based write bench — and
scripts/benchmarks/benchmark-loadgen): drive m3nsch agents at the JSON
write endpoint, measure sustained writes/sec, optionally verify a read."""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Optional

from .. import nsch


def http_write_fn(endpoint: str):
    """Build an nsch write_fn posting one sample per call to
    /api/v1/json/write (batching variants ride the wire transport)."""

    def write(ns, sid, tags, t_ns, value):
        body = {
            "tags": {"__name__": sid.decode(errors="replace"),
                     **({k.decode(): v.decode() for k, v in tags.items()
                         if k != b"__name__"} if tags else {})},
            "timestamp": t_ns / 1e9,
            "value": value,
        }
        req = urllib.request.Request(
            f"{endpoint}/api/v1/json/write",
            data=json.dumps(body).encode(), method="POST")
        req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()

    return write


def run_write_bench(endpoint: str, *, cardinality: int = 100,
                    n_agents: int = 4, duration_s: float = 5.0,
                    qps_per_agent: int = 100000,
                    clock=None) -> dict:
    """Returns {"writes", "errors", "writes_per_sec", "duration_s"}."""
    workload = nsch.Workload(
        metric_prefix=b"bench.metric", cardinality=cardinality,
        ingress_qps=qps_per_agent, datum=nsch.CounterDatum(rate=1.0))
    coord = nsch.NschCoordinator()
    coord.init(workload, [http_write_fn(endpoint) for _ in range(n_agents)],
               clock=clock)
    t0 = time.monotonic()
    coord.start()
    time.sleep(duration_s)
    coord.stop()
    dt = time.monotonic() - t0
    st = coord.status()
    return {
        "writes": st["total_written"],
        "errors": st["total_errors"],
        "writes_per_sec": st["total_written"] / dt,
        "duration_s": dt,
    }
